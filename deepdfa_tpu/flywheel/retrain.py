"""Log-driven candidate training: traffic-weighted fine-tune sets.

The flywheel's learning half. Serve and fleet logs already record
every scored request (`{"request": ...}` lines, the same stream
tune/ladder.py replays to fit the serving ladder); this module replays
them once more — this time to decide *what the candidate should train
on*. The mapping from traffic to training weight goes through the
incumbent's probability distribution: requests concentrate in some
probability bands (most real streams are mostly-benign with a hard
tail near the boundary), so training examples whose incumbent score
falls in traffic-heavy bands are oversampled. That is a calibration
set in the literal sense — the candidate is tuned hardest exactly
where the live decision boundary carries the most traffic.

`build_candidate` then does what `deepdfa-tpu train` does, in
miniature: warm-start from the incumbent checkpoint
(train/checkpoint.py:restore_candidate_params), a bounded number of
GraphTrainer.train_step calls over the weighted selection, and a
servable run dir (config.json + checkpoints/ manifest) that
`fleet-rollout` / the shadow replica can load unchanged. steps=0 is
legal and useful: it produces a candidate run dir that is the
incumbent re-saved — the smoke's "identical candidate" control.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path

#: probability-band resolution for traffic weighting; deciles are
#: coarse enough that a modest log populates every hot band and fine
#: enough to separate the boundary from the bulk
N_BANDS = 10


def traffic_weights_from_log(path: str | Path) -> dict:
    """Replay `{"request": ...}` lines (fleet_log or serve request-log
    shape — tune/ladder.py:batch_sizes_from_log precedent) into the
    traffic profile retraining weights derive from: total volume, the
    tenant mix, and a probability-band histogram over the incumbent's
    logged scores. Torn or foreign lines are skipped, not fatal."""
    tenants: Counter = Counter()
    bands = [0] * N_BANDS
    n = 0
    n_prob = 0
    path = Path(path)
    if not path.exists():
        return {"requests": 0, "scored": 0, "tenants": {},
                "prob_bands": bands}
    with path.open() as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            req = rec.get("request") if isinstance(rec, dict) else None
            if not isinstance(req, dict):
                continue
            n += 1
            tenants[str(req.get("tenant") or "default")] += 1
            prob = req.get("prob")
            if isinstance(prob, (int, float)):
                bands[band_of(float(prob))] += 1
                n_prob += 1
    return {
        "requests": n, "scored": n_prob,
        "tenants": dict(tenants.most_common()), "prob_bands": bands,
    }


def band_of(prob: float) -> int:
    return min(N_BANDS - 1, max(0, int(float(prob) * N_BANDS)))


def example_weights(probs, prob_bands) -> list[float]:
    """Per-example sampling weight = traffic mass of the band the
    incumbent scores that example into, floored at one notional
    request so zero-traffic bands stay representable (an empty band
    must not erase a class from the fine-tune set)."""
    total = float(sum(prob_bands)) or 1.0
    return [
        max(1.0, float(prob_bands[band_of(p)])) / total for p in probs
    ]


def select_weighted(weights, k: int, seed: int = 0) -> list[int]:
    """Deterministic weighted selection (with replacement) of k
    indices — systematic resampling over the cumulative weights, the
    same draw every run for a given (weights, k, seed) so candidate
    builds are reproducible from the log alone."""
    import random

    if not weights or k <= 0:
        return []
    total = float(sum(weights))
    if total <= 0:
        return list(range(min(k, len(weights))))
    rng = random.Random(int(seed))
    start = rng.random() / k
    points = [start + i / k for i in range(k)]
    out = []
    cum = 0.0
    i = 0
    for p in points:
        target = p * total
        while cum + weights[i] < target and i < len(weights) - 1:
            cum += weights[i]
            i += 1
        out.append(i)
    return out


def build_candidate(
    cfg,
    incumbent_run: str | Path,
    out_dir: str | Path,
    log_path: str | Path,
    *,
    steps: int = 0,
    max_examples: int = 512,
    seed: int = 0,
) -> dict:
    """Assemble the traffic-weighted set and produce a servable
    candidate run dir. Heavy imports stay inside the function — the
    router process imports this module's pure helpers for nothing and
    must not pay for JAX."""
    import numpy as np

    from deepdfa_tpu.core import config as config_mod
    from deepdfa_tpu.graphs import shard_bucket_batches
    from deepdfa_tpu.models import DeepDFA
    from deepdfa_tpu.serve.registry import (
        CKPT_DIR_BY_FAMILY,
        load_run_config,
    )
    from deepdfa_tpu.train.checkpoint import restore_candidate_params
    from deepdfa_tpu.train.loop import GraphTrainer

    incumbent_run = Path(incumbent_run)
    out_dir = Path(out_dir)
    run_cfg = load_run_config(incumbent_run)
    profile = traffic_weights_from_log(log_path)

    # the candidate trains on the same corpus the incumbent did — the
    # log contributes *weights*, not examples (raw request code is
    # sampled for shadow scoring, never persisted into training data)
    from deepdfa_tpu.cli.main import _load_graph_splits

    splits = _load_graph_splits(run_cfg)
    specs = splits["train"][: int(max_examples)]
    if not specs:
        raise ValueError("no training graphs — run `extract` first")

    model = DeepDFA.from_config(
        run_cfg.model, input_dim=run_cfg.data.feat.input_dim
    )
    trainer = GraphTrainer(model, run_cfg)

    pool_batches = list(shard_bucket_batches(
        specs, num_shards=1,
        num_graphs=max(1, run_cfg.data.batch.graphs_per_batch),
        node_budget=run_cfg.data.batch.node_budget,
        edge_budget=run_cfg.data.batch.edge_budget,
        oversized="singleton",
    ))
    state = trainer.init_state(pool_batches[0], seed=seed)
    params = restore_candidate_params(
        incumbent_run / CKPT_DIR_BY_FAMILY["deepdfa"], state.params
    )
    state = state.replace(params=params)

    # score the pool with the incumbent to place each example in a
    # traffic band, then systematic-resample the fine-tune selection
    probs = []
    for batch in pool_batches:
        p, _labels, mask, _per = trainer.eval_step(params, batch)
        flat = np.asarray(p).reshape(-1)
        for j, keep in enumerate(np.asarray(mask).reshape(-1)):
            if keep:
                probs.append(float(flat[j]))
    weights = example_weights(probs, profile["prob_bands"])
    chosen = select_weighted(weights, k=min(len(specs), int(max_examples)),
                             seed=seed)
    selection = [specs[i % len(specs)] for i in chosen] or list(specs)

    losses = []
    if steps > 0:
        train_batches = list(shard_bucket_batches(
            selection, num_shards=1,
            num_graphs=max(1, run_cfg.data.batch.graphs_per_batch),
            node_budget=run_cfg.data.batch.node_budget,
            edge_budget=run_cfg.data.batch.edge_budget,
            oversized="drop",
        ))
        for step in range(int(steps)):
            batch = train_batches[step % len(train_batches)]
            state, loss = trainer.train_step(state, batch)
            losses.append(float(loss))

    out_dir.mkdir(parents=True, exist_ok=True)
    config_mod.to_json(run_cfg, out_dir / "config.json")
    ckpts = trainer.make_checkpoints(
        out_dir / CKPT_DIR_BY_FAMILY["deepdfa"]
    )
    ckpts.save("candidate", state,
               {"val_loss": losses[-1] if losses else 0.0},
               step=int(state.step))
    return {
        "out_dir": str(out_dir), "steps": int(steps),
        "examples": len(selection), "pool": len(specs),
        "losses": losses, "traffic": profile,
    }
