"""Auto-promotion: turn shadow verdicts into (gated) rollouts.

The controller half of the flywheel decision. It never swaps a model
itself — a "promote" verdict is executed by calling the *existing*
`fleet/rollout.py:run_rollout`, so every automated promotion passes
the exact gates a human-initiated `deepdfa-tpu fleet-rollout` does:
the per-replica drift refusal, the SLO guard between swaps, rollback
on halt, and the steady-state-recompile census. A halted promotion is
recorded as both a `{"promotion": ...}` (rollout_ok=false) and a
`{"demotion": {"reason": "rollout_halted"}}` so the log tells the
whole story; a losing or drifting candidate is demoted without ever
touching live traffic.

Decisions are derived from the fleet_log itself (`decide_from_log`),
not from controller-private state: the latest `{"shadow": {"event":
"window"}}` record for the candidate carries the exact stats
`shadow.judge()` consumes, and an unresolved `shadow_regression` alert
(obs/alerts.py) vetoes promotion with a `"alert"` demotion. That makes
the decision replayable — `deepdfa-tpu flywheel --once` on a copied
log reaches the same verdict the live watcher did.
"""

from __future__ import annotations

import time
from pathlib import Path

from deepdfa_tpu.fleet import coord, rollout
from deepdfa_tpu.fleet.router import FleetLog
from deepdfa_tpu.flywheel import shadow as shadow_mod
from deepdfa_tpu.obs import metrics as obs_metrics


def tail_flywheel_records(
    log_path: str | Path,
    backend: coord.CoordinationBackend | None = None,
    max_bytes: int = 1 << 20,
) -> dict:
    """One pass over the fleet_log tail → the flywheel-relevant slice:
    shadow records (in order), promotions, demotions, and the set of
    alert rules currently firing (latest state per rule wins)."""
    backend = backend or coord.LOCAL
    records = backend.tail_records(log_path, max_bytes=max_bytes)
    out: dict = {"shadow": [], "promotions": [], "demotions": []}
    alert_state: dict[str, str] = {}
    for rec in records:
        if "shadow" in rec:
            out["shadow"].append(rec["shadow"])
        elif "promotion" in rec:
            out["promotions"].append(rec["promotion"])
        elif "demotion" in rec:
            out["demotions"].append(rec["demotion"])
        elif "alert" in rec:
            alert = rec["alert"]
            name = alert.get("rule")
            if name:
                alert_state[name] = alert.get("state") or ""
    out["firing_alerts"] = sorted(
        name for name, state in alert_state.items() if state == "firing"
    )
    return out


def decide_from_log(
    log_path: str | Path,
    candidate: str,
    *,
    min_samples: int,
    promote_margin: float,
    demote_margin: float,
    drift_bound: float,
    backend: coord.CoordinationBackend | None = None,
) -> tuple[str, str, dict]:
    """(action, reason, stats) for `candidate`, from the log alone.

    A firing `shadow_regression` alert is an unconditional veto (the
    alert engine saw a mid-ride degradation the current window may
    have already rotated past); otherwise the newest window record for
    the candidate is judged with the same bounds the live scorer used.
    """
    tail = tail_flywheel_records(log_path, backend=backend)
    if "shadow_regression" in tail["firing_alerts"]:
        return "demote", "alert", {}
    windows = [
        s for s in tail["shadow"]
        if s.get("event") == "window" and s.get("candidate") == candidate
    ]
    if not windows:
        return "hold", "insufficient_samples", {}
    stats = windows[-1]
    return (*shadow_mod.judge(
        stats,
        min_samples=min_samples,
        promote_margin=promote_margin,
        demote_margin=demote_margin,
        drift_bound=drift_bound,
    ), stats)


def run_promotion(
    cfg,
    fleet_dir: str | Path,
    candidate: str,
    log_path: str | Path,
    router_addr: tuple[str, int] | None = None,
    incumbent: str = "incumbent",
) -> dict:
    """Decide once and execute. Returns a report dict with `action`,
    `reason`, and (when the action was promote) the full run_rollout
    report under `rollout` — the caller prints it verbatim so an
    automated promotion reads exactly like a manual fleet-rollout."""
    fcfg = cfg.fleet
    backend = coord.backend_from_config(cfg)
    action, reason, stats = decide_from_log(
        log_path, candidate,
        min_samples=fcfg.flywheel_min_samples,
        promote_margin=fcfg.flywheel_promote_margin,
        demote_margin=fcfg.flywheel_demote_margin,
        drift_bound=fcfg.flywheel_drift_bound,
        backend=backend,
    )
    obs_metrics.REGISTRY.counter(f"flywheel/{action}").inc()
    report: dict = {
        "action": action, "reason": reason, "candidate": candidate,
        "stats": stats, "t_unix": round(time.time(), 3),
    }
    # the promotion controller opens its own append handle to the
    # shared fleet_log — same precedent as run_rollout, whose records
    # interleave with the router's
    log = FleetLog(log_path, backend=backend)
    try:
        if action == "promote":
            rollout_report = rollout.run_rollout(
                cfg, fleet_dir, candidate,
                router_addr=router_addr, log_path=log_path,
            )
            report["rollout"] = rollout_report
            ok = bool(rollout_report.get("ok"))
            shadow_mod.record_promotion(
                log, candidate, incumbent=incumbent, rollout_ok=ok,
                swapped=len(rollout_report.get("swapped") or ()),
                reason=reason, **_stat_fields(stats),
            )
            if not ok:
                # the PR-14 gates refused it: drift refusal, SLO guard
                # breach, or census failure — the rollback already ran
                # inside run_rollout, so the only flywheel-side duty is
                # the demotion record that ends the ride
                shadow_mod.record_demotion(
                    log, candidate, "rollout_halted",
                    halt_reason=rollout_report.get("halt_reason"),
                    incumbent=incumbent,
                )
                report["action"] = "demote"
                report["reason"] = "rollout_halted"
        elif action == "demote":
            shadow_mod.record_demotion(
                log, candidate, reason, incumbent=incumbent,
                **_stat_fields(stats),
            )
    finally:
        log.close()
    return report


def _stat_fields(stats: dict) -> dict:
    """The comparison scalars worth echoing into promotion/demotion
    records (full window stats stay on the window record)."""
    keep = ("samples", "labeled", "agreement", "prob_drift",
            "auc_candidate", "auc_incumbent")
    return {k: stats[k] for k in keep if k in stats}


def watch(
    cfg,
    fleet_dir: str | Path,
    candidate: str,
    log_path: str | Path,
    *,
    interval_s: float = 2.0,
    timeout_s: float = 300.0,
    router_addr: tuple[str, int] | None = None,
) -> dict:
    """Poll the log until the verdict stops being "hold" (or the bound
    expires — which ends the ride with an insufficient_samples/
    unlabeled demotion so a stuck candidate can't squat the shadow
    slot forever). Returns the final run_promotion report."""
    deadline = time.monotonic() + float(timeout_s)
    report: dict = {"action": "hold", "reason": "insufficient_samples"}
    while time.monotonic() < deadline:
        report = run_promotion(
            cfg, fleet_dir, candidate, log_path, router_addr=router_addr,
        )
        if report["action"] != "hold":
            return report
        time.sleep(max(0.05, float(interval_s)))
    reason = report.get("reason") or "insufficient_samples"
    if reason not in ("insufficient_samples", "unlabeled"):
        reason = "insufficient_samples"
    backend = coord.backend_from_config(cfg)
    log = FleetLog(log_path, backend=backend)
    try:
        shadow_mod.record_demotion(log, candidate, reason, timed_out=True)
    finally:
        log.close()
    report["action"] = "demote"
    report["reason"] = reason
    report["timed_out"] = True
    return report
