"""Shadow mode: mirror sampled live traffic onto a candidate model.

Three cooperating pieces, joined only by the coordination backend
(fleet/coord.py) so they work across processes and hosts exactly like
the rest of the fleet plane:

- ShadowSampler lives *inside the router process* (router_from_config
  attaches it when `fleet.flywheel` is on). After a 200 response is
  already on its way back to the client it appends a deterministic
  every-kth subsample of requests — code, the incumbent's probability,
  an optional rider label — to `shadow_samples.jsonl` under the fleet
  dir. It never blocks the reply path: one flushed append per sampled
  request, and a progress-doc backpressure check that *drops* samples
  (counted, never queued) when the scorer falls more than
  `max_inflight` behind.

- ShadowScorer runs in the flywheel controller process (`deepdfa-tpu
  flywheel`). It tails the sample stream, scores each code with the
  candidate (normally an HTTP POST to the shadow replica's /score —
  the replica whose heartbeat carries `shadow: true` so the router
  never routes live traffic to it), feeds a ShadowComparator, and
  every `window` samples lands one `{"shadow": {"event": "window",
  ...}}` record in fleet_log.

- ShadowComparator is pure state: rolling windows of (incumbent prob,
  candidate prob, label, lag) reduced to agreement / calibration-drift
  / rank-AUC stats, and `judge()` — the single decision function both
  the comparator and flywheel/promote.py apply, so the smoke, the CLI
  watcher, and the unit tests cannot disagree about what "beats the
  incumbent" means.

Record shapes are validated by fleet/router.py:validate_fleet_log and
documented in docs/flywheel.md.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path

from deepdfa_tpu.fleet import coord
from deepdfa_tpu.fleet.router import DEMOTION_REASONS, SHADOW_EVENTS
from deepdfa_tpu.obs import metrics as obs_metrics

#: sampled-request stream (sampler appends, scorer tails) — lives under
#: the fleet dir next to heartbeats/ and fleet_log.jsonl
SAMPLES_FILE = "shadow_samples.jsonl"
#: scorer -> sampler acknowledgement doc {"scored": <seq>}; the sampler
#: reads it (rate-limited) to bound how far the mirror stream can run
#: ahead of the shadow replica
PROGRESS_FILE = "shadow_progress.json"


def record_shadow(log, event: str, candidate: str, **fields) -> dict:
    """Append one `{"shadow": ...}` record; the schema gate lives here
    so every emitter (scorer, smoke, diag --smoke) fails loudly on a
    bad event instead of producing a line validate_fleet_log rejects."""
    if event not in SHADOW_EVENTS:
        raise ValueError(f"unknown shadow event {event!r} (not in "
                         f"{SHADOW_EVENTS})")
    entry = {
        "event": event, "candidate": str(candidate),
        "t_unix": round(time.time(), 3), **fields,
    }
    if log is not None:
        log.append({"shadow": entry})
    return entry


def record_promotion(log, candidate: str, **fields) -> dict:
    entry = {
        "candidate": str(candidate), "t_unix": round(time.time(), 3),
        **fields,
    }
    if log is not None:
        log.append({"promotion": entry})
    return entry


def record_demotion(log, candidate: str, reason: str, **fields) -> dict:
    if reason not in DEMOTION_REASONS:
        raise ValueError(f"unknown demotion reason {reason!r} (not in "
                         f"{DEMOTION_REASONS})")
    entry = {
        "candidate": str(candidate), "reason": reason,
        "t_unix": round(time.time(), 3), **fields,
    }
    if log is not None:
        log.append({"demotion": entry})
    return entry


def rank_auc(labels, scores) -> float | None:
    """Mann-Whitney rank AUC with tie-splitting; None unless both
    classes are present (an AUC over one class is undefined, and
    returning 0.5 there would let an all-negative window promote)."""
    pos = [s for y, s in zip(labels, scores) if y]
    neg = [s for y, s in zip(labels, scores) if not y]
    if not pos or not neg:
        return None
    wins = 0.0
    for p in pos:
        for n in neg:
            if p > n:
                wins += 1.0
            elif p == n:
                wins += 0.5
    return wins / (len(pos) * len(neg))


def judge(
    stats: dict,
    *,
    min_samples: int,
    promote_margin: float,
    demote_margin: float,
    drift_bound: float,
) -> tuple[str, str]:
    """The promotion decision, as one pure function of window stats.

    Returns (action, reason) with action in {"promote", "demote",
    "hold"}. Demote reasons come from router.DEMOTION_REASONS so the
    resulting record is schema-valid by construction. Ordering is
    deliberate: sample floor first (nothing is decidable on noise),
    then the drift gate (a candidate whose probabilities have walked
    away from the incumbent is demoted even if its AUC looks good —
    mirroring the PR-14 swap-time drift refusal, but cheaper and
    earlier), then the labeled AUC comparison, then the unlabeled
    agreement fallback. Without labels we never auto-promote: agreement
    only tells us the candidate is *the same*, not *better*.
    """
    n = int(stats.get("samples") or 0)
    if n < int(min_samples):
        return "hold", "insufficient_samples"
    drift = stats.get("prob_drift")
    if drift is not None and drift > drift_bound:
        return "demote", "drift"
    auc_c = stats.get("auc_candidate")
    auc_i = stats.get("auc_incumbent")
    if auc_c is not None and auc_i is not None:
        delta = auc_c - auc_i
        if delta >= promote_margin:
            return "promote", "auc_margin"
        if delta <= -float(demote_margin):
            return "demote", "trailing"
        return "hold", "within_margin"
    agreement = stats.get("agreement")
    if agreement is not None and agreement < 1.0 - float(demote_margin):
        # disagreeing hard with the incumbent on unlabeled traffic is
        # the unlabeled analogue of trailing — without labels the
        # incumbent is the only reference we have
        return "demote", "trailing"
    return "hold", "unlabeled"


class ShadowSampler:
    """Router-side mirror tap. Thread-safe (router handlers run on a
    ThreadingHTTPServer); every public method is wrapped in one lock,
    and the only I/O per sampled request is a single flushed append
    through the coordination backend — the same budget as the
    fleet_log request line the router already writes."""

    def __init__(
        self,
        fleet_dir: str | Path,
        sample_rate: float = 0.25,
        max_inflight: int = 64,
        backend: coord.CoordinationBackend | None = None,
        progress_refresh_s: float = 0.5,
    ):
        self.fleet_dir = Path(fleet_dir)
        self.backend = backend or coord.LOCAL
        # deterministic every-kth sampling: a period, not a coin flip,
        # so the smoke and the bench measure a reproducible stream
        rate = float(sample_rate)
        self.period = max(1, round(1.0 / rate)) if rate > 0 else 0
        self.max_inflight = int(max_inflight)
        self.progress_refresh_s = float(progress_refresh_s)
        self._lock = threading.Lock()
        self._seen = 0
        self._seq = 0
        self._scored = 0
        self._progress_read_t = 0.0
        self._handle = self.backend.open_log(self.fleet_dir / SAMPLES_FILE)
        self._m_samples = obs_metrics.REGISTRY.counter("shadow/samples")
        self._m_dropped = obs_metrics.REGISTRY.counter("shadow/dropped")

    def _inflight(self) -> int:
        """seq written minus scorer-acknowledged; the progress doc read
        is rate-limited so backpressure costs one small read per
        refresh window, not per request."""
        now = time.monotonic()
        if now - self._progress_read_t >= self.progress_refresh_s:
            self._progress_read_t = now
            try:
                text = self.backend.read_doc(self.fleet_dir / PROGRESS_FILE)
                if text:
                    self._scored = int(json.loads(text).get("scored") or 0)
            except (OSError, ValueError):
                pass
        return self._seq - self._scored

    def observe(
        self,
        request_id: str,
        payload: dict,
        prob: float | None,
        tenant: str = "default",
    ) -> bool:
        """Called by the router's POST epilogue after the 200 reply is
        already written. Returns True iff a sample was appended."""
        if self.period <= 0 or prob is None:
            return False
        code = payload.get("code") if isinstance(payload, dict) else None
        if not isinstance(code, str):
            return False
        with self._lock:
            self._seen += 1
            if self._seen % self.period != 0:
                return False
            if self._inflight() >= self.max_inflight:
                # drop, never queue: the mirror stream must not grow an
                # unbounded buffer inside the router when the shadow
                # replica is slow or dead
                self._m_dropped.inc()
                return False
            self._seq += 1
            sample = {
                "seq": self._seq, "id": str(request_id),
                "t_unix": round(time.time(), 3),
                "prob": round(float(prob), 6), "tenant": str(tenant),
                "code": code,
            }
            label = payload.get("label")
            if isinstance(label, (bool, int, float)) and float(label) in (
                0.0, 1.0,
            ):
                # labels ride the request body when the caller has
                # ground truth (the smoke does; scan pipelines can) —
                # /score ignores unknown keys so this is free
                sample["label"] = int(label)
            if not self._handle.closed:
                self._handle.write_line(json.dumps({"shadow_sample": sample}))
            self._m_samples.inc()
            return True

    def close(self) -> None:
        with self._lock:
            if not self._handle.closed:
                self._handle.close()


class ShadowComparator:
    """Pure rolling comparison of candidate vs incumbent. No I/O, no
    clock: everything observable is a function of the (p_inc, p_cand,
    label, lag) tuples added so far, which is what makes the promotion
    logic unit-testable without a fleet."""

    def __init__(self, window: int = 64):
        self.window = max(1, int(window))
        self._rows: list[tuple[float, float, int | None, float]] = []
        self.total = 0

    def add(
        self,
        p_incumbent: float,
        p_candidate: float,
        label: int | None = None,
        lag_s: float = 0.0,
    ) -> None:
        self.total += 1
        self._rows.append(
            (float(p_incumbent), float(p_candidate),
             None if label is None else int(label), float(lag_s))
        )
        if len(self._rows) > self.window:
            del self._rows[: len(self._rows) - self.window]

    def stats(self) -> dict:
        """Windowed stats in the exact key vocabulary `judge()` and the
        `{"shadow": ...}` record use (docs/flywheel.md)."""
        rows = self._rows
        n = len(rows)
        out: dict = {"samples": n, "total": self.total}
        if not n:
            return out
        agree = sum(
            1 for pi, pc, _, _ in rows if (pi >= 0.5) == (pc >= 0.5)
        )
        out["agreement"] = round(agree / n, 4)
        out["prob_drift"] = round(
            sum(abs(pi - pc) for pi, pc, _, _ in rows) / n, 4
        )
        out["lag_s"] = round(max(lag for _, _, _, lag in rows), 3)
        labeled = [(y, pi, pc) for pi, pc, y, _ in rows if y is not None]
        out["labeled"] = len(labeled)
        if labeled:
            ys = [y for y, _, _ in labeled]
            auc_i = rank_auc(ys, [pi for _, pi, _ in labeled])
            auc_c = rank_auc(ys, [pc for _, _, pc in labeled])
            if auc_i is not None:
                out["auc_incumbent"] = round(auc_i, 4)
            if auc_c is not None:
                out["auc_candidate"] = round(auc_c, 4)
        return out


class ShadowScorer:
    """Controller-side half of the ride: tail the sample stream, score
    with the candidate, compare, emit windowed records.

    `score_fn(code) -> float | None` abstracts *where* the candidate
    runs: `http_score_fn` posts to the shadow replica over the wire
    (the production shape — the candidate's compiled programs live in
    its own process, so the incumbent census can't change), while tests
    pass an in-process callable. None means the score failed; the
    sample is counted under shadow/score_errors and skipped.
    """

    def __init__(
        self,
        fleet_dir: str | Path,
        candidate: str,
        incumbent: str,
        score_fn,
        log=None,
        *,
        window: int = 64,
        min_samples: int = 50,
        promote_margin: float = 0.02,
        demote_margin: float = 0.05,
        drift_bound: float = 0.25,
        backend: coord.CoordinationBackend | None = None,
    ):
        self.fleet_dir = Path(fleet_dir)
        self.candidate = str(candidate)
        self.incumbent = str(incumbent)
        self.score_fn = score_fn
        self.log = log
        self.backend = backend or coord.LOCAL
        self.window = max(1, int(window))
        self.bounds = dict(
            min_samples=int(min_samples),
            promote_margin=float(promote_margin),
            demote_margin=float(demote_margin),
            drift_bound=float(drift_bound),
        )
        self.comparator = ShadowComparator(window=self.window)
        self.last_seq = 0
        self.windows = 0
        self.last_window_stats: dict = {}
        reg = obs_metrics.REGISTRY
        self._m_scored = reg.counter("shadow/scored")
        self._m_errors = reg.counter("shadow/score_errors")
        self._m_windows = reg.counter("shadow/windows")
        self._m_regressions = reg.counter("shadow/regressions")
        self._g_agreement = reg.gauge("shadow/agreement")
        self._g_drift = reg.gauge("shadow/prob_drift")
        self._g_lag = reg.gauge("shadow/lag_s")

    def ride_start(self, **fields) -> dict:
        return record_shadow(
            self.log, "ride_start", self.candidate,
            incumbent=self.incumbent, **fields,
        )

    def ride_end(self, **fields) -> dict:
        stats = self.comparator.stats()
        return record_shadow(
            self.log, "ride_end", self.candidate,
            incumbent=self.incumbent, windows=self.windows, **stats,
            **fields,
        )

    def _ack(self) -> None:
        self.backend.write_doc(
            self.fleet_dir / PROGRESS_FILE,
            json.dumps({"scored": self.last_seq,
                        "t_unix": round(time.time(), 3)}),
        )

    def _emit_window(self) -> dict:
        stats = self.comparator.stats()
        self.windows += 1
        self.last_window_stats = stats
        self._m_windows.inc()
        if "agreement" in stats:
            self._g_agreement.set(stats["agreement"])
        if "prob_drift" in stats:
            self._g_drift.set(stats["prob_drift"])
        if "lag_s" in stats:
            self._g_lag.set(stats["lag_s"])
        action, reason = judge(stats, **self.bounds)
        if action == "demote":
            # the alert catalog's shadow_regression rule fires off this
            # counter (obs/alerts.py) — a degrading candidate alerts
            # mid-ride, before promotion could ever trigger
            self._m_regressions.inc()
        record_shadow(
            self.log, "window", self.candidate,
            incumbent=self.incumbent, verdict=action,
            verdict_reason=reason, **stats,
        )
        return stats

    def poll(self, max_bytes: int = 1 << 20) -> int:
        """Score every unseen sample in the stream tail; returns how
        many were scored. Torn trailing lines are tolerated by
        tail_records and picked up next poll."""
        records = self.backend.tail_records(
            self.fleet_dir / SAMPLES_FILE, max_bytes=max_bytes
        )
        scored = 0
        for rec in records:
            sample = rec.get("shadow_sample")
            if not isinstance(sample, dict):
                continue
            seq = int(sample.get("seq") or 0)
            if seq <= self.last_seq:
                continue
            self.last_seq = seq
            prob = self.score_fn(sample.get("code"))
            if prob is None:
                self._m_errors.inc()
                continue
            lag = max(0.0, time.time() - float(sample.get("t_unix") or 0.0))
            self.comparator.add(
                float(sample.get("prob") or 0.0), float(prob),
                label=sample.get("label"), lag_s=lag,
            )
            self._m_scored.inc()
            scored += 1
            if self.comparator.total % self.window == 0:
                self._emit_window()
        if scored:
            self._ack()
        return scored

    def decide(self) -> tuple[str, str]:
        """Apply `judge()` to the current window — the same stats the
        last emitted record carries, so log watchers and the live
        scorer always agree."""
        return judge(self.comparator.stats(), **self.bounds)


def http_score_fn(host: str, port: int, timeout_s: float = 10.0):
    """score_fn that POSTs to the shadow replica's /score and returns
    its calibrated probability (the same field the router logs for the
    incumbent, so the comparison is like-for-like)."""
    import http.client

    def score(code):
        if not isinstance(code, str):
            return None
        try:
            conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
            try:
                conn.request(
                    "POST", "/score", json.dumps({"code": code}),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                body = json.loads(resp.read().decode() or "{}")
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            return None
        if resp.status != 200:
            return None
        prob = body.get("calibrated_prob", body.get("prob"))
        return float(prob) if prob is not None else None

    return score
