"""Data flywheel: shadow serving, log-driven retraining, auto-promotion.

The subsystem that closes ROADMAP item 4's loop (docs/flywheel.md):

- `shadow`  — the router-side sampler that mirrors a bounded stream of
  live requests to a candidate replica, plus the scorer/comparator that
  turns both models' scores into windowed `{"shadow": ...}` fleet_log
  records.
- `retrain` — replays serve/fleet logs through the tune/ladder manifest
  idiom to assemble a traffic-weighted fine-tune set and produce a
  servable candidate run dir with the existing trainers.
- `promote` — watches the shadow record and, when the candidate clears
  the configured bound, drives the *existing* `fleet-rollout` path so
  the PR-14 drift gate, SLO guard, and rollback cover automated
  promotions; losing/drifting candidates are demoted with a
  schema-valid `{"demotion": ...}` record instead of touching traffic.

Everything here is gated on `fleet.flywheel` (default off); with the
flag off no module in this package is imported on the serving path and
the default fleet path is byte-identical.
"""

from deepdfa_tpu.flywheel.shadow import (  # noqa: F401
    ShadowComparator,
    ShadowSampler,
    ShadowScorer,
    judge,
    rank_auc,
)
