"""T5-family encoder + defect-classification head (the CodeT5 path).

Re-design of the reference's CodeT5 DefectModel (CodeT5/models.py:125-192:
T5 encoder, eos-token pooling, Linear(hidden [+ graph out_dim], 2)) in the
same explicit-pytree style as models/transformer.py.

T5 architectural specifics implemented here (and verified against HF
FlaxT5EncoderModel in tests/test_t5.py):
- RMS layer norm (no mean subtraction, no bias), pre-LN residual blocks,
- bias-free linear projections, NO 1/sqrt(d) attention scaling,
- bucketed relative position bias (bidirectional) computed once in the
  first block and shared by all layers,
- final RMS norm after the last block.

Tensor parallelism: heads / FFN shard over `tp` exactly like the RoBERTa
encoder, with the relative-bias head axis sharded too; the Megatron region
ops provide the gradient bookkeeping.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.core.config import PAD_ID_BY_FAMILY
from deepdfa_tpu.parallel.megatron import region_end, region_start


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32100
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    head_dim: int = 64
    ffn_size: int = 3072
    rel_buckets: int = 32
    rel_max_distance: int = 128
    layer_norm_eps: float = 1e-6
    dropout_rate: float = 0.1
    eos_token_id: int = 2
    # the shared collater/encoder pad convention (core/config.py) — the
    # attention mask derives from `input_ids != pad_token_id`
    pad_token_id: int = PAD_ID_BY_FAMILY["t5"]
    #: unlike the RoBERTa family there is NO hard positional capacity —
    #: the relative-position bias log-buckets and clamps distances, so
    #: any T is numerically safe. This optional bound exists so a
    #: misconfigured bucket edge (data.seq_buckets) fails loudly against
    #: the recipe's intended max_length instead of silently training on
    #: sequences the recipe never meant to cover. None = unbounded.
    max_sequence_length: int | None = None
    dtype: str = "float32"
    remat: bool = True
    #: sequence-parallel attention scheme under sp>1 meshes: "ring"
    #: (k/v rotation, per-step relative-bias blocks) or "ulysses"
    #: (all-to-all head sharding, head-sliced global bias)
    sp_variant: str = "ring"
    #: encoder local-attention lowering ("auto"/"xla"/"flash"): same
    #: semantics as TransformerConfig.attn_impl; the flash kernel takes
    #: the relative-position bias as an additive operand (dbias via its
    #: batch-accumulating backward kernel)
    attn_impl: str = "auto"
    #: remat granularity when remat=True — "full" | "attn_saved", same
    #: semantics as TransformerConfig.remat_policy (the flash kernel's
    #: named outputs make attn_saved skip its backward re-run)
    remat_policy: str = "full"

    @classmethod
    def tiny(cls, **kw) -> "T5Config":
        base = dict(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            head_dim=16, ffn_size=128,
        )
        base.update(kw)
        return cls(**base)


def init_params(cfg: T5Config, key: jax.Array) -> dict:
    k = iter(jax.random.split(key, 12))
    D, H, Dh, F, L = (
        cfg.hidden_size, cfg.num_heads, cfg.head_dim, cfg.ffn_size,
        cfg.num_layers,
    )

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape) * scale).astype(jnp.float32)

    return {
        "word": norm(next(k), (cfg.vocab_size, D), 1.0),
        "rel_bias": norm(next(k), (cfg.rel_buckets, H), 0.1),
        "layers": {
            "wq": norm(next(k), (L, D, H, Dh), (D * Dh) ** -0.5),
            "wk": norm(next(k), (L, D, H, Dh), D**-0.5),
            "wv": norm(next(k), (L, D, H, Dh), D**-0.5),
            "wo": norm(next(k), (L, H, Dh, D), (H * Dh) ** -0.5),
            "ln1": jnp.ones((L, D)),
            "wi": norm(next(k), (L, D, F), D**-0.5),
            "wo_ffn": norm(next(k), (L, F, D), F**-0.5),
            "ln2": jnp.ones((L, D)),
        },
        "final_ln": jnp.ones((D,)),
    }


def _rms_norm(x, scale, eps):
    dt = x.dtype
    x = x.astype(jnp.float32)
    var = (x * x).mean(-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(dt)


def relative_position_buckets(
    q_pos: jax.Array,
    k_pos: jax.Array,
    num_buckets: int,
    max_distance: int,
    bidirectional: bool = True,
) -> jax.Array:
    """T5 relative-position bucketing ([Tq, Tk] int32).

    bidirectional=True is the encoder scheme (half the buckets for each
    direction); bidirectional=False is the decoder scheme (all buckets
    cover the non-positive "attend to the past" offsets).
    """
    rel = k_pos[None, :] - q_pos[:, None]
    if bidirectional:
        nb = num_buckets // 2
        out = jnp.where(rel > 0, nb, 0)
        n = jnp.abs(rel)
    else:
        nb = num_buckets
        out = jnp.zeros_like(rel)
        n = jnp.maximum(-rel, 0)
    max_exact = nb // 2
    is_small = n < max_exact
    log_ratio = jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
    log_denom = np.log(max_distance / max_exact)
    large = max_exact + (log_ratio / log_denom * (nb - max_exact)).astype(jnp.int32)
    large = jnp.minimum(large, nb - 1)
    return out + jnp.where(is_small, n, large)


def _attention(q, k, v, mask, bias):
    """T5 attention: NO 1/sqrt(d) scaling; additive position bias.

    Deliberate divergence from HF T5 (modeling_t5.py applies
    nn.Dropout(dropout_rate) to the softmax probs in training): no
    attention-probs dropout here — regularization lives on the residual
    branches (encoder_layer/decoder_layer `_dropout` calls).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) + bias[None]
    neg = jnp.finfo(s.dtype).min
    s = jnp.where(mask[:, None, None, :], s, neg)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def encoder_rel_bias(
    cfg: T5Config,
    rel_bias_param: jax.Array,
    T: int,
    dt,
    sp_axis: str | None = None,
):
    """(bias, bias_fn) for the encoder's shared relative-position bias.

    Without sp: one [H, T, T] bias from global positions, bias_fn None.
    With sp (T = the LOCAL block length), the form follows
    cfg.sp_variant:
    - "ring": per-rotation-step bias blocks precomputed from global
      positions ([n_sp, H, T, T]) so ring attention's scan only indexes,
      never re-gathers — returned via bias_fn;
    - "ulysses": after the all-to-all each device attends the FULL
      sequence with a head slice, so the bias is the [H/n_sp, S, S]
      head-slice of the global bias (S = n_sp * T; the full [H, S, S]
      is built then sliced — same O(S^2) footprint class as the
      attention scores themselves) — returned via bias.
    """
    if sp_axis is None:
        pos = jnp.arange(T)
        buckets = relative_position_buckets(
            pos, pos, cfg.rel_buckets, cfg.rel_max_distance
        )
        # [Tq, Tk, H] -> [H, Tq, Tk]; head axis shards over tp with layers
        return rel_bias_param[buckets].astype(dt).transpose(2, 0, 1), None

    sp_idx = jax.lax.axis_index(sp_axis)
    n_sp = jax.lax.psum(1, sp_axis)  # static inside shard_map

    if cfg.sp_variant == "ulysses":
        h = rel_bias_param.shape[1]
        if h % n_sp:
            raise ValueError(
                f"{h} rel-bias heads not divisible by sp={n_sp} "
                "(ulysses shards heads; use sp_variant='ring')"
            )
        h_local = h // n_sp
        # slice the TINY param table's head axis first, so only the
        # [S, S, H/P] local bias ever materializes (not the full
        # [H, S, S] — 1/P the footprint on the memory-bound path)
        param_local = jax.lax.dynamic_slice_in_dim(
            rel_bias_param, sp_idx * h_local, h_local, axis=1
        )
        S = n_sp * T
        pos = jnp.arange(S)
        buckets = relative_position_buckets(
            pos, pos, cfg.rel_buckets, cfg.rel_max_distance
        )
        return param_local[buckets].astype(dt).transpose(2, 0, 1), None

    q_pos = sp_idx * T + jnp.arange(T)

    def _step_bias(step):
        # the block arriving at rotation `step` originated on shard
        # (sp_idx - step) mod n_sp; its global k positions follow
        origin = jnp.mod(sp_idx - step, n_sp)
        k_pos = origin * T + jnp.arange(T)
        b = relative_position_buckets(
            q_pos, k_pos, cfg.rel_buckets, cfg.rel_max_distance
        )
        return rel_bias_param[b].astype(dt).transpose(2, 0, 1)

    all_bias = jnp.stack([_step_bias(s) for s in range(n_sp)])

    def bias_fn(step):
        return all_bias[step]

    return None, bias_fn


def encoder_layer(
    cfg: T5Config,
    lp: dict,
    x: jax.Array,
    attn_mask: jax.Array,
    key,
    bias,
    bias_fn,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
) -> jax.Array:
    """One pre-RMSNorm T5 encoder layer (HF t5 semantics); shared by the
    stacked-scan encoder below and the GPipe pipeline
    (parallel/pipeline.py t5_pipeline_stage_forward)."""
    dt = x.dtype
    k1 = k2 = None
    if key is not None:
        k1, k2 = jax.random.split(key)
    h_in = _rms_norm(x, lp["ln1"], cfg.layer_norm_eps)
    h_in = region_start(h_in, tp_axis) if tp_axis is not None else h_in
    q = jnp.einsum("btd,dhk->bhtk", h_in, lp["wq"].astype(dt))
    k = jnp.einsum("btd,dhk->bhtk", h_in, lp["wk"].astype(dt))
    v = jnp.einsum("btd,dhk->bhtk", h_in, lp["wv"].astype(dt))
    if sp_axis is not None and cfg.sp_variant == "ulysses":
        from deepdfa_tpu.models.transformer import _flash_interpret
        from deepdfa_tpu.parallel.ulysses import ulysses_attention

        ctx = ulysses_attention(
            q, k, v, attn_mask, axis_name=sp_axis, scale=1.0, bias=bias,
            attn_impl=getattr(cfg, "attn_impl", "auto"),
            flash_interpret=_flash_interpret(),
        )
    elif sp_axis is not None:
        from deepdfa_tpu.parallel.ring_attention import ring_attention

        ctx = ring_attention(
            q, k, v, attn_mask, axis_name=sp_axis, scale=1.0,
            bias_fn=bias_fn,
        )
    else:
        from deepdfa_tpu.models.transformer import (
            _flash_interpret,
            _resolve_attn_impl,
        )

        if _resolve_attn_impl(cfg, q.shape[2], cfg.head_dim,
                              biased=True) == "flash":
            from deepdfa_tpu.nn.flash_attention import flash_attention

            # T5 semantics: no 1/sqrt(d) scaling, additive position
            # bias. Deliberate divergence from HF T5: HF applies
            # dropout(p=dropout_rate) to the attention probs in
            # training; this implementation regularizes only the
            # residual branches below (both XLA and flash paths agree,
            # so flash-vs-xla A/Bs stay apples-to-apples).
            ctx = flash_attention(
                q, k, v, attn_mask, scale=1.0, bias=bias,
                interpret="tpu" if _flash_interpret() else False,
            )
        else:
            ctx = _attention(q, k, v, attn_mask, bias)
    from jax.ad_checkpoint import checkpoint_name

    ctx = checkpoint_name(ctx, "attn_ctx")
    out = jnp.einsum("bhtk,hkd->btd", ctx, lp["wo"].astype(dt))
    if tp_axis is not None:
        out = region_end(out, tp_axis)
    from deepdfa_tpu.models.transformer import _dropout

    x = x + _dropout(out, cfg.dropout_rate, k1)

    h2 = _rms_norm(x, lp["ln2"], cfg.layer_norm_eps)
    h2 = region_start(h2, tp_axis) if tp_axis is not None else h2
    h2 = jax.nn.relu(jnp.einsum("btd,df->btf", h2, lp["wi"].astype(dt)))
    h2 = jnp.einsum("btf,fd->btd", h2, lp["wo_ffn"].astype(dt))
    if tp_axis is not None:
        h2 = region_end(h2, tp_axis)
    return x + _dropout(h2, cfg.dropout_rate, k2)


def encode(
    cfg: T5Config,
    params: dict,
    input_ids: jax.Array,
    attn_mask: jax.Array | None = None,
    dropout_key: jax.Array | None = None,
    tp_axis: str | None = None,
    inputs_embeds: jax.Array | None = None,
    sp_axis: str | None = None,
) -> jax.Array:
    """[B, T] -> [B, T, D] final hidden states (post final-RMSNorm).

    inputs_embeds replaces the word-embedding gather (HF convention) —
    the hook the gradient-attribution localizers differentiate through.

    sp_axis: sequence parallelism — T is the LOCAL block length; the
    scheme follows cfg.sp_variant: "ring" rotates k/v with
    per-rotation-step relative-position bias blocks computed from global
    positions (the "per-shard relative-bias blocks" the roberta path
    gets for free from absolute positions), "ulysses" all-to-alls into
    full-sequence attention over a head slice with the head-sliced
    global bias (encoder_rel_bias)."""
    from deepdfa_tpu.models.transformer import _dropout

    # capacity guard (see T5Config.max_sequence_length): local T under
    # sp understates the global length, so this catches per-shard edges
    # only — the combined CLI sets the bound to its max_length
    if (
        cfg.max_sequence_length is not None
        and input_ids.shape[1] > cfg.max_sequence_length
    ):
        raise ValueError(
            f"sequence length {input_ids.shape[1]} exceeds "
            f"max_sequence_length={cfg.max_sequence_length} — lower the "
            f"bucket edge (data.seq_buckets) / max_length or raise the "
            f"configured bound"
        )
    if attn_mask is None:
        attn_mask = input_ids != cfg.pad_token_id
    dt = jnp.dtype(cfg.dtype)
    if inputs_embeds is None:
        x = params["word"][input_ids].astype(dt)
    else:
        x = inputs_embeds.astype(dt)
    k_embed = k_layers = k_final = None
    if dropout_key is not None and cfg.dropout_rate > 0.0:
        k_embed, k_layers, k_final = jax.random.split(dropout_key, 3)
    x = _dropout(x, cfg.dropout_rate, k_embed)

    bias, bias_fn = encoder_rel_bias(
        cfg, params["rel_bias"], input_ids.shape[1], dt, sp_axis
    )

    def layer(x, inputs):
        lp, key = inputs
        return encoder_layer(
            cfg, lp, x, attn_mask, key, bias, bias_fn,
            tp_axis=tp_axis, sp_axis=sp_axis,
        )

    from deepdfa_tpu.models.transformer import remat_wrap

    fn = remat_wrap(cfg, layer)
    n_layers = params["layers"]["wq"].shape[0]
    keys = (
        jax.random.split(k_layers, n_layers) if k_layers is not None else None
    )
    if keys is None:
        x, _ = jax.lax.scan(
            lambda x, lp: (fn(x, (lp, None)), None), x, params["layers"]
        )
    else:
        x, _ = jax.lax.scan(
            lambda x, inp: (fn(x, inp), None), x, (params["layers"], keys)
        )
    x = _rms_norm(x, params["final_ln"], cfg.layer_norm_eps)
    return _dropout(x, cfg.dropout_rate, k_final)


def eos_pool(cfg: T5Config, hidden: jax.Array, input_ids: jax.Array) -> jax.Array:
    """Hidden state at the LAST eos token per row (reference DefectModel
    get_t5_vec, CodeT5/models.py:138-152)."""
    is_eos = input_ids == cfg.eos_token_id
    T = input_ids.shape[1]
    # index of last eos (rows without eos fall back to the last position)
    idx = jnp.where(
        is_eos.any(axis=1),
        T - 1 - jnp.argmax(is_eos[:, ::-1], axis=1),
        T - 1,
    )
    return jnp.take_along_axis(hidden, idx[:, None, None], axis=1)[:, 0, :]


def eos_pool_sp(
    cfg: T5Config, hidden: jax.Array, input_ids: jax.Array, sp_axis: str
) -> jax.Array:
    """eos_pool when the sequence is sharded over `sp_axis`: the last eos
    may live on any shard, so shards agree on its global position via
    pmax, the owner contributes the vector, and a psum-forward /
    identity-backward broadcast (region_end, cf. models/combined.py CLS
    pooling) replicates it without double-counting gradients."""
    from deepdfa_tpu.parallel.megatron import region_end

    T = input_ids.shape[1]
    idx = jax.lax.axis_index(sp_axis)
    n_sp = jax.lax.psum(1, sp_axis)
    is_eos = input_ids == cfg.eos_token_id
    local_last = T - 1 - jnp.argmax(is_eos[:, ::-1], axis=1)
    local_global = jnp.where(is_eos.any(axis=1), idx * T + local_last, -1)
    global_pos = jax.lax.pmax(local_global, sp_axis)
    global_pos = jnp.where(global_pos < 0, n_sp * T - 1, global_pos)
    owner = (global_pos // T) == idx
    local_off = jnp.clip(global_pos - idx * T, 0, T - 1)
    vec = jnp.take_along_axis(hidden, local_off[:, None, None], axis=1)[:, 0]
    vec = jnp.where(owner[:, None], vec, jnp.zeros_like(vec))
    return region_end(vec, sp_axis)


def tp_layer_specs():
    """Megatron PartitionSpecs for the stacked T5 layer params (heads and
    FFN hidden shard over "tp"; norms replicated)."""
    from jax.sharding import PartitionSpec as P

    return {
        "wq": P(None, None, "tp", None),
        "wk": P(None, None, "tp", None),
        "wv": P(None, None, "tp", None),
        "wo": P(None, "tp", None, None),
        "ln1": P(None, None),
        "wi": P(None, None, "tp"),
        "wo_ffn": P(None, "tp", None),
        "ln2": P(None, None),
    }


# ---------------------------------------------------------------------------
# HF weight import


def params_from_hf_torch(cfg: T5Config, state_dict) -> dict:
    """Convert a HF torch T5EncoderModel/T5Model state_dict."""

    def get(name):
        for prefix in ("", "encoder.", "transformer."):
            k = prefix + name
            if k in state_dict:
                return np.asarray(state_dict[k].detach().cpu().numpy())
        raise KeyError(name)

    D, H, Dh, L = cfg.hidden_size, cfg.num_heads, cfg.head_dim, cfg.num_layers

    def blk(i, name):
        return get(f"block.{i}.layer.{name}")

    def stack(fn):
        return np.stack([fn(i) for i in range(L)])

    try:
        word = get("shared.weight")
    except KeyError:
        word = get("embed_tokens.weight")
    params = {
        "word": word,
        "rel_bias": get(
            "block.0.layer.0.SelfAttention.relative_attention_bias.weight"
        ),
        "layers": {
            "wq": stack(lambda i: blk(i, "0.SelfAttention.q.weight").T.reshape(D, H, Dh)),
            "wk": stack(lambda i: blk(i, "0.SelfAttention.k.weight").T.reshape(D, H, Dh)),
            "wv": stack(lambda i: blk(i, "0.SelfAttention.v.weight").T.reshape(D, H, Dh)),
            "wo": stack(lambda i: blk(i, "0.SelfAttention.o.weight").T.reshape(H, Dh, D)),
            "ln1": stack(lambda i: blk(i, "0.layer_norm.weight")),
            "wi": stack(lambda i: blk(i, "1.DenseReluDense.wi.weight").T),
            "wo_ffn": stack(lambda i: blk(i, "1.DenseReluDense.wo.weight").T),
            "ln2": stack(lambda i: blk(i, "1.layer_norm.weight")),
        },
        "final_ln": get("final_layer_norm.weight"),
    }
    return jax.tree.map(lambda a: jnp.asarray(a, jnp.float32), params)


# ---------------------------------------------------------------------------
# defect classifier


@dataclasses.dataclass(frozen=True)
class DefectConfig:
    encoder: T5Config
    graph_hidden_dim: int = 32
    graph_input_dim: int = 1002
    num_classes: int = 2
    use_graph: bool = True

    @property
    def graph_out_dim(self) -> int:
        return 8 * self.graph_hidden_dim


def init_defect_params(cfg: DefectConfig, key: jax.Array) -> dict:
    from deepdfa_tpu.models.combined import make_graph_encoder_for

    k_enc, k_graph, k_head = jax.random.split(key, 3)
    D = cfg.encoder.hidden_size
    in_dim = D + (cfg.graph_out_dim if cfg.use_graph else 0)
    params = {
        "encoder": init_params(cfg.encoder, k_enc),
        "head": {
            "w": jax.random.normal(k_head, (in_dim, cfg.num_classes)) * 0.02,
            "b": jnp.zeros((cfg.num_classes,)),
        },
    }
    if cfg.use_graph:
        graph_enc, dummy = make_graph_encoder_for(
            cfg.graph_input_dim, cfg.graph_hidden_dim
        )
        params["graph"] = graph_enc.init(k_graph, dummy)
    return params


def defect_forward(
    cfg: DefectConfig,
    params: dict,
    input_ids: jax.Array,
    graph_batch=None,
    has_graph: jax.Array | None = None,
    dropout_key: jax.Array | None = None,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
    inputs_embeds: jax.Array | None = None,
    pp_axis: str | None = None,
    pp_stages: int = 1,
    pp_microbatches: int = 4,
) -> jax.Array:
    """With `pp_axis` set (inside shard_map, encoder layers stage-sharded
    over that axis) the encoder runs the GPipe microbatch schedule with a
    region_end broadcast (the trainer computes a loss copy per stage;
    parallel/pipeline.py docstring); composes with sp_axis (local
    sequence chunks, ring attention inside the stage body)."""
    from deepdfa_tpu.models.combined import make_graph_encoder_for

    if pp_axis is not None:
        if inputs_embeds is not None:
            raise ValueError(
                "inputs_embeds (attribution hook) is a single-device "
                "contract; the pipeline path embeds internally"
            )
        from deepdfa_tpu.parallel.pipeline import t5_pipeline_stage_forward

        enc = params["encoder"]
        hidden = t5_pipeline_stage_forward(
            cfg.encoder,
            enc["layers"],
            {k: v for k, v in enc.items() if k != "layers"},
            input_ids,
            input_ids != cfg.encoder.pad_token_id,
            dropout_key,
            pp_microbatches,
            pp_stages,
            pp_axis,
            broadcast="region_end",
            tp_axis=tp_axis,
            sp_axis=sp_axis,
        )
    else:
        hidden = encode(
            cfg.encoder, params["encoder"], input_ids,
            dropout_key=dropout_key, tp_axis=tp_axis, sp_axis=sp_axis,
            inputs_embeds=inputs_embeds,
        )
    if sp_axis is not None:
        vec = eos_pool_sp(cfg.encoder, hidden, input_ids, sp_axis)
    else:
        vec = eos_pool(cfg.encoder, hidden, input_ids)
    if cfg.use_graph:
        if graph_batch is None:
            raise ValueError("use_graph=True requires a graph_batch")
        graph_enc, _ = make_graph_encoder_for(
            cfg.graph_input_dim, cfg.graph_hidden_dim
        )
        gvec = graph_enc.apply(params["graph"], graph_batch)
        if has_graph is not None:
            gvec = gvec * has_graph[:, None].astype(gvec.dtype)
        vec = jnp.concatenate([vec, gvec.astype(vec.dtype)], axis=-1)
    return vec @ params["head"]["w"] + params["head"]["b"]
