"""The DeepDFA model: abstract-dataflow GGNN graph classifier.

TPU-native re-design of the reference FlowGNNGGNNModule
(DDFA/code_gnn/models/flow_gnn/ggnn.py:22-109):

  node idx --4x Embed--> feat_embed (4*H)
           --GatedGraphConv n_steps--> ggnn_out (4*H)
  concat [ggnn_out, feat_embed] (8*H)
  label_style == "graph": GlobalAttentionPooling -> [G, 8*H]
  encoder_mode: return pooled embedding (out_dim = 8*H = 256 at H=32)
  else: OutputHead -> logits

With the reference flagship config (hidden_dim 32, concat_all_absdf=True,
n_steps 5, input_dim 1002) parameter count is ~25k-class, all
embedding-gather + small matmul work.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from deepdfa_tpu.core.config import ModelConfig
from deepdfa_tpu.graphs.batch import GraphBatch
from deepdfa_tpu.nn import (
    AbstractDataflowEmbedding,
    GatedGraphConv,
    GlobalAttentionPooling,
    OutputHead,
)


class DeepDFA(nn.Module):
    input_dim: int  # vocab size per subkey table (limit_all + 2)
    hidden_dim: int = 32
    n_steps: int = 5
    n_etypes: int = 1
    scan_steps: bool = False
    num_output_layers: int = 3
    concat_all_absdf: bool = True
    # graph | node | dataflow_solution_in | dataflow_solution_out
    # (the dataflow styles supervise per-node reaching-definitions
    # bitvectors, reference base_module.py:83-95)
    label_style: str = "graph"
    encoder_mode: bool = False
    param_dtype: jnp.dtype = jnp.float32
    #: mesh axis for edge-sharded message passing (parallel/graph_shard.py)
    edge_axis: str | None = None
    #: embed the family-invariant structural channels appended after the
    #: 4 subkey columns (frontend/structfeat.py; VERDICT r4 #3)
    struct_feats: bool = False
    #: Pallas-fused GGNN step (nn/ggnn_kernel.py, docs/ggnn_kernel.md);
    #: wired through GatedGraphConv so train, serve scoring, and the
    #: localization/scan paths all switch at the one call site
    ggnn_kernel: bool = False
    ggnn_kernel_scatter: str = "auto"
    ggnn_kernel_accum: str = "fp32"
    ggnn_kernel_unroll: str = "per_step"
    #: tuned block/tile sizes (deepdfa_tpu/tune/, docs/tuning.md);
    #: 0 = the hand-picked defaults in nn/ggnn_kernel.py:block_sizes
    ggnn_kernel_block_nodes: int = 0
    ggnn_kernel_block_edges: int = 0

    @classmethod
    def from_config(cls, cfg: ModelConfig, input_dim: int, **overrides) -> "DeepDFA":
        kw = dict(
            input_dim=input_dim,
            hidden_dim=cfg.hidden_dim,
            n_steps=cfg.n_steps,
            n_etypes=cfg.n_etypes,
            scan_steps=cfg.scan_steps,
            num_output_layers=cfg.num_output_layers,
            concat_all_absdf=cfg.concat_all_absdf,
            label_style=cfg.label_style,
            encoder_mode=cfg.encoder_mode,
            struct_feats=getattr(cfg, "struct_feats", False),
            ggnn_kernel=getattr(cfg, "ggnn_kernel", False),
            ggnn_kernel_scatter=getattr(cfg, "ggnn_kernel_scatter", "auto"),
            ggnn_kernel_accum=getattr(cfg, "ggnn_kernel_accum", "fp32"),
            ggnn_kernel_unroll=getattr(
                cfg, "ggnn_kernel_unroll", "per_step"
            ),
            ggnn_kernel_block_nodes=getattr(
                cfg, "ggnn_kernel_block_nodes", 0
            ),
            ggnn_kernel_block_edges=getattr(
                cfg, "ggnn_kernel_block_edges", 0
            ),
            param_dtype=jnp.dtype(cfg.param_dtype),
        )
        kw.update(overrides)
        return cls(**kw)

    @property
    def out_dim(self) -> int:
        """Width of the encoder embedding (reference ggnn.py:62-64)."""
        mult = 4 if self.concat_all_absdf else 1
        if self.struct_feats:
            from deepdfa_tpu.frontend.structfeat import STRUCT_VOCAB

            mult += len(STRUCT_VOCAB)
        return 2 * self.hidden_dim * mult

    @nn.compact
    def __call__(self, batch: GraphBatch) -> jax.Array:
        struct_vocab: tuple[int, ...] = ()
        if self.struct_feats:
            from deepdfa_tpu.frontend.structfeat import STRUCT_VOCAB

            struct_vocab = STRUCT_VOCAB
        embed = AbstractDataflowEmbedding(
            input_dim=self.input_dim,
            embedding_dim=self.hidden_dim,
            concat_all=self.concat_all_absdf,
            param_dtype=self.param_dtype,
            struct_vocab=struct_vocab,
            name="embedding",
        )
        feat_embed = embed(batch.node_feats)

        width = feat_embed.shape[-1]
        ggnn_out = GatedGraphConv(
            out_features=width,
            n_steps=self.n_steps,
            n_etypes=self.n_etypes,
            scan_steps=self.scan_steps,
            param_dtype=self.param_dtype,
            axis_name=self.edge_axis,
            use_kernel=self.ggnn_kernel,
            kernel_scatter=self.ggnn_kernel_scatter,
            kernel_accum=self.ggnn_kernel_accum,
            kernel_unroll=self.ggnn_kernel_unroll,
            kernel_block_nodes=self.ggnn_kernel_block_nodes,
            kernel_block_edges=self.ggnn_kernel_block_edges,
            name="ggnn",
        )(batch, feat_embed)

        out = jnp.concatenate([ggnn_out, feat_embed], axis=-1)

        if self.label_style.startswith("dataflow_solution"):
            # bitvector supervision: the head sees the GGNN features plus
            # the gen/kill problem inputs and a differentiable n_steps
            # reaching-definitions propagation (nn/bitprop.py) with a
            # learned kill gate — the network only has to learn residual
            # corrections to an almost-exact prior
            from deepdfa_tpu.nn.bitprop import BitvectorPropagation

            if batch.node_gen is None:
                raise ValueError(
                    f"label_style={self.label_style} needs bit labels; "
                    "extract the corpus with max_defs set"
                )
            # reaching definitions is a CFG fixpoint: on typed graphs the
            # propagation rides only the type-0 (cfg) edges
            edge_mask = batch.edge_mask
            if batch.edge_type is not None:
                edge_mask = edge_mask & (batch.edge_type == 0)
            bp_in, bp_out = BitvectorPropagation(
                n_steps=self.n_steps,
                union_type="relu",
                learned_gate=True,
                axis_name=self.edge_axis,
                name="bitprop",
            )(
                batch.node_gen,
                batch.node_kill,
                batch.edge_src,
                batch.edge_dst,
                edge_mask,
                node_feats=feat_embed,
            )
            out = jnp.concatenate(
                [out, batch.node_gen, batch.node_kill, bp_in, bp_out],
                axis=-1,
            )
            if self.encoder_mode:
                return out
            return OutputHead(
                num_layers=self.num_output_layers,
                out_features=batch.node_gen.shape[-1],
                param_dtype=self.param_dtype,
                name="head",
            )(out)

        if self.label_style == "graph":
            out = GlobalAttentionPooling(
                param_dtype=self.param_dtype, name="pooling"
            )(batch, out)

        if self.encoder_mode:
            return out  # [G, out_dim] graph embeddings (or [N, out_dim])

        logits = OutputHead(
            num_layers=self.num_output_layers,
            param_dtype=self.param_dtype,
            name="head",
        )(out)
        return logits[..., 0]
