"""T5 decoder stack + seq2seq generation (the CodeT5 run_gen path).

Role parity with the reference's generation models
(CodeT5/models.py:build_or_load_gen_model — T5ForConditionalGeneration for
model_type t5/codet5 — and the Seq2Seq/Beam classes, CodeT5/models.py:195-360)
used by run_gen.py / run_multi_gen.py, re-designed TPU-first:

- The decoder is the same explicit-pytree, scan-over-layers style as the
  encoder in models/t5.py: RMS norms, bias-free projections, no 1/sqrt(d)
  attention scaling, unidirectional relative-position bias shared across
  layers, cross-attention without position bias, LM head tied to the
  shared embedding with the d_model**-0.5 rescale (HF tie semantics).
- Teacher forcing shifts targets right with the pad id as the decoder
  start token (HF T5 _shift_right); the loss masks pad positions (the
  reference feeds unmasked labels to HF, which also scores pads — we mask
  them, which only removes the degenerate predict-pad term).
- Decoding is jit-compiled beam search with a static-shape KV cache under
  `lax.while_loop` (exits early when every beam is finished — the
  compiler-friendly analog of HF generate(num_beams, early_stopping)).
  The reference's Python-loop Beam class (models.py:300-360) keeps
  dynamic hypothesis lists; on TPU we keep [B, K, T] tensors and freeze
  finished beams on the pad token instead. Final ranking applies a
  length penalty (HF GenerationConfig.length_penalty, default 1.0).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models.t5 import (
    T5Config,
    _rms_norm,
    encode,
    init_params,
    relative_position_buckets,
)


@dataclasses.dataclass(frozen=True)
class GenConfig:
    encoder: T5Config
    num_decoder_layers: int | None = None  # default: same as encoder
    max_target_length: int = 128
    beam_size: int = 5
    length_penalty: float = 1.0

    @property
    def n_dec_layers(self) -> int:
        if self.num_decoder_layers is None:
            return self.encoder.num_layers
        return self.num_decoder_layers


# ---------------------------------------------------------------------------
# params


def init_gen_params(cfg: GenConfig, key: jax.Array) -> dict:
    """{"encoder": ..., "decoder": ...}; the LM head is the tied shared
    embedding (params["encoder"]["word"])."""
    ecfg = cfg.encoder
    k_enc, k_dec = jax.random.split(key)
    k = iter(jax.random.split(k_dec, 16))
    D, H, Dh, F, L = (
        ecfg.hidden_size, ecfg.num_heads, ecfg.head_dim, ecfg.ffn_size,
        cfg.n_dec_layers,
    )

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape) * scale).astype(jnp.float32)

    return {
        "encoder": init_params(ecfg, k_enc),
        "decoder": {
            "rel_bias": norm(next(k), (ecfg.rel_buckets, H), 0.1),
            "layers": {
                "wq": norm(next(k), (L, D, H, Dh), (D * Dh) ** -0.5),
                "wk": norm(next(k), (L, D, H, Dh), D**-0.5),
                "wv": norm(next(k), (L, D, H, Dh), D**-0.5),
                "wo": norm(next(k), (L, H, Dh, D), (H * Dh) ** -0.5),
                "ln1": jnp.ones((L, D)),
                "cq": norm(next(k), (L, D, H, Dh), (D * Dh) ** -0.5),
                "ck": norm(next(k), (L, D, H, Dh), D**-0.5),
                "cv": norm(next(k), (L, D, H, Dh), D**-0.5),
                "co": norm(next(k), (L, H, Dh, D), (H * Dh) ** -0.5),
                "lnc": jnp.ones((L, D)),
                "wi": norm(next(k), (L, D, F), D**-0.5),
                "wo_ffn": norm(next(k), (L, F, D), F**-0.5),
                "ln2": jnp.ones((L, D)),
            },
            "final_ln": jnp.ones((D,)),
        },
    }


def gen_params_from_hf_torch(cfg: GenConfig, state_dict) -> dict:
    """Convert a HF torch T5ForConditionalGeneration state_dict."""
    from deepdfa_tpu.models.t5 import params_from_hf_torch

    ecfg = cfg.encoder

    def get(name):
        return np.asarray(state_dict[name].detach().cpu().numpy())

    D, H, Dh, L = ecfg.hidden_size, ecfg.num_heads, ecfg.head_dim, cfg.n_dec_layers

    def blk(i, name):
        return get(f"decoder.block.{i}.layer.{name}")

    def stack(fn):
        return np.stack([fn(i) for i in range(L)])

    enc_sd = {
        k[len("encoder."):]: v
        for k, v in state_dict.items()
        if k.startswith("encoder.")
    }
    enc_sd["shared.weight"] = state_dict["shared.weight"]
    decoder: dict = {
        "rel_bias": get(
            "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
        ),
        "layers": {
            "wq": stack(lambda i: blk(i, "0.SelfAttention.q.weight").T.reshape(D, H, Dh)),
            "wk": stack(lambda i: blk(i, "0.SelfAttention.k.weight").T.reshape(D, H, Dh)),
            "wv": stack(lambda i: blk(i, "0.SelfAttention.v.weight").T.reshape(D, H, Dh)),
            "wo": stack(lambda i: blk(i, "0.SelfAttention.o.weight").T.reshape(H, Dh, D)),
            "ln1": stack(lambda i: blk(i, "0.layer_norm.weight")),
            "cq": stack(lambda i: blk(i, "1.EncDecAttention.q.weight").T.reshape(D, H, Dh)),
            "ck": stack(lambda i: blk(i, "1.EncDecAttention.k.weight").T.reshape(D, H, Dh)),
            "cv": stack(lambda i: blk(i, "1.EncDecAttention.v.weight").T.reshape(D, H, Dh)),
            "co": stack(lambda i: blk(i, "1.EncDecAttention.o.weight").T.reshape(H, Dh, D)),
            "lnc": stack(lambda i: blk(i, "1.layer_norm.weight")),
            "wi": stack(lambda i: blk(i, "2.DenseReluDense.wi.weight").T),
            "wo_ffn": stack(lambda i: blk(i, "2.DenseReluDense.wo.weight").T),
            "ln2": stack(lambda i: blk(i, "2.layer_norm.weight")),
        },
        "final_ln": get("decoder.final_layer_norm.weight"),
    }
    # untied LM head (tie_word_embeddings=False checkpoints): keep the
    # trained projection instead of silently falling back to the shared
    # embedding — the tied path also rescales by d_model**-0.5, which is
    # wrong for untied weights (HF skips the rescale exactly then)
    if "lm_head.weight" in state_dict:
        head = get("lm_head.weight")
        if not np.array_equal(head, get("shared.weight")):
            decoder["lm_head"] = head
    return {
        "encoder": params_from_hf_torch(ecfg, enc_sd),
        "decoder": jax.tree.map(
            lambda a: jnp.asarray(a, jnp.float32), decoder
        ),
    }


# ---------------------------------------------------------------------------
# teacher-forced decoding (training / ppl)


def shift_right(cfg: T5Config, target_ids: jax.Array) -> jax.Array:
    """HF T5 _shift_right: decoder inputs = [pad] + target[:-1]."""
    return jnp.concatenate(
        [
            jnp.full_like(target_ids[:, :1], cfg.pad_token_id),
            target_ids[:, :-1],
        ],
        axis=1,
    )


def _lm_logits(ecfg: T5Config, params: dict, x: jax.Array, eq: str) -> jax.Array:
    """Project decoder states to vocab logits: untied lm_head when the
    checkpoint has one, else the tied embedding with the HF d_model**-0.5
    rescale (applied only in the tied case, matching HF)."""
    head = params["decoder"].get("lm_head")
    if head is None:
        x = x * (ecfg.hidden_size**-0.5)
        head = params["encoder"]["word"]
    return jnp.einsum(eq, x, head.astype(x.dtype))


def _attend(q, k, v, mask, bias):
    """mask [B, Tq, Tk] boolean; bias [H, Tq, Tk] or None."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    if bias is not None:
        s = s + bias[None]
    s = jnp.where(mask[:, None], s, jnp.finfo(s.dtype).min)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def decode_train(
    cfg: GenConfig,
    params: dict,
    dec_input_ids: jax.Array,
    dec_mask: jax.Array,
    enc_hidden: jax.Array,
    enc_mask: jax.Array,
    dropout_key: jax.Array | None = None,
    return_hidden: bool = False,
) -> jax.Array:
    """[B, T] decoder inputs -> [B, T, V] LM logits (teacher-forced).

    return_hidden=True yields the [B, T, D] post-final-norm decoder states
    instead (the HF decoder_hidden_states[-1] the CloneModel pools,
    CodeT5/models.py:72-84)."""
    from deepdfa_tpu.models.transformer import _dropout

    ecfg = cfg.encoder
    dt = jnp.dtype(ecfg.dtype)
    word = params["encoder"]["word"]
    dp = params["decoder"]
    x = word[dec_input_ids].astype(dt)
    k_embed = k_layers = k_final = None
    if dropout_key is not None and ecfg.dropout_rate > 0.0:
        k_embed, k_layers, k_final = jax.random.split(dropout_key, 3)
    x = _dropout(x, ecfg.dropout_rate, k_embed)

    T = dec_input_ids.shape[1]
    S = enc_mask.shape[1]
    pos = jnp.arange(T)
    buckets = relative_position_buckets(
        pos, pos, ecfg.rel_buckets, ecfg.rel_max_distance, bidirectional=False
    )
    bias = dp["rel_bias"][buckets].astype(dt).transpose(2, 0, 1)
    # flash lowering (teacher-forced training only; the incremental
    # beam-search decode path keeps its KV-cached XLA attention): the
    # kernel takes causal as a static mask and the cross attention as
    # the rectangular Tq != Tk case
    from deepdfa_tpu.models.transformer import (
        _flash_interpret,
        _flash_shape_ok,
        _resolve_attn_impl,
    )

    use_flash = _resolve_attn_impl(ecfg, T, ecfg.head_dim,
                                   biased=True) == "flash"
    if use_flash and not _flash_shape_ok(S, ecfg.head_dim):
        if ecfg.attn_impl == "flash":
            raise ValueError(
                f"attn_impl='flash' needs the encoder length to tile too "
                f"(S={S}: need S%128==0 on hardware, and S<=512 or "
                f"S%512==0)")
        use_flash = False  # auto quietly falls back, as everywhere else
    interp = "tpu" if _flash_interpret() else False
    from deepdfa_tpu.nn.flash_attention import flash_attention

    self_mask = cross_mask = None
    if not use_flash:
        # dense [B,T,T]/[B,T,S] masks exist only on the XLA path — the
        # kernel takes kv masks + a static causal flag instead
        causal = jnp.tril(jnp.ones((T, T), bool))
        self_mask = causal[None] & dec_mask[:, None, :].astype(bool)
        cross_mask = jnp.broadcast_to(
            enc_mask[:, None, :].astype(bool),
            (x.shape[0], T, enc_mask.shape[1]),
        )
    enc_h = enc_hidden.astype(dt)

    def layer(x, inputs):
        lp, key = inputs
        k1 = k2 = k3 = None
        if key is not None:
            k1, k2, k3 = jax.random.split(key, 3)
        h = _rms_norm(x, lp["ln1"], ecfg.layer_norm_eps)
        q = jnp.einsum("btd,dhk->bhtk", h, lp["wq"].astype(dt))
        k = jnp.einsum("btd,dhk->bhtk", h, lp["wk"].astype(dt))
        v = jnp.einsum("btd,dhk->bhtk", h, lp["wv"].astype(dt))
        if use_flash:
            ctx = flash_attention(
                q, k, v, dec_mask, scale=1.0, bias=bias, causal=True,
                interpret=interp,
            )
        else:
            ctx = _attend(q, k, v, self_mask, bias)
        from jax.ad_checkpoint import checkpoint_name

        ctx = checkpoint_name(ctx, "attn_ctx")
        out = jnp.einsum("bhtk,hkd->btd", ctx, lp["wo"].astype(dt))
        x = x + _dropout(out, ecfg.dropout_rate, k1)

        h = _rms_norm(x, lp["lnc"], ecfg.layer_norm_eps)
        q = jnp.einsum("btd,dhk->bhtk", h, lp["cq"].astype(dt))
        k = jnp.einsum("bsd,dhk->bhsk", enc_h, lp["ck"].astype(dt))
        v = jnp.einsum("bsd,dhk->bhsk", enc_h, lp["cv"].astype(dt))
        if use_flash:
            ctx = flash_attention(
                q, k, v, enc_mask, scale=1.0, interpret=interp
            )
        else:
            ctx = _attend(q, k, v, cross_mask, None)
        ctx = checkpoint_name(ctx, "attn_ctx")
        out = jnp.einsum("bhtk,hkd->btd", ctx, lp["co"].astype(dt))
        x = x + _dropout(out, ecfg.dropout_rate, k2)

        h = _rms_norm(x, lp["ln2"], ecfg.layer_norm_eps)
        h = jax.nn.relu(jnp.einsum("btd,df->btf", h, lp["wi"].astype(dt)))
        h = jnp.einsum("btf,fd->btd", h, lp["wo_ffn"].astype(dt))
        return x + _dropout(h, ecfg.dropout_rate, k3)

    from deepdfa_tpu.models.transformer import remat_wrap

    fn = remat_wrap(ecfg, layer)
    n_layers = dp["layers"]["wq"].shape[0]
    keys = jax.random.split(k_layers, n_layers) if k_layers is not None else None
    if keys is None:
        x, _ = jax.lax.scan(
            lambda x, lp: (fn(x, (lp, None)), None), x, dp["layers"]
        )
    else:
        x, _ = jax.lax.scan(lambda x, inp: (fn(x, inp), None), x, (dp["layers"], keys))
    x = _rms_norm(x, dp["final_ln"], ecfg.layer_norm_eps)
    x = _dropout(x, ecfg.dropout_rate, k_final)
    if return_hidden:
        return x
    return _lm_logits(ecfg, params, x, "btd,vd->btv")


def seq2seq_logits(
    cfg: GenConfig,
    params: dict,
    source_ids: jax.Array,
    target_ids: jax.Array,
    dropout_key: jax.Array | None = None,
) -> jax.Array:
    """Full teacher-forced pass: encode source, decode shifted targets."""
    ecfg = cfg.encoder
    k_enc = k_dec = None
    if dropout_key is not None:
        k_enc, k_dec = jax.random.split(dropout_key)
    enc_mask = source_ids != ecfg.pad_token_id
    enc_hidden = encode(ecfg, params["encoder"], source_ids, dropout_key=k_enc)
    dec_in = shift_right(ecfg, target_ids)
    dec_mask = jnp.ones_like(dec_in, bool)  # start token attends; pads masked in loss
    return decode_train(
        cfg, params, dec_in, dec_mask, enc_hidden, enc_mask, dropout_key=k_dec
    )


def seq2seq_loss(
    cfg: GenConfig,
    params: dict,
    source_ids: jax.Array,
    target_ids: jax.Array,
    dropout_key: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(mean CE over non-pad target tokens, token count)."""
    logits = seq2seq_logits(cfg, params, source_ids, target_ids, dropout_key)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tok_lp = jnp.take_along_axis(logp, target_ids[..., None], axis=-1)[..., 0]
    mask = (target_ids != cfg.encoder.pad_token_id).astype(jnp.float32)
    n_tok = jnp.maximum(mask.sum(), 1.0)
    return -(tok_lp * mask).sum() / n_tok, n_tok


# ---------------------------------------------------------------------------
# incremental decoding with KV cache + beam search


def _precompute_cross_kv(cfg: GenConfig, params: dict, enc_hidden: jax.Array):
    """Cross-attention K/V once per sequence: ([L, B, H, S, Dh], same)."""
    dt = jnp.dtype(cfg.encoder.dtype)
    lp = params["decoder"]["layers"]
    enc_h = enc_hidden.astype(dt)
    ck = jnp.einsum("bsd,ldhk->lbhsk", enc_h, lp["ck"].astype(dt))
    cv = jnp.einsum("bsd,ldhk->lbhsk", enc_h, lp["cv"].astype(dt))
    return ck, cv


def _decode_step(
    cfg: GenConfig,
    params: dict,
    tokens: jax.Array,  # [N] current input token per row
    t: jax.Array,  # scalar: position being written (0-based)
    cache_k: jax.Array,  # [L, N, H, Tmax, Dh]
    cache_v: jax.Array,
    cross_k: jax.Array,  # [L, N, H, S, Dh]
    cross_v: jax.Array,
    enc_mask: jax.Array,  # [N, S]
):
    """One cached decoder step -> ([N, V] logits, updated caches)."""
    ecfg = cfg.encoder
    dt = jnp.dtype(ecfg.dtype)
    word = params["encoder"]["word"]
    dp = params["decoder"]
    Tmax = cache_k.shape[3]

    x = word[tokens].astype(dt)  # [N, D]
    k_pos = jnp.arange(Tmax)
    buckets = relative_position_buckets(
        t[None], k_pos, ecfg.rel_buckets, ecfg.rel_max_distance,
        bidirectional=False,
    )  # [1, Tmax]
    bias = dp["rel_bias"][buckets[0]].astype(dt).T  # [H, Tmax]
    self_mask = k_pos <= t  # [Tmax]
    cross_mask = enc_mask.astype(bool)  # [N, S]

    def layer(x, inputs):
        lp, ck_l, cv_l, k_cache, v_cache = inputs
        h = _rms_norm(x, lp["ln1"], ecfg.layer_norm_eps)
        q = jnp.einsum("nd,dhk->nhk", h, lp["wq"].astype(dt))
        k_new = jnp.einsum("nd,dhk->nhk", h, lp["wk"].astype(dt))
        v_new = jnp.einsum("nd,dhk->nhk", h, lp["wv"].astype(dt))
        k_cache = jax.lax.dynamic_update_slice_in_dim(
            k_cache, k_new[:, :, None], t, axis=2
        )
        v_cache = jax.lax.dynamic_update_slice_in_dim(
            v_cache, v_new[:, :, None], t, axis=2
        )
        s = jnp.einsum("nhk,nhtk->nht", q, k_cache) + bias[None]
        s = jnp.where(self_mask[None, None], s, jnp.finfo(s.dtype).min)
        ctx = jnp.einsum(
            "nht,nhtk->nhk", jax.nn.softmax(s, axis=-1), v_cache
        )
        out = jnp.einsum("nhk,hkd->nd", ctx, lp["wo"].astype(dt))
        x = x + out

        h = _rms_norm(x, lp["lnc"], ecfg.layer_norm_eps)
        q = jnp.einsum("nd,dhk->nhk", h, lp["cq"].astype(dt))
        s = jnp.einsum("nhk,nhsk->nhs", q, ck_l)
        s = jnp.where(cross_mask[:, None], s, jnp.finfo(s.dtype).min)
        ctx = jnp.einsum("nhs,nhsk->nhk", jax.nn.softmax(s, axis=-1), cv_l)
        out = jnp.einsum("nhk,hkd->nd", ctx, lp["co"].astype(dt))
        x = x + out

        h = _rms_norm(x, lp["ln2"], ecfg.layer_norm_eps)
        h = jax.nn.relu(jnp.einsum("nd,df->nf", h, lp["wi"].astype(dt)))
        h = jnp.einsum("nf,fd->nd", h, lp["wo_ffn"].astype(dt))
        return x + h, (k_cache, v_cache)

    x, (cache_k, cache_v) = jax.lax.scan(
        layer, x, (dp["layers"], cross_k, cross_v, cache_k, cache_v)
    )
    x = _rms_norm(x, dp["final_ln"], ecfg.layer_norm_eps)
    logits = _lm_logits(ecfg, params, x, "nd,vd->nv")
    return logits.astype(jnp.float32), cache_k, cache_v


def beam_search(
    cfg: GenConfig,
    params: dict,
    source_ids: jax.Array,
    beam_size: int | None = None,
    max_length: int | None = None,
) -> jax.Array:
    """Beam-search decode: [B, S] source ids -> [B, max_length] token ids.

    Jit-friendly: static shapes throughout; a lax.while_loop exits as soon
    as every beam of every example has emitted EOS (the analog of HF
    generate(..., early_stopping=True)). Finished beams continue on the
    pad token with frozen scores. Final ranking divides each finished
    beam's log-prob by length**length_penalty.
    """
    ecfg = cfg.encoder
    K = beam_size or cfg.beam_size
    Tmax = max_length or cfg.max_target_length
    B, S = source_ids.shape
    L = cfg.n_dec_layers
    H, Dh = ecfg.num_heads, ecfg.head_dim
    pad, eos = ecfg.pad_token_id, ecfg.eos_token_id
    V = ecfg.vocab_size
    NEG = jnp.float32(-1e9)

    enc_mask = source_ids != pad
    enc_hidden = encode(ecfg, params["encoder"], source_ids)
    # expand to beams: [B, ...] -> [B*K, ...] (beam-major inner axis)
    enc_hidden_b = jnp.repeat(enc_hidden, K, axis=0)
    enc_mask_b = jnp.repeat(enc_mask, K, axis=0)
    cross_k, cross_v = _precompute_cross_kv(cfg, params, enc_hidden_b)

    N = B * K
    seqs0 = jnp.full((B, K, Tmax), pad, jnp.int32)
    # only beam 0 is live at step 0 so topk doesn't pick K duplicates
    scores0 = jnp.tile(
        jnp.concatenate([jnp.zeros((1,)), jnp.full((K - 1,), NEG)])[None],
        (B, 1),
    ).astype(jnp.float32)
    done0 = jnp.zeros((B, K), bool)
    tokens0 = jnp.full((N,), pad, jnp.int32)  # decoder start token
    cache_k0 = jnp.zeros((L, N, H, Tmax, Dh), jnp.dtype(ecfg.dtype))
    cache_v0 = jnp.zeros_like(cache_k0)

    def cond(state):
        t, _, _, done, _, _, _ = state
        return (t < Tmax) & ~done.all()

    def body(state):
        t, seqs, scores, done, tokens, cache_k, cache_v = state
        logits, cache_k, cache_v = _decode_step(
            cfg, params, tokens, t, cache_k, cache_v, cross_k, cross_v,
            enc_mask_b,
        )
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, V)
        pad_only = jnp.full((V,), NEG).at[pad].set(0.0)
        logp = jnp.where(done[..., None], pad_only[None, None], logp)
        cand = scores[..., None] + logp
        flat = cand.reshape(B, K * V)
        new_scores, flat_idx = jax.lax.top_k(flat, K)
        origin = flat_idx // V
        tok = (flat_idx % V).astype(jnp.int32)

        seqs = jnp.take_along_axis(seqs, origin[..., None], axis=1)
        seqs = jax.lax.dynamic_update_slice_in_dim(
            seqs, tok[..., None], t, axis=2
        )
        done = jnp.take_along_axis(done, origin, axis=1)
        done = done | (tok == eos)
        row = (jnp.arange(B)[:, None] * K + origin).reshape(-1)
        cache_k = cache_k[:, row]
        cache_v = cache_v[:, row]
        return t + 1, seqs, new_scores, done, tok.reshape(-1), cache_k, cache_v

    t, seqs, scores, done, _, _, _ = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), seqs0, scores0, done0, tokens0, cache_k0, cache_v0),
    )

    # length-penalized final ranking; unfinished beams rank below finished
    lengths = (seqs != pad).sum(-1).astype(jnp.float32)
    norm = jnp.maximum(lengths, 1.0) ** cfg.length_penalty
    final = scores / norm + jnp.where(done, 0.0, NEG)
    # if nothing finished (hit Tmax), fall back to raw normalized scores
    final = jnp.where(done.any(-1, keepdims=True), final, scores / norm)
    best = jnp.argmax(final, axis=1)
    return jnp.take_along_axis(seqs, best[:, None, None], axis=1)[:, 0]


def greedy_decode(
    cfg: GenConfig, params: dict, source_ids: jax.Array,
    max_length: int | None = None,
) -> jax.Array:
    """Greedy = beam search with K=1 (shares the cached step path)."""
    return beam_search(cfg, params, source_ids, beam_size=1, max_length=max_length)


# ---------------------------------------------------------------------------
# clone detection (CodeT5/models.py:64-123 CloneModel / run_clone.py)


@dataclasses.dataclass(frozen=True)
class CloneConfig:
    """Pairwise code-clone classifier over the T5 seq2seq stack.

    The reference runs each code of the pair through the full
    encoder-decoder with labels=source_ids, pools the LAST-eos decoder
    hidden state (get_t5_vec, models.py:72-84), then classifies the
    concatenated pair vector with RobertaClassificationHead
    (Linear(2D->D) -> tanh -> Linear(D->2), models.py:48-62)."""

    encoder: T5Config
    num_classes: int = 2


def init_clone_params(cfg: CloneConfig, key: jax.Array) -> dict:
    k_s2s, k_dense, k_out = jax.random.split(key, 3)
    D = cfg.encoder.hidden_size
    return {
        "seq2seq": init_gen_params(GenConfig(encoder=cfg.encoder), k_s2s),
        "head": {
            "dense_w": jax.random.normal(k_dense, (2 * D, D)) * 0.02,
            "dense_b": jnp.zeros((D,)),
            "out_w": jax.random.normal(k_out, (D, cfg.num_classes)) * 0.02,
            "out_b": jnp.zeros((cfg.num_classes,)),
        },
    }


def clone_vec(
    cfg: CloneConfig,
    params: dict,
    source_ids: jax.Array,  # [N, T] (each code of each pair is a row)
    dropout_key: jax.Array | None = None,
) -> jax.Array:
    """[N, D] last-eos decoder hidden per code (get_t5_vec role)."""
    from deepdfa_tpu.models.t5 import eos_pool

    ecfg = cfg.encoder
    gcfg = GenConfig(encoder=ecfg)
    k_enc = k_dec = None
    if dropout_key is not None:
        k_enc, k_dec = jax.random.split(dropout_key)
    mask = source_ids != ecfg.pad_token_id
    enc_hidden = encode(
        ecfg, params["seq2seq"]["encoder"], source_ids, dropout_key=k_enc
    )
    dec_in = shift_right(ecfg, source_ids)
    hidden = decode_train(
        gcfg, params["seq2seq"], dec_in, mask, enc_hidden, mask,
        dropout_key=k_dec, return_hidden=True,
    )
    return eos_pool(ecfg, hidden, source_ids)


def clone_forward(
    cfg: CloneConfig,
    params: dict,
    pair_ids: jax.Array,  # [B, 2, T]
    dropout_key: jax.Array | None = None,
) -> jax.Array:
    """[B, num_classes] logits over code pairs."""
    B, two, T = pair_ids.shape
    vec = clone_vec(
        cfg, params, pair_ids.reshape(B * two, T), dropout_key=dropout_key
    )
    x = vec.reshape(B, -1)  # [B, 2D] (models.py:57 reshape)
    h = params["head"]
    x = jnp.tanh(x @ h["dense_w"] + h["dense_b"])
    return x @ h["out_w"] + h["out_b"]


def trim_at_eos(ids: np.ndarray, eos_id: int, pad_id: int = 0) -> list[list[int]]:
    """Host-side: cut each row at its first EOS, drop pads."""
    out = []
    for row in np.asarray(ids):
        toks = []
        for t in row.tolist():
            if t == eos_id:
                break
            if t != pad_id:
                toks.append(t)
        out.append(toks)
    return out
