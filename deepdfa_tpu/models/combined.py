"""Combined transformer+graph classifiers (the DeepDFA+LineVul family).

Re-design of the reference combined models:
- LineVul/linevul/linevul_model.py:15-69 — RobertaClassificationHead over
  [CLS-token hidden ‖ pooled graph embedding] with dropout/tanh, 2-way
  softmax; the GGNN runs in encoder_mode and its out_dim widens the head.
- the index-join bridge (DDFA/sastvd/linevd/dataset.py:63-76 get_indices):
  the reference drops transformer rows whose graph is missing; with XLA
  static shapes we instead carry a per-row `has_graph` mask, zero the
  missing graph embeddings, and keep every row in the loss (the reference
  skips those examples entirely — both treat the text signal as primary
  and the graph as additive).

Functional style matching models/transformer.py: explicit param pytrees,
shard_map-compatible (tp/sp axes thread through to the encoder).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deepdfa_tpu.graphs.batch import GraphBatch
from deepdfa_tpu.models import transformer as tfm
from deepdfa_tpu.models.deepdfa import DeepDFA
from deepdfa_tpu.parallel.megatron import region_end


@dataclasses.dataclass(frozen=True)
class CombinedConfig:
    encoder: tfm.TransformerConfig
    graph_hidden_dim: int = 32
    graph_n_steps: int = 5
    graph_input_dim: int = 1002
    num_classes: int = 2
    head_dropout: float = 0.1
    use_graph: bool = True
    # optional sparse expert adapter on the [CLS] path (residual MoE block
    # before the head): capacity without per-row FLOPs, and the expert
    # dimension shards over the ep mesh axis (parallel/moe.py). 0 = off
    # (the flagship reference-parity configuration).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_aux_weight: float = 0.01

    @property
    def graph_out_dim(self) -> int:
        return 8 * self.graph_hidden_dim  # concat_all_absdf encoder out_dim

    @property
    def moe_cfg(self):
        from deepdfa_tpu.parallel.moe import MoEConfig

        return MoEConfig(
            hidden_size=self.encoder.hidden_size,
            intermediate_size=self.encoder.intermediate_size,
            num_experts=self.moe_experts,
            top_k=self.moe_top_k,
        )


def make_graph_encoder(cfg: CombinedConfig) -> DeepDFA:
    return make_graph_encoder_for(
        cfg.graph_input_dim, cfg.graph_hidden_dim, cfg.graph_n_steps
    )[0]


def _dummy_graph_batch() -> GraphBatch:
    return GraphBatch(
        node_feats=jnp.zeros((8, 4), jnp.int32),
        node_vuln=jnp.zeros((8,), jnp.int32),
        node_graph=jnp.zeros((8,), jnp.int32),
        node_mask=jnp.ones((8,), bool),
        edge_src=jnp.zeros((8,), jnp.int32),
        edge_dst=jnp.zeros((8,), jnp.int32),
        edge_mask=jnp.ones((8,), bool),
        graph_label=jnp.zeros((2,)),
        graph_mask=jnp.ones((2,), bool),
        graph_ids=jnp.zeros((2,), jnp.int32),
        num_graphs=2,
    )


def make_graph_encoder_for(
    graph_input_dim: int, graph_hidden_dim: int, n_steps: int = 5
) -> tuple[DeepDFA, GraphBatch]:
    """(encoder-mode GGNN, init dummy batch) — shared by all combined
    heads (RoBERTa-style and the T5 DefectModel)."""
    enc = DeepDFA(
        input_dim=graph_input_dim,
        hidden_dim=graph_hidden_dim,
        n_steps=n_steps,
        num_output_layers=0,
        concat_all_absdf=True,
        label_style="graph",
        encoder_mode=True,
    )
    return enc, _dummy_graph_batch()


def init_params(cfg: CombinedConfig, key: jax.Array) -> dict:
    k_enc, k_graph, k_head = jax.random.split(key, 3)
    D = cfg.encoder.hidden_size
    in_dim = D + (cfg.graph_out_dim if cfg.use_graph else 0)
    std = 0.02
    enc = tfm.init_params(cfg.encoder, k_enc)
    enc.pop("pooler", None)  # unused by this head; keep it out of adamw
    params = {
        "encoder": enc,
        "head": {
            "dense_w": jax.random.normal(k_head, (in_dim, D)) * std,
            "dense_b": jnp.zeros((D,)),
            "out_w": jax.random.normal(
                jax.random.fold_in(k_head, 1), (D, cfg.num_classes)
            )
            * std,
            "out_b": jnp.zeros((cfg.num_classes,)),
        },
    }
    if cfg.use_graph:
        graph_enc = make_graph_encoder(cfg)
        params["graph"] = graph_enc.init(k_graph, _dummy_graph_batch())
    if cfg.moe_experts:
        from deepdfa_tpu.parallel.moe import init_moe_params

        params["moe"] = init_moe_params(
            cfg.moe_cfg, jax.random.fold_in(k_head, 2)
        )
    return params


def head_logits(
    cfg: CombinedConfig,
    head: dict,
    cls_vec: jax.Array,
    graph_vec: jax.Array | None,
    dropout_key: jax.Array | None = None,
) -> jax.Array:
    """RobertaClassificationHead: dropout -> dense -> tanh -> dropout -> out."""
    x = cls_vec
    if graph_vec is not None:
        x = jnp.concatenate([x, graph_vec.astype(x.dtype)], axis=-1)
    k1 = k2 = None
    if dropout_key is not None:
        k1, k2 = jax.random.split(dropout_key)
    x = tfm._dropout(x, cfg.head_dropout, k1)
    x = jnp.tanh(x @ head["dense_w"] + head["dense_b"])
    x = tfm._dropout(x, cfg.head_dropout, k2)
    return x @ head["out_w"] + head["out_b"]


def forward(
    cfg: CombinedConfig,
    params: dict,
    input_ids: jax.Array,
    graph_batch: GraphBatch | None = None,
    has_graph: jax.Array | None = None,
    dropout_key: jax.Array | None = None,
    sp_axis: str | None = None,
    tp_axis: str | None = None,
    position_offset: int = 0,
    pp_axis: str | None = None,
    pp_stages: int = 1,
    pp_microbatches: int = 4,
    ep_axis: str | None = None,
    ep_size: int = 1,
    with_aux: bool = False,
) -> jax.Array:
    """[B, T] ids (+ aligned GraphBatch of B graphs) -> [B, num_classes].

    With `pp_axis` set (inside shard_map, layer params stage-sharded over
    that axis, sp off) the encoder runs the GPipe microbatch schedule;
    the broadcast uses region_end because this forward's caller computes
    a loss copy on every stage (parallel/pipeline.py docstring). With
    cfg.moe_experts > 0 the [CLS] vector passes through a residual MoE
    adapter (expert-parallel over `ep_axis` when set). `with_aux=True`
    returns (logits, aux_loss) — the MoE load-balancing term the trainer
    adds to the objective (0.0 when no MoE)."""
    k_enc = k_head = None
    if dropout_key is not None:
        k_enc, k_head = jax.random.split(dropout_key)
    if pp_axis is not None:
        if position_offset != 0:
            raise ValueError(
                "position_offset is computed inside the pipeline from "
                "sp_axis; callers must pass 0 on the pp path"
            )
        from deepdfa_tpu.parallel.pipeline import pipeline_stage_forward

        enc = params["encoder"]
        hidden = pipeline_stage_forward(
            cfg.encoder,
            enc["layers"],
            {k: v for k, v in enc.items() if k != "layers"},
            input_ids,
            input_ids != cfg.encoder.pad_token_id,
            k_enc,
            pp_microbatches,
            pp_stages,
            pp_axis,
            broadcast="region_end",
            tp_axis=tp_axis,
            sp_axis=sp_axis,
        )
    else:
        hidden = tfm.encode(
            cfg.encoder,
            params["encoder"],
            input_ids,
            dropout_key=k_enc,
            sp_axis=sp_axis,
            tp_axis=tp_axis,
            position_offset=position_offset,
        )
    aux = jnp.zeros((), jnp.float32)
    cls_vec = hidden[:, 0, :]
    if sp_axis is not None:
        # [CLS] lives on the first sp shard; broadcast with psum-forward /
        # identity-backward (region_end) — a raw psum would transpose to
        # another psum and multiply the encoder cotangent by sp (the CE
        # loss is computed once per sp member)
        idx = jax.lax.axis_index(sp_axis)
        cls_vec = region_end(
            jnp.where(idx == 0, cls_vec, jnp.zeros_like(cls_vec)), sp_axis
        )

    if cfg.moe_experts:
        from deepdfa_tpu.parallel.moe import moe_ffn, moe_stage_forward

        if ep_axis is not None:
            moe_out, aux = moe_stage_forward(
                cfg.moe_cfg, params["moe"], cls_vec, ep_size, ep_axis,
                broadcast="region_end",
            )
        else:
            moe_out, aux = moe_ffn(cfg.moe_cfg, params["moe"], cls_vec)
        cls_vec = cls_vec + moe_out  # residual: dropped tokens pass through

    graph_vec = None
    if cfg.use_graph:
        if graph_batch is None:
            raise ValueError(
                "CombinedConfig.use_graph=True requires a graph_batch "
                "(text-only ablations: set use_graph=False, which sizes "
                "the head without the graph block)"
            )
        graph_enc = make_graph_encoder(cfg)
        graph_vec = graph_enc.apply(params["graph"], graph_batch)  # [B, 8H]
        if has_graph is not None:
            graph_vec = graph_vec * has_graph[:, None].astype(graph_vec.dtype)
    logits = head_logits(cfg, params["head"], cls_vec, graph_vec, k_head)
    if with_aux:
        return logits, aux
    return logits
