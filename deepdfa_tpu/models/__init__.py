from deepdfa_tpu.models.deepdfa import DeepDFA

__all__ = ["DeepDFA"]
