from deepdfa_tpu.models import combined, transformer
from deepdfa_tpu.models.combined import CombinedConfig
from deepdfa_tpu.models.deepdfa import DeepDFA
from deepdfa_tpu.models.transformer import TransformerConfig

__all__ = [
    "DeepDFA",
    "combined",
    "transformer",
    "CombinedConfig",
    "TransformerConfig",
]
