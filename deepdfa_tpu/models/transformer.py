"""RoBERTa-family transformer encoder, written TPU-first.

This is the framework's replacement for the reference's HF torch encoders
(LineVul's RobertaForSequenceClassification, linevul_model.py:26-69;
UniXcoder; CodeT5's encoder stack). Design choices:

- parameters are an explicit pytree of arrays (no module framework in the
  forward path): `lax.scan` over stacked layer weights gives one compiled
  layer body regardless of depth, and manual-parallelism shard_map code can
  address the head/ffn axes directly.
- tensor parallelism is Megatron-style: attention heads and the FFN hidden
  dimension are sharded over the `tp` mesh axis; inside shard_map each
  device computes its local heads/columns and one psum per residual branch
  restores the full activation.
- sequence parallelism: the token axis shards over `sp`; attention runs
  the exact ring algorithm (parallel/ring_attention.py); everything else
  is token-local so no other collective is needed.
- weights import from a HF torch `roberta` state_dict via
  `params_from_hf_torch` for pretrained initialization (codebert etc.).

HF-compatible numerics: GELU (tanh approximation NOT used — HF roberta
uses erf gelu), post-layer-norm residual blocks, learned positions with
RoBERTa's pad-offset position ids.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.core.config import PAD_ID_BY_FAMILY
from deepdfa_tpu.parallel.megatron import region_end, region_start
from deepdfa_tpu.nn.flash_attention import flash_attention
from deepdfa_tpu.parallel.ring_attention import full_attention, ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50265
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 514
    type_vocab_size: int = 1
    # the shared collater/encoder pad convention (core/config.py) — the
    # attention mask derives from `input_ids != pad_token_id`
    pad_token_id: int = PAD_ID_BY_FAMILY["roberta"]
    layer_norm_eps: float = 1e-5
    dropout_rate: float = 0.1
    dtype: str = "float32"  # activation dtype (bfloat16 for big runs)
    # sequence-parallel attention scheme: "ring" (k/v rotate over ICI,
    # O(S/P) memory) or "ulysses" (two all-to-alls shard heads — usually
    # faster at moderate S; needs heads % sp == 0). Both exact.
    sp_variant: str = "ring"
    remat: bool = True  # rematerialize layer activations in backward
    # (HBM is the bottleneck: without remat, a 12-layer/512-token/bs-32
    # backward stacks ~18GB of attention+FFN temps and exceeds one v5e)
    # local-attention lowering: "auto" picks the fused Pallas flash
    # kernel (nn/flash_attention.py) on TPU when the shape qualifies,
    # else the XLA einsum path; "xla"/"flash" force one. Only the
    # sp_axis=None branch is affected (ring/ulysses own the sp seam).
    attn_impl: str = "auto"
    # remat granularity when remat=True: "full" recomputes the whole
    # layer in backward; "attn_saved" saves each layer's attention
    # context by name (+~[B,T,D] HBM per layer). With the flash lowering
    # the kernel's custom-vjp outputs (ctx + lse) carry the names, so
    # backward skips re-running the attention kernel entirely; with the
    # xla lowering only the downstream projection recompute is saved
    # (its softmax still replays for dq/dk/dv).
    remat_policy: str = "full"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, **kw) -> "TransformerConfig":
        base = dict(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position_embeddings=66,
        )
        base.update(kw)
        return cls(**base)


def init_params(cfg: TransformerConfig, key: jax.Array) -> dict:
    """Random-init parameter pytree (HF-style truncated-normal 0.02)."""
    k = iter(jax.random.split(key, 16))
    std = 0.02
    D, H, Dh, F, L = (
        cfg.hidden_size,
        cfg.num_heads,
        cfg.head_dim,
        cfg.intermediate_size,
        cfg.num_layers,
    )

    def norm(key, shape):
        return (jax.random.normal(key, shape) * std).astype(jnp.float32)

    def zeros(shape):
        return jnp.zeros(shape, jnp.float32)

    def ones(shape):
        return jnp.ones(shape, jnp.float32)

    emb = {
        "word": norm(next(k), (cfg.vocab_size, D)),
        "position": norm(next(k), (cfg.max_position_embeddings, D)),
        "token_type": norm(next(k), (cfg.type_vocab_size, D)),
        "ln_scale": ones((D,)),
        "ln_bias": zeros((D,)),
    }
    layers = {
        "wq": norm(next(k), (L, D, H, Dh)),
        "bq": zeros((L, H, Dh)),
        "wk": norm(next(k), (L, D, H, Dh)),
        "bk": zeros((L, H, Dh)),
        "wv": norm(next(k), (L, D, H, Dh)),
        "bv": zeros((L, H, Dh)),
        "wo": norm(next(k), (L, H, Dh, D)),
        "bo": zeros((L, D)),
        "ln1_scale": ones((L, D)),
        "ln1_bias": zeros((L, D)),
        "w1": norm(next(k), (L, D, F)),
        "b1": zeros((L, F)),
        "w2": norm(next(k), (L, F, D)),
        "b2": zeros((L, D)),
        "ln2_scale": ones((L, D)),
        "ln2_bias": zeros((L, D)),
    }
    pooler = {"w": norm(next(k), (D, D)), "b": zeros((D,))}
    return {"embeddings": emb, "layers": layers, "pooler": pooler}


def _flash_interpret() -> bool:
    """Test hook: DEEPDFA_TPU_FLASH_INTERPRET=1 runs the flash kernel in
    Pallas TPU-interpret mode so the integration path is exercisable on
    CPU (where `attn_impl="flash"` would otherwise fail to lower)."""
    import os

    return os.environ.get("DEEPDFA_TPU_FLASH_INTERPRET", "") == "1"


def _flash_shape_ok(T: int, head_dim: int) -> bool:
    from deepdfa_tpu.nn.flash_attention import flash_shape_ok

    return flash_shape_ok(T, head_dim, lax_alignment=_flash_interpret())


def _resolve_attn_impl(cfg, T: int, head_dim: int, *, Tk: int | None = None,
                       biased: bool = False) -> str:
    """Concrete lowering for cfg.attn_impl at this problem shape (thin
    wrapper over nn.flash_attention.resolve_impl — the single source of
    truth for tileability, the biased VMEM cap, and forced-vs-auto
    semantics — adding the CPU-interpreter test hook)."""
    from deepdfa_tpu.nn.flash_attention import resolve_impl

    return resolve_impl(
        getattr(cfg, "attn_impl", "auto"), T, head_dim, Tk=Tk,
        biased=biased, interpret_hint=_flash_interpret())


def remat_wrap(cfg, layer_fn):
    """Apply cfg.remat / cfg.remat_policy to a layer function — the one
    definition of the selective-save policy (saved names: the flash
    kernel's custom-vjp outputs + the xla lowerings' checkpoint_name'd
    attention contexts). Shared by both encoder families and the GPipe
    stage runner."""
    if not getattr(cfg, "remat", True):
        return layer_fn
    if getattr(cfg, "remat_policy", "full") == "attn_saved":
        return jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_ctx", "attn_lse"),
        )
    return jax.checkpoint(layer_fn)


def _layer_norm(x, scale, bias, eps):
    """LayerNorm in float32 regardless of activation dtype (bf16-safe)."""
    dt = x.dtype
    x = x.astype(jnp.float32)
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    return (out + bias.astype(jnp.float32)).astype(dt)


def _dropout(x, rate, key):
    if key is None or rate <= 0.0:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0)


def embed(
    cfg: TransformerConfig,
    params: dict,
    input_ids: jax.Array,
    position_offset: int = 0,
    dropout_key: jax.Array | None = None,
) -> jax.Array:
    """Token+position+type embeddings. `position_offset` is the number of
    tokens on earlier sp shards (sequence-parallel position ids)."""
    # capacity guard: RoBERTa's pad-offset position ids run up to
    # T + offset + pad_token_id, and a gather past the table's end would
    # silently index OOB (XLA clamps) instead of failing — a
    # misconfigured bucket edge (data.seq_buckets) must fail loudly
    # here. Under sequence parallelism the offset is traced
    # (axis_index * T_local), so only the static-offset case is
    # checkable; the local-T check still catches edges past the table.
    if isinstance(position_offset, int):
        top = input_ids.shape[1] + position_offset + cfg.pad_token_id
        if top > cfg.max_position_embeddings - 1:
            raise ValueError(
                f"sequence length {input_ids.shape[1]} (+ position offset "
                f"{position_offset}) needs position ids up to {top}, but "
                f"the learned position table has only "
                f"{cfg.max_position_embeddings} rows "
                f"(max_position_embeddings) — lower the bucket edge / "
                f"max_length or grow the table (RoBERTa ids run "
                f"pad_token_id+1 .. pad_token_id+T)"
            )
    e = params["embeddings"]
    # roberta position ids: pad_token_id + 1 + running index of non-pad...
    # HF actually uses cumulative non-pad positions; fine-tuning on fixed
    # right-padded batches makes simple offsets equivalent
    mask = (input_ids != cfg.pad_token_id).astype(jnp.int32)
    pos = (jnp.cumsum(mask, axis=-1) + position_offset) * mask + cfg.pad_token_id
    x = (
        e["word"][input_ids]
        + e["position"][pos]
        + e["token_type"][jnp.zeros_like(input_ids)]
    )
    x = _layer_norm(x, e["ln_scale"], e["ln_bias"], cfg.layer_norm_eps)
    x = _dropout(x, cfg.dropout_rate, dropout_key)
    return x.astype(jnp.dtype(cfg.dtype))


def encoder_layer(
    cfg: TransformerConfig,
    lp: dict,
    x: jax.Array,
    attn_mask: jax.Array,
    dropout_key: jax.Array | None = None,
    sp_axis: str | None = None,
    tp_axis: str | None = None,
):
    """One post-LN transformer layer (HF roberta semantics).

    x: [B, T, D]; attn_mask: [B, T] bool. Inside shard_map, `tp_axis`
    means lp holds this device's head/ffn shard and activations are
    full-width after each psum; `sp_axis` means T is the local sequence
    chunk and ring attention rotates k/v.
    """
    k1 = k2 = k3 = None
    if dropout_key is not None:
        k1, k2, k3 = jax.random.split(dropout_key, 3)
        if tp_axis is not None:
            # attention-probs dropout acts on tp-local heads: decorrelate
            # masks across head shards (k1/k2 act on replicated activations
            # and MUST stay identical across tp members)
            k3 = jax.random.fold_in(k3, jax.lax.axis_index(tp_axis))

    # params stay float32 (optimizer precision); compute in activation dtype
    dt = x.dtype
    lp = jax.tree.map(lambda a: a.astype(dt), lp)

    # attention: a Megatron parallel region when heads are tp-sharded
    x_in = region_start(x, tp_axis) if tp_axis is not None else x
    q = jnp.einsum("btd,dhk->bhtk", x_in, lp["wq"]) + lp["bq"][:, None, :]
    k = jnp.einsum("btd,dhk->bhtk", x_in, lp["wk"]) + lp["bk"][:, None, :]
    v = jnp.einsum("btd,dhk->bhtk", x_in, lp["wv"]) + lp["bv"][:, None, :]

    if sp_axis is not None and cfg.sp_variant == "ulysses":
        from deepdfa_tpu.parallel.ulysses import ulysses_attention

        ctx = ulysses_attention(
            q, k, v, attn_mask, axis_name=sp_axis,
            dropout_rate=cfg.dropout_rate, dropout_key=k3,
            # raw attn_impl: ulysses resolves it at the FULL sequence
            # length (the shape the kernel actually runs at, known only
            # after its all-to-all)
            attn_impl=getattr(cfg, "attn_impl", "auto"),
            flash_interpret=_flash_interpret(),
        )
    elif sp_axis is not None:
        ctx = ring_attention(
            q, k, v, attn_mask, axis_name=sp_axis,
            dropout_rate=cfg.dropout_rate, dropout_key=k3,
        )
    elif _resolve_attn_impl(cfg, q.shape[2], cfg.head_dim) == "flash":
        rate = cfg.dropout_rate if k3 is not None else 0.0
        seed = None
        if rate > 0.0:
            # int32 PRNG seed for the in-kernel dropout mask (unique per
            # layer: k3 comes from the per-layer key split in encode())
            from deepdfa_tpu.nn.flash_attention import derive_seed

            seed = derive_seed(k3)
        ctx = flash_attention(
            q, k, v, attn_mask, dropout_rate=rate, seed=seed,
            interpret="tpu" if _flash_interpret() else False,
        )
    else:
        ctx = full_attention(
            q, k, v, attn_mask, dropout_rate=cfg.dropout_rate, dropout_key=k3
        )

    from jax.ad_checkpoint import checkpoint_name

    ctx = checkpoint_name(ctx, "attn_ctx")
    out = jnp.einsum("bhtk,hkd->btd", ctx, lp["wo"])
    if tp_axis is not None:
        out = region_end(out, tp_axis)
    out = out + lp["bo"]
    out = _dropout(out, cfg.dropout_rate, k1)
    x = _layer_norm(x + out, lp["ln1_scale"], lp["ln1_bias"], cfg.layer_norm_eps)

    # FFN: the second Megatron region; b1 shards along F with w1's columns
    h_in = region_start(x, tp_axis) if tp_axis is not None else x
    h = jnp.einsum("btd,df->btf", h_in, lp["w1"]) + lp["b1"]
    h = jax.nn.gelu(h, approximate=False)
    h = jnp.einsum("btf,fd->btd", h, lp["w2"])
    if tp_axis is not None:
        h = region_end(h, tp_axis)
    h = h + lp["b2"]
    h = _dropout(h, cfg.dropout_rate, k2)
    x = _layer_norm(x + h, lp["ln2_scale"], lp["ln2_bias"], cfg.layer_norm_eps)
    return x


def encode(
    cfg: TransformerConfig,
    params: dict,
    input_ids: jax.Array,
    attn_mask: jax.Array | None = None,
    dropout_key: jax.Array | None = None,
    sp_axis: str | None = None,
    tp_axis: str | None = None,
    position_offset: int = 0,
) -> jax.Array:
    """Full encoder: [B, T] ids -> [B, T, D] hidden states."""
    if attn_mask is None:
        attn_mask = input_ids != cfg.pad_token_id
    if dropout_key is not None and sp_axis is not None:
        # every sp shard holds different tokens: decorrelate the embed /
        # residual dropout masks across shards
        dropout_key = jax.random.fold_in(
            dropout_key, jax.lax.axis_index(sp_axis)
        )
    x = embed(cfg, params, input_ids, position_offset, dropout_key)

    layers = params["layers"]
    n_layers = layers["wq"].shape[0]

    if dropout_key is None:
        def layer_fn(x, lp):
            return encoder_layer(
                cfg, lp, x, attn_mask, None, sp_axis=sp_axis, tp_axis=tp_axis
            )
    else:
        def layer_fn(x, inputs):
            lp, key = inputs
            return encoder_layer(
                cfg, lp, x, attn_mask, key, sp_axis=sp_axis, tp_axis=tp_axis
            )

    layer_fn = remat_wrap(cfg, layer_fn)

    xs = layers if dropout_key is None else (layers, jax.random.split(dropout_key, n_layers))
    x, _ = jax.lax.scan(lambda x, inp: (layer_fn(x, inp), None), x, xs)
    return x


def cls_pool(cfg: TransformerConfig, params: dict, hidden: jax.Array) -> jax.Array:
    """[CLS] (position 0) through the tanh pooler -> [B, D]."""
    cls = hidden[:, 0, :]
    p = params["pooler"]
    return jnp.tanh(cls @ p["w"] + p["b"])


def tp_layer_specs():
    """PartitionSpecs for the stacked layer params under Megatron tensor
    parallelism: attention heads (axis 2 of [L,D,H,Dh]) and the FFN hidden
    axis shard over "tp"; everything else replicated. Lives next to
    init_params so layout changes update exactly one table."""
    from jax.sharding import PartitionSpec as P

    return {
        "wq": P(None, None, "tp", None), "bq": P(None, "tp", None),
        "wk": P(None, None, "tp", None), "bk": P(None, "tp", None),
        "wv": P(None, None, "tp", None), "bv": P(None, "tp", None),
        "wo": P(None, "tp", None, None), "bo": P(None, None),
        "ln1_scale": P(None, None), "ln1_bias": P(None, None),
        "w1": P(None, None, "tp"), "b1": P(None, "tp"),
        "w2": P(None, "tp", None), "b2": P(None, None),
        "ln2_scale": P(None, None), "ln2_bias": P(None, None),
    }


# ---------------------------------------------------------------------------
# HF weight import


def params_from_hf_torch(cfg: TransformerConfig, state_dict) -> dict:
    """Convert a HF torch `RobertaModel` state_dict (prefix 'roberta.' or
    none) into this module's parameter pytree. Tested against
    transformers' FlaxRobertaModel numerics (tests/test_transformer.py)."""

    def get(name):
        for prefix in ("", "roberta."):
            k = prefix + name
            if k in state_dict:
                return np.asarray(state_dict[k].detach().cpu().numpy())
        raise KeyError(name)

    D, H, Dh, L = cfg.hidden_size, cfg.num_heads, cfg.head_dim, cfg.num_layers
    emb = {
        "word": get("embeddings.word_embeddings.weight"),
        "position": get("embeddings.position_embeddings.weight"),
        "token_type": get("embeddings.token_type_embeddings.weight"),
        "ln_scale": get("embeddings.LayerNorm.weight"),
        "ln_bias": get("embeddings.LayerNorm.bias"),
    }

    def layer(i, name):
        return get(f"encoder.layer.{i}.{name}")

    def stack(fn):
        return np.stack([fn(i) for i in range(L)])

    layers = {
        # torch Linear weight [out, in] -> transpose -> reshape heads
        "wq": stack(lambda i: layer(i, "attention.self.query.weight").T.reshape(D, H, Dh)),
        "bq": stack(lambda i: layer(i, "attention.self.query.bias").reshape(H, Dh)),
        "wk": stack(lambda i: layer(i, "attention.self.key.weight").T.reshape(D, H, Dh)),
        "bk": stack(lambda i: layer(i, "attention.self.key.bias").reshape(H, Dh)),
        "wv": stack(lambda i: layer(i, "attention.self.value.weight").T.reshape(D, H, Dh)),
        "bv": stack(lambda i: layer(i, "attention.self.value.bias").reshape(H, Dh)),
        "wo": stack(lambda i: layer(i, "attention.output.dense.weight").T.reshape(H, Dh, D)),
        "bo": stack(lambda i: layer(i, "attention.output.dense.bias")),
        "ln1_scale": stack(lambda i: layer(i, "attention.output.LayerNorm.weight")),
        "ln1_bias": stack(lambda i: layer(i, "attention.output.LayerNorm.bias")),
        "w1": stack(lambda i: layer(i, "intermediate.dense.weight").T),
        "b1": stack(lambda i: layer(i, "intermediate.dense.bias")),
        "w2": stack(lambda i: layer(i, "output.dense.weight").T),
        "b2": stack(lambda i: layer(i, "output.dense.bias")),
        "ln2_scale": stack(lambda i: layer(i, "output.LayerNorm.weight")),
        "ln2_bias": stack(lambda i: layer(i, "output.LayerNorm.bias")),
    }
    try:
        pooler = {"w": get("pooler.dense.weight").T, "b": get("pooler.dense.bias")}
    except KeyError:
        pooler = {
            "w": np.zeros((D, D), np.float32),
            "b": np.zeros((D,), np.float32),
        }
    tree = {"embeddings": emb, "layers": layers, "pooler": pooler}
    return jax.tree.map(lambda a: jnp.asarray(a, jnp.float32), tree)
