"""Backend health observability (docs/slo.md).

BENCH_r01..r05 all record the same production failure: the remote TPU
compile service wedges, the bench's inline probe times out, and the run
silently falls back to CPU with the evidence buried in a
``fallback_from`` string nobody gates on. This module lifts that inline
probe/retry logic into the runtime proper so backend health is an
OBSERVED signal, not a bench-local branch:

- `BackendHealth.probe()` — the bounded compile-and-execute probe
  (`core/backend.py:probe_default_backend` in a subprocess, so a wedged
  compile service can never hang the caller) with bounded retries,
  emitting `backend/*` registry metrics and cat="backend" trace
  instants for every attempt: probe latency, retries, wedge detected
  (timeout => the compile service is hung, not dead), failures.
- `BackendHealth.record_fallback()` — the moment a caller gives up on
  the default backend and pins CPU, counted and traced.
- `probe_backend()` / `record_fallback()` module-level wrappers over a
  process-wide singleton — what bench.py's probe-gated retry loop calls
  so its fallback path shows up in the same metrics the serving
  `/healthz?deep=1` mode reads.

The probe function is injectable (`probe_fn`) so tests can drive the
timeout/wedge path without a real 60s subprocess hang.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from deepdfa_tpu.obs import metrics as obs_metrics, trace as obs_trace


def _default_probe(timeout_s: float) -> tuple[bool, str]:
    from deepdfa_tpu.core.backend import probe_default_backend

    # use_cache=False: health checks sample NOW, not the process's first
    # impression — a wedge that develops mid-run must be seen
    return probe_default_backend(timeout_s, use_cache=False)


def looks_wedged(detail: str) -> bool:
    """A probe TIMEOUT means the compile service accepted the connection
    and hung (the r1-r5 wedge signature); a nonzero-exit probe means the
    backend errored fast (tunnel down, no accelerator) — different
    failure, different operator action."""
    return "timed out" in detail


class BackendHealth:
    """Probe runner + last-result cache for one process.

    `/healthz?deep=1` calls `probe()` per request (bounded by the
    configured timeout); `last()` serves the cached result to callers
    that want the newest evidence without paying a probe."""

    def __init__(
        self,
        probe_fn: Callable[[float], tuple[bool, str]] | None = None,
        registry: obs_metrics.MetricsRegistry | None = None,
    ):
        self.probe_fn = probe_fn or _default_probe
        r = registry if registry is not None else obs_metrics.REGISTRY
        self._m_probes = r.counter("backend/probes")
        self._m_failures = r.counter("backend/probe_failures")
        self._m_retries = r.counter("backend/probe_retries")
        self._m_wedges = r.counter("backend/wedges")
        self._m_fallbacks = r.counter("backend/fallbacks")
        self._m_seconds = r.histogram("backend/probe_seconds")
        self._m_healthy = r.gauge("backend/healthy")
        self._lock = threading.Lock()
        self._last: dict | None = None

    def probe(
        self,
        timeout_s: float = 60.0,
        retries: int = 0,
        retry_wait_s: float = 0.0,
    ) -> dict:
        """Run the bounded probe (plus up to `retries` retries) and
        return the attempt report:

        {"ok", "platform"|"error", "latency_s", "attempts", "wedged",
         "timeout_s"} — also cached for `last()` and mirrored into the
        `backend/*` metrics + trace stream."""
        attempts = 0
        report: dict = {"ok": False, "timeout_s": float(timeout_s)}
        while True:
            attempts += 1
            self._m_probes.inc()
            if attempts > 1:
                self._m_retries.inc()
            t0 = time.perf_counter()
            ok, detail = self.probe_fn(timeout_s)
            dt = time.perf_counter() - t0
            self._m_seconds.observe(dt)
            report.update(
                ok=bool(ok), latency_s=round(dt, 3), attempts=attempts
            )
            if ok:
                report["platform"] = detail
                report.pop("error", None)
                report["wedged"] = False
                break
            wedged = looks_wedged(detail)
            report.update(error=detail, wedged=wedged)
            self._m_failures.inc()
            if wedged:
                self._m_wedges.inc()
                # a WEDGE is the r1-r5 terminal signature: dump the
                # flight recorder (no-op unless installed) so "bench
                # silently fell back to CPU" leaves a machine-readable
                # artifact, not a log-tail anecdote
                from deepdfa_tpu.obs import flight as obs_flight

                obs_flight.crash_dump("backend_wedge", extra={
                    "error": detail[:500], "attempt": attempts,
                    "timeout_s": float(timeout_s),
                })
            obs_trace.instant(
                "backend_probe_failed", cat="backend",
                error=detail[:200], wedged=wedged, attempt=attempts,
            )
            if attempts > retries:
                break
            if retry_wait_s:
                time.sleep(retry_wait_s)
        self._m_healthy.set(1.0 if report["ok"] else 0.0)
        obs_trace.instant(
            "backend_probe", cat="backend",
            ok=report["ok"], latency_s=report["latency_s"],
            attempts=attempts,
        )
        with self._lock:
            self._last = dict(report)
        return report

    def record_fallback(self, reason: str) -> None:
        """The caller is abandoning the default backend for CPU — the
        event every BENCH_r* record buried in `fallback_from`."""
        self._m_fallbacks.inc()
        self._m_healthy.set(0.0)
        obs_trace.instant(
            "backend_fallback", cat="backend", reason=reason[:500]
        )
        with self._lock:
            if self._last is not None:
                self._last["fallback"] = True

    def last(self) -> dict | None:
        with self._lock:
            return dict(self._last) if self._last else None


_singleton: BackendHealth | None = None
_singleton_lock = threading.Lock()


def shared() -> BackendHealth:
    """The process-wide BackendHealth (bench.py + CLI entry points)."""
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            _singleton = BackendHealth()
        return _singleton


def probe_backend(timeout_s: float = 60.0) -> tuple[bool, str]:
    """Drop-in for `core.backend.probe_default_backend(t, use_cache=False)`
    that also lands the attempt in the `backend/*` metrics — bench.py's
    probe-gated retry loop routes through here so every probe that
    sampled the window is on the observable record, not only in a
    concatenated error string."""
    report = shared().probe(timeout_s)
    if report["ok"]:
        return True, report.get("platform", "unknown")
    return False, report.get("error", "probe failed")


def record_fallback(reason: str) -> None:
    shared().record_fallback(reason)


def summary() -> dict:
    """Snapshot of the backend/* counters + the newest probe report —
    what a CPU-fallback bench record embeds as `backend_health`."""
    snap = obs_metrics.REGISTRY.snapshot()
    out = {
        k[len("backend/"):]: v
        for k, v in snap.items()
        if k.startswith("backend/")
    }
    last = shared().last()
    if last is not None:
        out["last_probe"] = last
    return out
