"""Declarative alert engine over the fleet's SLO + registry signals
(docs/alerts.md).

docs/slo.md shipped starter alert RULES as prose; this module makes
them executable. An `AlertEngine` holds a rule catalog and is driven on
a cadence (the router's poll loop when `fleet.alerts` is on, or the
standalone `deepdfa-tpu alerts` CLI replaying a fleet_log). Every state
transition (pending -> firing -> resolved) is emitted as a schema-valid
`{"alert": ...}` fleet_log record carrying the rule, window, observed
value, and threshold — alerts are evidence, not just paging.

Rule kinds:

  burn_rate       multi-window burn rate on an SLO error budget
                  (Google SRE workbook shape): the engine keeps its own
                  windowed error/total counts per configured window and
                  the condition holds only when EVERY window's
                  error_rate/budget exceeds the threshold — the fast
                  window gives detection speed, the slow window keeps a
                  brief blip from paging.
  slo_p99         a window's p99 latency (from the SLO snapshot signal)
                  above a millisecond threshold.
  gauge_above     any registry gauge/counter value above a threshold
                  (queue saturation, autoscale at max).
  counter_rate    windowed INCREASE of a (fnmatch pattern of) counter(s)
                  above a threshold — coord faults, poll exhaustion.
  drift           per-tenant calibration drift, reusing PR 12's
                  temperature/band machinery (ROADMAP 4a): calibrated
                  in-band fraction drifting away from the fitted target
                  escalation by more than the threshold.
  escalation_rate per-tenant in-band (escalate-to-expensive-model)
                  fraction above a threshold.

The engine is clock-injectable and purely synchronous — evaluation
happens only inside `evaluate()`, so tests and log replay drive it
deterministically.
"""

from __future__ import annotations

import fnmatch
import json
import logging
from collections import deque
from dataclasses import dataclass, field

from deepdfa_tpu.obs import metrics as obs_metrics
from deepdfa_tpu.obs.slo import WindowedCounts, WindowedSamples

logger = logging.getLogger(__name__)

ALERT_STATES = ("pending", "firing", "resolved")

#: tenant label used when a request carries none
DEFAULT_TENANT = "default"


@dataclass
class AlertRule:
    """One declarative rule. `windows` are seconds; `for_s` is how long
    the condition must hold before pending promotes to firing (0 =
    immediately). `params` carries kind-specific knobs (budget, key,
    pattern, tenant, temperature, band, target, min_samples)."""

    name: str
    kind: str
    threshold: float
    for_s: float = 0.0
    windows: tuple = (60.0, 300.0)
    params: dict = field(default_factory=dict)

    def window_label(self) -> str:
        return "+".join(f"{int(w)}s" for w in self.windows)


class _ExactCounts:
    """Exact per-event counter for SHORT alert windows. WindowedCounts
    buckets per second and evicts a bucket once its INTEGER second
    falls behind the horizon — correct for the SLO engine's 60 s+
    windows, but a sub-5 s burn window would evict its own live second
    partway through. Event-timestamp storage is exact at any horizon;
    fine here because short windows hold few events by construction."""

    __slots__ = ("horizon_s", "_t")

    def __init__(self, horizon_s: float):
        self.horizon_s = float(horizon_s)
        self._t: deque[float] = deque()

    def observe(self, now: float) -> None:
        self._t.append(now)

    def total(self, now: float) -> int:
        cutoff = now - self.horizon_s
        while self._t and self._t[0] < cutoff:
            self._t.popleft()
        return len(self._t)


def _window_counts(horizon_s: float):
    return (
        _ExactCounts(horizon_s) if horizon_s < 5.0
        else WindowedCounts(horizon_s)
    )


class _WindowSum:
    """Windowed sum of observed increments (for counter_rate rules)."""

    def __init__(self, horizon_s: float):
        self.horizon_s = float(horizon_s)
        self._events: list[tuple[float, float]] = []

    def observe(self, amount: float, now: float) -> None:
        self._events.append((now, float(amount)))

    def total(self, now: float) -> float:
        cutoff = now - self.horizon_s
        self._events = [e for e in self._events if e[0] >= cutoff]
        return sum(a for _, a in self._events)


class _RuleState:
    __slots__ = (
        "rule", "state", "pending_since", "err", "tot", "probs",
        "last_counter", "window_sum",
    )

    def __init__(self, rule: AlertRule):
        self.rule = rule
        self.state = "inactive"
        self.pending_since: float | None = None
        # burn_rate: own windowed error/total counts per window
        self.err = {w: _window_counts(w) for w in rule.windows}
        self.tot = {w: _window_counts(w) for w in rule.windows}
        # drift / escalation_rate: windowed per-tenant probs
        self.probs = WindowedSamples(
            max(rule.windows), max_samples=4096
        )
        # counter_rate: last seen absolute value + windowed increments
        self.last_counter: float | None = None
        self.window_sum = _WindowSum(max(rule.windows))


def _calibrated_in_band_fraction(
    probs, temperature: float, band
) -> float | None:
    """Fraction of (temperature-scaled) probs inside the escalation
    band — PR 12's machinery, imported lazily so the engine stays
    numpy-free until a drift rule actually evaluates."""
    if not probs:
        return None
    import numpy as np

    from deepdfa_tpu.eval.calibrate import in_band, temperature_scale

    arr = np.asarray(list(probs), dtype=np.float64)
    scaled = temperature_scale(arr, float(temperature))
    lo, hi = band
    return float(np.mean([in_band(float(p), (lo, hi)) for p in scaled]))


class AlertEngine:
    """Evaluate a rule catalog against fed signals; emit transition
    records.

    Request-level signals arrive via `observe_request` (status, tenant,
    calibrated prob); snapshot-level signals (SLO windows, registry
    counters/gauges) arrive as the `signals` dict at `evaluate` time:

        {"slo": <SloEngine.snapshot()>, "counters": {...},
         "gauges": {...}}

    `sink` (optional) is a callable receiving each transition record —
    the router passes its FleetLog.append."""

    def __init__(self, rules, clock=None, sink=None):
        import time

        self.clock = clock if clock is not None else time.time
        self.sink = sink
        self._states = {r.name: _RuleState(r) for r in rules}
        r = obs_metrics.REGISTRY
        self._m_evals = r.counter("alert/evaluations")
        self._m_transitions = r.counter("alert/transitions")
        self._m_firing = r.gauge("alert/firing")

    @property
    def rules(self) -> list[AlertRule]:
        return [s.rule for s in self._states.values()]

    # -- signal feed ---------------------------------------------------------

    def observe_request(
        self,
        status: int,
        tenant: str | None = None,
        prob: float | None = None,
        now: float | None = None,
    ) -> None:
        now = self.clock() if now is None else now
        tenant = tenant or DEFAULT_TENANT
        err = not (200 <= int(status) < 300)
        for st in self._states.values():
            rule = st.rule
            if rule.kind == "burn_rate":
                for w in rule.windows:
                    st.tot[w].observe(now)
                    if err:
                        st.err[w].observe(now)
            elif rule.kind in ("drift", "escalation_rate"):
                if (
                    prob is not None
                    and rule.params.get("tenant", tenant) == tenant
                ):
                    st.probs.observe(float(prob), now)

    # -- evaluation ----------------------------------------------------------

    def _condition(
        self, st: _RuleState, signals: dict, now: float
    ) -> tuple[bool, float | None]:
        """(holds, observed value) for one rule against the signals."""
        rule = st.rule
        if rule.kind == "burn_rate":
            budget = float(rule.params.get("budget", 0.01))
            burns = []
            for w in rule.windows:
                total = st.tot[w].total(now)
                min_count = int(rule.params.get("min_count", 1))
                if total < min_count:
                    return False, None
                burns.append(
                    (st.err[w].total(now) / total) / max(budget, 1e-12)
                )
            observed = min(burns)  # the binding (slowest) window
            return observed > rule.threshold, observed
        if rule.kind == "slo_p99":
            slo = signals.get("slo") or {}
            wlabel = rule.params.get(
                "window", f"{int(rule.windows[0])}s"
            )
            stage = rule.params.get("stage", "total")
            view = slo.get(wlabel)
            if not isinstance(view, dict):
                return False, None
            lat = (view.get("latency_ms") or {}).get(stage) or {}
            p99 = lat.get("p99")
            if p99 is None:
                return False, None
            return float(p99) > rule.threshold, float(p99)
        if rule.kind == "gauge_above":
            key = rule.params.get("key", "")
            gauges = signals.get("gauges") or {}
            counters = signals.get("counters") or {}
            v = gauges.get(key, counters.get(key))
            if v is None:
                return False, None
            return float(v) > rule.threshold, float(v)
        if rule.kind == "counter_rate":
            pattern = rule.params.get("pattern", "")
            counters = signals.get("counters") or {}
            current = sum(
                float(v) for k, v in counters.items()
                if fnmatch.fnmatch(k, pattern)
            )
            if st.last_counter is None:
                st.last_counter = current
                return False, None
            delta = current - st.last_counter
            st.last_counter = current
            if delta > 0:
                st.window_sum.observe(delta, now)
            observed = st.window_sum.total(now)
            return observed > rule.threshold, observed
        if rule.kind in ("drift", "escalation_rate"):
            min_samples = int(rule.params.get("min_samples", 20))
            probs = st.probs.values(now)
            if len(probs) < min_samples:
                return False, None
            frac = _calibrated_in_band_fraction(
                probs,
                rule.params.get("temperature", 1.0),
                tuple(rule.params.get("band", (0.35, 0.65))),
            )
            if frac is None:
                return False, None
            if rule.kind == "escalation_rate":
                return frac > rule.threshold, frac
            target = float(rule.params.get("target", 0.1))
            observed = abs(frac - target)
            return observed > rule.threshold, observed
        raise ValueError(f"unknown alert rule kind: {rule.kind!r}")

    def _record(
        self, st: _RuleState, state: str, observed, now: float
    ) -> dict:
        rule = st.rule
        body = {
            "rule": rule.name,
            "state": state,
            "kind": rule.kind,
            "window": rule.window_label(),
            "observed": (
                None if observed is None else round(float(observed), 6)
            ),
            "threshold": float(rule.threshold),
            "for_s": float(rule.for_s),
            "t_unix": round(now, 3),
        }
        tenant = rule.params.get("tenant")
        if tenant is not None:
            body["tenant"] = tenant
        return {"alert": body}

    def evaluate(
        self, signals: dict | None = None, now: float | None = None
    ) -> list[dict]:
        """Run every rule's state machine once; returns (and sinks) the
        transition records. pending -> inactive is silent (a blip that
        never held for `for_s` is not worth a log line); every other
        transition is a record."""
        now = self.clock() if now is None else now
        signals = signals or {}
        self._m_evals.inc()
        out: list[dict] = []
        for st in self._states.values():
            try:
                holds, observed = self._condition(st, signals, now)
            except Exception:
                logger.exception(
                    "alert rule %s evaluation failed", st.rule.name
                )
                continue
            if st.state in ("inactive", "resolved"):
                if holds:
                    st.state = "pending"
                    st.pending_since = now
                    out.append(self._record(st, "pending", observed, now))
                else:
                    st.state = "inactive"
            if st.state == "pending":
                if not holds:
                    st.state = "inactive"
                    st.pending_since = None
                elif now - st.pending_since >= st.rule.for_s:
                    st.state = "firing"
                    out.append(self._record(st, "firing", observed, now))
            elif st.state == "firing" and not holds:
                st.state = "resolved"
                st.pending_since = None
                out.append(self._record(st, "resolved", observed, now))
        if out:
            self._m_transitions.inc(len(out))
            if self.sink is not None:
                for rec in out:
                    self.sink(rec)
        self._m_firing.set(
            sum(1 for s in self._states.values() if s.state == "firing")
        )
        return out

    def firing(self) -> list[str]:
        return sorted(
            name for name, s in self._states.items()
            if s.state == "firing"
        )

    def snapshot(self) -> dict:
        return {
            "rules": {
                name: {
                    "state": s.state,
                    "kind": s.rule.kind,
                    "threshold": s.rule.threshold,
                    "window": s.rule.window_label(),
                }
                for name, s in sorted(self._states.items())
            },
            "firing": self.firing(),
        }


def validate_alert_record(rec: dict) -> list[str]:
    """Problems with one {"alert": ...} record (empty = valid) — the
    shape check_obs_schema --fleet-log enforces."""
    problems: list[str] = []
    body = rec.get("alert") if isinstance(rec, dict) else None
    if not isinstance(body, dict):
        return ["not an alert record"]
    if not body.get("rule") or not isinstance(body.get("rule"), str):
        problems.append("alert missing rule name")
    if body.get("state") not in ALERT_STATES:
        problems.append(f"bad alert state: {body.get('state')!r}")
    for key in ("threshold", "t_unix", "for_s"):
        if not isinstance(body.get(key), (int, float)):
            problems.append(f"alert missing/non-numeric {key}")
    if body.get("observed") is not None and not isinstance(
        body.get("observed"), (int, float)
    ):
        problems.append("alert observed is non-numeric")
    if not body.get("window"):
        problems.append("alert missing window")
    return problems


# ---------------------------------------------------------------------------
# rule catalog

def default_rules() -> list[AlertRule]:
    """docs/slo.md's starter rules, executable, plus the coord/autoscale
    watches the fleet grew since. Per-tenant drift/escalation rules are
    deployment-specific (they need a fitted temperature + band) and are
    added via `fleet.alert_rules` JSON — see docs/alerts.md."""
    return [
        # error budget 5% (docs/slo.md availability target 99.9% is the
        # aspiration; the starter rule pages at 5% error rate) — fast
        # window for detection, slow window to ride out blips
        AlertRule(
            name="serve_high_error_rate", kind="burn_rate",
            threshold=1.0, for_s=0.0, windows=(60.0, 300.0),
            params={"budget": 0.05, "min_count": 5},
        ),
        AlertRule(
            name="serve_p99_degraded", kind="slo_p99",
            threshold=250.0, for_s=60.0, windows=(300.0,),
            params={"window": "300s", "stage": "total"},
        ),
        AlertRule(
            name="serve_queue_saturated", kind="gauge_above",
            threshold=0.8, for_s=10.0, windows=(60.0,),
            params={"key": "queue_ratio"},
        ),
        AlertRule(
            name="coord_backend_faults", kind="counter_rate",
            threshold=0.0, for_s=0.0, windows=(60.0,),
            params={"pattern": "coord/faults/*"},
        ),
        AlertRule(
            name="coord_poll_exhausted", kind="counter_rate",
            threshold=0.0, for_s=0.0, windows=(300.0,),
            params={"pattern": "coord/poll_exhausted"},
        ),
        AlertRule(
            name="autoscale_saturated", kind="gauge_above",
            threshold=0.0, for_s=30.0, windows=(60.0,),
            params={"key": "autoscale/at_max"},
        ),
        # a flywheel candidate degrading mid-ride: the shadow scorer
        # bumps shadow/regressions whenever a comparison window judges
        # demote-worthy (flywheel/shadow.py), so this fires BEFORE the
        # promotion controller could ever act on stale good windows —
        # and flywheel/promote.py treats the firing rule as an
        # unconditional promotion veto (demotion reason "alert")
        AlertRule(
            name="shadow_regression", kind="counter_rate",
            threshold=0.0, for_s=0.0, windows=(300.0,),
            params={"pattern": "shadow/regressions"},
        ),
    ]


def rule_from_doc(doc: dict) -> AlertRule:
    return AlertRule(
        name=str(doc["name"]),
        kind=str(doc["kind"]),
        threshold=float(doc["threshold"]),
        for_s=float(doc.get("for_s", 0.0)),
        windows=tuple(
            float(w) for w in doc.get("windows", (60.0, 300.0))
        ),
        params=dict(doc.get("params") or {}),
    )


def rules_from_config(cfg) -> list[AlertRule]:
    """Default catalog, overlaid with `cfg.fleet.alert_rules` (a JSON
    list). An entry with a known name REPLACES the default; an entry
    {"name": ..., "disable": true} removes it; new names append — this
    is how a deployment adds its per-tenant drift rules."""
    rules = {r.name: r for r in default_rules()}
    raw = getattr(cfg.fleet, "alert_rules", "") or ""
    if raw.strip():
        docs = json.loads(raw)
        if not isinstance(docs, list):
            raise ValueError("fleet.alert_rules must be a JSON list")
        for doc in docs:
            name = str(doc.get("name", ""))
            if not name:
                raise ValueError(f"alert rule without a name: {doc}")
            if doc.get("disable"):
                rules.pop(name, None)
            else:
                rules[name] = rule_from_doc(doc)
    return list(rules.values())


# ---------------------------------------------------------------------------
# standalone replay (the `deepdfa-tpu alerts` CLI)

def replay_fleet_log(
    path,
    rules=None,
    backend=None,
    interval_s: float = 1.0,
    max_bytes: int = 64 << 20,
) -> dict:
    """Drive an AlertEngine over an existing fleet_log as if the rules
    had been live: request records feed observe_request (status,
    tenant, calibrated prob when the router recorded one), summary
    records provide the SLO/counter signals, and the engine is
    evaluated every `interval_s` of RECORD time (the log's own t_unix
    cursor — replay is deterministic, wall clock never enters)."""
    from deepdfa_tpu.fleet import coord

    backend = backend or coord.LOCAL
    engine_rules = rules if rules is not None else default_rules()
    transitions: list[dict] = []
    # the clock is the log's time cursor, advanced by records
    cursor = {"t": 0.0}
    engine = AlertEngine(engine_rules, clock=lambda: cursor["t"])
    signals: dict = {}
    next_eval = 0.0
    n_records = 0
    for rec in backend.tail_records(path, max_bytes=max_bytes):
        n_records += 1
        if "request" in rec:
            req = rec["request"]
            t = float(req.get("t_unix") or cursor["t"])
            cursor["t"] = max(cursor["t"], t)
            engine.observe_request(
                int(req.get("status", 0)),
                tenant=req.get("tenant"),
                prob=req.get("prob"),
                now=cursor["t"],
            )
        elif "fleet" in rec or "fleet_slo" in rec:
            signals = {
                "slo": rec.get("fleet_slo") or signals.get("slo") or {},
                "counters": rec.get("fleet") or signals.get(
                    "counters"
                ) or {},
                "gauges": rec.get("fleet") or {},
            }
        elif "alert" in rec:
            continue  # don't re-alert on alerts
        if next_eval == 0.0:
            next_eval = cursor["t"] + float(interval_s)
        while cursor["t"] >= next_eval:
            transitions.extend(
                engine.evaluate(signals, now=next_eval)
            )
            next_eval += float(interval_s)
    transitions.extend(engine.evaluate(signals, now=cursor["t"]))
    return {
        "records": n_records,
        "transitions": transitions,
        "fired": sorted({
            t["alert"]["rule"] for t in transitions
            if t["alert"]["state"] == "firing"
        }),
        "resolved": sorted({
            t["alert"]["rule"] for t in transitions
            if t["alert"]["state"] == "resolved"
        }),
        "firing": engine.firing(),
    }
