"""Fleet-wide metrics federation + cross-host trace stitching
(docs/observability.md, docs/fleet.md).

Every observability surface before this one was per-process: the SLO
engine answers for ONE router or replica, the trace merge reads ONE
shared directory, diag rebuilds percentiles from locally-readable
serve logs. This module makes the fleet observable when the processes
never share a disk, by carrying everything over the coordination
backend (fleet/coord.py):

  metrics federation   each replica periodically publishes a schema-
                       validated snapshot (SnapshotPublisher) as a coord
                       doc: registry counters/gauges, SLO window views,
                       and the windowed latency SAMPLES re-encoded as
                       fixed-bucket mergeable histograms. The router's
                       FleetAggregator collects the snapshots and serves
                       a fleet-level /metrics with `replica=` labels
                       plus merged families.
  exact merge          all histograms share ONE fixed log-spaced bucket
                       grid, so merging is count addition and the merged
                       percentile EQUALS the percentile of the union of
                       the published sample multisets — zero merge error,
                       unlike averaging per-replica percentiles (which
                       has no defensible semantics) or sketches (which
                       approximate). Bucket resolution (~3.1% relative)
                       is the only quantization, applied once at encode.
  staleness            a replica whose newest snapshot ages past the
                       heartbeat window is MARKED stale (its own gauge +
                       the stats section) and still merged — never
                       silently dropped, so an operator sees "r1 went
                       quiet" instead of a fleet p99 that silently lost
                       a replica.
  torn-write safety    snapshots alternate between two doc slots by
                       sequence parity; a torn write (FaultableBackend,
                       or a real crash mid-write) corrupts at most one
                       slot and the reader falls back to the other —
                       plus an in-process cache of the last good
                       snapshot per source, which also rides out
                       backend partitions (aging into staleness rather
                       than vanishing).
  trace stitching      TraceShipper appends this process's Chrome-trace
                       events (plus one wall-clock anchor) to a bounded
                       coord log; stitch_fleet_trace folds every
                       source's segments into one Perfetto timeline —
                       pids remapped per source so same-pid processes on
                       different hosts cannot collide, timestamps
                       shifted onto the shared wall clock via each
                       source's anchor, torn lines skipped per the tail
                       contract. The X-Request-Id flow chain
                       (router_forward "s" -> replica "t"/"f",
                       docs/slo.md) survives the hop because flow events
                       are keyed by request id, not by pid or clock.

Everything defaults OFF (`fleet.telemetry`); the default fleet path
never constructs a publisher or aggregator.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from pathlib import Path

from deepdfa_tpu.fleet import coord
from deepdfa_tpu.obs import metrics as obs_metrics, trace as obs_trace
from deepdfa_tpu.obs.slo import QUANTILES, percentile

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# fixed-bucket mergeable histogram

#: the ONE latency grid every publisher and the aggregator share —
#: log-spaced from 0.1 ms to 600 s. 512 buckets give ~3.1% relative
#: resolution (exp(ln(6e6)/512) - 1), applied once at encode time;
#: merging is exact by construction because the grid is fixed.
HIST_LO = 1e-4
HIST_HI = 600.0
HIST_BUCKETS = 512

_EDGES_CACHE: dict[tuple[float, float, int], tuple[float, ...]] = {}


def bucket_edges(lo: float, hi: float, n: int) -> tuple[float, ...]:
    """Deterministic log-spaced lower edges for an (lo, hi, n) grid —
    recomputed identically on every host, so a snapshot doc only needs
    to carry the three grid parameters, never the edges."""
    key = (float(lo), float(hi), int(n))
    edges = _EDGES_CACHE.get(key)
    if edges is None:
        llo, lhi = math.log(key[0]), math.log(key[1])
        step = (lhi - llo) / key[2]
        edges = tuple(math.exp(llo + step * i) for i in range(key[2]))
        _EDGES_CACHE[key] = edges
    return edges


class FixedBucketHistogram:
    """Mergeable latency histogram on the shared fixed grid.

    `observe` quantizes a value to its bucket's lower edge; `merged`
    adds counts bucket-by-bucket (grids must match — mismatches raise,
    they are a deploy-skew bug, not data). `percentile` applies THE
    repo-wide quantile rule (obs/slo.py:percentile) to the cumulative
    counts, so it equals `percentile(sorted(expanded samples), p)`
    exactly — the property tests/test_fleet_obs.py pins against brute
    force."""

    __slots__ = ("lo", "hi", "n", "_llo", "_step", "counts")

    def __init__(
        self,
        lo: float = HIST_LO,
        hi: float = HIST_HI,
        n: int = HIST_BUCKETS,
    ):
        self.lo = float(lo)
        self.hi = float(hi)
        self.n = int(n)
        if not (self.lo > 0 and self.hi > self.lo and self.n > 0):
            raise ValueError(
                f"bad histogram grid lo={lo} hi={hi} n={n}"
            )
        self._llo = math.log(self.lo)
        self._step = (math.log(self.hi) - self._llo) / self.n
        #: sparse {bucket index: count} — snapshots stay small even on
        #: a 512-bucket grid because a window only touches a few dozen
        self.counts: dict[int, int] = {}

    def grid(self) -> tuple[float, float, int]:
        return (self.lo, self.hi, self.n)

    def bucket_index(self, value: float) -> int:
        v = float(value)
        if not v > self.lo:  # <= lo, zero, negative, NaN -> first bucket
            return 0
        if v >= self.hi:
            return self.n - 1
        i = int((math.log(v) - self._llo) / self._step)
        return min(max(i, 0), self.n - 1)

    def bucket_value(self, index: int) -> float:
        """The bucket's representative (its lower edge) — what a sample
        becomes once encoded."""
        return bucket_edges(self.lo, self.hi, self.n)[index]

    def quantize(self, value: float) -> float:
        return self.bucket_value(self.bucket_index(value))

    def observe(self, value: float) -> None:
        i = self.bucket_index(value)
        self.counts[i] = self.counts.get(i, 0) + 1

    def observe_all(self, values) -> None:
        for v in values:
            self.observe(v)

    def total(self) -> int:
        return sum(self.counts.values())

    def expand(self) -> list[float]:
        """The encoded sample multiset, sorted — the brute-force
        reference the merge property is checked against."""
        edges = bucket_edges(self.lo, self.hi, self.n)
        out: list[float] = []
        for i in sorted(self.counts):
            out.extend([edges[i]] * self.counts[i])
        return out

    def percentile(self, p: float) -> float | None:
        """== slo.percentile(self.expand(), p), computed from cumulative
        counts without expanding."""
        total = self.total()
        if total == 0:
            return None
        target = min(total - 1, int(float(p) * total))
        cum = 0
        edges = bucket_edges(self.lo, self.hi, self.n)
        for i in sorted(self.counts):
            cum += self.counts[i]
            if cum > target:
                return edges[i]
        return edges[max(self.counts)]  # unreachable; defensive

    @classmethod
    def merged(cls, hists) -> "FixedBucketHistogram":
        hists = list(hists)
        if not hists:
            return cls()
        out = cls(*hists[0].grid())
        for h in hists:
            if h.grid() != out.grid():
                raise ValueError(
                    f"cannot merge histograms on different grids: "
                    f"{h.grid()} vs {out.grid()}"
                )
            for i, c in h.counts.items():
                out.counts[i] = out.counts.get(i, 0) + int(c)
        return out

    def to_doc(self) -> dict:
        return {
            "lo": self.lo, "hi": self.hi, "n": self.n,
            "counts": {str(i): c for i, c in sorted(self.counts.items())},
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "FixedBucketHistogram":
        h = cls(doc["lo"], doc["hi"], doc["n"])
        for k, c in (doc.get("counts") or {}).items():
            i = int(k)
            if not 0 <= i < h.n:
                raise ValueError(f"bucket index {i} outside grid n={h.n}")
            h.counts[i] = int(c)
        return h


# ---------------------------------------------------------------------------
# snapshot publication (replica side)

#: snapshot doc name: metrics-<source>-<slot>.json; two slots alternated
#: by sequence parity so a torn write never destroys the only copy
SNAPSHOT_PREFIX = "metrics-"
SNAPSHOT_SLOTS = ("a", "b")


def snapshot_path(fleet_dir: str | Path, source: str, slot: str) -> Path:
    return Path(fleet_dir) / f"{SNAPSHOT_PREFIX}{source}-{slot}.json"


def build_snapshot(
    source: str,
    slo_engines: dict,
    seq: int,
    registry=None,
    now_unix: float | None = None,
) -> dict:
    """One publishable snapshot doc: the registry snapshot, every
    engine's window views, and the windowed latency samples re-encoded
    on the shared histogram grid (merged across co-served engines —
    the fleet latency view is per replica, not per model entry)."""
    r = registry if registry is not None else obs_metrics.REGISTRY
    now_unix = time.time() if now_unix is None else now_unix
    hist: dict[str, dict[str, FixedBucketHistogram]] = {}
    slo_views: dict[str, dict] = {}
    requests_total = 0.0
    for name, engine in sorted(slo_engines.items()):
        slo_views[name] = engine.snapshot()
        requests_total += float(engine.requests_total)
        for wlabel, by_stage in engine.latency_samples().items():
            stages = hist.setdefault(wlabel, {})
            for stage, samples in by_stage.items():
                if not samples:
                    continue
                h = stages.setdefault(stage, FixedBucketHistogram())
                h.observe_all(samples)
    return {"fleet_snapshot": {
        "source": str(source),
        "seq": int(seq),
        "t_unix": round(now_unix, 3),
        # the cross-host clock anchor: unix wall time and the monotonic
        # trace clock sampled back to back, so stitched trace segments
        # from this process can be shifted onto the shared wall axis
        "anchor_unix_us": now_unix * 1e6,
        "anchor_mono_us": obs_trace.Tracer.now_us(),
        "metrics": r.snapshot(),
        "slo": slo_views,
        "requests_total": requests_total,
        "hist": {
            w: {s: h.to_doc() for s, h in sorted(stages.items())}
            for w, stages in sorted(hist.items())
        },
    }}


def validate_snapshot(doc: dict) -> list[str]:
    """Structural + schema problems with one snapshot doc (empty = ok).
    Every registry tag it carries must be SCHEMA-declared — the same
    drift guard the run logs get — and every histogram must parse on a
    sane grid."""
    problems: list[str] = []
    snap = doc.get("fleet_snapshot") if isinstance(doc, dict) else None
    if not isinstance(snap, dict):
        return ["not a fleet_snapshot doc"]
    if not snap.get("source"):
        problems.append("missing source")
    for key in ("t_unix", "seq"):
        if not isinstance(snap.get(key), (int, float)):
            problems.append(f"missing/non-numeric {key}")
    metrics = snap.get("metrics")
    if not isinstance(metrics, dict):
        problems.append("missing metrics dict")
    else:
        for tag, value in metrics.items():
            if not obs_metrics.declared(tag):
                problems.append(f"undeclared metrics tag: {tag}")
            if not isinstance(value, (int, float)):
                problems.append(f"non-numeric metric {tag!r}")
    hist = snap.get("hist") or {}
    if not isinstance(hist, dict):
        problems.append("hist is not a dict")
        hist = {}
    for wlabel, stages in hist.items():
        if not isinstance(stages, dict):
            problems.append(f"hist[{wlabel}] is not a dict")
            continue
        for stage, hdoc in stages.items():
            try:
                FixedBucketHistogram.from_doc(hdoc)
            except (KeyError, TypeError, ValueError) as e:
                problems.append(f"bad histogram {wlabel}/{stage}: {e}")
    return problems


class SnapshotPublisher:
    """Periodic snapshot publication for one replica (or router).

    `slo_engines` is a zero-arg callable returning {name: SloEngine} so
    the publisher follows hot swaps / co-serving changes without being
    rebuilt. Publication failures count (`agg/publish_failures`) and
    log — they never take down the serving loop."""

    def __init__(
        self,
        fleet_dir: str | Path,
        source: str,
        slo_engines,
        backend: coord.CoordinationBackend | None = None,
        interval_s: float = 2.0,
        registry=None,
        clock=time.time,
    ):
        self.fleet_dir = Path(fleet_dir)
        self.source = str(source)
        self.backend = backend or coord.LOCAL
        self.interval_s = float(interval_s)
        self.registry = registry
        self.clock = clock
        self._slo_engines = (
            slo_engines if callable(slo_engines) else (lambda: slo_engines)
        )
        self.seq = 0
        self._next = 0.0
        r = obs_metrics.REGISTRY
        self._m_published = r.counter("agg/snapshots_published")
        self._m_failed = r.counter("agg/publish_failures")

    def publish(self, now: float | None = None) -> Path | None:
        now = self.clock() if now is None else now
        doc = build_snapshot(
            self.source, self._slo_engines(), self.seq,
            registry=self.registry, now_unix=now,
        )
        problems = validate_snapshot(doc)
        if problems:
            # a snapshot that fails its own schema is a bug, not load —
            # loud, counted, and never published half-valid
            self._m_failed.inc()
            logger.error(
                "refusing to publish invalid snapshot for %s: %s",
                self.source, problems[:5],
            )
            return None
        slot = SNAPSHOT_SLOTS[self.seq % len(SNAPSHOT_SLOTS)]
        path = snapshot_path(self.fleet_dir, self.source, slot)
        try:
            self.backend.write_doc(path, json.dumps(doc))
        except OSError:
            self._m_failed.inc()
            logger.exception("snapshot publish failed for %s", self.source)
            return None
        self.seq += 1
        self._m_published.inc()
        return path

    def maybe_publish(self, now: float | None = None) -> bool:
        now = self.clock() if now is None else now
        if now < self._next:
            return False
        self._next = now + self.interval_s
        return self.publish(now=now) is not None


# ---------------------------------------------------------------------------
# fleet aggregation (router side)


def _fmt(v: float) -> str:
    """Exposition float that round-trips exactly through float() — the
    merged-percentile exactness contract must survive the scrape."""
    return repr(float(v))


class FleetAggregator:
    """Collect + merge the published snapshots for the fleet /metrics
    and /stats surfaces.

    Per source, the newest parseable+valid slot wins; a source whose
    both slots are torn/unreadable falls back to the in-process cache
    of its last good snapshot (so a torn write or a backend partition
    ages a replica into staleness instead of vanishing it). Staleness =
    snapshot age past `stale_after_s` (the heartbeat window by
    default): marked, counted, still merged."""

    def __init__(
        self,
        fleet_dir: str | Path,
        backend: coord.CoordinationBackend | None = None,
        stale_after_s: float = 10.0,
        clock=time.time,
    ):
        self.fleet_dir = Path(fleet_dir)
        self.backend = backend or coord.LOCAL
        self.stale_after_s = float(stale_after_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._cache: dict[str, dict] = {}  # source -> last good snapshot
        r = obs_metrics.REGISTRY
        self._m_collects = r.counter("agg/collects")
        self._m_failures = r.counter("agg/collect_failures")
        self._m_stale = r.gauge("agg/stale_replicas")
        self._m_replicas = r.gauge("agg/replicas")

    def _read_slots(self) -> tuple[dict[str, dict], list[str]]:
        """{source: best snapshot body} + problems, newest valid slot
        per source (torn or invalid slots are skipped with a note)."""
        best: dict[str, dict] = {}
        problems: list[str] = []
        try:
            paths = self.backend.scan(
                self.fleet_dir, f"{SNAPSHOT_PREFIX}*.json"
            )
        except OSError as e:
            self._m_failures.inc()
            return {}, [f"snapshot scan failed: {e}"]
        for path in paths:
            stem = Path(path).name[len(SNAPSHOT_PREFIX):-len(".json")]
            source = stem.rsplit("-", 1)[0] if "-" in stem else stem
            try:
                doc = json.loads(self.backend.read_doc(path))
            except (OSError, json.JSONDecodeError) as e:
                # a torn slot: the OTHER slot (or the cache) covers it
                problems.append(f"unreadable slot {Path(path).name}: {e}")
                continue
            if validate_snapshot(doc):
                problems.append(f"invalid snapshot in {Path(path).name}")
                continue
            snap = doc["fleet_snapshot"]
            prev = best.get(source)
            if prev is None or (
                (snap["t_unix"], snap["seq"])
                > (prev["t_unix"], prev["seq"])
            ):
                best[source] = snap
        return best, problems

    def collect(self, now: float | None = None) -> dict:
        """The aggregated fleet view: per-source snapshot + age + stale
        flag, merged histograms per (window, stage), and the problems
        the read surfaced (never raising past a fault)."""
        now = self.clock() if now is None else now
        self._m_collects.inc()
        fresh, problems = self._read_slots()
        with self._lock:
            self._cache.update(fresh)
            snapshots = dict(self._cache)
        replicas: dict[str, dict] = {}
        merged: dict[str, dict[str, FixedBucketHistogram]] = {}
        for source, snap in sorted(snapshots.items()):
            age = max(0.0, now - float(snap["t_unix"]))
            stale = age > self.stale_after_s
            replicas[source] = {
                "snapshot": snap,
                "age_s": round(age, 3),
                "stale": stale,
                "cached": source not in fresh,
            }
            for wlabel, stages in (snap.get("hist") or {}).items():
                out_stages = merged.setdefault(wlabel, {})
                for stage, hdoc in stages.items():
                    h = FixedBucketHistogram.from_doc(hdoc)
                    cur = out_stages.get(stage)
                    out_stages[stage] = (
                        h if cur is None
                        else FixedBucketHistogram.merged([cur, h])
                    )
        n_stale = sum(1 for r in replicas.values() if r["stale"])
        self._m_replicas.set(len(replicas))
        self._m_stale.set(n_stale)
        return {
            "replicas": replicas,
            "merged_hist": merged,
            "stale": sorted(
                s for s, r in replicas.items() if r["stale"]
            ),
            "problems": problems,
        }

    # -- render --------------------------------------------------------------

    @staticmethod
    def _status_totals(snap: dict) -> dict[str, dict[str, int]]:
        """{window: {status: count}} summed across the snapshot's
        engines."""
        out: dict[str, dict[str, int]] = {}
        for view in (snap.get("slo") or {}).values():
            for wlabel, wview in view.items():
                if not isinstance(wview, dict):
                    continue
                counts = wview.get("status") or {}
                agg = out.setdefault(wlabel, {})
                for code, c in counts.items():
                    agg[code] = agg.get(code, 0) + int(c)
        return out

    def exposition(
        self, collected: dict | None = None, now: float | None = None
    ) -> str:
        """The fleet half of the router's /metrics: per-replica families
        labeled `replica="<id>"` plus exact merged families labeled
        `replica="fleet"`, staleness gauges included. Values are printed
        via repr so the merged percentiles survive the scrape parse
        bit-exactly."""
        collected = self.collect(now=now) if collected is None else collected
        replicas = collected["replicas"]
        lines: list[str] = []

        def family(name: str, tag: str, kind: str) -> None:
            lines.append(f"# HELP {name} tag={tag}")
            lines.append(f"# TYPE {name} {kind}")

        name = "deepdfa_fleet_agg_latency_ms"
        family(name, "agg/latency_ms", "gauge")

        def latency_lines(rid: str, hists: dict) -> None:
            for wlabel, stages in sorted(hists.items()):
                for stage, h in sorted(stages.items()):
                    for q in QUANTILES:
                        v = h.percentile(q)
                        if v is None:
                            continue
                        lines.append(
                            f'{name}{{replica="{rid}",window="{wlabel}",'
                            f'stage="{stage}",quantile="{q}"}} '
                            f"{_fmt(v * 1e3)}"
                        )

        latency_lines("fleet", collected["merged_hist"])
        for rid, rep in sorted(replicas.items()):
            latency_lines(rid, {
                w: {
                    s: FixedBucketHistogram.from_doc(d)
                    for s, d in stages.items()
                }
                for w, stages in (
                    rep["snapshot"].get("hist") or {}
                ).items()
            })

        name = "deepdfa_fleet_agg_requests_total"
        family(name, "agg/requests", "counter")
        fleet_requests = 0.0
        for rid, rep in sorted(replicas.items()):
            v = float(rep["snapshot"].get("requests_total") or 0.0)
            fleet_requests += v
            lines.append(f'{name}{{replica="{rid}"}} {v:g}')
        lines.append(f'{name}{{replica="fleet"}} {fleet_requests:g}')

        name = "deepdfa_fleet_agg_error_rate"
        family(name, "agg/error_rate", "gauge")
        fleet_counts: dict[str, dict[str, int]] = {}
        for rid, rep in sorted(replicas.items()):
            by_window = self._status_totals(rep["snapshot"])
            for wlabel, counts in sorted(by_window.items()):
                total = sum(counts.values())
                if not total:
                    continue
                errors = sum(
                    c for code, c in counts.items()
                    if not code.startswith("2")
                )
                lines.append(
                    f'{name}{{replica="{rid}",window="{wlabel}"}} '
                    f"{_fmt(errors / total)}"
                )
                agg = fleet_counts.setdefault(wlabel, {})
                for code, c in counts.items():
                    agg[code] = agg.get(code, 0) + c
        for wlabel, counts in sorted(fleet_counts.items()):
            total = sum(counts.values())
            errors = sum(
                c for code, c in counts.items()
                if not code.startswith("2")
            )
            lines.append(
                f'{name}{{replica="fleet",window="{wlabel}"}} '
                f"{_fmt(errors / total)}"
            )

        name = "deepdfa_fleet_replica_stale"
        family(name, "agg/stale", "gauge")
        for rid, rep in sorted(replicas.items()):
            lines.append(
                f'{name}{{replica="{rid}"}} {1 if rep["stale"] else 0}'
            )
        name = "deepdfa_fleet_snapshot_age_s"
        family(name, "agg/snapshot_age_s", "gauge")
        for rid, rep in sorted(replicas.items()):
            lines.append(f'{name}{{replica="{rid}"}} {rep["age_s"]:g}')
        name = "deepdfa_fleet_agg_replicas"
        family(name, "agg/replicas", "gauge")
        lines.append(f"{name} {len(replicas)}")
        name = "deepdfa_fleet_agg_stale_replicas"
        family(name, "agg/stale_replicas", "gauge")
        lines.append(f"{name} {len(collected['stale'])}")
        return "\n".join(lines) + "\n"

    def stats_section(
        self, collected: dict | None = None, now: float | None = None
    ) -> dict:
        """The /stats `fleet_telemetry` section: per-replica snapshot
        metadata + the merged window quantiles (JSON keeps full float
        precision, so this carries the same exact merged percentiles the
        scrape does)."""
        collected = self.collect(now=now) if collected is None else collected
        merged = {
            wlabel: {
                stage: {
                    f"p{int(q * 100)}_ms": (
                        None if h.percentile(q) is None
                        else h.percentile(q) * 1e3
                    )
                    for q in QUANTILES
                } | {"count": h.total()}
                for stage, h in sorted(stages.items())
            }
            for wlabel, stages in sorted(collected["merged_hist"].items())
        }
        return {
            "replicas": {
                rid: {
                    "t_unix": rep["snapshot"]["t_unix"],
                    "seq": rep["snapshot"]["seq"],
                    "age_s": rep["age_s"],
                    "stale": rep["stale"],
                    "cached": rep["cached"],
                    "requests_total": rep["snapshot"].get(
                        "requests_total"
                    ),
                }
                for rid, rep in sorted(collected["replicas"].items())
            },
            "merged_latency": merged,
            "stale": collected["stale"],
            "problems": collected["problems"],
        }


def validate_fleet_scrape(text: str) -> dict:
    """`check_obs_schema --fleet-metrics`: every family SCHEMA-declared,
    the merged-histogram family present with a replica="fleet" series,
    per-replica labels on every per-replica family, staleness markers
    present for every replica the scrape names."""
    from deepdfa_tpu.obs.slo import parse_exposition

    problems: list[str] = []
    try:
        families = parse_exposition(text)
    except ValueError as e:
        return {"ok": False, "problems": [str(e)], "families": 0}
    replicas: set[str] = set()
    import re

    replica_re = re.compile(r'replica="([^"]+)"')
    for fam_name, fam in sorted(families.items()):
        tag = fam.get("tag")
        if not tag:
            problems.append(f"{fam_name}: no tag= HELP annotation")
        elif not (
            obs_metrics.declared(tag)
            or obs_metrics.declared(f"{tag}/count")
        ):
            problems.append(f"{fam_name}: tag {tag!r} not in SCHEMA")
        if fam_name.startswith("deepdfa_fleet_agg_") and fam_name not in (
            "deepdfa_fleet_agg_replicas",
            "deepdfa_fleet_agg_stale_replicas",
        ):
            for labels, _ in fam["samples"]:
                m = replica_re.search(labels)
                if m is None:
                    problems.append(
                        f"{fam_name}: sample without replica= label"
                    )
                elif m.group(1) != "fleet":
                    replicas.add(m.group(1))
    lat = families.get("deepdfa_fleet_agg_latency_ms")
    if lat is None:
        problems.append("no deepdfa_fleet_agg_latency_ms family")
    elif not any(
        'replica="fleet"' in labels for labels, _ in lat["samples"]
    ):
        problems.append("no merged (replica=\"fleet\") latency series")
    stale = families.get("deepdfa_fleet_replica_stale")
    stale_replicas = set()
    if stale is not None:
        for labels, _ in stale["samples"]:
            m = replica_re.search(labels)
            if m is not None:
                stale_replicas.add(m.group(1))
    missing = sorted(replicas - stale_replicas)
    if replicas and stale is None:
        problems.append("no deepdfa_fleet_replica_stale family")
    elif missing:
        problems.append(
            f"replicas without staleness markers: {missing}"
        )
    return {
        "ok": not problems,
        "problems": problems,
        "families": len(families),
        "replicas": sorted(replicas),
    }


# ---------------------------------------------------------------------------
# cross-host trace shipping + stitching

#: trace segment log name per source (an append-only coord log; the
#: backend's torn-tolerant tail is the read side)
TRACE_SEG_PREFIX = "trace-seg-"


def trace_segment_path(fleet_dir: str | Path, source: str) -> Path:
    return Path(fleet_dir) / f"{TRACE_SEG_PREFIX}{source}.jsonl"


class TraceShipper:
    """Ship this process's Chrome-trace events through the backend.

    Reads the (already flushed) local trace file incrementally and
    appends complete lines to the source's coord log, preceded by ONE
    wall-clock anchor record ({unix_us, mono_us} sampled back to back)
    so the stitcher can place the events on the shared wall axis. The
    ship volume is bounded (`max_segment_bytes`); past the bound the
    shipper stops and counts the truncation — fleet telemetry must
    never become an unbounded trace mirror."""

    def __init__(
        self,
        fleet_dir: str | Path,
        source: str,
        backend: coord.CoordinationBackend | None = None,
        tracer: obs_trace.Tracer | None = None,
        interval_s: float = 2.0,
        max_segment_bytes: int = 4 << 20,
    ):
        self.fleet_dir = Path(fleet_dir)
        self.source = str(source)
        self.backend = backend or coord.LOCAL
        self.tracer = tracer  # None -> the module-level tracer
        self.interval_s = float(interval_s)
        self.max_segment_bytes = int(max_segment_bytes)
        self._offset = 0
        self._shipped_bytes = 0
        self._handle = None
        self._anchored = False
        self._next = 0.0
        r = obs_metrics.REGISTRY
        self._m_events = r.counter("agg/trace_events_shipped")
        self._m_truncated = r.counter("agg/trace_ship_truncated")

    def _trace_path(self) -> Path | None:
        if self.tracer is not None:
            self.tracer.flush()
            return self.tracer.path
        return obs_trace.current_trace_path()

    def ship(self) -> int:
        """Append every new complete trace line; returns events shipped.
        OSErrors count and log (the backend may be partitioned) — never
        raised into the serving loop."""
        path = self._trace_path()
        if path is None:
            return 0
        if self._shipped_bytes >= self.max_segment_bytes:
            return 0
        try:
            with path.open("rb") as f:
                f.seek(self._offset)
                chunk = f.read(
                    self.max_segment_bytes - self._shipped_bytes
                )
        except OSError:
            return 0
        if not chunk:
            return 0
        # only complete lines ship; a partial tail stays for next time
        end = chunk.rfind(b"\n")
        if end < 0:
            return 0
        chunk = chunk[: end + 1]
        try:
            if self._handle is None:
                self._handle = self.backend.open_log(
                    trace_segment_path(self.fleet_dir, self.source)
                )
            if not self._anchored:
                now_unix = time.time()
                self._handle.write_line(json.dumps({"trace_anchor": {
                    "source": self.source,
                    "pid": os.getpid(),
                    "unix_us": now_unix * 1e6,
                    "mono_us": obs_trace.Tracer.now_us(),
                }}))
                self._anchored = True
            shipped = 0
            for raw in chunk.split(b"\n"):
                if not raw.strip():
                    continue
                self._handle.write_line(raw.decode("utf-8", "replace"))
                shipped += 1
        except OSError:
            logger.exception("trace ship failed for %s", self.source)
            return 0
        self._offset += len(chunk)
        self._shipped_bytes += len(chunk)
        if self._shipped_bytes >= self.max_segment_bytes:
            self._m_truncated.inc()
            logger.warning(
                "trace shipping for %s hit the %d-byte bound; further "
                "events stay local only", self.source,
                self.max_segment_bytes,
            )
        self._m_events.inc(shipped)
        return shipped

    def maybe_ship(self, now: float | None = None) -> int:
        now = time.monotonic() if now is None else now
        if now < self._next:
            return 0
        self._next = now + self.interval_s
        return self.ship()

    def close(self) -> None:
        try:
            self.ship()
        finally:
            if self._handle is not None and not self._handle.closed:
                self._handle.close()
                self._handle = None


def read_trace_segments(
    fleet_dir: str | Path,
    backend: coord.CoordinationBackend | None = None,
    max_bytes_per_source: int = 8 << 20,
) -> dict[str, dict]:
    """{source: {"anchor": {...} | None, "events": [...]}} from every
    shipped segment log — bounded tail per source, torn/unparseable
    lines skipped (the FaultableBackend torn-write contract)."""
    backend = backend or coord.LOCAL
    fleet_dir = Path(fleet_dir)
    out: dict[str, dict] = {}
    try:
        paths = backend.scan(fleet_dir, f"{TRACE_SEG_PREFIX}*.jsonl")
    except OSError:
        return out
    for path in paths:
        source = Path(path).name[
            len(TRACE_SEG_PREFIX):-len(".jsonl")
        ]
        try:
            lines = backend.tail(path, max_bytes_per_source)
        except OSError:
            continue
        anchor = None
        events: list[dict] = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn line: skip per the tail contract
            if not isinstance(rec, dict):
                continue
            if "trace_anchor" in rec:
                anchor = rec["trace_anchor"]
            elif "ph" in rec:
                events.append(rec)
        out[source] = {"anchor": anchor, "events": events}
    return out


def stitch_events(segments: dict[str, dict]) -> tuple[list[dict], dict]:
    """Fold per-source segments into one event list on a shared
    timeline: pids remapped per (source, original pid) so same-pid
    processes from different hosts cannot collide, timestamps shifted
    by each source's anchor (unix_us - mono_us) onto the wall clock,
    process_name metadata prefixed with the source id. Sources without
    an anchor stay on their own clock and are flagged."""
    events: list[dict] = []
    summary: dict = {"sources": {}, "unanchored": []}
    pid_map: dict[tuple[str, int], int] = {}
    named_pids: set[int] = set()
    next_pid = 1

    def synth_pid(source: str, pid: int) -> int:
        nonlocal next_pid
        key = (source, int(pid))
        p = pid_map.get(key)
        if p is None:
            p = pid_map[key] = next_pid
            next_pid += 1
        return p

    for source, seg in sorted(segments.items()):
        anchor = seg.get("anchor")
        shift = 0.0
        if anchor is not None:
            shift = float(anchor["unix_us"]) - float(anchor["mono_us"])
        else:
            summary["unanchored"].append(source)
        n = 0
        for ev in seg.get("events", ()):
            ev = dict(ev)
            pid = synth_pid(source, ev.get("pid", 0))
            ev["pid"] = pid
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    args = dict(ev.get("args") or {})
                    args["name"] = f"{source}:{args.get('name', '?')}"
                    ev["args"] = args
                    named_pids.add(pid)
            else:
                ev["ts"] = float(ev.get("ts", 0.0)) + shift
            events.append(ev)
            n += 1
        summary["sources"][source] = n
    # a segment whose process_name metadata was torn away still labels
    for (source, _), pid in sorted(pid_map.items()):
        if pid not in named_pids:
            events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "tid": 0, "ts": 0, "args": {"name": source},
            })
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return events, summary


def flow_chains(events) -> dict[str, dict]:
    """{flow id: {"phases": [...], "pids": [...], "unbroken": bool}} for
    every flow event chain in a stitched event list. Unbroken = the
    chain starts ("s") and arrives ("t" or "f") with the arrival on a
    DIFFERENT process than the start — the router->replica hop the
    X-Request-Id contract promises."""
    chains: dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") not in ("s", "t", "f"):
            continue
        fid = str(ev.get("id"))
        c = chains.setdefault(fid, {"phases": [], "pids": []})
        c["phases"].append(ev["ph"])
        pid = ev.get("pid")
        if pid not in c["pids"]:
            c["pids"].append(pid)
    for c in chains.values():
        c["unbroken"] = (
            "s" in c["phases"]
            and any(p in c["phases"] for p in ("t", "f"))
            and len(c["pids"]) >= 2
        )
    return chains


def stitch_fleet_trace(
    fleet_dir: str | Path,
    out_path: str | Path,
    backend: coord.CoordinationBackend | None = None,
    local_trace_dirs=(),
    max_bytes_per_source: int = 8 << 20,
) -> dict:
    """One Perfetto-loadable timeline from every shipped segment (plus
    optional locally-readable trace dirs, kept on their own clock and
    flagged unanchored). Returns the stitch summary incl. the flow-chain
    census `diag --fleet` reports."""
    segments = read_trace_segments(
        fleet_dir, backend=backend,
        max_bytes_per_source=max_bytes_per_source,
    )
    for d in local_trace_dirs:
        d = Path(d)
        if not d.is_dir():
            continue
        segments[f"local:{d.name}"] = {
            "anchor": None,
            "events": obs_trace.merge(d),
        }
    events, summary = stitch_events(segments)
    chains = flow_chains(events)
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    out_path.write_text(json.dumps(
        {"traceEvents": events, "displayTimeUnit": "ms"}
    ))
    summary.update(
        out=str(out_path),
        events=len(events),
        flows={
            fid: c for fid, c in sorted(chains.items())
        },
        unbroken_flows=sorted(
            fid for fid, c in chains.items() if c["unbroken"]
        ),
        broken_flows=sorted(
            fid for fid, c in chains.items() if not c["unbroken"]
        ),
    )
    return summary
