"""Low-overhead cross-process span/event tracing (Chrome trace format).

One merged timeline for the whole stack: train-loop steps, prefetch
producer threads, spawn-pool packer workers (data/mp_pack.py), and Joern
JVM calls all report here. Each PROCESS appends Chrome-trace events to
its own ``trace-<pid>.jsonl`` under a shared trace directory;
``merge()`` / ``write_chrome_trace()`` fold every per-process file into
one Perfetto/chrome://tracing-loadable timeline. Timestamps come from
``time.monotonic_ns()`` (CLOCK_MONOTONIC on linux — one system-wide
clock), so events from different processes on the same host line up
without any clock handshake.

Cross-process forwarding is environment-based: ``enable(...,
export_env=True)`` publishes the trace directory in
``DEEPDFA_OBS_TRACE_DIR``; any child process (the spawn packer pool, CLI
subprocesses) lazily self-enables on its first span because ``span()``
checks that variable once. No queue, no socket, no pickle of events —
the filesystem is the transport and the merge is offline.

Overhead contract: everything here defaults OFF. A disabled ``span()``
is one module-global load, one flag check, and a shared no-op context
manager — no allocation, no clock read — so the call sites in the train
loops and the input pipeline cost nothing measurable when tracing is
off (bench.py reports the ENABLED cost as ``obs_overhead_fraction``,
bounded at <=2% of step time on the smoke config).

Event vocabulary (``cat`` groups what diag aggregates):

- cat="input":  ``load``/``pack`` (source pulls), ``place`` (H2D),
  ``wait`` (consumer input-starved) — mirrors PipelineStats.
- cat="train":  ``train_step`` (host dispatch), ``step_device``
  (lagged-fetch device window, obs/xprof.py:StepTimer).
- cat="pack_worker": ``pack_plan``/``collate_plan`` in pool workers.
- cat="joern":  ``joern_exchange`` JVM round-trips.
- cat="resilience": instants — ``train_stall``, ``step_skipped``,
  ``rollback``, ``resumed``, ``preempted``.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from pathlib import Path

ENV_TRACE_DIR = "DEEPDFA_OBS_TRACE_DIR"

#: compact separators: measurably cheaper dumps on this box and smaller
#: trace files; Chrome/Perfetto do not care about whitespace
_SEP = (",", ":")


class _NullSpan:
    """Shared no-op context manager returned by a disabled span()."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

#: synthetic tid for the reconstructed device-step track: StepTimer
#: emits deliberately BACKDATED windows (ts = dispatch time, observed at
#: the lagged fetch), which on the emitting thread's own track would be
#: rewritten by the per-thread strictly-increasing nudge below — a
#: separate track keeps them placed at their true dispatch times (and
#: renders as its own "device-steps" lane in the viewer)
DEVICE_TRACK_TID = 2**31 - 2

#: synthetic tid for the serve batcher's BACKDATED queue-wait windows
#: (ts = each request's submit time, observed at flush): on the
#: scheduler thread's own track the per-thread nudge would clamp them
#: forward into the device spans (same hazard StepTimer dodges above);
#: a dedicated track keeps them at their true submit times — requests
#: within a batch are popped FIFO, so their backdated timestamps arrive
#: (near-)sorted and the nudge stays at tie-breaking magnitude
QUEUE_TRACK_TID = 2**31 - 3

_tracer: "Tracer | None" = None
#: True once the env var has been consulted, so a disabled hot path
#: never re-reads os.environ (and an explicit disable() stays disabled)
_env_checked = False
_init_lock = threading.Lock()

#: optional mirror for instant() events — the flight recorder
#: (obs/flight.py) subscribes here so resilience/backend instants reach
#: its bounded ring WHETHER OR NOT tracing is enabled. Instants are rare
#: (stalls, rollbacks, probes), so the extra call costs nothing on the
#: span hot path; when no mirror is set this is one module-global check.
_instant_mirror = None


def set_instant_mirror(fn) -> None:
    global _instant_mirror
    _instant_mirror = fn

_tls = threading.local()


def _native_id() -> int:
    """threading.get_native_id() cached per thread: on older kernels it
    is an uncached gettid() syscall (~13us on this box — measured), which
    at serve-request event rates would dominate the event cost itself."""
    tid = getattr(_tls, "tid", None)
    if tid is None:
        tid = _tls.tid = threading.get_native_id()
    return tid


class Tracer:
    """Per-process event sink: buffered JSONL appends to one file.

    Thread-safe; emits ``process_name``/``thread_name`` metadata events
    (ph="M") the first time a process/thread reports, so merged traces
    are labeled in the viewer. Per-thread timestamps are nudged to be
    strictly increasing (two sub-microsecond events would otherwise tie
    and render order-ambiguously).
    """

    def __init__(
        self,
        directory: str | Path,
        process_name: str | None = None,
        flush_every: int = 64,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.pid = os.getpid()
        self.path = self.directory / f"trace-{self.pid}.jsonl"
        self.flush_every = max(1, int(flush_every))
        self._lock = threading.Lock()
        self._buf: list[str] = []
        self._file = None
        self._seen_tids: set[int] = set()
        self._last_ts: dict[int, float] = {}
        name = process_name or f"pid-{self.pid}"
        self._emit_raw({
            "name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
            "ts": 0, "args": {"name": name},
        })

    @staticmethod
    def now_us() -> float:
        return time.monotonic_ns() / 1000.0

    def _emit_raw(self, event: dict) -> None:
        with self._lock:
            self._buf.append(json.dumps(event, default=str, separators=_SEP))
            if len(self._buf) >= self.flush_every:
                self._flush_locked()

    def emit(self, event: dict, track_name: str | None = None) -> None:
        """`event` may pre-set "tid" to land on a synthetic track (named
        by `track_name`); otherwise the emitting thread's tid is used."""
        tid = event.get("tid")
        if tid is None:
            tid = _native_id()
        event["pid"] = self.pid
        event["tid"] = tid
        with self._lock:
            if tid not in self._seen_tids:
                self._seen_tids.add(tid)
                self._buf.append(json.dumps({
                    "name": "thread_name", "ph": "M", "pid": self.pid,
                    "tid": tid, "ts": 0,
                    "args": {"name": (
                        track_name or threading.current_thread().name
                    )},
                }))
            # strictly increasing per-thread timestamps: a tie within a
            # thread is possible at sub-us span rates and breaks viewers'
            # ordering; nudging by 1ns-equivalents keeps durations honest
            last = self._last_ts.get(tid, -1.0)
            if event["ts"] <= last:
                event["ts"] = last + 0.001
            self._last_ts[tid] = event["ts"]
            self._buf.append(json.dumps(event, default=str, separators=_SEP))
            if len(self._buf) >= self.flush_every:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        if self._file is None:
            self._file = self.path.open("a")
        self._file.write("\n".join(self._buf) + "\n")
        self._file.flush()
        self._buf.clear()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._file is not None:
                self._file.close()
                self._file = None


class _Span:
    """Context manager emitting one complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: Tracer, name: str, cat: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._t0 = Tracer.now_us()
        return self

    def __exit__(self, *exc):
        t1 = Tracer.now_us()
        event = {
            "name": self._name, "cat": self._cat, "ph": "X",
            "ts": self._t0, "dur": max(0.0, t1 - self._t0),
        }
        if self._args:
            event["args"] = self._args
        self._tracer.emit(event)
        return False


# ---------------------------------------------------------------------------
# module API (what the rest of the codebase calls)


def _lazy_init() -> "Tracer | None":
    """Self-enable from the environment exactly once — this is how spawn
    workers and CLI subprocesses join the parent's timeline."""
    global _env_checked
    with _init_lock:
        if _tracer is not None or _env_checked:
            return _tracer
        _env_checked = True
        d = os.environ.get(ENV_TRACE_DIR)
        if d:
            _enable_locked(d)
        return _tracer


def _enable_locked(
    directory: str | Path, process_name: str | None = None
) -> Tracer:
    global _tracer
    _tracer = Tracer(directory, process_name=process_name)
    atexit.register(_tracer.close)
    return _tracer


def enable(
    directory: str | Path,
    process_name: str | None = None,
    export_env: bool = False,
) -> Tracer:
    """Start tracing this process into `directory`. With `export_env`,
    children spawned from here (process pools, CLI subprocesses) inherit
    the directory and self-enable on their first span."""
    global _env_checked
    with _init_lock:
        if _tracer is not None:
            _tracer.close()
        tracer = _enable_locked(directory, process_name)
        _env_checked = True
    if export_env:
        os.environ[ENV_TRACE_DIR] = str(directory)
    return tracer


def disable() -> None:
    """Flush + stop tracing; stays off (env is not re-consulted)."""
    global _tracer, _env_checked
    with _init_lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = None
        _env_checked = True
    os.environ.pop(ENV_TRACE_DIR, None)


def enabled() -> bool:
    return (_tracer or _lazy_init()) is not None


def current_trace_path() -> "Path | None":
    """The active tracer's (flushed) on-disk file, or None when tracing
    is off — what the fleet TraceShipper tails incrementally."""
    t = _tracer or _lazy_init()
    if t is None:
        return None
    t.flush()
    return t.path


def span(name: str, cat: str = "app", **args):
    """Context manager timing a block; no-op (shared singleton, no
    allocation) when tracing is off."""
    t = _tracer or _lazy_init()
    if t is None:
        return _NULL_SPAN
    return _Span(t, name, cat, args)


def instant(name: str, cat: str = "app", **args) -> None:
    """A point event (ph="i") — stalls, rollbacks, resume markers."""
    if _instant_mirror is not None:
        try:
            _instant_mirror(name, cat, dict(args) if args else None)
        except Exception:  # the mirror must never cost the event
            pass
    t = _tracer or _lazy_init()
    if t is None:
        return
    event = {
        "name": name, "cat": cat, "ph": "i", "s": "p",
        "ts": Tracer.now_us(),
    }
    if args:
        event["args"] = args
    t.emit(event)


def counter(name: str, value: float, cat: str = "app") -> None:
    """A counter sample (ph="C") rendered as a track in the viewer."""
    t = _tracer or _lazy_init()
    if t is None:
        return
    t.emit({
        "name": name, "cat": cat, "ph": "C", "ts": Tracer.now_us(),
        "args": {"value": value},
    })


def complete_event(
    name: str,
    ts_us: float,
    dur_us: float,
    cat: str = "app",
    tid: int | None = None,
    track_name: str | None = None,
    args: dict | None = None,
) -> None:
    """Emit a complete ("X") event with an EXPLICIT (possibly backdated)
    timestamp, optionally on a synthetic track — how StepTimer places
    reconstructed device windows at their true dispatch times (and how
    the serve batcher places queue-wait windows at submit time)."""
    t = _tracer or _lazy_init()
    if t is None:
        return
    event: dict = {
        "name": name, "cat": cat, "ph": "X",
        "ts": ts_us, "dur": max(0.0, dur_us),
    }
    if tid is not None:
        event["tid"] = tid
    if args:
        event["args"] = args
    t.emit(event, track_name=track_name)


def flow(
    name: str,
    flow_id: str,
    phase: str,
    cat: str = "app",
    ts_us: float | None = None,
    tid: int | None = None,
    track_name: str | None = None,
    **args,
) -> None:
    """One Chrome-trace flow event: phase "s" (start), "t" (step), or
    "f" (end). Events sharing a `flow_id` render as one linked arrow
    chain across threads and processes — how a serve request's
    frontend/queue/device spans connect in the merged Perfetto timeline
    (docs/slo.md). A flow event binds to the slice enclosing its
    timestamp on the emitting thread, so emit it INSIDE (or with a
    `ts_us` inside) the span it should attach to; no-op when tracing is
    off, like every emitter here."""
    t = _tracer or _lazy_init()
    if t is None:
        return
    if phase not in ("s", "t", "f"):
        raise ValueError(f"flow phase must be s/t/f, got {phase!r}")
    event: dict = {
        "name": name, "cat": cat, "ph": phase, "id": flow_id,
        "ts": Tracer.now_us() if ts_us is None else ts_us,
    }
    if phase == "f":
        event["bp"] = "e"  # bind to the enclosing slice, not the next
    if tid is not None:
        event["tid"] = tid
    if args:
        event["args"] = args
    t.emit(event, track_name=track_name)


def flush() -> None:
    if _tracer is not None:
        _tracer.flush()


# ---------------------------------------------------------------------------
# offline merge (what diag and the tests consume)


def merge(directory: str | Path) -> list[dict]:
    """All events from every per-process file, sorted by timestamp.
    Tolerates a torn trailing line (a worker killed mid-flush)."""
    events: list[dict] = []
    for path in sorted(Path(directory).glob("trace-*.jsonl")):
        for line in path.read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def write_chrome_trace(directory: str | Path, out_path: str | Path) -> int:
    """Fold the per-process JSONL files into one ``{"traceEvents": []}``
    JSON file loadable by Perfetto / chrome://tracing. Returns the event
    count."""
    events = merge(directory)
    Path(out_path).write_text(
        json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})
    )
    return len(events)
