"""Crash flight recorder: bounded in-memory history + postmortem dumps
(docs/efficiency.md).

BENCH_r1-r5 and the resilience rounds share one operational pattern: a
run dies (watchdog abort, SIGTERM, NaN spiral, wedged backend, OOM) and
the evidence of its last moments is scattered across log tails that may
not have flushed. The flight recorder keeps a bounded ring of the last N
step records and recent telemetry instants IN MEMORY, and on any
terminal event dumps one machine-readable `postmortem.json` (atomic,
core/ioutil.py) containing:

- the step ring (last N train-step numbers + host timestamps),
- the event ring (cat="resilience"/"backend"/... instants — mirrored
  from obs/trace.py:instant whether or not tracing is enabled),
- the efficiency + HBM ledger snapshot (obs/ledger.py) when the ledger
  is on — the OOM-forensics payload,
- the backend-health summary (obs/health.py) and the metrics-registry
  snapshot (every tag SCHEMA-declared; `scripts/check_obs_schema.py
  --postmortem` validates a dumped file).

Dump triggers (train/resilience.py, obs/health.py, the installed
excepthook): watchdog abort (exit 113), SIGTERM preemption, NaN-guard
rollback, backend WEDGE, unhandled exception — classified "oom" when
the exception is RESOURCE_EXHAUSTED (obs/ledger.py:is_oom).

Default OFF (`cfg.obs.flight`): every `note_*`/`crash_dump` call is one
module-global check when not installed. A dump must never mask the
failure that caused it — every writer path swallows its own errors.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from pathlib import Path

from deepdfa_tpu.obs import metrics as obs_metrics, trace as obs_trace

POSTMORTEM_VERSION = 1

#: the trigger vocabulary a valid postmortem must name (validated by
#: validate_postmortem; "manual"/"smoke_test" are the operator/test
#: dumps the serve/scan smokes exercise end to end)
TRIGGERS = (
    "watchdog_abort",
    "sigterm",
    "nan_rollback",
    "backend_wedge",
    "oom",
    "exception",
    "manual",
    "smoke_test",
)

_recorder: "FlightRecorder | None" = None
_lock = threading.Lock()
_prev_excepthook = None


class FlightRecorder:
    """Bounded rings + the atomic postmortem writer for one process."""

    def __init__(
        self,
        path: str | Path,
        max_steps: int = 64,
        max_events: int = 128,
    ):
        self.path = Path(path)
        self.max_steps = max(1, int(max_steps))
        self.max_events = max(1, int(max_events))
        self._steps: deque[dict] = deque(maxlen=self.max_steps)
        self._events: deque[dict] = deque(maxlen=self.max_events)
        self._lk = threading.Lock()
        self.dumps = 0
        self.last_trigger: str | None = None

    def note_step(self, step: int, **info) -> None:
        entry = {"step": int(step), "t_unix": round(time.time(), 3)}
        if info:
            entry.update(info)
        with self._lk:
            self._steps.append(entry)

    def note_event(self, name: str, cat: str = "app", args: dict | None = None) -> None:
        entry = {
            "name": str(name), "cat": str(cat),
            "t_unix": round(time.time(), 3),
        }
        if args:
            # args may carry non-JSON values (arrays); stringify defensively
            entry["args"] = {
                k: (v if isinstance(v, (int, float, str, bool, type(None)))
                    else str(v)[:200])
                for k, v in args.items()
            }
        with self._lk:
            self._events.append(entry)

    def document(self, trigger: str, extra: dict | None = None) -> dict:
        from deepdfa_tpu.obs import ledger as obs_ledger

        with self._lk:
            steps = list(self._steps)
            events = list(self._events)
        doc: dict = {
            "version": POSTMORTEM_VERSION,
            "trigger": str(trigger),
            "t_unix": round(time.time(), 3),
            "pid": os.getpid(),
            "steps": steps,
            "events": events,
        }
        try:
            doc["metrics"] = obs_metrics.REGISTRY.snapshot()
        except Exception:
            doc["metrics"] = {}
        led = obs_ledger.snapshot_or_none()
        if led is not None:
            doc["ledger"] = led
        try:
            from deepdfa_tpu.obs import health as obs_health

            backend = obs_health.summary()
            if backend:
                doc["backend"] = backend
        except Exception:
            pass
        if extra:
            try:
                json.dumps(extra)
                doc["extra"] = extra
            except (TypeError, ValueError):
                doc["extra"] = {"repr": str(extra)[:2000]}
        return doc

    def dump(self, trigger: str, extra: dict | None = None) -> Path | None:
        """Write `postmortem.json` atomically; last dump wins (the file
        always holds ONE complete document). Never raises."""
        try:
            doc = self.document(trigger, extra=extra)
            from deepdfa_tpu.core.ioutil import atomic_write_text

            self.path.parent.mkdir(parents=True, exist_ok=True)
            atomic_write_text(
                self.path, json.dumps({"postmortem": doc}, indent=1)
            )
            self.dumps += 1
            self.last_trigger = str(trigger)
            obs_metrics.REGISTRY.counter("flight/dumps").inc()
            obs_metrics.REGISTRY.counter(f"flight/dumps/{trigger}").inc()
            return self.path
        except Exception:  # a dump must never mask the original failure
            return None


# ---------------------------------------------------------------------------
# module surface


def install(
    path: str | Path,
    max_steps: int = 64,
    max_events: int = 128,
) -> FlightRecorder:
    """Install the process flight recorder: rings start filling (trace
    instants mirror in whether or not tracing is on), and unhandled
    exceptions dump a postmortem through a chained excepthook."""
    global _recorder, _prev_excepthook
    with _lock:
        _recorder = FlightRecorder(
            path, max_steps=max_steps, max_events=max_events
        )
        obs_trace.set_instant_mirror(_recorder.note_event)
        if _prev_excepthook is None:
            _prev_excepthook = sys.excepthook
            sys.excepthook = _excepthook
    return _recorder


def uninstall() -> None:
    global _recorder, _prev_excepthook
    with _lock:
        _recorder = None
        obs_trace.set_instant_mirror(None)
        if _prev_excepthook is not None:
            sys.excepthook = _prev_excepthook
            _prev_excepthook = None


def get() -> FlightRecorder | None:
    return _recorder


def installed() -> bool:
    return _recorder is not None


def note_step(step: int, **info) -> None:
    rec = _recorder
    if rec is not None:
        rec.note_step(step, **info)


def note_event(name: str, cat: str = "app", args: dict | None = None) -> None:
    rec = _recorder
    if rec is not None:
        rec.note_event(name, cat=cat, args=args)


def crash_dump(trigger: str, extra: dict | None = None) -> Path | None:
    """Dump a postmortem for `trigger` (no-op None when the recorder is
    not installed). The one function every terminal path calls."""
    rec = _recorder
    if rec is None:
        return None
    return rec.dump(trigger, extra=extra)


def note_exception(exc: BaseException, where: str = "") -> Path | None:
    """Classify + dump for an exception a runtime component caught but
    considers terminal-worthy evidence (e.g. a batch that died with
    RESOURCE_EXHAUSTED inside the serve batcher): trigger "oom" for
    device out-of-memory, "exception" otherwise."""
    from deepdfa_tpu.obs import ledger as obs_ledger

    rec = _recorder
    if rec is None:
        return None
    trigger = "oom" if obs_ledger.is_oom(exc) else "exception"
    return rec.dump(trigger, extra={
        "error": f"{type(exc).__name__}: {exc}"[:2000],
        **({"where": where} if where else {}),
    })


def _excepthook(exc_type, exc, tb) -> None:
    try:
        note_exception(exc, where="sys.excepthook")
    finally:
        hook = _prev_excepthook or sys.__excepthook__
        hook(exc_type, exc, tb)


# ---------------------------------------------------------------------------
# postmortem validation (scripts/check_obs_schema.py --postmortem)


def validate_postmortem(doc: dict) -> dict:
    """Structural + schema validation of one postmortem document (the
    parsed JSON of a dumped postmortem.json). Checks the format contract
    AND that every metrics tag the dump embeds is declared in
    obs/metrics.py:SCHEMA (a summary/histogram tag maps to its
    `<tag>/count` declaration, same rule as the /metrics scrape check).
    Returns {"ok", "problems", "trigger", "steps", "events"}."""
    from deepdfa_tpu.obs import metrics

    problems: list[str] = []
    pm = doc.get("postmortem") if isinstance(doc, dict) else None
    if not isinstance(pm, dict):
        return {
            "ok": False,
            "problems": ["missing top-level 'postmortem' object"],
        }
    if pm.get("version") != POSTMORTEM_VERSION:
        problems.append(
            f"version {pm.get('version')!r} != {POSTMORTEM_VERSION}"
        )
    trigger = pm.get("trigger")
    if trigger not in TRIGGERS:
        problems.append(
            f"trigger {trigger!r} not in declared set {TRIGGERS}"
        )
    for key in ("t_unix", "pid"):
        if not isinstance(pm.get(key), (int, float)):
            problems.append(f"{key} missing or non-numeric")
    for ring in ("steps", "events"):
        v = pm.get(ring)
        if not isinstance(v, list) or not all(
            isinstance(e, dict) for e in v
        ):
            problems.append(f"{ring} must be a list of objects")
    metrics_snap = pm.get("metrics")
    if not isinstance(metrics_snap, dict):
        problems.append("metrics snapshot missing")
    else:
        undeclared = sorted(
            tag for tag in metrics_snap
            if not (
                metrics.declared(tag) or metrics.declared(f"{tag}/count")
            )
        )
        for tag in undeclared:
            problems.append(f"undeclared metrics tag: {tag}")
    led = pm.get("ledger")
    if led is not None:
        if not isinstance(led, dict) or not isinstance(
            led.get("sites"), dict
        ):
            problems.append("ledger section present but malformed")
    return {
        "ok": not problems,
        "problems": problems,
        "trigger": trigger,
        "steps": len(pm.get("steps") or []),
        "events": len(pm.get("events") or []),
    }


def validate_postmortem_file(path: str | Path) -> dict:
    path = Path(path)
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        return {"ok": False, "problems": [f"unreadable: {e}"]}
    out = validate_postmortem(doc)
    out["path"] = str(path)
    return out
