"""Unified run telemetry (docs/observability.md).

One observability layer the whole stack reports into:

- `obs.trace`   — cross-process Chrome-trace spans/events (JSONL),
  including request-scoped flow events (docs/slo.md).
- `obs.metrics` — process-wide counter/gauge/histogram registry +
  the declared run-log schema (scripts/check_obs_schema.py).
- `obs.xprof`   — on-demand jax.profiler capture, device memory stats,
  lagged-fetch step-time decomposition.
- `obs.slo`     — rolling-window SLO aggregation + Prometheus text
  exposition for the serving stack (docs/slo.md).
- `obs.health`  — bounded backend-health probes emitting `backend/*`
  events (/healthz?deep=1, bench.py fallback path).
- `obs.bench_gate` — the bench-trajectory regression gate
  (scripts/bench_gate.py).
- `obs.ledger`  — device efficiency ledger: per-executable cost-analysis
  flops/bytes, compile time, HBM watermarks, rolling per-signature MFU
  (docs/efficiency.md).
- `obs.flight`  — crash flight recorder: bounded step/event rings dumped
  as postmortem.json on terminal events (docs/efficiency.md).
- `obs.diag`    — the `deepdfa-tpu diag <run_dir>` renderer.

The train loops talk to it through two seams that keep their signatures
unchanged and the default path byte-identical:

- `session(cfg, run_dir)` — CLI-side context manager that enables
  tracing (exporting the trace dir to child processes) and installs the
  xprof controller per `cfg.obs`; everything off by default.
- `instruments(cfg)` — per-fit facade the loops call for step spans,
  lagged step timing, and epoch-record enrichment; returns a shared
  no-op when nothing is enabled.
"""

from __future__ import annotations

import contextlib
from pathlib import Path

from deepdfa_tpu.obs import (
    flight,
    ledger,
    metrics,
    trace,
    xprof,
)

#: bump when the shape/meaning of emitted bench records changes —
#: BENCH_*.json artifacts are compared across PRs (ISSUE 4 satellite)
BENCH_SCHEMA_VERSION = 1


class Instruments:
    """Live per-fit instrumentation: step spans + xprof stepping +
    lagged step timer + epoch-record enrichment."""

    active = True

    def __init__(self, metrics_on: bool):
        self.metrics_on = bool(metrics_on)
        #: the efficiency ledger / flight recorder installed by
        #: session() (or directly by tests/benches); None when off
        self.ledger = ledger.get()
        self.flight = flight.get()
        # the StepTimer exists for metrics OR the ledger: the ledger's
        # rolling per-signature MFU is the lagged device-time join
        self.timer = (
            xprof.StepTimer(
                on_step_seconds=(
                    ledger.observe_step_seconds
                    if self.ledger is not None else None
                )
            )
            if (self.metrics_on or self.ledger is not None)
            else None
        )

    def step_span(self, step: int):
        """Wraps one train-step dispatch; also advances the xprof
        controller (window/trigger capture boundaries) and the flight
        recorder's step ring."""
        xprof.controller_on_step(step)
        if self.flight is not None:
            self.flight.note_step(step)
        return trace.span("train_step", cat="train", step=step)

    def observe_step_compile(self, tag: str, signature: str, fn_jit, args):
        """First-signature hook from the train loops (ledger only).

        Declares the active (tag, signature) step site for the
        StepTimer join, and — once per signature — AOT lower+compiles
        the loop's ALREADY-JITTED step to read XLA's cost analysis
        (jit's call cache is not seeded by `.lower().compile()`, so this
        is a second compile of the same program: an opt-in warmup cost,
        zero new program signatures, never steady-state). Errors land in
        the ledger's error list, never in the run."""
        led = self.ledger
        if led is None:
            return
        led.set_step_site(tag, signature)
        if led.has_site(tag, signature):
            return
        import time as _time

        t0 = _time.perf_counter()
        try:
            compiled = fn_jit.lower(*args).compile()
        except Exception as e:  # accounting must never cost the run
            led._note_error(
                f"step_compile[{tag}/{signature}]: "
                f"{type(e).__name__}: {e}"
            )
            led.record_compile(tag, signature, None, 0.0)
            return
        led.record_compile(
            tag, signature, compiled, _time.perf_counter() - t0
        )

    def dispatched(self, loss_handle, dispatch_seconds=None) -> None:
        if self.timer is not None:
            self.timer.dispatched(loss_handle, dispatch_seconds)

    def observe_pipeline(self, stats) -> None:
        if self.metrics_on:
            metrics.publish_pipeline_stats(stats)

    def observe_signatures(self, signature_stats: dict) -> None:
        if self.metrics_on:
            metrics.publish_signature_stats(signature_stats)

    def finish_epoch(self, record: dict) -> dict:
        """Drain the lagged timer and (when metrics are on) attach the
        registry snapshot + device memory stats to the epoch record —
        the ONE hook that routes every absorbed counter into the
        existing RunLogger jsonl/TensorBoard path."""
        if self.timer is not None:
            self.timer.drain()
        if self.ledger is not None:
            # per-phase HBM watermark + the efficiency snapshot ride the
            # epoch record (flattened to SCHEMA-declared ledger/* tags)
            self.ledger.record_memory("epoch")
            record["ledger"] = self.ledger.snapshot()
        if not self.metrics_on:
            return record
        snap = metrics.REGISTRY.snapshot()
        obs_snap = {
            k[len("obs/"):]: v for k, v in snap.items()
            if k.startswith("obs/")
        }
        if obs_snap:
            record["obs"] = obs_snap
        mem = xprof.device_memory_stats()
        if mem:
            record["device_memory"] = mem
        return record


class _NullInstruments:
    """Default-path stand-in: every call is a no-op; step_span returns
    the tracer's shared null span (no allocation)."""

    active = False
    metrics_on = False
    timer = None
    ledger = None
    flight = None

    def step_span(self, step: int):
        return trace._NULL_SPAN

    def observe_step_compile(self, tag, signature, fn_jit, args) -> None:
        pass

    def dispatched(self, loss_handle, dispatch_seconds=None) -> None:
        pass

    def observe_pipeline(self, stats) -> None:
        pass

    def observe_signatures(self, signature_stats: dict) -> None:
        pass

    def finish_epoch(self, record: dict) -> dict:
        return record


NULL_INSTRUMENTS = _NullInstruments()


def instruments(cfg) -> "Instruments | _NullInstruments":
    """The loops' entry point. Anything to do? (cfg.obs.metrics on,
    tracing enabled — by session() or directly/env — or an xprof
    controller installed) -> live Instruments; else the shared no-op."""
    ocfg = getattr(cfg, "obs", None)
    metrics_on = bool(ocfg is not None and ocfg.metrics)
    if (
        metrics_on
        or trace.enabled()
        or xprof._controller is not None
        or ledger.enabled()
        or flight.installed()
    ):
        return Instruments(metrics_on)
    return NULL_INSTRUMENTS


@contextlib.contextmanager
def session(cfg, run_dir):
    """CLI-side telemetry lifecycle for one run (cmd_train,
    cmd_train_combined, cmd_train_gen). All knobs default off; with
    `obs.trace=true` the per-process JSONL files land under
    `<run_dir>/trace/` (children join via the exported env var) and a
    merged `trace.json` is written at exit."""
    ocfg = getattr(cfg, "obs", None)
    if ocfg is None:
        yield
        return
    # multi-host (parallel/sharding.py, docs/sharding.md): the trace
    # dir, efficiency ledger, flight recorder, and xprof captures are
    # single-writer resources — process 0 owns them, so an N-host run
    # writes ONE telemetry tree instead of N racing copies (no-op gate
    # in single-process runs)
    from deepdfa_tpu.parallel import sharding as _sharding

    if not _sharding.is_primary():
        yield
        return
    trace_dir = None
    if ocfg.trace:
        trace_dir = (
            Path(ocfg.trace_dir) if ocfg.trace_dir
            else Path(run_dir) / "trace"
        )
        trace.enable(trace_dir, process_name="main", export_env=True)
    if ocfg.xprof_start_step >= 0 or ocfg.xprof_trigger:
        xprof.install_controller(
            Path(run_dir) / "xprof",
            start_step=ocfg.xprof_start_step,
            num_steps=ocfg.xprof_num_steps,
            trigger=ocfg.xprof_trigger,
        )
    # device efficiency ledger + crash flight recorder
    # (docs/efficiency.md): installed for the session so every AOT
    # compile site and terminal path in this process reports; the flight
    # recorder goes in FIRST so an enable-time failure still dumps
    ledger_on = bool(getattr(ocfg, "ledger", False))
    flight_on = bool(getattr(ocfg, "flight", False))
    if flight_on:
        flight.install(
            Path(run_dir) / "postmortem.json",
            max_steps=getattr(ocfg, "flight_steps", 64),
            max_events=getattr(ocfg, "flight_events", 128),
        )
    if ledger_on:
        ledger.enable(
            ceilings=bool(getattr(ocfg, "ledger_ceilings", False))
        )
    try:
        yield
    finally:
        xprof.uninstall_controller()
        if ledger_on:
            ledger.disable()
        if flight_on:
            flight.uninstall()
        if trace_dir is not None:
            trace.disable()
            try:
                trace.write_chrome_trace(
                    trace_dir, Path(trace_dir) / "trace.json"
                )
            except OSError:
                pass


_git_sha: str | None = None


def run_stamp() -> dict:
    """Provenance fields every emitted bench/JSON record carries so
    BENCH_*.json files are comparable across PRs: record schema version,
    the repo sha the numbers were measured at, and the jax that ran
    them."""
    global _git_sha
    if _git_sha is None:
        import subprocess

        try:
            _git_sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=Path(__file__).resolve().parents[2],
                capture_output=True, text=True, timeout=10,
            ).stdout.strip() or "unknown"
        except Exception:
            _git_sha = "unknown"
    try:
        import jax

        jax_version = jax.__version__
    except Exception:
        jax_version = "unknown"
    return {
        "schema_version": BENCH_SCHEMA_VERSION,
        "git_sha": _git_sha,
        "jax_version": jax_version,
    }
