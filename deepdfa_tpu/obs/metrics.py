"""Process-wide metrics registry: one place every counter in the stack
reports to, snapshotted into the existing RunLogger jsonl/TensorBoard
path.

PRs 1-3 each grew ad-hoc counters — `PipelineStats` stage seconds,
per-signature compile counts (`CombinedTrainer.signature_stats`),
resilience rollback/skip counters — that reach the run log through
loop-specific record plumbing. This registry absorbs them behind three
primitives (counter / gauge / histogram) so any component can publish
without threading state through the loops, and the loops emit ONE
`record["obs"] = snapshot()` blob per epoch (flattened to `obs/<name>`
TensorBoard tags by train/logging.py:flatten_scalars).

Naming rules (docs/observability.md): slash-separated lowercase paths,
`<subsystem>/<metric>[_<unit>]` — e.g. `input/load_seconds`,
`resilience/rollbacks`, `step/seconds`. Every name emitted into a run
log must match a declared pattern in `SCHEMA` below;
scripts/check_obs_schema.py enforces that against a smoke run in tier-1,
which is what catches jsonl/TensorBoard tag drift at PR time.
"""

from __future__ import annotations

import fnmatch
import math
import threading


class Counter:
    """Monotonic accumulator (float to absorb seconds counters)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v


class Gauge:
    """Last-write-wins sample."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Streaming count/sum/min/max — enough for p50-free step-time
    summaries without holding samples (snapshot adds a derived mean)."""

    __slots__ = ("name", "count", "sum", "min", "max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        if not math.isfinite(v):
            return
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)


class MetricsRegistry:
    """Name -> metric instance; get-or-create, kind-checked."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict[str, float]:
        """Flat {name: value}; histograms expand to /count /mean /max
        (min is rarely load-bearing and would double the tag count)."""
        out: dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Histogram):
                if m.count:
                    out[f"{m.name}/count"] = float(m.count)
                    out[f"{m.name}/mean"] = m.sum / m.count
                    out[f"{m.name}/max"] = m.max
            else:
                out[m.name] = float(m.value)
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


#: the process-wide registry every component publishes to
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# the declared run-log schema


#: fnmatch patterns for every scalar tag a train run may emit into
#: train_log.jsonl (and therefore TensorBoard). Adding a new record key
#: without declaring it here fails scripts/check_obs_schema.py in
#: tier-1 — that is the point: the schema is reviewed, not accreted.
SCHEMA: tuple[str, ...] = (
    # core loop records
    "epoch", "step", "loss", "train_loss", "epoch_seconds",
    # host stage attribution (docs/input_pipeline.md)
    "host_load_seconds", "host_pack_seconds", "host_place_seconds",
    "input_wait_seconds", "input_wait_fraction",
    # sequence-bucketing observables
    "train_examples_per_sec", "train_tokens_per_sec",
    "real_tokens", "padded_tokens", "padding_waste",
    "warmup_signatures", "warmup_compile_seconds",
    "step_signatures/*/compiles", "step_signatures/*/compile_seconds",
    "step_signatures/*/train_steps", "step_signatures/*/eval_steps",
    "jit_lowerings",
    # validation metrics (metric set varies by task)
    "val_*",
    # self-healing observables (docs/resilience.md)
    "resumed_from_step", "skipped_steps", "rollbacks",
    # the obs registry snapshot (this module): input pipeline mirrors,
    # resilience events, lagged step-time decomposition, logging guards
    "obs/input/load_seconds", "obs/input/pack_seconds",
    "obs/input/place_seconds", "obs/input/wait_seconds",
    "obs/input/produced", "obs/input/consumed",
    "obs/input/real_tokens", "obs/input/padded_tokens", "obs/input/rows",
    "obs/resilience/skipped_steps", "obs/resilience/rollbacks",
    "obs/resilience/preemptions", "obs/resilience/watchdog_stalls",
    "obs/resilience/resumed_from_step",
    "obs/step/seconds/count", "obs/step/seconds/mean",
    "obs/step/seconds/max",
    "obs/step/fetch_wait_seconds/count",
    "obs/step/fetch_wait_seconds/mean", "obs/step/fetch_wait_seconds/max",
    "obs/step/dispatch_seconds/count", "obs/step/dispatch_seconds/mean",
    "obs/step/dispatch_seconds/max",
    "obs/logging/nonfinite_dropped", "obs/logging/flatten_collisions",
    "obs/compile/signatures/*",
    # per-device memory stats (obs/xprof.py; TPU runtimes only)
    "device_memory/bytes_in_use", "device_memory/peak_bytes_in_use",
    "device_memory/bytes_limit", "device_memory/largest_alloc_size",
    # xprof capture bookkeeping
    "obs/xprof/captures",
    # -- online inference (deepdfa_tpu/serve/, docs/serving.md) --
    # serve_log.jsonl summary record (score/serve CLI, bench_serve)
    "serve_scored", "serve_failed_requests", "serve_seconds",
    "serve_requests_per_sec", "serve_latency_p50_ms",
    "serve_latency_p99_ms", "serve_batch_occupancy_mean",
    "serve_jit_lowerings", "serve_steady_state_recompiles",
    # pipelined execution (ISSUE 17, docs/serving.md "Pipelined
    # execution"): the configured depth rides the summary record so
    # check_obs_schema can demand pipeline evidence; bench_serve stamps
    # the serial-vs-pipelined comparison + the device-idle fraction
    "serve_pipeline_depth", "serve_device_idle_fraction",
    "serve_serial_req_per_sec", "serve_pipeline_req_per_sec",
    "serve_pipeline_speedup",
    # the serve registry snapshot (batcher/frontend/registry counters)
    "serve/requests", "serve/rejected", "serve/failed", "serve/batches",
    "serve/compiles", "serve/hot_swaps",
    "serve/cache_hits", "serve/cache_misses",
    "serve/queue_depth",
    "serve/batch_occupancy/count", "serve/batch_occupancy/mean",
    "serve/batch_occupancy/max",
    "serve/latency_seconds/count", "serve/latency_seconds/mean",
    "serve/latency_seconds/max",
    "serve/queue_wait_seconds/count", "serve/queue_wait_seconds/mean",
    "serve/queue_wait_seconds/max",
    "serve/device_seconds/count", "serve/device_seconds/mean",
    "serve/device_seconds/max",
    # pipelined execution stages (serve/batcher.py): in-flight depth +
    # per-stage seconds histograms, FIFO-union device busy/idle
    # counters, overlap seconds, idle-fraction gauge — a reviewed
    # wildcard because histogram suffixes expand per field
    "serve/pipeline/*",
    "serve/frontend_seconds/count", "serve/frontend_seconds/mean",
    "serve/frontend_seconds/max",
    # rolling SLO windows (obs/slo.py, docs/slo.md): the summary record
    # embeds the engine snapshot under "serve_slo" — window labels,
    # stages, and observed status codes are data-dependent, so this is
    # a reviewed wildcard (like obs/compile/signatures/*)
    "serve_slo/*",
    # per-request serve_log.jsonl entries (serve.request_log;
    # server.py:RequestLog) — request_id and the string fields ride in
    # the same entry but only scalars become tags
    "request/status", "request/latency_ms", "request/frontend_ms",
    "request/queue_ms", "request/device_ms", "request/batch_size",
    "request/t_unix",
    # backend health observability (obs/health.py): bounded
    # compile-and-execute probes, wedge/fallback events
    "backend/probes", "backend/probe_failures", "backend/probe_retries",
    "backend/wedges", "backend/fallbacks", "backend/healthy",
    "backend/probe_seconds/count", "backend/probe_seconds/mean",
    "backend/probe_seconds/max",
    # -- whole-repo scanning (deepdfa_tpu/scan/, docs/scanning.md) --
    # scan_log.jsonl summary record (scan CLI, bench_scan)
    "scan_files", "scan_files_reused", "scan_functions", "scan_reused",
    "scan_extracted", "scan_scored", "scan_functions_failed",
    "scan_findings", "scan_seconds", "scan_functions_per_sec",
    "scan_incremental_skip_fraction", "scan_cache_hit_fraction",
    "scan_walk_seconds", "scan_split_seconds", "scan_frontend_seconds",
    "scan_score_seconds", "scan_attribute_seconds", "scan_write_seconds",
    "scan_steady_state_recompiles", "scan_lines_steady_state_recompiles",
    # the scan registry snapshot (scan/scanner.py counters + stage
    # histograms)
    "scan/runs", "scan/files", "scan/files_reused", "scan/files_skipped",
    "scan/functions", "scan/functions_reused", "scan/functions_failed",
    "scan/scored", "scan/findings",
    "scan/walk_seconds/count", "scan/walk_seconds/mean",
    "scan/walk_seconds/max",
    "scan/split_seconds/count", "scan/split_seconds/mean",
    "scan/split_seconds/max",
    "scan/frontend_seconds/count", "scan/frontend_seconds/mean",
    "scan/frontend_seconds/max",
    "scan/score_seconds/count", "scan/score_seconds/mean",
    "scan/score_seconds/max",
    "scan/attribute_seconds/count", "scan/attribute_seconds/mean",
    "scan/attribute_seconds/max",
    "scan/write_seconds/count", "scan/write_seconds/mean",
    "scan/write_seconds/max",
    # served line-level localization (serve/localize.py AOT executables)
    "localize/requests", "localize/batches", "localize/compiles",
    "localize/seconds/count", "localize/seconds/mean",
    "localize/seconds/max",
    # -- two-stage cascaded inference + quantized serving executables
    # (serve/cascade.py, serve/quant.py, docs/cascade.md) --
    # the cascade's registry counters/gauges (escalation accounting,
    # stage-2 timing histogram)
    "serve/cascade_requests", "serve/cascade_escalations",
    "serve/cascade_sheds", "serve/cascade_failures",
    "serve/cascade_escalation_rate",
    "serve/cascade_stage2_seconds/count",
    "serve/cascade_stage2_seconds/mean",
    "serve/cascade_stage2_seconds/max",
    # the serve_record "cascade" section (escalation accounting + the
    # stage-2 recompile census) and the bench_cascade record fields
    # (scripts/bench_cascade.py via bench.py --child-cascade; gated in
    # obs/bench_gate.py) — both under reviewed wildcards because the
    # frontier bench carries per-stage sub-records
    "cascade/*", "cascade_*",
    # quantized-entry observables: the per-entry density/drift stamps
    # (registry info, bench records)
    "quant/*", "quant_*",
    # cascade fields on per-request serve_log entries (which stage
    # decided, the screen's prob, the calibrated prob, shed/degrade
    # markers, per-stage ms)
    "request/stage", "request/stage1_prob", "request/calibrated_prob",
    "request/cascade_shed", "request/cascade_failed",
    "request/cascade_stage1_ms", "request/cascade_stage2_ms",
    # Pallas-fused GGNN step (nn/ggnn_kernel.py, docs/ggnn_kernel.md):
    # trace-time lowering census per batch signature — both the obs
    # registry mirror and the epoch-record blob train loops embed when
    # model.ggnn_kernel is on (signature labels are data-dependent, so
    # this is a reviewed wildcard like obs/compile/signatures/*) —
    # plus the whole-unroll fusion's admission counter
    # (ggnn_kernel/fused_fallbacks: a fused request resolved to
    # per_step because the VMEM residency check or the scan_steps
    # gradient policy said no — the layout knob asked for something
    # the kernel refused, which the counter makes loud)
    "ggnn_kernel/*", "obs/ggnn_kernel/*",
    # measured roofline ceilings (eval/profiling.py probes — matmul
    # TFLOP/s, stream + gather GB/s): every probe mirrors its scalar
    # ceiling into a `roofline/<name>` gauge so obs-enabled runs carry
    # the measured ceiling in the run log next to the throughput it
    # defends (docs/roofline.md, docs/ggnn_kernel.md)
    "roofline/*",
    # device efficiency ledger (obs/ledger.py, docs/efficiency.md):
    # per-(tag, signature) cost-analysis flops/bytes/live-bytes,
    # compile counters, rolling MFU/roofline gauges, per-phase HBM
    # watermarks, per-registry-entry param bytes — tag/signature labels
    # are data-dependent, so this is a reviewed wildcard (like
    # obs/compile/signatures/*); the embedded epoch/serve/scan record
    # section flattens under the same prefix
    "ledger/*",
    # crash flight recorder (obs/flight.py): postmortem dump counters,
    # keyed by trigger
    "flight/*",
    # -- serving fleet (deepdfa_tpu/fleet/, docs/fleet.md) --
    # router/admission registry counters + gauges (request/forward/
    # retry/eject/readmit totals, shed counts by reason/tenant/priority,
    # routable-replica gauges) — tenant labels are data-dependent, so
    # this is a reviewed wildcard (like obs/compile/signatures/*); the
    # fleet_log summary record embeds the same snapshot under "fleet"
    "fleet/*",
    # the router's rolling SLO windows (obs/slo.py engine snapshot in
    # fleet_log summary records)
    "fleet_slo/*",
    # fleet_event lifecycle entries in fleet_log.jsonl (join/eject/
    # readmit/drain_observed/gone; fleet/router.py:EVENTS): scalar
    # fields like t_unix/failures/heartbeat_age_s
    "fleet_event/*",
    # per-request fleet_log entries (router request log; the admission
    # fields beyond the serve request/* set). `request/prob` is the
    # replica's calibrated score echoed into the router's log when the
    # alert engine is on — the drift watch's replay signal
    "request/deadline_ms", "request/priority", "request/retries",
    "request/shed", "request/prob",
    # router HA (fleet/ha.py, docs/fleet.md): takeover/stepdown
    # counters, the active-role gauge, measured failover seconds, and
    # the admission re-seed accounting — plus the scalar fields the
    # takeover/stepdown fleet_event entries carry
    "fleet_ha/*",
    # the fleet_log summary record's admission snapshot (token-bucket
    # levels per tenant + the service-time EWMA) — the re-seed source a
    # restarted/failed-over router restores from; tenant labels are
    # data-dependent, so a reviewed wildcard
    "fleet_admission/*",
    # zero-downtime rollout (fleet/rollout.py, docs/fleet.md): the
    # controller's registry counters (swaps/refusals/halts/rollbacks by
    # event name) and the {"rollout": {...}} fleet_log records' scalar
    # fields (t_unix, drift, checkpoint_step, recompiles, guard stats)
    "rollout/*",
    # pluggable coordination backend (fleet/coord.py): poll-exhaustion
    # and fenced-publish counters, plus the FaultableBackend's injected
    # fault counters (coord/faults/<kind>) the chaos drills assert on
    "coord/*",
    # scheduled chaos drills (fleet/drill.py; DRILL_r* records gated in
    # obs/bench_gate.py:gate_drill): round/failure counters and the
    # record's measured recovery-time fields (drill_failover_s,
    # drill_reseed_s, drill_readmit_s, drill_rollback_s, drill_bound_s)
    "drill/*", "drill_*",
    # predictive autoscaling (fleet/autoscale.py): decision counters by
    # action plus the {"autoscale": {...}} fleet_log records' scalar
    # fields (forecast/capacity rates, ratio, replica counts, stage)
    "autoscale/*", "autoscale_*",
    # fleet telemetry plane (obs/aggregate.py, docs/observability.md):
    # snapshot publish/collect counters, staleness gauges, and trace-
    # shipping accounting — plus the aggregated /metrics families'
    # tags (agg/latency_ms, agg/requests, agg/error_rate, agg/stale,
    # agg/snapshot_age_s) the fleet scrape validator checks
    "agg/*",
    # alert engine (obs/alerts.py, docs/alerts.md): evaluation/
    # transition counters, the firing gauge, and the {"alert": {...}}
    # fleet_log records' scalar fields (observed, threshold, for_s,
    # t_unix); fleet_alert_* covers bench/drill alert stamps
    # (alert_mttd_s rides bench records; drill records carry
    # drill_alert_mttd_s under drill_*)
    "alert/*", "fleet_alert_*", "alert_mttd_s",
    # data flywheel (deepdfa_tpu/flywheel/, docs/flywheel.md):
    # shadow/* = sampler/scorer counters-gauges (samples, dropped,
    # windows, regressions, agreement, prob_drift, lag_s) AND the
    # {"shadow": {...}} fleet_log records' scalar fields (t_unix,
    # samples, agreement, auc_candidate/auc_incumbent, lag_s);
    # shadow_* = the bench_load stamps (shadow_agreement,
    # shadow_sample_lag_s, shadow_overhead_fraction — gated in
    # obs/bench_gate.py); flywheel/* = the promotion controller's
    # counters (decisions by outcome); promotion/* and demotion/* =
    # the {"promotion"/"demotion": {...}} records' scalar fields
    "shadow/*", "shadow_*", "flywheel/*", "promotion/*", "demotion/*",
    # federation + alert-evaluation overhead bound (scripts/
    # bench_load.py interleaved reps; ≤2% ABSOLUTE_UPPER_BOUNDS in
    # obs/bench_gate.py)
    "obs_fleet_overhead_fraction",
    # fleet_log summary + bench_load record fields (scripts/
    # bench_load.py, bench.py --child-fleet; gated in obs/bench_gate.py)
    "fleet_replicas", "fleet_requests_per_sec", "fleet_seconds",
    "fleet_offered_rate_per_sec", "fleet_requests_total",
    "fleet_admitted", "fleet_shed", "fleet_shed_rate",
    "fleet_failed_other", "fleet_p99_overload_ms",
    "fleet_latency_p50_ms", "fleet_warm_requests_per_sec",
    "fleet_steady_state_recompiles", "overload_factor",
    "shed_by_tenant/*",
    # unified sharding layer (parallel/sharding.py, docs/sharding.md):
    # mesh/* = the run's topology stamp (non-collapsed axis sizes,
    # device/process counts, logical shards — publish_mesh gauges and
    # the MULTICHIP record's per-mesh-shape sections); shard/* = the
    # per-mesh-shape per-shard efficiency fields derived from the
    # PR-10 ledger in dryrun_multichip (per-shard MFU vs ceiling, HBM
    # watermarks, compile seconds) — axis/shape labels are
    # data-dependent, so both are reviewed wildcards
    "mesh/*", "shard/*",
    # bench-record ledger stamps (bench.py, gated in obs/bench_gate.py):
    # per-site MFU-vs-measured-ceiling map, total AOT compile wall time
    # (lower is better), and the interleaved-reps ledger overhead bound;
    # the train child's stamps carry a train_ prefix so the merged
    # record keeps both children's accounting
    "ledger_mfu/*", "compile_seconds_total",
    "train_ledger_mfu/*", "train_compile_seconds_total",
    "obs_ledger_overhead_fraction",
    # ledger-driven autotuner (deepdfa_tpu/tune/, docs/tuning.md):
    # the serve executors' per-rung real/padded row counters + the
    # process-wide waste gauge (the pow2 blind-spot made visible even
    # with tuning off — rung labels are data-dependent, so a reviewed
    # wildcard), and the bench child's stamps (bench.py --child-tune,
    # gated in obs/bench_gate.py: tuned_ggnn_step_us +
    # tuned_ladder_padding_waste lower-is-better, tune_search_seconds
    # absolute-bounded)
    "serve/ladder_waste", "serve/ladder_real_rows",
    "serve/ladder_padded_rows", "serve/ladder/*",
    "tune/*", "tune_*", "tuned_*",
)


def declared(name: str, schema: tuple[str, ...] = SCHEMA) -> bool:
    """Is a flattened scalar tag covered by the declared schema?"""
    return any(fnmatch.fnmatchcase(name, pat) for pat in schema)


def undeclared_tags(records, schema: tuple[str, ...] = SCHEMA) -> list[str]:
    """Flatten run-log records the exact way RunLogger does and return
    every tag no schema pattern covers (sorted, deduped)."""
    from deepdfa_tpu.train.logging import flatten_scalars

    bad: set[str] = set()
    for rec in records:
        for tag in flatten_scalars(rec):
            if not declared(tag, schema):
                bad.add(tag)
    return sorted(bad)


def publish_pipeline_stats(stats, registry: MetricsRegistry = None) -> None:
    """Absorb a PipelineStats epoch into the registry (cumulative across
    epochs — counters, not gauges, so multi-epoch runs aggregate)."""
    r = registry if registry is not None else REGISTRY
    r.counter("obs/input/load_seconds").inc(stats.load_seconds)
    r.counter("obs/input/pack_seconds").inc(stats.pack_seconds)
    r.counter("obs/input/place_seconds").inc(stats.place_seconds)
    r.counter("obs/input/wait_seconds").inc(stats.wait_seconds)
    r.counter("obs/input/produced").inc(stats.produced)
    r.counter("obs/input/consumed").inc(stats.consumed)
    if stats.padded_tokens:
        r.counter("obs/input/real_tokens").inc(stats.real_tokens)
        r.counter("obs/input/padded_tokens").inc(stats.padded_tokens)
        r.counter("obs/input/rows").inc(stats.rows)


def publish_signature_stats(
    signature_stats: dict, registry: MetricsRegistry = None
) -> None:
    """Absorb the combined trainer's per-signature compile counters
    (gauges: the trainer's own dict is already cumulative)."""
    r = registry if registry is not None else REGISTRY
    for sig, stats in signature_stats.items():
        r.gauge(f"obs/compile/signatures/{sig}").set(
            stats.get("compiles", 0)
        )
