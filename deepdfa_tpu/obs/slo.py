"""Rolling-window SLO aggregation + Prometheus text exposition
(docs/slo.md).

The serving stack's operational half: `/stats` needs "what is p99 over
the last minute", an alerting scrape needs "error rate over 5 minutes",
and neither is answerable from the process-lifetime counters in
`obs/metrics.py` (a histogram's lifetime mean buries a latency spike
minutes after it happened). `SloEngine` keeps bounded, time-stamped
sample windows per request stage and answers both on demand:

- per-window (default 60s/300s) p50/p95/p99 latency for every stage a
  request passes through (frontend, queue, device, total);
- request/error counts by HTTP status code -> windowed error rate;
- batch occupancy quantiles, live queue depth, hot-swap count.

Percentile convention: `percentile()` below is THE repo-wide quantile
rule (upper-biased index over a sorted sample) — serve/batcher.py,
bench_serve, and this engine all import it from here so the p99 a bench
record reports and the p99 `/metrics` exposes can never disagree on
convention.

`/metrics` exposition (`registry_exposition` + `SloEngine.exposition`)
is Prometheus text format 0.0.4, stdlib-only. Every metric family
carries a `# HELP <name> tag=<registry-tag>` line mapping it back to the
declared schema in `obs/metrics.py:SCHEMA`; that mapping is what lets
`scripts/check_obs_schema.py --metrics` validate a live scrape against
the same reviewed registry the run logs are validated against.

Everything here is serve-path only — the training default path never
constructs an engine, so the PR-4 "default path byte-identical"
contract is untouched.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Sequence


def percentile(sorted_vals: Sequence[float], p: float) -> float | None:
    """Upper-biased quantile over a PRE-SORTED sample; None when empty.
    The one index rule `/stats`, `/metrics`, the score summaries, and
    bench_serve all share — private copies would drift apart."""
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1, int(p * len(sorted_vals)))]


#: the quantile set every latency window exposes
QUANTILES = (0.50, 0.95, 0.99)

#: request stages a serve request is attributed across (docs/serving.md
#: lifecycle: frontend extraction -> bounded queue -> device execution)
STAGES = ("total", "frontend", "queue", "device")

#: the extra stages a cascade-mode service attributes (docs/cascade.md):
#: the stage-1 GGNN screen and the (escalations-only) stage-2 pass
CASCADE_STAGES = ("cascade_stage1", "cascade_stage2")


class WindowedSamples:
    """Time-stamped sample ring for one (window, series) pair.

    Samples older than `horizon_s` age out on read; at most
    `max_samples` newest samples are retained (an overloaded window
    degrades to "quantiles over the newest N", never to unbounded
    memory). Thread-safe; `clock` is injectable so tests can drive
    eviction deterministically."""

    __slots__ = ("horizon_s", "_samples", "_lock")

    def __init__(self, horizon_s: float, max_samples: int = 2048):
        self.horizon_s = float(horizon_s)
        self._samples: deque[tuple[float, float]] = deque(
            maxlen=int(max_samples)
        )
        self._lock = threading.Lock()

    def observe(self, value: float, now: float) -> None:
        with self._lock:
            self._samples.append((now, float(value)))

    def values(self, now: float) -> list[float]:
        """Samples still inside the window at `now` (evicts the rest)."""
        cutoff = now - self.horizon_s
        with self._lock:
            while self._samples and self._samples[0][0] < cutoff:
                self._samples.popleft()
            return [v for _, v in self._samples]


class WindowedCounts:
    """Time-stamped event COUNTER for one (window, series) pair:
    per-second buckets bounded by the horizon itself, so counts are
    EXACT at any traffic rate (a sample-ring would truncate the busiest
    status first and distort windowed error rates — status counts need
    totals, not quantiles, so they get counter semantics)."""

    __slots__ = ("horizon_s", "_buckets", "_lock")

    def __init__(self, horizon_s: float):
        self.horizon_s = float(horizon_s)
        # [second-bucket, count]; at most horizon_s+1 entries ever live
        self._buckets: deque[list[float]] = deque()
        self._lock = threading.Lock()

    def _evict_locked(self, now: float) -> None:
        cutoff = now - self.horizon_s
        while self._buckets and self._buckets[0][0] < cutoff:
            self._buckets.popleft()

    def observe(self, now: float) -> None:
        sec = int(now)
        with self._lock:
            # evict on WRITE as well as read: a server nobody scrapes
            # must not grow one bucket per active second forever
            self._evict_locked(now)
            if self._buckets and self._buckets[-1][0] == sec:
                self._buckets[-1][1] += 1
            else:
                self._buckets.append([sec, 1])

    def total(self, now: float) -> int:
        with self._lock:
            self._evict_locked(now)
            return int(sum(c for _, c in self._buckets))


class SloEngine:
    """Rolling-window SLO state for one scoring service.

    `observe_request` is the single ingest point (the HTTP handler and
    the offline score drive both call it once per finished request);
    `snapshot` renders every window for `/stats` and the serve_log
    summary record; `exposition` renders the same content as Prometheus
    gauges for `/metrics`."""

    def __init__(
        self,
        windows: Sequence[float] = (60, 300),
        max_samples: int = 2048,
        clock: Callable[[], float] = time.monotonic,
        stages: Sequence[str] = STAGES,
    ):
        if not windows:
            raise ValueError("SloEngine needs at least one window")
        self.clock = clock
        self.windows = tuple(float(w) for w in windows)
        self.max_samples = int(max_samples)
        #: stage vocabulary this engine attributes across — a cascade
        #: service extends the default set with CASCADE_STAGES; extras
        #: arrive via observe_request(extra=...)
        self.stages = tuple(stages)
        self._lock = threading.Lock()
        # {window -> {stage -> WindowedSamples}} latency seconds
        self._latency = {
            w: {s: WindowedSamples(w, max_samples) for s in self.stages}
            for w in self.windows
        }
        # {window -> {status -> WindowedCounts}} exact per-second counts
        self._status: dict[float, dict[int, WindowedCounts]] = {
            w: {} for w in self.windows
        }
        self._occupancy = {
            w: WindowedSamples(w, max_samples) for w in self.windows
        }
        self.queue_depth = 0.0
        self.hot_swaps = 0.0
        # lifetime totals (status -> count): the monotone half /metrics
        # needs (windowed counts go up AND down as samples age out)
        self._status_totals: dict[int, float] = {}
        self.requests_total = 0.0

    @staticmethod
    def window_label(w: float) -> str:
        return f"{int(w)}s"

    # -- ingest --------------------------------------------------------------

    def observe_request(
        self,
        status: int,
        latency_s: float | None,
        frontend_s: float | None = None,
        queue_s: float | None = None,
        device_s: float | None = None,
        now: float | None = None,
        extra: dict | None = None,
    ) -> None:
        """`extra` carries stage seconds beyond the default four (e.g.
        cascade_stage1/cascade_stage2); only stages this engine declared
        at construction are ingested — an undeclared stage is a caller
        bug surfaced by the snapshot's absence, never a KeyError on the
        request path."""
        now = self.clock() if now is None else now
        status = int(status)
        stages = {
            "total": latency_s, "frontend": frontend_s,
            "queue": queue_s, "device": device_s,
        }
        if extra:
            stages.update(extra)
        for w in self.windows:
            ring_by_stage = self._latency[w]
            for stage, v in stages.items():
                if v is not None and stage in ring_by_stage:
                    ring_by_stage[stage].observe(v, now)
            with self._lock:
                ring = self._status[w].get(status)
                if ring is None:
                    ring = self._status[w][status] = WindowedCounts(w)
            ring.observe(now)
        with self._lock:
            self.requests_total += 1
            self._status_totals[status] = (
                self._status_totals.get(status, 0.0) + 1
            )

    def observe_batch(self, occupancy: float, now: float | None = None):
        now = self.clock() if now is None else now
        for w in self.windows:
            self._occupancy[w].observe(occupancy, now)

    def set_queue_depth(self, depth: float) -> None:
        self.queue_depth = float(depth)

    def observe_hot_swap(self) -> None:
        with self._lock:
            self.hot_swaps += 1

    # -- render --------------------------------------------------------------

    def _window_view(self, w: float, now: float) -> dict:
        out: dict = {}
        for stage in self.stages:
            vals = sorted(self._latency[w][stage].values(now))
            if not vals:
                continue
            st = out.setdefault("latency_ms", {})[stage] = {}
            for q in QUANTILES:
                st[f"p{int(q * 100)}"] = round(1e3 * percentile(vals, q), 3)
            st["count"] = len(vals)
        with self._lock:
            status_rings = dict(self._status[w])
        counts = {
            str(code): ring.total(now)
            for code, ring in sorted(status_rings.items())
        }
        counts = {k: v for k, v in counts.items() if v}
        n = sum(counts.values())
        if counts:
            out["status"] = counts
            errors = sum(
                v for k, v in counts.items() if not k.startswith("2")
            )
            out["error_rate"] = round(errors / n, 4)
            out["requests_per_sec"] = round(n / w, 3)
        occ = sorted(self._occupancy[w].values(now))
        if occ:
            out["batch_occupancy_p50"] = round(
                percentile(occ, 0.50), 4
            )
        return out

    def snapshot(self, now: float | None = None) -> dict:
        """Nested {window-label: view} + live gauges — the `/stats` SLO
        section and (flattened to `serve_slo/*` tags) the serve_log
        summary record."""
        now = self.clock() if now is None else now
        out: dict = {
            self.window_label(w): self._window_view(w, now)
            for w in self.windows
        }
        out["queue_depth"] = self.queue_depth
        out["hot_swaps"] = self.hot_swaps
        out["requests_total"] = self.requests_total
        return out

    def latency_samples(self, now: float | None = None) -> dict:
        """The raw windowed latency sample lists, {window-label:
        {stage: [seconds, ...]}} — what fleet federation re-encodes
        onto the shared histogram grid (obs/aggregate.py) so merged
        fleet percentiles stay exact."""
        now = self.clock() if now is None else now
        return {
            self.window_label(w): {
                stage: self._latency[w][stage].values(now)
                for stage in self.stages
            }
            for w in self.windows
        }

    # -- Prometheus ----------------------------------------------------------

    def exposition(self, now: float | None = None) -> str:
        """The SLO half of `/metrics` (Prometheus text format 0.0.4):
        windowed quantiles/error rates as labeled gauges, lifetime
        status counts as a labeled counter."""
        now = self.clock() if now is None else now
        # ONE view per window: each _window_view evicts/copies/sorts
        # every ring it reads, so recomputing it per family would
        # triple the scrape cost on the serving process
        views = {
            w: self._window_view(w, now) for w in self.windows
        }
        lines: list[str] = []

        def family(name: str, tag: str, kind: str) -> None:
            lines.append(f"# HELP {name} tag={tag}")
            lines.append(f"# TYPE {name} {kind}")

        name = "deepdfa_serve_slo_latency_ms"
        family(name, "serve_slo/latency_ms", "gauge")
        for w in self.windows:
            lbl = self.window_label(w)
            for stage, st in views[w].get("latency_ms", {}).items():
                for q in QUANTILES:
                    lines.append(
                        f'{name}{{window="{lbl}",stage="{stage}",'
                        f'quantile="{q}"}} '
                        f"{st[f'p{int(q * 100)}']}"
                    )
        name = "deepdfa_serve_slo_error_rate"
        family(name, "serve_slo/error_rate", "gauge")
        for w in self.windows:
            if "error_rate" in views[w]:
                lines.append(
                    f'{name}{{window="{self.window_label(w)}"}} '
                    f"{views[w]['error_rate']}"
                )
        name = "deepdfa_serve_slo_requests_per_sec"
        family(name, "serve_slo/requests_per_sec", "gauge")
        for w in self.windows:
            if "requests_per_sec" in views[w]:
                lines.append(
                    f'{name}{{window="{self.window_label(w)}"}} '
                    f"{views[w]['requests_per_sec']}"
                )
        name = "deepdfa_serve_requests_by_status_total"
        family(name, "serve_slo/status", "counter")
        with self._lock:
            totals = sorted(self._status_totals.items())
        for code, count in totals:
            lines.append(f'{name}{{status="{code}"}} {count:g}')
        name = "deepdfa_serve_slo_queue_depth"
        family(name, "serve_slo/queue_depth", "gauge")
        lines.append(f"{name} {self.queue_depth:g}")
        name = "deepdfa_serve_slo_hot_swaps_total"
        family(name, "serve_slo/hot_swaps", "counter")
        lines.append(f"{name} {self.hot_swaps:g}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Prometheus exposition of the process-wide metrics registry


def prom_name(tag: str) -> str:
    """Registry tag -> Prometheus metric name (slashes/dots -> '_',
    `deepdfa_` prefix). `serve/queue_depth` -> `deepdfa_serve_queue_depth`."""
    safe = "".join(
        c if c.isalnum() or c == "_" else "_" for c in tag
    ).strip("_")
    return f"deepdfa_{safe}"


def registry_exposition(registry=None) -> str:
    """Every counter/gauge/histogram in the metrics registry as
    Prometheus text. Counters export as `<name>_total`; histograms (the
    streaming count/sum/min/max kind) export `_count`/`_sum` counters
    plus a `_max` gauge. Each family's HELP line carries the registry
    tag so `check_obs_schema.py --metrics` can validate a scrape against
    `obs/metrics.py:SCHEMA`."""
    from deepdfa_tpu.obs import metrics as obs_metrics

    r = registry if registry is not None else obs_metrics.REGISTRY
    with r._lock:
        items = sorted(r._metrics.items())
    lines: list[str] = []
    for tag, m in items:
        base = prom_name(tag)
        if isinstance(m, obs_metrics.Counter):
            lines.append(f"# HELP {base}_total tag={tag}")
            lines.append(f"# TYPE {base}_total counter")
            lines.append(f"{base}_total {m.value:g}")
        elif isinstance(m, obs_metrics.Gauge):
            lines.append(f"# HELP {base} tag={tag}")
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{base} {m.value:g}")
        else:  # Histogram
            lines.append(f"# HELP {base} tag={tag}")
            lines.append(f"# TYPE {base} summary")
            lines.append(f"{base}_count {m.count:g}")
            lines.append(f"{base}_sum {m.sum:g}")
            if m.count:
                lines.append(f"# HELP {base}_max tag={tag}")
                lines.append(f"# TYPE {base}_max gauge")
                lines.append(f"{base}_max {m.max:g}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# scrape parsing (check_obs_schema --metrics, tests)


def parse_exposition(text: str) -> dict:
    """Parse a Prometheus text scrape into
    {metric-name: {"type": ..., "tag": ..., "samples": [(labels, value)]}}.
    Raises ValueError on any line that is neither a comment nor a
    well-formed sample — the format guard the tests and the schema
    checker share."""
    import re

    families: dict[str, dict] = {}
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+"
        r"([-+]?(?:[0-9.]+(?:[eE][-+]?[0-9]+)?|[Nn]a[Nn]|[-+Ii]nf\w*))$"
    )
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):].split(None, 1)
            fam = families.setdefault(
                rest[0], {"type": None, "tag": None, "samples": []}
            )
            if len(rest) > 1 and rest[1].startswith("tag="):
                fam["tag"] = rest[1][len("tag="):].strip()
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):].split()
            fam = families.setdefault(
                rest[0], {"type": None, "tag": None, "samples": []}
            )
            fam["type"] = rest[1] if len(rest) > 1 else None
            continue
        if line.startswith("#"):
            continue
        m = sample_re.match(line)
        if m is None:
            raise ValueError(
                f"unparseable exposition line {lineno}: {line!r}"
            )
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        # bind to an EXACTLY-matching declared family first (a summary's
        # sibling `<base>_max` gauge declares its own family and must
        # not fold into `<base>`); only then fold _total/_count/_sum/
        # _max samples into their base family
        base = name
        if base not in families:
            for suffix in ("_total", "_count", "_sum", "_max"):
                if base.endswith(suffix):
                    base = base[: -len(suffix)]
                    break
        fam = families.setdefault(
            base, {"type": None, "tag": None, "samples": []}
        )
        fam["samples"].append((labels, float(value)))
    return families
