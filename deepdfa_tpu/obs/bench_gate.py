"""Bench-trajectory regression gate (scripts/bench_gate.py, docs/slo.md).

The repo accumulates one BENCH_r<N>.json driver artifact per round plus
hand-committed BENCH_TPU_*.json watchdog captures; until now nothing
GATED on them — BENCH_r02..r05 all shipped with the bench silently
running on CPU fallback (`fallback_from` buried in the record). This
module turns the trajectory into a pass/fail verdict:

- `load_trajectory()` parses every committed bench artifact, tolerating
  the real-world shapes: a clean `parsed` record, a truncated `tail`
  whose head was cut mid-JSON, an rc!=0 round with only a traceback.
- `gate()` compares a candidate record against the newest healthy
  SAME-PLATFORM reference with per-metric tolerances, and classifies
  failures:
    * `cpu_fallback` — the record ran on CPU because the accelerator
      probe failed (`fallback_from` present). This is an EXPLICIT
      failure class, not a soft warning: a fallback record's numbers
      must never silently re-baseline the trajectory.
    * `regression` — a gated metric fell below (or, for
      lower-is-better metrics, rose above) tolerance vs the reference.
    * `error` — the record itself is an error record.
- `render_markdown()` emits the verdict table the PR/driver logs keep.

Pure stdlib + json — importable without jax (the gate must run even
when the backend is the thing that is broken).
"""

from __future__ import annotations

import json
import re
from pathlib import Path

#: fail when `new < (1 - tol) * reference` (higher is better)
DEFAULT_TOLERANCES: dict[str, float] = {
    "value": 0.15,                    # headline infer graphs/s
    "train_graphs_per_sec": 0.15,
    "serve_requests_per_sec": 0.20,
    # pipelined-drive warm throughput (ISSUE 17, bench_serve interleaved
    # serial-vs-pipelined passes) — a drop past tolerance means the
    # overlap stopped paying for its thread handoffs
    "serve_pipeline_req_per_sec": 0.20,
    "combined_train_tokens_per_sec": 0.20,
    "mfu": 0.25,
    "train_mfu": 0.25,
    # whole-repo scanning (ISSUE 8; gated once both records carry it)
    "scan_functions_per_sec": 0.20,
    "scan_incremental_functions_per_sec": 0.25,
    # GGNN-step MFU against the same-window measured matmul ceiling
    # (ISSUE 9, scripts/bench_scatter.py:bench_ggnn_step): ggnn_mfu is
    # the production LAX chain's, ggnn_kernel_mfu the fused Pallas
    # kernel's — both gated so a regression on either lowering is
    # tracked
    "ggnn_mfu": 0.25,
    "ggnn_kernel_mfu": 0.25,
    # cascaded inference (ISSUE 12, scripts/bench_cascade.py via
    # bench.py --child-cascade behind DEEPDFA_BENCH_CASCADE): end-to-end
    # cascade req/s over the same dev set the combined-only baseline
    # serves — the capacity multiplier the cascade exists for
    "cascade_req_per_sec": 0.25,
    # the frontier's other axis: the cascade's measured speedup over
    # combined-only serving must stay a WIN (>1 means more requests per
    # device-second; gated against the trajectory so the margin cannot
    # silently erode)
    "cascade_speedup": 0.20,
    # shadow-ride agreement between the mirror candidate and the
    # incumbent over the bench's mini ride (ISSUE 20,
    # scripts/bench_load.py behind DEEPDFA_BENCH_FLEET): here the
    # candidate IS the incumbent's checkpoint, so agreement falling is
    # a comparison-plumbing regression (sampler/scorer pairing drift),
    # not a model difference
    "shadow_agreement": 0.10,
}

#: fail when `new > (1 + tol) * reference` (lower is better)
LOWER_IS_BETTER: dict[str, float] = {
    "serve_latency_p99_ms": 0.25,
    # device-idle share of the pipelined serve drive (ISSUE 17,
    # FIFO-union busy/idle windows, serve/batcher.py:DeviceWindow) —
    # the fraction the pipeline exists to shrink
    "serve_device_idle_fraction": 0.25,
    "padding_waste": 0.10,
    # fused GGNN per-step time (ISSUE 9; us/step, platform-resolved
    # kernel scatter) — a rise past tolerance is a hot-path regression
    "ggnn_step_us": 0.25,
    # the whole-unroll fusion's per-step time (ISSUE 16: all n_steps
    # inside ONE pallas_call, node state VMEM-resident) — gated
    # separately from ggnn_step_us so the fusion's margin over the
    # per-step kernel chain is a tracked number, not a one-off claim
    "ggnn_unroll_step_us": 0.25,
    # serving fleet under overload (ISSUE 11, scripts/bench_load.py via
    # bench.py --child-fleet behind DEEPDFA_BENCH_FLEET): p99 latency of
    # ADMITTED requests while the open-loop generator overloads the
    # fleet, and the shed fraction at that fixed offered rate — both
    # rising past tolerance means the router/admission path got slower
    # or the fleet lost capacity
    "fleet_p99_overload_ms": 0.25,
    "fleet_shed_rate": 0.25,
    # alert time-to-detect (ISSUE 19, scripts/bench_load.py): wall-clock
    # from an injected error burst to the burn-rate rule's firing
    # transition (obs/alerts.py). Generous: the episode is short and the
    # cadence granularity dominates.
    "alert_mttd_s": 0.5,
    # efficiency-ledger compile accounting (ISSUE 10): total AOT
    # compile wall time per bench child — a rise past tolerance means
    # the compiled programs got slower to build (or a site started
    # recompiling). Generous: compile time is the noisiest metric on a
    # shared compile service.
    "compile_seconds_total": 1.0,
    "train_compile_seconds_total": 1.0,
    # cascaded inference (ISSUE 12): the escalation rate at the fitted
    # band — creeping up means the calibration drifted or the band
    # widened, eroding the FLOP savings (generous: it is a property of
    # the fitted band on a synthetic dev set)
    "cascade_escalation_rate": 0.5,
    # the quantized entry's param-bytes fraction vs fp32 — rising means
    # the quantizer stopped covering weights it used to cover
    "quant_param_bytes_fraction": 0.10,
    # ledger-driven autotuner (ISSUE 15, bench.py --child-tune behind
    # DEEPDFA_BENCH_TUNE): the winning kernel layout's measured per-step
    # time on the smoke signature, and the fitted ladder's expected
    # padded-compute fraction on the skewed smoke distribution — either
    # rising past tolerance means the search started picking worse
    # layouts (docs/tuning.md)
    "tuned_ggnn_step_us": 0.25,
    "tuned_ladder_padding_waste": 0.10,
    # shadow sample lag (ISSUE 20): seconds from a sampled request
    # landing in shadow_samples.jsonl to the scorer consuming it during
    # the bench's mini ride — rising past tolerance means the mirror
    # stream is falling behind the traffic it shadows (generous: the
    # mini ride is short and poll cadence dominates)
    "shadow_sample_lag_s": 0.5,
}

#: lower-is-better metrics whose 0.0 reference is an EXACT-FIT claim,
#: not a degenerate ratio: they keep gating (absolute epsilon floor)
#: instead of being skipped when the reference round recorded 0.0
ZERO_REFERENCE_STRICT = frozenset({"tuned_ladder_padding_waste"})

#: ABSOLUTE upper bounds, checked whenever the candidate carries the
#: metric — no reference needed (the <=2% overhead contracts the PR-4
#: obs measurement established, now also covering the ledger's per-step
#: join). Exceeding one is a `regression`.
ABSOLUTE_UPPER_BOUNDS: dict[str, float] = {
    "obs_ledger_overhead_fraction": 0.02,
    # the fleet telemetry plane (ISSUE 19, obs/aggregate.py +
    # obs/alerts.py): snapshot publication + alert evaluation riding the
    # serving path must cost <= 2% of closed-loop throughput, measured
    # by scripts/bench_load.py's interleaved on/off reps
    "obs_fleet_overhead_fraction": 0.02,
    # shadow mirror sampling on the router's reply path (ISSUE 20,
    # flywheel/shadow.py:ShadowSampler): the every-kth sample append +
    # backpressure check must cost <= 2% of closed-loop router
    # throughput, measured by the same interleaved on/off reps
    "shadow_overhead_fraction": 0.02,
    # the cascade's pinned accuracy contract (ISSUE 12, docs/cascade.md):
    # dev-set AUC may trail combined-only serving by at most the drift
    # bound (one-sided — a cascade that scores BETTER is not a
    # regression); mirrors serve.quant_drift_bound's default
    "cascade_score_drift": 0.05,
    # int8 matmul weights + bf16 rest must keep the quantized entry
    # under half the fp32 bytes or the quantizer is not doing its job
    "quant_param_bytes_fraction": 0.5,
    # the autotuner's search must stay an offline bounded pass, never a
    # creeping compile storm: an ABSOLUTE ceiling on the measured
    # search wall time the bench child stamps (ISSUE 15)
    "tune_search_seconds": 300.0,
    # int8 MXU activations ride under a drift ADMISSION contract, not a
    # trajectory tolerance: the bench child's measured rel-err vs the
    # lax fp32 reference must stay inside the bound in every round
    # (mirrors nn/ggnn_kernel.py:INT8_DRIFT_BOUND — this module must
    # stay importable without jax; the pair is pinned equal in tests)
    "ggnn_kernel_int8_rel_err": 0.05,
}


def _record_from_tail(tail: str) -> dict | None:
    """Best-effort record recovery from a driver `tail` capture: the
    last full JSON line wins; a tail whose head was truncated mid-record
    (BENCH_r05) yields nothing rather than a wrong parse."""
    best = None
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            best = rec
    return best


def load_trajectory(root: str | Path) -> list[dict]:
    """Every committed bench artifact under `root`, oldest first:
    [{"source", "round"|None, "captured_at"|None, "record"|None,
    "note"|None}]. BENCH_r<N>.json are driver rounds (ordered by N);
    BENCH_TPU_*.json watchdog captures interleave by timestamp after
    them (they are fresher evidence by construction)."""
    root = Path(root)
    out: list[dict] = []
    for path in sorted(root.glob("BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)", path.name)
        entry: dict = {
            "source": path.name,
            "round": int(m.group(1)) if m else None,
        }
        try:
            artifact = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            entry["note"] = f"unreadable: {e}"
            out.append(entry)
            continue
        rec = artifact.get("parsed")
        if not isinstance(rec, dict):
            rec = _record_from_tail(str(artifact.get("tail", "")))
            if rec is not None:
                entry["note"] = "recovered from tail"
        if rec is None:
            entry["note"] = (
                f"no parseable record (driver rc={artifact.get('rc')})"
            )
        entry["record"] = rec
        out.append(entry)
    out.sort(key=lambda e: (e.get("round") or 0, e["source"]))
    captures = []
    for path in sorted(root.glob("BENCH_TPU_*.json")):
        try:
            artifact = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        rec = artifact.get("bench")
        if isinstance(rec, dict):
            captures.append({
                "source": path.name,
                "captured_at": artifact.get("captured_at"),
                "record": rec,
            })
    captures.sort(key=lambda e: str(e.get("captured_at") or ""))
    return out + captures


def classify(record: dict) -> str:
    """"healthy" | "cpu_fallback" | "error" for one bench record."""
    if not isinstance(record, dict) or "error" in record:
        return "error"
    if record.get("fallback_from"):
        return "cpu_fallback"
    return "healthy"


def reference_for(
    trajectory: list[dict],
    platform: str | None,
    exclude_source: str | None = None,
) -> dict | None:
    """The newest healthy record on the same platform (fallback records
    never become the baseline — that is the silent-rebaseline failure
    this gate exists to stop). Also looks inside `last_healthy_tpu`
    embeddings when the platform sought is tpu. `exclude_source` drops
    one trajectory entry — the candidate itself, when it is already
    committed: a record compared against itself passes vacuously."""
    best = None
    for entry in trajectory:
        rec = entry.get("record")
        if not isinstance(rec, dict):
            continue
        if exclude_source is not None and entry.get("source") == (
            exclude_source
        ):
            continue
        if classify(rec) == "healthy" and (
            platform is None or rec.get("platform") == platform
        ):
            best = {"record": rec, "source": entry["source"]}
        embedded = rec.get("last_healthy_tpu")
        if (
            platform == "tpu"
            and isinstance(embedded, dict)
            and isinstance(embedded.get("bench"), dict)
        ):
            best = {
                "record": embedded["bench"],
                "source": (
                    f"{entry['source']}:last_healthy_tpu"
                    f"[{embedded.get('artifact', '?')}]"
                ),
            }
    return best


def gate(
    record: dict,
    trajectory: list[dict],
    tolerances: dict[str, float] | None = None,
    expect_platform: str | None = None,
    exclude_source: str | None = None,
) -> dict:
    """Verdict for one candidate record against the trajectory.

    {"verdict": "pass"|"fail", "failure_classes": [...], "checks":
    [{metric, new, reference, ref_source, tolerance, direction, ok,
    ratio}], "notes": [...]}."""
    tol = dict(DEFAULT_TOLERANCES)
    lower = dict(LOWER_IS_BETTER)
    for k, v in (tolerances or {}).items():
        (lower if k in lower else tol)[k] = float(v)
    failure_classes: list[str] = []
    notes: list[str] = []
    checks: list[dict] = []

    cls = classify(record)
    if cls == "error":
        failure_classes.append("error")
        notes.append(
            f"record is an error record: {record.get('error', '?')!s:.200}"
        )
    elif cls == "cpu_fallback":
        failure_classes.append("cpu_fallback")
        notes.append(
            "record ran on CPU FALLBACK (accelerator probe failed: "
            f"{str(record.get('fallback_from'))[:200]}) — its numbers "
            "do not gate the accelerator trajectory and must not "
            "re-baseline it"
        )
    platform = record.get("platform")
    if expect_platform and platform != expect_platform:
        if "cpu_fallback" not in failure_classes:
            failure_classes.append("cpu_fallback")
        notes.append(
            f"expected platform {expect_platform!r}, record ran on "
            f"{platform!r}"
        )

    for metric, bound in sorted(ABSOLUTE_UPPER_BOUNDS.items()):
        v = record.get(metric)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        ok = v <= bound
        checks.append({
            "metric": metric,
            "new": v,
            "reference": bound,
            "ref_source": "absolute_bound",
            "tolerance": 0.0,
            "direction": "bound",
            "ratio": round(v / bound, 4) if bound else None,
            "ok": ok,
        })
        if not ok and "regression" not in failure_classes:
            failure_classes.append("regression")

    ref = reference_for(
        trajectory, platform, exclude_source=exclude_source
    )
    if ref is None:
        notes.append(
            f"no healthy {platform or 'any'}-platform reference in the "
            "trajectory — throughput checks skipped"
        )
    else:
        for metric, frac in sorted({**tol, **lower}.items()):
            new_v, ref_v = record.get(metric), ref["record"].get(metric)
            if not isinstance(new_v, (int, float)) or not isinstance(
                ref_v, (int, float)
            ) or isinstance(new_v, bool) or isinstance(ref_v, bool):
                continue
            is_lower = metric in lower
            if ref_v == 0:
                if metric not in ZERO_REFERENCE_STRICT:
                    # ratios against 0 are meaningless for ordinary
                    # throughput/rate metrics (a 0.0 shed-rate round
                    # must not hard-fail the first round that sheds
                    # one request) — skipped, as always
                    continue
                # ... but an exact-fit claim (padding waste 0.0) is a
                # CONTRACT: skipping would blind the gate forever
                # after the first perfect round, so those named
                # metrics compare with an absolute epsilon floor
                # (the gate_tuned rule)
                ok = new_v <= 1e-6
                ratio = None
            else:
                ratio = round(new_v / ref_v, 4)
                ok = (
                    new_v / ref_v <= 1 + frac if is_lower
                    else new_v / ref_v >= 1 - frac
                )
            checks.append({
                "metric": metric,
                "new": new_v,
                "reference": ref_v,
                "ref_source": ref["source"],
                "tolerance": frac,
                "direction": "lower" if is_lower else "higher",
                "ratio": ratio,
                "ok": ok,
            })
            if not ok and "regression" not in failure_classes:
                failure_classes.append("regression")
    return {
        "verdict": "fail" if failure_classes else "pass",
        "failure_classes": failure_classes,
        "platform": platform,
        "checks": checks,
        "notes": notes,
    }


# ---------------------------------------------------------------------------
# MULTICHIP_r* round-over-round gating (ROADMAP item 1 remainder): the
# dryrun_multichip artifact carries per-mesh-shape ledger sites (the
# `shard` table: flops/s, compile seconds, MFU when probed) and the
# serve ladder's zero-recompile pin — gate them against the newest
# healthy same-scale round with the BENCH_r* reference-selection rules
# (a failed/skipped round never re-baselines).

#: per-site higher-is-better tolerances (fractions below reference)
MULTICHIP_TOLERANCES: dict[str, float] = {
    # per-mesh-shape sustained FLOP/s — the MFU numerator on boxes
    # whose runtime ceiling was not probed; generous, this box drifts
    "flops_per_sec": 0.40,
    "per_shard_flops_per_sec": 0.40,
    # the roofline position itself, gated whenever BOTH rounds probed
    # the measured ceiling (docs/roofline.md method)
    "mfu_vs_measured_ceiling": 0.30,
}

#: per-site lower-is-better tolerances (fractions above reference);
#: compile time shares the bench gate's generous bound — a shared
#: compile service is the noisiest thing this repo measures
MULTICHIP_LOWER: dict[str, float] = {
    "compile_seconds": 1.0,
}


def multichip_record(artifact: dict) -> dict | None:
    """The {"multichip": ...} record inside one MULTICHIP_r* artifact:
    `parsed` (r07+) wins, else recovered from the last parseable tail
    line (the BENCH_r* tail-recovery rule)."""
    if not isinstance(artifact, dict):
        return None
    parsed = artifact.get("parsed")
    if isinstance(parsed, dict) and isinstance(
        parsed.get("multichip"), dict
    ):
        return parsed["multichip"]
    for line in reversed(str(artifact.get("tail", "")).splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and isinstance(
            rec.get("multichip"), dict
        ):
            return rec["multichip"]
    return None


def load_multichip_trajectory(root: str | Path) -> list[dict]:
    """Every committed MULTICHIP_r*.json, oldest round first:
    [{"source", "round", "artifact"|None, "record"|None, "note"|None}].
    Artifact keys: {n_devices, rc, ok, skipped, tail} (+ parsed since
    r07); rounds without a parseable record carry a note instead."""
    root = Path(root)
    out: list[dict] = []
    for path in sorted(root.glob("MULTICHIP_r*.json")):
        m = re.search(r"MULTICHIP_r(\d+)", path.name)
        entry: dict = {
            "source": path.name,
            "round": int(m.group(1)) if m else None,
        }
        try:
            artifact = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            entry["note"] = f"unreadable: {e}"
            out.append(entry)
            continue
        entry["artifact"] = artifact
        rec = multichip_record(artifact)
        if rec is None:
            entry["note"] = (
                f"no parseable multichip record "
                f"(rc={artifact.get('rc')}, ok={artifact.get('ok')})"
            )
        entry["record"] = rec
        out.append(entry)
    out.sort(key=lambda e: (e.get("round") or 0, e["source"]))
    return out


def _multichip_healthy(entry: dict) -> bool:
    art = entry.get("artifact") or {}
    return (
        isinstance(entry.get("record"), dict)
        and art.get("rc") == 0
        and bool(art.get("ok"))
        and not art.get("skipped")
    )


def multichip_reference_for(
    trajectory: list[dict],
    n_devices: int | None,
    exclude_source: str | None = None,
) -> dict | None:
    """The newest healthy same-scale round (n_devices must match — a
    dp8 record gated against a dp4 baseline compares nothing): the
    BENCH_r* rules, minus platform (the artifact doesn't carry one; the
    device count is the comparable-scale key)."""
    best = None
    for entry in trajectory:
        if exclude_source is not None and entry.get("source") == (
            exclude_source
        ):
            continue
        if not _multichip_healthy(entry):
            continue
        art = entry.get("artifact") or {}
        if n_devices is not None and art.get("n_devices") != n_devices:
            continue
        best = {"record": entry["record"], "source": entry["source"]}
    return best


def gate_multichip(
    artifact: dict,
    trajectory: list[dict],
    tolerances: dict[str, float] | None = None,
    exclude_source: str | None = None,
) -> dict:
    """Verdict for one MULTICHIP artifact against the committed
    trajectory — the same shape `gate()` returns. Checks: per-mesh-shape
    ledger sites present in BOTH rounds (flops/s and MFU higher-better,
    compile seconds lower-better), compile_seconds_total, and the serve
    ladder's zero-steady-state-recompile pin as an absolute bound."""
    tol = dict(MULTICHIP_TOLERANCES)
    lower = dict(MULTICHIP_LOWER)
    for k, v in (tolerances or {}).items():
        (lower if k in lower else tol)[k] = float(v)
    failure_classes: list[str] = []
    notes: list[str] = []
    checks: list[dict] = []
    record = multichip_record(artifact)
    if record is None or artifact.get("rc") != 0 or not artifact.get(
        "ok", True
    ):
        failure_classes.append("error")
        notes.append(
            f"artifact is not a healthy multichip round "
            f"(rc={artifact.get('rc')}, ok={artifact.get('ok')}, "
            f"record={'present' if record else 'missing'})"
        )
        record = record or {}

    # the Morphling pin, absolute: the sharded serve ladder must report
    # zero steady-state recompiles in every gated round
    recompiles = (record.get("serve") or {}).get(
        "steady_state_recompiles"
    )
    if recompiles is not None:
        ok = recompiles == 0
        checks.append({
            "metric": "serve/steady_state_recompiles",
            "new": recompiles,
            "reference": 0,
            "ref_source": "absolute_bound",
            "tolerance": 0.0,
            "direction": "bound",
            "ratio": None,
            "ok": ok,
        })
        if not ok and "regression" not in failure_classes:
            failure_classes.append("regression")

    ref = multichip_reference_for(
        trajectory, artifact.get("n_devices"),
        exclude_source=exclude_source,
    )
    if ref is None:
        notes.append(
            f"no healthy {artifact.get('n_devices')}-device reference "
            "round in the trajectory — per-site checks skipped"
        )
    else:
        new_sites = record.get("shard") or {}
        ref_sites = ref["record"].get("shard") or {}
        shared = sorted(set(new_sites) & set(ref_sites))
        skipped = sorted(
            set(new_sites) ^ set(ref_sites)
        )
        if skipped:
            notes.append(
                "sites in only one round (mesh shapes moved), not "
                f"gated: {skipped}"
            )
        for site in shared:
            for field, frac in sorted({**tol, **lower}.items()):
                new_v = new_sites[site].get(field)
                ref_v = ref_sites[site].get(field)
                if not isinstance(new_v, (int, float)) or not (
                    isinstance(ref_v, (int, float))
                ) or isinstance(new_v, bool) or isinstance(
                    ref_v, bool
                ) or ref_v == 0:
                    continue
                is_lower = field in lower
                ratio = new_v / ref_v
                ok = (
                    ratio <= 1 + frac if is_lower else ratio >= 1 - frac
                )
                checks.append({
                    "metric": f"{site}/{field}",
                    "new": new_v,
                    "reference": ref_v,
                    "ref_source": ref["source"],
                    "tolerance": frac,
                    "direction": "lower" if is_lower else "higher",
                    "ratio": round(ratio, 4),
                    "ok": ok,
                })
                if not ok and "regression" not in failure_classes:
                    failure_classes.append("regression")
        new_total = record.get("compile_seconds_total")
        ref_total = ref["record"].get("compile_seconds_total")
        if isinstance(new_total, (int, float)) and isinstance(
            ref_total, (int, float)
        ) and ref_total:
            frac = lower.get("compile_seconds", 1.0)
            ratio = new_total / ref_total
            ok = ratio <= 1 + frac
            checks.append({
                "metric": "compile_seconds_total",
                "new": new_total,
                "reference": ref_total,
                "ref_source": ref["source"],
                "tolerance": frac,
                "direction": "lower",
                "ratio": round(ratio, 4),
                "ok": ok,
            })
            if not ok and "regression" not in failure_classes:
                failure_classes.append("regression")
    return {
        "verdict": "fail" if failure_classes else "pass",
        "failure_classes": failure_classes,
        "n_devices": artifact.get("n_devices"),
        "checks": checks,
        "notes": notes,
    }


# ---------------------------------------------------------------------------
# TUNED_r* round-over-round gating (ISSUE 15, docs/tuning.md): the
# committed tuned.json trajectory joins the bench gate the way
# BENCH_r*/MULTICHIP_r* did — a tuned layout that regresses against its
# OWN record (winner step time up, fitted padding waste up, or the
# fit losing to the pow2 baseline it exists to beat) fails CI.

#: per-signature / per-ladder lower-is-better tolerances — derived from
#: the bench-record entries above so the TUNED_r* gate and the
#: BENCH-record gate can never enforce different bounds on the same
#: quantities
TUNED_TOLERANCES: dict[str, float] = {
    "winner_step_us": LOWER_IS_BETTER["tuned_ggnn_step_us"],
    "padding_waste": LOWER_IS_BETTER["tuned_ladder_padding_waste"],
}

#: absolute wall-time ceiling on one recorded search pass (the ONE
#: declaration lives in ABSOLUTE_UPPER_BOUNDS)
TUNED_SEARCH_SECONDS_BOUND = ABSOLUTE_UPPER_BOUNDS["tune_search_seconds"]


def _tuned_doc(artifact: dict) -> dict | None:
    if not isinstance(artifact, dict):
        return None
    doc = artifact.get("tuned") if "records" not in artifact else artifact
    if isinstance(doc, dict) and isinstance(doc.get("records"), list):
        return doc
    return None


def tuned_reference_for(
    trajectory: list[dict],
    hardware: dict,
    exclude_source: str | None = None,
) -> dict | None:
    """The newest trajectory record whose hardware key matches exactly
    (a v5e layout gated against a v4 baseline compares nothing) — the
    BENCH_r* reference-selection rules with the hardware key as the
    comparable-scale axis."""
    from deepdfa_tpu.tune.cache import find_record

    best = None
    for entry in trajectory:
        if exclude_source is not None and entry.get("source") == (
            exclude_source
        ):
            continue
        doc = entry.get("record")
        if not isinstance(doc, dict):
            continue
        rec = find_record(doc, hardware)
        if rec is not None:
            best = {"record": rec, "source": entry["source"]}
    return best


def gate_tuned(
    artifact: dict,
    trajectory: list[dict],
    tolerances: dict[str, float] | None = None,
    exclude_source: str | None = None,
) -> dict:
    """Verdict for one tuned.json / TUNED_r* document against the
    committed trajectory — the shape `gate()` returns. Checks, per
    hardware-keyed record: schema validity (an invalid document is an
    `error`), the search-seconds absolute bound, per-signature winner
    step time vs the newest same-hardware reference, per-ladder fitted
    padding waste vs the reference, and the fit-beats-pow2 invariant as
    an absolute bound."""
    from deepdfa_tpu.tune.cache import validate_tuned

    tol = dict(TUNED_TOLERANCES)
    for k, v in (tolerances or {}).items():
        tol[k] = float(v)
    failure_classes: list[str] = []
    notes: list[str] = []
    checks: list[dict] = []

    doc = _tuned_doc(artifact)
    verdict = validate_tuned(artifact)
    if doc is None or not verdict["ok"]:
        failure_classes.append("error")
        notes.extend(
            f"schema: {p}" for p in verdict.get("problems", [])[:8]
        )
        doc = doc or {"records": []}

    def fail(cls: str = "regression") -> None:
        if cls not in failure_classes:
            failure_classes.append(cls)

    for rec in doc.get("records", []):
        if not isinstance(rec, dict):
            continue
        hw = rec.get("hardware") or {}
        hw_label = (
            f"{hw.get('device_kind')}@"
            f"{hw.get('node_budget')}x{hw.get('edge_budget')}"
        )
        secs = rec.get("search_seconds")
        if isinstance(secs, (int, float)) and not isinstance(secs, bool):
            ok = secs <= TUNED_SEARCH_SECONDS_BOUND
            checks.append({
                "metric": f"{hw_label}/search_seconds",
                "new": secs,
                "reference": TUNED_SEARCH_SECONDS_BOUND,
                "ref_source": "absolute_bound",
                "tolerance": 0.0,
                "direction": "bound",
                "ratio": round(secs / TUNED_SEARCH_SECONDS_BOUND, 4),
                "ok": ok,
            })
            if not ok:
                fail()
        # the fit must beat (or tie) its own recorded pow2 baseline —
        # absolute, no reference round needed
        for name, lr in (rec.get("ladders") or {}).items():
            if not isinstance(lr, dict):
                continue
            w, p = lr.get("padding_waste"), lr.get("pow2_padding_waste")
            if isinstance(w, (int, float)) and isinstance(
                p, (int, float)
            ) and not isinstance(w, bool) and not isinstance(p, bool):
                ok = w <= p
                checks.append({
                    "metric": f"{hw_label}/ladders/{name}/fit_vs_pow2",
                    "new": w,
                    "reference": p,
                    "ref_source": "absolute_bound",
                    "tolerance": 0.0,
                    "direction": "bound",
                    "ratio": round(w / p, 4) if p else None,
                    "ok": ok,
                })
                if not ok:
                    fail()
        ref = tuned_reference_for(
            trajectory, hw, exclude_source=exclude_source
        )
        if ref is None:
            notes.append(
                f"no same-hardware reference for {hw_label} in the "
                "trajectory — round-over-round checks skipped"
            )
            continue
        rrec = ref["record"]
        new_kernel = rec.get("kernel") or {}
        ref_kernel = rrec.get("kernel") or {}
        for sig in sorted(set(new_kernel) & set(ref_kernel)):
            # variant axes ride the winner row (winner_scatter since
            # ISSUE 15; winner_accum/winner_unroll since ISSUE 16 —
            # absent on older rounds, where per_step/fp32 was the only
            # mode timed): a flip between rounds is WORTH A NOTE (the
            # search changed its mind about the layout family) but
            # never a failure — the step-time check below is the
            # arbiter of whether the new winner is actually better
            for axis, default in (
                ("winner_scatter", None),
                ("winner_accum", "fp32"),
                ("winner_unroll", "per_step"),
            ):
                new_a = (new_kernel[sig] or {}).get(axis, default)
                ref_a = (ref_kernel[sig] or {}).get(axis, default)
                if (
                    isinstance(new_a, str)
                    and isinstance(ref_a, str)
                    and new_a != ref_a
                ):
                    notes.append(
                        f"{hw_label}/kernel/{sig}: {axis} flipped "
                        f"{ref_a!r} -> {new_a!r} vs {ref['source']}"
                    )
            new_v = (new_kernel[sig] or {}).get("winner_step_us")
            ref_v = (ref_kernel[sig] or {}).get("winner_step_us")
            if not isinstance(new_v, (int, float)) or not isinstance(
                ref_v, (int, float)
            ) or isinstance(new_v, bool) or isinstance(
                ref_v, bool
            ) or not ref_v:
                continue
            frac = tol["winner_step_us"]
            ratio = new_v / ref_v
            ok = ratio <= 1 + frac
            checks.append({
                "metric": f"{hw_label}/kernel/{sig}/winner_step_us",
                "new": new_v,
                "reference": ref_v,
                "ref_source": ref["source"],
                "tolerance": frac,
                "direction": "lower",
                "ratio": round(ratio, 4),
                "ok": ok,
            })
            if not ok:
                fail()
        new_ladders = rec.get("ladders") or {}
        ref_ladders = rrec.get("ladders") or {}
        for name in sorted(set(new_ladders) & set(ref_ladders)):
            new_v = (new_ladders[name] or {}).get("padding_waste")
            ref_v = (ref_ladders[name] or {}).get("padding_waste")
            if not isinstance(new_v, (int, float)) or not isinstance(
                ref_v, (int, float)
            ) or isinstance(new_v, bool) or isinstance(ref_v, bool):
                continue
            frac = tol["padding_waste"]
            # waste can legitimately be 0.0 (an exact fit): compare with
            # an absolute epsilon floor so a 0-reference still gates
            bound = ref_v * (1 + frac) + 1e-6
            ok = new_v <= bound
            checks.append({
                "metric": f"{hw_label}/ladders/{name}/padding_waste",
                "new": new_v,
                "reference": ref_v,
                "ref_source": ref["source"],
                "tolerance": frac,
                "direction": "lower",
                "ratio": (
                    round(new_v / ref_v, 4) if ref_v else None
                ),
                "ok": ok,
            })
            if not ok:
                fail()
    return {
        "verdict": "fail" if failure_classes else "pass",
        "failure_classes": failure_classes,
        "checks": checks,
        "notes": notes,
    }


# ---------------------------------------------------------------------------
# DRILL_r* round-over-round gating (docs/fleet.md "Scheduled drills"):
# the scheduled chaos drills (fleet/drill.py) measure recovery times —
# failover, admission reseed, readmit, rollback — and commit one
# DRILL_r<N>.json per round. The trajectory joins the gate the way
# BENCH_r*/MULTICHIP_r*/TUNED_r* did: a drill whose measured failover
# regressed past tolerance vs the newest healthy same-mode round fails
# CI, and the documented 3.2 s failover bound is an ABSOLUTE ceiling in
# every round, reference or not.

#: lower-is-better tolerances on the measured recovery times — generous
#: (shared-CPU wall-clock timing is the noisiest thing the fleet
#: measures; the absolute bound below is the hard line)
DRILL_TOLERANCES: dict[str, float] = {
    "drill_failover_s": 1.0,
    "drill_reseed_s": 1.0,
    "drill_readmit_s": 1.0,
    "drill_rollback_s": 1.0,
}

#: ABSOLUTE ceiling on measured router failover, every round (mirrors
#: fleet/drill.py:DRILL_BOUND_S — this module must stay importable
#: without the fleet stack; the pair is pinned equal in tests)
DRILL_FAILOVER_BOUND_S = 3.2


def load_drill_trajectory(root: str | Path) -> list[dict]:
    """Every committed DRILL_r*.json under `root`, oldest round first:
    [{"source", "round", "record"|None, "note"|None}]. The drill record
    IS the artifact (no driver tail wrapper to recover from); unreadable
    files carry a note instead of a record."""
    root = Path(root)
    out: list[dict] = []
    for path in sorted(root.glob("DRILL_r*.json")):
        m = re.search(r"DRILL_r(\d+)", path.name)
        entry: dict = {
            "source": path.name,
            "round": int(m.group(1)) if m else None,
        }
        try:
            record = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            entry["note"] = f"unreadable: {e}"
            entry["record"] = None
            out.append(entry)
            continue
        entry["record"] = record if isinstance(record, dict) else None
        if entry["record"] is None:
            entry["note"] = "not a JSON object"
        out.append(entry)
    out.sort(key=lambda e: (e.get("round") or 0, e["source"]))
    return out


def _drill_healthy(record) -> bool:
    return (
        isinstance(record, dict)
        and record.get("ok") is True
        and isinstance(record.get("drill_failover_s"), (int, float))
        and not isinstance(record.get("drill_failover_s"), bool)
    )


def drill_reference_for(
    trajectory: list[dict],
    mode: str | None,
    exclude_source: str | None = None,
) -> dict | None:
    """The newest healthy SAME-MODE round (a smoke drill's in-process
    stub timings gated against a full drill's subprocess timings compare
    nothing) — the BENCH_r* reference rules with `mode` as the
    comparable-scale key; a failed round never re-baselines."""
    best = None
    for entry in trajectory:
        if exclude_source is not None and entry.get("source") == (
            exclude_source
        ):
            continue
        rec = entry.get("record")
        if not _drill_healthy(rec):
            continue
        if mode is not None and rec.get("mode") != mode:
            continue
        best = {"record": rec, "source": entry["source"]}
    return best


def gate_drill(
    record: dict,
    trajectory: list[dict],
    tolerances: dict[str, float] | None = None,
    exclude_source: str | None = None,
) -> dict:
    """Verdict for one DRILL record against the committed trajectory —
    the shape `gate()` returns. Checks: structural validity (an invalid
    or failed record is an `error`), the 3.2 s failover bound as an
    absolute ceiling, and each measured recovery time present in BOTH
    rounds vs the newest healthy same-mode reference."""
    from deepdfa_tpu.fleet.drill import validate_drill_record

    tol = dict(DRILL_TOLERANCES)
    for k, v in (tolerances or {}).items():
        tol[k] = float(v)
    failure_classes: list[str] = []
    notes: list[str] = []
    checks: list[dict] = []

    problems = validate_drill_record(record)
    if problems:
        failure_classes.append("error")
        notes.extend(f"schema: {p}" for p in problems[:8])
        record = record if isinstance(record, dict) else {}
    elif record.get("ok") is not True:
        failure_classes.append("error")
        failed = [
            f"round {r.get('round')}: {r.get('error', 'failed')}"
            for r in record.get("per_round", [])
            if not r.get("ok")
        ]
        notes.append(
            "drill record is not healthy (ok=false): "
            + ("; ".join(failed)[:300] or "failover over bound")
        )

    failover = record.get("drill_failover_s")
    if isinstance(failover, (int, float)) and not isinstance(
        failover, bool
    ):
        ok = failover <= DRILL_FAILOVER_BOUND_S
        checks.append({
            "metric": "drill_failover_s",
            "new": failover,
            "reference": DRILL_FAILOVER_BOUND_S,
            "ref_source": "absolute_bound",
            "tolerance": 0.0,
            "direction": "bound",
            "ratio": round(failover / DRILL_FAILOVER_BOUND_S, 4),
            "ok": ok,
        })
        if not ok and "regression" not in failure_classes:
            failure_classes.append("regression")

    ref = drill_reference_for(
        trajectory, record.get("mode"), exclude_source=exclude_source
    )
    if ref is None:
        notes.append(
            f"no healthy {record.get('mode') or 'any'}-mode reference "
            "round in the trajectory — round-over-round checks skipped"
        )
    else:
        for metric, frac in sorted(tol.items()):
            new_v = record.get(metric)
            ref_v = ref["record"].get(metric)
            if not isinstance(new_v, (int, float)) or not isinstance(
                ref_v, (int, float)
            ) or isinstance(new_v, bool) or isinstance(
                ref_v, bool
            ) or ref_v == 0:
                continue
            ratio = new_v / ref_v
            ok = ratio <= 1 + frac
            checks.append({
                "metric": metric,
                "new": new_v,
                "reference": ref_v,
                "ref_source": ref["source"],
                "tolerance": frac,
                "direction": "lower",
                "ratio": round(ratio, 4),
                "ok": ok,
            })
            if not ok and "regression" not in failure_classes:
                failure_classes.append("regression")
    return {
        "verdict": "fail" if failure_classes else "pass",
        "failure_classes": failure_classes,
        "mode": record.get("mode"),
        "checks": checks,
        "notes": notes,
    }


def render_markdown(result: dict, record: dict | None = None) -> str:
    """The human half of the verdict: a status line, the failure
    classes, and the per-metric table."""
    icon = "✅" if result["verdict"] == "pass" else "❌"
    lines = [
        f"## Bench gate: {icon} {result['verdict'].upper()}",
        "",
    ]
    if record is not None:
        lines.append(
            f"- record: `{record.get('metric', '?')}` = "
            f"{record.get('value', '?')} {record.get('unit', '')} on "
            f"`{record.get('platform', '?')}` "
            f"(git `{record.get('git_sha', '?')}`)"
        )
    for c in result["failure_classes"]:
        lines.append(f"- failure class: **{c}**")
    for n in result["notes"]:
        lines.append(f"- {n}")
    if result["checks"]:
        lines += [
            "",
            "| metric | new | reference | ratio | tolerance | ok |",
            "|---|---|---|---|---|---|",
        ]
        for c in result["checks"]:
            arrow = "↓ok" if c["direction"] == "lower" else "↑ok"
            lines.append(
                f"| {c['metric']} | {c['new']:g} | {c['reference']:g} "
                f"({c['ref_source']}) | {c['ratio']} | "
                f"±{c['tolerance']} ({arrow}) | "
                f"{'✅' if c['ok'] else '❌'} |"
            )
    return "\n".join(lines) + "\n"
