"""On-demand XLA profiling + sync-free step-time decomposition.

Three capabilities, all default-off (core/config.py:ObsConfig):

- **XprofController** — `jax.profiler` trace capture of a configured
  step window (`obs.xprof_start_step` + `obs.xprof_num_steps`), plus
  LIVE-run triggers: SIGUSR2 or touching `<run_dir>/xprof/TRIGGER`
  arms a capture of the next `xprof_num_steps` steps without restarting
  the run. Captures land under `<run_dir>/xprof/` for TensorBoard's
  profile plugin (the deep-dive layer under the host-side trace in
  obs/trace.py — same division of labor as the reference's DeepSpeed
  FlopsProfiler vs CUDA-event timing, eval/profiling.py).
- **StepTimer** — per-step host/device decomposition with the
  lagged-fetch pattern from train/resilience.py (`guard_lag`): step k's
  loss handle is fetched only after step k+lag has been dispatched, so
  the fetch blocks only when the device is genuinely behind — the happy
  path stays sync-free. Emits `obs/step/*` histograms into the metrics
  registry and, when tracing is on, `step_device` spans reconstructing
  the device-paced timeline in the merged trace.
- **device_memory_stats()** — per-epoch allocator stats
  (bytes_in_use / peak) where the backend exposes them (TPU/GPU; CPU
  returns {} and the record key is simply absent).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from collections import deque
from pathlib import Path

from deepdfa_tpu.obs import metrics, trace

#: polling a trigger file stat() every step would be measurable on ms
#: steps; every N steps it is noise
_TRIGGER_POLL_STEPS = 20

_controller: "XprofController | None" = None


class XprofController:
    """Start/stop jax.profiler traces on step boundaries.

    `on_step(step)` is called by the train loops once per step (before
    dispatch); it is a few comparisons when idle. Window capture fires
    once per run; triggers re-arm (each SIGUSR2 / TRIGGER touch captures
    one window)."""

    def __init__(
        self,
        log_dir: str | Path,
        start_step: int = -1,
        num_steps: int = 5,
        trigger: bool = False,
    ):
        self.log_dir = Path(log_dir)
        self.start_step = int(start_step)
        self.num_steps = max(1, int(num_steps))
        self.trigger_path = self.log_dir / "TRIGGER"
        self._armed = threading.Event()
        self._active_until: int | None = None
        self._window_done = False
        self._captures = 0
        self._prev_handler = None
        self._trigger = bool(trigger)
        if self._trigger:
            self.log_dir.mkdir(parents=True, exist_ok=True)
            if threading.current_thread() is threading.main_thread():
                try:
                    self._prev_handler = signal.signal(
                        signal.SIGUSR2, self._on_signal
                    )
                except (ValueError, OSError):
                    self._prev_handler = None

    def _on_signal(self, signum, frame) -> None:
        self._armed.set()

    def _check_trigger(self, step: int) -> bool:
        if self._armed.is_set():
            self._armed.clear()
            return True
        if step % _TRIGGER_POLL_STEPS == 0 and self.trigger_path.exists():
            try:
                self.trigger_path.unlink()
            except OSError:
                pass
            return True
        return False

    def _start(self, step: int, reason: str) -> None:
        import jax

        out = self.log_dir / f"step-{step:08d}"
        out.mkdir(parents=True, exist_ok=True)
        try:
            jax.profiler.start_trace(str(out))
        except Exception:  # a second start (external profiler) must not
            return  # kill the training run
        self._active_until = step + self.num_steps
        self._captures += 1
        metrics.REGISTRY.counter("obs/xprof/captures").inc()
        trace.instant("xprof_capture_start", cat="train",
                      step=step, reason=reason)

    def _stop(self) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        self._active_until = None

    def on_step(self, step: int) -> None:
        if self._active_until is not None:
            if step >= self._active_until:
                self._stop()
            return
        if (
            self.start_step >= 0
            and not self._window_done
            and step >= self.start_step
        ):
            self._window_done = True
            self._start(step, "window")
            return
        if self._trigger and self._check_trigger(step):
            self._start(step, "trigger")

    def close(self) -> None:
        if self._active_until is not None:
            self._stop()
        if self._prev_handler is not None:
            try:
                signal.signal(signal.SIGUSR2, self._prev_handler)
            except (ValueError, OSError):
                pass
            self._prev_handler = None


def install_controller(
    log_dir: str | Path, start_step: int, num_steps: int, trigger: bool
) -> XprofController:
    """Module-global controller so the loops reach it without new fit()
    parameters (obs.instruments routes on_step here)."""
    global _controller
    if _controller is not None:
        _controller.close()
    _controller = XprofController(
        log_dir, start_step=start_step, num_steps=num_steps, trigger=trigger
    )
    return _controller


def uninstall_controller() -> None:
    global _controller
    if _controller is not None:
        _controller.close()
        _controller = None


def controller_on_step(step: int) -> None:
    if _controller is not None:
        _controller.on_step(step)


class StepTimer:
    """Lagged-fetch step-time decomposition (no happy-path sync).

    Per step the loop calls `dispatched(loss_handle)` right after the
    async train-step dispatch. The handle is queued; once more than
    `lag` are pending, the oldest is fetched — by then the device has
    normally finished it, so `jax.device_get` returns without blocking
    and the inter-completion interval approximates the device-paced
    step time. `fetch_wait` > 0 is the signal the device is the
    bottleneck at the measured moment (the complement of
    input_wait_fraction, which indicts the host)."""

    def __init__(self, lag: int = 1, registry=None, on_step_seconds=None):
        self.lag = max(0, int(lag))
        self._r = registry if registry is not None else metrics.REGISTRY
        self._pending: deque = deque()
        self._last_done: float | None = None
        #: optional consumer of each measured device-paced step second —
        #: the efficiency ledger's per-signature MFU join
        #: (obs/ledger.py:observe_step_seconds); None = metrics only
        self._on_step_seconds = on_step_seconds

    def dispatched(self, handle, dispatch_seconds: float | None = None) -> None:
        import jax

        now = time.perf_counter()
        if dispatch_seconds is not None:
            self._r.histogram("obs/step/dispatch_seconds").observe(
                dispatch_seconds
            )
        self._pending.append((now, handle))
        if len(self._pending) <= self.lag:
            return
        t_disp, h = self._pending.popleft()
        t0 = time.perf_counter()
        jax.device_get(h)
        done = time.perf_counter()
        self._r.histogram("obs/step/fetch_wait_seconds").observe(done - t0)
        if self._last_done is not None:
            step_s = done - self._last_done
            self._r.histogram("obs/step/seconds").observe(step_s)
            if self._on_step_seconds is not None:
                self._on_step_seconds(step_s)
        self._last_done = done
        if trace.enabled():
            # reconstruct the device window in the merged timeline: from
            # the step's dispatch to its (lagged) observed completion —
            # on the dedicated device track so the backdated start is
            # not rewritten by the per-thread monotonic nudge
            now_us = trace.Tracer.now_us()
            dur_us = (done - t_disp) * 1e6
            trace.complete_event(
                "step_device", now_us - dur_us, dur_us, cat="train",
                tid=trace.DEVICE_TRACK_TID, track_name="device-steps",
            )

    def drain(self) -> None:
        """Fetch everything still pending (epoch end)."""
        import jax

        while self._pending:
            _, h = self._pending.popleft()
            jax.device_get(h)
        self._last_done = None


def device_memory_stats() -> dict[str, float]:
    """Allocator stats for device 0, {} where unsupported (CPU)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return {}
    if not stats:
        return {}
    keep = (
        "bytes_in_use", "peak_bytes_in_use", "bytes_limit",
        "largest_alloc_size",
    )
    return {k: float(stats[k]) for k in keep if k in stats}
