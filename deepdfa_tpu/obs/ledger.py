"""Device efficiency ledger (docs/efficiency.md).

The paper's headline claim is *efficiency* (Table 5: GFLOPs and
ms-per-example per model), and the obs stack so far sees host stages and
serving SLOs but is blind on-device: nothing in the runtime answers
"what did each compiled executable cost, how full is HBM, and how close
to the measured ceiling is each signature running". That knowledge lived
in one-shot scripts (eval/profiling.py, scripts/bench_scatter.py). This
module is the runtime half:

- **one cost-analysis reader** — `read_cost_analysis(compiled)` is THE
  jax list-vs-dict `Compiled.cost_analysis()` shim (jax <= 0.4.x returns
  a one-entry list; newer jax the dict). `eval/profiling.py:
  compiled_cost` is a thin client, so Table-5 profiling and runtime
  accounting cannot drift.
- **per-signature efficiency sites** — every AOT `lower()->compile()`
  in the stack (GraphTrainer/CombinedTrainer step caches, the
  `GgnnExecutor`/`CombinedExecutor` warmup ladders, `GgnnLocalizer`)
  reports `record_compile(tag, signature, compiled, seconds)`:
  XLA-exact flops + bytes, compile wall time, and the executable's
  memory-analysis live bytes. Executions report
  `observe_execution(tag, signature, seconds)` (the serve batcher per
  batch; the train loops via the PR-4 sync-free `StepTimer` join —
  `set_step_site` + `observe_step_seconds`), so the snapshot derives a
  ROLLING per-signature FLOP/s and, when measured ceilings are present,
  the roofline position (`mfu_vs_measured_ceiling`,
  `bytes_vs_gather_ceiling` — the docs/roofline.md method, generalized
  from scripts/bench_scatter.py into the runtime).
- **HBM memory ledger** — `record_memory(phase)` keeps per-phase
  allocator watermarks (xprof.device_memory_stats), and
  `record_params(tag, params)` the per-registry-entry parameter bytes
  (the ROADMAP item-3/item-5 co-serving capacity signal).
- **OOM forensics** — `is_oom(exc)` recognizes RESOURCE_EXHAUSTED, and
  the flight recorder (obs/flight.py) dumps the ledger into
  postmortem.json when one escapes.

Everything is default OFF (`cfg.obs.ledger`): the module-level wrappers
are one `is None` check when disabled, no call site pays anything, and
no program signature is added — the ledger only *reads* executables the
stack already compiles. The one exception is opt-in and documented:
with the ledger ON, GraphTrainer AOT-compiles its (already jitted) step
once per signature to read the cost analysis (jit's call cache is not
seeded by `.lower().compile()`, so this is a second compile of the SAME
program — warmup cost only, never steady-state).
"""

from __future__ import annotations

import math
import threading
import time

from deepdfa_tpu.obs import metrics as obs_metrics

#: bump when the snapshot / postmortem "ledger" section shape changes
LEDGER_VERSION = 1

_ledger: "EfficiencyLedger | None" = None
_lock = threading.Lock()


# ---------------------------------------------------------------------------
# the ONE cost-analysis reader (eval/profiling.compiled_cost is a client)


def read_cost_analysis(compiled) -> dict:
    """XLA cost analysis of a Compiled executable, normalized:
    {"flops", "bytes_accessed", "cost_analysis": {numeric fields}}.

    THE list-vs-dict shim: jax <= 0.4.x returns a one-entry list of
    per-executable dicts from `Compiled.cost_analysis()`; newer jax
    returns the dict directly. Every consumer (Table-5 profiling,
    bench.py MFU fields, this ledger) reads through here."""
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "cost_analysis": {
            k: v for k, v in cost.items() if isinstance(v, (int, float))
        },
    }


def executable_memory(compiled) -> dict:
    """Numeric fields of `Compiled.memory_analysis()` ({} where the
    backend does not implement it), plus a derived `live_bytes` total
    (arguments + outputs + temps + generated code, aliasing credited) —
    the executable's device-memory footprint the HBM ledger tracks."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    out: dict = {}
    for name in dir(mem):
        if name.startswith("_"):
            continue
        try:
            v = getattr(mem, name)
        except Exception:
            continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[name] = float(v)
    live = 0.0
    for k in (
        "argument_size_in_bytes", "output_size_in_bytes",
        "temp_size_in_bytes", "generated_code_size_in_bytes",
    ):
        live += out.get(k, 0.0)
    live -= out.get("alias_size_in_bytes", 0.0)
    if live > 0:
        out["live_bytes"] = live
    return out


def is_oom(exc: BaseException) -> bool:
    """Does an exception look like a device out-of-memory? XLA surfaces
    OOM as RESOURCE_EXHAUSTED (XlaRuntimeError); the allocator's own
    message spells it out. The flight recorder uses this to classify a
    crash as trigger="oom" and dump the HBM ledger with it."""
    text = f"{type(exc).__name__}: {exc}"
    return "RESOURCE_EXHAUSTED" in text or "Out of memory" in text


# ---------------------------------------------------------------------------
# the ledger


def _new_site() -> dict:
    return {
        "flops": 0.0,
        "bytes_accessed": 0.0,
        "compile_seconds": 0.0,
        "compiles": 0,
        "live_bytes": 0.0,
        "executions": 0,
        "device_seconds": 0.0,
    }


class EfficiencyLedger:
    """Per-(tag, signature) compile + execution accounting for one
    process. Host-side only: it never traces, lowers, or compiles on its
    own — call sites hand it executables they already built."""

    def __init__(self, registry: obs_metrics.MetricsRegistry | None = None):
        self._r = registry if registry is not None else obs_metrics.REGISTRY
        self._lk = threading.Lock()
        self._sites: dict[tuple[str, str], dict] = {}
        self._memory: dict[str, dict[str, float]] = {}
        self._params: dict[str, float] = {}
        #: measured ceilings (matmul FLOP/s, gather bytes/s) the rolling
        #: MFU/roofline fields are read against; {} = raw FLOP/s only
        self.ceilings: dict[str, float] = {}
        self.errors: list[str] = []
        self.created_unix = time.time()

    # -- compile side --------------------------------------------------------

    def record_compile(
        self,
        tag: str,
        signature: str,
        compiled=None,
        seconds: float = 0.0,
        flops: float | None = None,
        bytes_accessed: float | None = None,
        live_bytes: float | None = None,
    ) -> None:
        """One lower()->compile() at an AOT site. `compiled` (when
        given) supplies XLA-exact flops/bytes + live bytes through the
        one reader above; the explicit kwargs exist for fixtures and for
        lazy jit compiles where only the wall time is known."""
        cost: dict = {}
        mem: dict = {}
        if compiled is not None:
            try:
                cost = read_cost_analysis(compiled)
            except Exception as e:  # accounting must never cost the run
                self._note_error(f"cost_analysis[{tag}/{signature}]: {e}")
            mem = executable_memory(compiled)
        with self._lk:
            site = self._sites.setdefault((tag, signature), _new_site())
            site["compiles"] += 1
            site["compile_seconds"] += float(seconds)
            f = flops if flops is not None else cost.get("flops", 0.0)
            b = (
                bytes_accessed if bytes_accessed is not None
                else cost.get("bytes_accessed", 0.0)
            )
            lv = (
                live_bytes if live_bytes is not None
                else mem.get("live_bytes", 0.0)
            )
            if f:
                site["flops"] = float(f)
            if b:
                site["bytes_accessed"] = float(b)
            if lv:
                site["live_bytes"] = float(lv)
        base = f"ledger/{tag}/{signature}"
        self._r.counter(f"{base}/compiles").inc()
        self._r.counter(f"{base}/compile_seconds").inc(float(seconds))
        self._r.counter("ledger/compile_seconds_total").inc(float(seconds))
        if f:
            self._r.gauge(f"{base}/flops").set(float(f))
        if b:
            self._r.gauge(f"{base}/bytes_accessed").set(float(b))
        if lv:
            self._r.gauge(f"{base}/live_bytes").set(float(lv))

    def has_site(self, tag: str, signature: str) -> bool:
        with self._lk:
            return (tag, signature) in self._sites

    # -- execution side ------------------------------------------------------

    def observe_execution(
        self, tag: str, signature: str, seconds: float, n: int = 1
    ) -> None:
        """`n` executions of a signature took `seconds` of measured
        device(-paced) time — the join that turns static cost analysis
        into rolling FLOP/s. Hot-path cost: one lock + three adds."""
        if not (seconds > 0.0) or not math.isfinite(seconds):
            return
        with self._lk:
            site = self._sites.setdefault((tag, signature), _new_site())
            site["executions"] += int(n)
            site["device_seconds"] += float(seconds)

    #: the train loops run ONE signature at a time; the StepTimer join
    #: routes its lagged step seconds to whatever site the loop declared
    def set_step_site(self, tag: str, signature: str) -> None:
        with self._lk:
            self._step_site = (tag, signature)

    _step_site: tuple[str, str] | None = None

    def observe_step_seconds(self, seconds: float) -> None:
        site = self._step_site
        if site is not None:
            self.observe_execution(site[0], site[1], seconds)

    # -- HBM side ------------------------------------------------------------

    def record_memory(self, phase: str, stats: dict | None = None) -> None:
        """Fold the current allocator stats into the `phase` watermark
        (max-merge, so the phase keeps its peak). CPU backends report no
        stats and the phase is simply absent; `stats` is injectable for
        tests and fixtures."""
        if stats is None:
            from deepdfa_tpu.obs import xprof

            stats = xprof.device_memory_stats()
        if not stats:
            return
        with self._lk:
            mark = self._memory.setdefault(phase, {})
            for k, v in stats.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    mark[k] = max(mark.get(k, -math.inf), float(v))
        for k, v in stats.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self._r.gauge(f"ledger/memory/{phase}/{k}").set(float(v))

    def record_params(self, tag: str, params) -> float:
        """Parameter bytes of one registry entry / model — the
        co-serving capacity signal (how many entries fit one chip's
        HBM). Returns the byte count."""
        import numpy as np

        total = 0.0
        try:
            import jax

            leaves = jax.tree.leaves(params)
        except Exception:
            leaves = []
        for leaf in leaves:
            try:
                total += float(
                    np.prod(leaf.shape) * np.dtype(leaf.dtype).itemsize
                )
            except Exception:
                continue
        with self._lk:
            self._params[tag] = total
        self._r.gauge(f"ledger/params/{tag}/bytes").set(total)
        return total

    # -- derived views -------------------------------------------------------

    def _site_view(self, site: dict) -> dict:
        out = {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in site.items()
        }
        secs = site["device_seconds"]
        if secs > 0 and site["executions"]:
            fps = site["flops"] * site["executions"] / secs
            bps = site["bytes_accessed"] * site["executions"] / secs
            if site["flops"]:
                out["flops_per_sec"] = round(fps, 1)
            if site["bytes_accessed"]:
                out["bytes_per_sec"] = round(bps, 1)
            ceil_f = self.ceilings.get("matmul_flops_per_sec", 0.0)
            if site["flops"] and ceil_f > 0:
                out["mfu_vs_measured_ceiling"] = round(fps / ceil_f, 6)
            ceil_b = self.ceilings.get("gather_bytes_per_sec", 0.0)
            if site["bytes_accessed"] and ceil_b > 0:
                out["bytes_vs_gather_ceiling"] = round(bps / ceil_b, 6)
        return out

    def snapshot(self) -> dict:
        """The whole ledger as one JSON-able dict — what epoch records,
        /stats, serve/scan log records, and the postmortem embed
        (flattens to SCHEMA-declared `ledger/*` tags)."""
        with self._lk:
            sites = {
                f"{tag}/{sig}": dict(site)
                for (tag, sig), site in self._sites.items()
            }
            memory = {p: dict(m) for p, m in self._memory.items()}
            params = dict(self._params)
        out: dict = {
            "version": LEDGER_VERSION,
            "sites": {
                label: self._site_view(site)
                for label, site in sites.items()
            },
            "compile_seconds_total": round(
                sum(s["compile_seconds"] for s in sites.values()), 3
            ),
        }
        if self.ceilings:
            out["ceilings"] = {
                k: v for k, v in self.ceilings.items()
                if isinstance(v, (int, float))
            }
        if memory:
            out["memory"] = memory
        if params:
            out["params"] = params
        if self.errors:
            out["errors"] = list(self.errors)
        return out

    def publish_gauges(self) -> None:
        """Mirror the derived per-site MFU/throughput into `ledger/*`
        gauges so a `/metrics` scrape carries the rolling roofline
        position, not only the static compile-time fields."""
        with self._lk:
            sites = {
                f"{tag}/{sig}": dict(site)
                for (tag, sig), site in self._sites.items()
            }
        for label, site in sites.items():
            view = self._site_view(site)
            for k in (
                "flops_per_sec", "bytes_per_sec",
                "mfu_vs_measured_ceiling", "bytes_vs_gather_ceiling",
                "device_seconds", "executions",
            ):
                if k in view and isinstance(view[k], (int, float)):
                    self._r.gauge(f"ledger/{label}/{k}").set(
                        float(view[k])
                    )

    def mfu_record(self) -> dict:
        """Bench stamping view: {"ledger_mfu": {site: mfu-or-flops/s},
        "compile_seconds_total": ...} — the fields BENCH_*.json records
        carry (declared in obs/metrics.py:SCHEMA, gated in
        obs/bench_gate.py)."""
        snap = self.snapshot()
        mfu: dict[str, float] = {}
        for label, view in snap["sites"].items():
            v = view.get("mfu_vs_measured_ceiling")
            if v is None:
                v = view.get("flops_per_sec")
            if isinstance(v, (int, float)):
                mfu[label] = v
        out: dict = {"compile_seconds_total": snap["compile_seconds_total"]}
        if mfu:
            out["ledger_mfu"] = mfu
        return out

    def _note_error(self, msg: str) -> None:
        with self._lk:
            if len(self.errors) < 16:
                self.errors.append(str(msg)[:200])


# ---------------------------------------------------------------------------
# measured runtime ceilings (docs/roofline.md, generalized into the runtime)


def measure_runtime_ceilings() -> dict[str, float]:
    """Small-size measured-ceiling probes for the RUNTIME ledger: the
    same docs/roofline.md method bench_scatter uses (dense-matmul FLOP/s
    + gather/segment-sum bytes/s on the CURRENT device, same window),
    sized to cost ~a second so enabling the ledger on a training run is
    cheap. Same contemporaneous-point-sample caveat as the bench probes:
    on a time-shared chip the ceiling moves, so treat ratios > 1 as "the
    probe sampled a slower window", not as broken accounting."""
    from deepdfa_tpu.eval import profiling

    out: dict[str, float] = {}
    try:
        m = profiling.measure_matmul_ceiling(n=1024, chain=2, reps=1)
        out["matmul_flops_per_sec"] = m["matmul_tflops_measured"] * 1e12
    except Exception:
        pass
    try:
        g = profiling.measure_gather_bandwidth(
            rows=2048, dim=64, idx_len=8192, chain=2, reps=1
        )
        out["gather_bytes_per_sec"] = g["gather_gbps_measured"] * 1e9
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# module surface (what every call site uses; no-ops when disabled)


def enable(
    ceilings: bool | dict = False,
    registry: obs_metrics.MetricsRegistry | None = None,
) -> EfficiencyLedger:
    """Install the process ledger. `ceilings=True` runs the runtime
    measured-ceiling probes once (so per-site MFU is vs the measured
    ceiling, docs/roofline.md); a dict injects ceilings directly
    (tests, fixtures)."""
    global _ledger
    with _lock:
        led = EfficiencyLedger(registry=registry)
        if isinstance(ceilings, dict):
            led.ceilings = dict(ceilings)
        _ledger = led
    if ceilings is True:
        led.ceilings = measure_runtime_ceilings()
    return led


def disable() -> None:
    global _ledger
    with _lock:
        _ledger = None


def get() -> EfficiencyLedger | None:
    return _ledger


def enabled() -> bool:
    return _ledger is not None


def record_compile(tag, signature, compiled=None, seconds=0.0, **kw) -> None:
    led = _ledger
    if led is not None:
        led.record_compile(tag, signature, compiled, seconds, **kw)


def observe_execution(tag, signature, seconds, n: int = 1) -> None:
    led = _ledger
    if led is not None:
        led.observe_execution(tag, signature, seconds, n=n)


def set_step_site(tag, signature) -> None:
    led = _ledger
    if led is not None:
        led.set_step_site(tag, signature)


def observe_step_seconds(seconds: float) -> None:
    led = _ledger
    if led is not None:
        led.observe_step_seconds(seconds)


def record_memory(phase: str, stats: dict | None = None) -> None:
    led = _ledger
    if led is not None:
        led.record_memory(phase, stats=stats)


def record_params(tag: str, params) -> None:
    led = _ledger
    if led is not None:
        led.record_params(tag, params)


def publish_gauges() -> None:
    led = _ledger
    if led is not None:
        led.publish_gauges()


def snapshot_or_none() -> dict | None:
    led = _ledger
    return led.snapshot() if led is not None else None
