"""`deepdfa-tpu diag <run_dir>` — render what a run did from its
telemetry artifacts.

Reads the three streams a run leaves behind (any subset may be absent):

- `train_log.jsonl`      — epoch/step records (train/logging.py)
- `trace/trace-*.jsonl`  — the merged-timeline event stream (obs/trace.py)
- `checkpoints*-step/`   — resume manifests + watchdog diagnostics
  (train/resilience.py)

and renders: run summary, per-epoch throughput timeline, host/device
stage attribution (from the epoch records AND recomputed independently
from the trace spans — the cross-check that the event stream carries the
run's attribution), the resilience event log (stalls, skips,
rollbacks, resume points), and — when the run served — the SLO, scan,
fleet (per-replica traffic/occupancy, shed by tenant/priority,
eject/readmit log; docs/fleet.md), efficiency, and postmortem sections.
`--json` emits the same content as one machine-readable object;
`--smoke` builds a synthetic run dir through the real emission APIs and
renders it (the tier-1 regression surface).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

from deepdfa_tpu.obs import trace

#: trace span names that constitute host input-stage attribution
_INPUT_STAGES = ("load", "pack", "place", "wait")


def _read_jsonl(path: Path) -> list[dict]:
    """Best-effort JSONL reader shared by every run-log stream: blank
    and truncated lines (a crash mid-append) are skipped, never fatal."""
    if not path.exists():
        return []
    records = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return records


def load_records(run_dir: Path) -> list[dict]:
    return _read_jsonl(run_dir / "train_log.jsonl")


def load_events(run_dir: Path) -> list[dict]:
    tdir = run_dir / "trace"
    return trace.merge(tdir) if tdir.is_dir() else []


def stage_attribution_from_records(records: list[dict]) -> dict:
    """Host-stage totals as the epoch records report them."""
    keys = {
        "load": "host_load_seconds", "pack": "host_pack_seconds",
        "place": "host_place_seconds", "wait": "input_wait_seconds",
    }
    epochs = [r for r in records if "epoch_seconds" in r]
    if not epochs:
        return {}
    out = {
        stage: round(sum(float(r.get(k, 0.0)) for r in epochs), 3)
        for stage, k in keys.items()
    }
    out["epoch_seconds"] = round(
        sum(float(r["epoch_seconds"]) for r in epochs), 3
    )
    return out


def stage_attribution_from_events(events: list[dict]) -> dict:
    """The same attribution recomputed from trace spans alone (cat
    "input"), plus packer-worker and train-dispatch totals and the
    process census — the proof the event stream is self-sufficient."""
    stages = {s: 0.0 for s in _INPUT_STAGES}
    worker_seconds = 0.0
    train_dispatch_seconds = 0.0
    device_seconds = 0.0
    pids: set[int] = set()
    spans_by_pid: dict[int, int] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        pid = e.get("pid", 0)
        pids.add(pid)
        spans_by_pid[pid] = spans_by_pid.get(pid, 0) + 1
        dur_s = float(e.get("dur", 0.0)) / 1e6
        cat, name = e.get("cat"), e.get("name")
        if cat == "input" and name in stages:
            stages[name] += dur_s
        elif cat == "pack_worker":
            worker_seconds += dur_s
        elif cat == "train" and name == "train_step":
            train_dispatch_seconds += dur_s
        elif cat == "train" and name == "step_device":
            device_seconds += dur_s
    if not pids:
        return {}
    return {
        **{s: round(v, 3) for s, v in stages.items()},
        "pack_worker_seconds": round(worker_seconds, 3),
        "train_dispatch_seconds": round(train_dispatch_seconds, 3),
        "device_step_seconds": round(device_seconds, 3),
        "processes": sorted(pids),
        "spans_per_process": {
            str(pid): n for pid, n in sorted(spans_by_pid.items())
        },
    }


def throughput_timeline(records: list[dict]) -> list[dict]:
    rows = []
    for r in records:
        if "epoch_seconds" not in r:
            continue
        secs = float(r["epoch_seconds"])
        row = {
            "epoch": r.get("epoch"),
            "epoch_seconds": round(secs, 3),
            "train_loss": r.get("train_loss"),
            "input_wait_fraction": r.get("input_wait_fraction"),
        }
        for k in ("train_examples_per_sec", "train_tokens_per_sec"):
            if k in r:
                row[k] = r[k]
        rows.append(row)
    return rows


def resilience_log(run_dir: Path, records, events) -> dict:
    out: dict = {"events": [], "counters": {}, "watchdog": []}
    for e in events:
        if e.get("cat") == "resilience":
            out["events"].append({
                "name": e.get("name"), "ts_us": e.get("ts"),
                **(e.get("args") or {}),
            })
    last = next(
        (r for r in reversed(records) if "rollbacks" in r), None
    )
    if last is not None:
        out["counters"] = {
            k: last.get(k)
            for k in ("resumed_from_step", "skipped_steps", "rollbacks")
        }
    for diag_path in sorted(run_dir.glob("**/watchdog_diagnostic.json")):
        try:
            out["watchdog"].append(json.loads(diag_path.read_text()))
        except (json.JSONDecodeError, OSError):
            continue
    for manifest in sorted(run_dir.glob("checkpoints*-step/resume.json")):
        try:
            m = json.loads(manifest.read_text())
            out.setdefault("resume_manifests", []).append({
                "path": str(manifest.relative_to(run_dir)),
                "step": m.get("step"), "epoch": m.get("epoch"),
                "reason": m.get("reason"),
            })
        except (json.JSONDecodeError, OSError):
            continue
    return out


def load_serve_records(run_dir: Path) -> list[dict]:
    """serve_log.jsonl records (the serve/score CLI append one metrics
    record per drive; docs/serving.md)."""
    return _read_jsonl(run_dir / "serve_log.jsonl")


def serve_attribution(serve_records: list[dict]) -> dict:
    """Serving latency attribution from the newest serve record: how
    much of a scored request's time went to the frontend, the queue,
    and the device (histogram count/mean from the serve registry
    snapshot), plus the throughput/occupancy headline."""
    if not serve_records:
        return {}
    rec = serve_records[-1]
    snap = rec.get("serve", {})
    out = {
        k: rec[k]
        for k in (
            "serve_requests_per_sec", "serve_latency_p50_ms",
            "serve_latency_p99_ms", "serve_batch_occupancy_mean",
            "serve_steady_state_recompiles",
        )
        if k in rec
    }
    for stage, name in (
        ("frontend", "frontend_seconds"),
        ("queue", "queue_wait_seconds"),
        ("device", "device_seconds"),
    ):
        mean = snap.get(f"{name}/mean")
        if mean is not None:
            out[f"{stage}_mean_ms"] = round(1e3 * mean, 3)
    for k in ("requests", "rejected", "failed", "batches",
              "cache_hits", "cache_misses", "hot_swaps"):
        if k in snap:
            out[k] = snap[k]
    # the ladder blind-spot view (deepdfa_tpu/tune/, docs/tuning.md):
    # per-rung real vs padded rows from the executor counters, so a
    # stream whose sizes all land just above a rung is visible here
    # even with tuning off
    rungs: dict[str, dict] = {}
    for k, v in snap.items():
        if not k.startswith("ladder/"):
            continue
        parts = k.split("/")
        if len(parts) != 3:
            continue
        _, rung, field = parts
        if field in ("real_rows", "padded_rows"):
            rungs.setdefault(rung, {})[field] = v
    if rungs:
        for rung, agg in rungs.items():
            real = agg.get("real_rows", 0.0)
            padded = agg.get("padded_rows", 0.0)
            total = real + padded
            if total:
                agg["waste"] = round(padded / total, 4)

        def rung_order(label: str):
            # numeric order, not lexicographic (G2 before G16); graph
            # rungs (G*) before combined bucket labels (T*xR*)
            m = re.match(r"([A-Za-z]+)(\d+)", label)
            if m:
                return (m.group(1), int(m.group(2)))
            return (label, 0)

        out["ladder"] = {
            k: rungs[k] for k in sorted(rungs, key=rung_order)
        }
    if "ladder_waste" in snap:
        out["ladder_waste"] = snap["ladder_waste"]
    return out


def slo_section(serve_records: list[dict]) -> dict:
    """The serving SLO section, rebuilt from serve_log.jsonl alone: the
    per-request entries (`serve.request_log`) give exact percentiles and
    status counts over ALL logged requests plus the trailing 60s/300s
    windows (relative to the newest entry's wall clock), and the newest
    summary record contributes the engine's own live snapshot — two
    independently-derived views of the same SLO, like the
    records-vs-trace stage attribution above."""
    from deepdfa_tpu.obs.slo import percentile

    entries = [
        r["request"] for r in serve_records
        if isinstance(r.get("request"), dict)
    ]
    out: dict = {}
    engine = next(
        (
            rec["serve_slo"] for rec in reversed(serve_records)
            if isinstance(rec.get("serve_slo"), dict)
        ),
        None,
    )
    # the windows the run was actually configured with (engine snapshot
    # labels like "60s"), so the two views describe the SAME horizons;
    # default to the stock 60s/300s when no summary record exists
    horizons = sorted(
        int(k[:-1]) for k in (engine or {})
        if isinstance(k, str) and k.endswith("s") and k[:-1].isdigit()
    ) or [60, 300]
    if entries:
        def view(rows: list[dict]) -> dict:
            lat = sorted(
                e["latency_ms"] for e in rows if "latency_ms" in e
            )
            v: dict = {"requests": len(rows)}
            if lat:
                v["latency_ms"] = {
                    f"p{int(q * 100)}": round(percentile(lat, q), 3)
                    for q in (0.50, 0.95, 0.99)
                }
            status: dict[str, int] = {}
            for e in rows:
                if "status" in e:
                    s = str(int(e["status"]))
                    status[s] = status.get(s, 0) + 1
            if status:
                v["status"] = dict(sorted(status.items()))
                n = sum(status.values())
                errs = sum(
                    c for s, c in status.items()
                    if not s.startswith("2")
                )
                v["error_rate"] = round(errs / n, 4)
            for stage in ("frontend_ms", "queue_ms", "device_ms"):
                vals = [e[stage] for e in rows if stage in e]
                if vals:
                    v[f"{stage}_mean"] = round(
                        sum(vals) / len(vals), 3
                    )
            return v

        out["all"] = view(entries)
        newest = max(
            (e.get("t_unix", 0.0) for e in entries), default=0.0
        )
        for horizon in horizons:
            rows = [
                e for e in entries
                if e.get("t_unix", 0.0) >= newest - horizon
            ]
            if rows:
                out[f"{horizon}s"] = view(rows)
    if engine is not None:
        out["engine"] = engine
    return out


def cascade_section(serve_records: list[dict]) -> dict:
    """The two-stage cascade section (serve/cascade.py, docs/cascade.md),
    rebuilt from serve_log.jsonl: escalation accounting from the newest
    summary's cascade section, the observed stage-1-vs-stage-2 latency
    attribution from per-request entries, and the quantized-vs-fp32
    per-entry param bytes from the embedded ledger snapshot."""
    entries = [
        r["request"] for r in serve_records
        if isinstance(r.get("request"), dict)
        and "stage" in r["request"]
    ]
    summary = next(
        (
            rec["cascade"] for rec in reversed(serve_records)
            if isinstance(rec.get("cascade"), dict)
        ),
        None,
    )
    out: dict = {}
    if summary is not None:
        out["counters"] = summary
    if entries:
        esc = sum(1 for e in entries if int(e.get("stage", 1)) == 2)
        out["requests"] = len(entries)
        out["escalated"] = esc
        out["escalation_rate_observed"] = round(esc / len(entries), 4)
        out["sheds_observed"] = sum(
            1 for e in entries if e.get("cascade_shed")
        )
        for stage in ("cascade_stage1_ms", "cascade_stage2_ms"):
            vals = [e[stage] for e in entries if stage in e]
            if vals:
                out[f"{stage}_mean"] = round(sum(vals) / len(vals), 3)
    # quantized entries next to their fp32 twins: the density win the
    # per-entry param-bytes ledger measures (serve/quant.py)
    led_params = next(
        (
            rec["ledger"]["params"] for rec in reversed(serve_records)
            if isinstance(rec.get("ledger"), dict)
            and isinstance(rec["ledger"].get("params"), dict)
        ),
        None,
    )
    if led_params:
        quant_entries = {}
        for tag, nbytes in sorted(led_params.items()):
            if not tag.endswith("@int8"):
                continue
            twin = led_params.get(tag[: -len("@int8")])
            quant_entries[tag] = {
                "bytes": nbytes,
                "fp32_bytes": twin,
                "fraction": (
                    round(nbytes / twin, 4) if twin else None
                ),
            }
        if quant_entries:
            out["quant_entries"] = quant_entries
    return out


def load_scan_records(run_dir: Path) -> list[dict]:
    """scan_log.jsonl records (one summary per repo scan,
    deepdfa_tpu/scan/scanner.py; docs/scanning.md)."""
    return _read_jsonl(run_dir / "scan_log.jsonl")


def scan_section(scan_records: list[dict]) -> dict:
    """The repo-scan section, rebuilt from scan_log.jsonl alone: the
    newest scan's throughput/coverage headline, the incremental skip
    and frontend cache-hit rates, and the per-stage latency attribution
    (walk/split/frontend/score/attribute/write seconds)."""
    if not scan_records:
        return {}
    rec = scan_records[-1]
    out = {
        k: rec[k]
        for k in (
            "scan_files", "scan_functions", "scan_reused",
            "scan_scored", "scan_functions_failed", "scan_findings",
            "scan_seconds", "scan_functions_per_sec",
            "scan_incremental_skip_fraction", "scan_cache_hit_fraction",
            "scan_steady_state_recompiles",
            "scan_lines_steady_state_recompiles", "repo",
        )
        if k in rec
    }
    stages = {}
    for stage in ("walk", "split", "frontend", "score", "attribute",
                  "write"):
        v = rec.get(f"scan_{stage}_seconds")
        if v is not None:
            stages[stage] = v
    if stages:
        out["stage_seconds"] = stages
    out["scans"] = sum(
        1 for r in scan_records if "scan_functions" in r
    )
    return out


def load_fleet_records(run_dir: Path) -> list[dict]:
    """fleet_log.jsonl entries (per-request + lifecycle events +
    summary records, deepdfa_tpu/fleet/router.py; docs/fleet.md)."""
    return _read_jsonl(run_dir / "fleet_log.jsonl")


def fleet_section(run_dir: Path, fleet_records: list[dict]) -> dict:
    """The serving-fleet section, rebuilt from the router's
    fleet_log.jsonl (plus each replica's own serve log under
    fleet/<id>/ when present): per-replica req/s and batch occupancy,
    shed rate by tenant and priority class, and the eject/readmit/drain
    event log — the operator view ISSUE 11 asks `diag` for."""
    if not fleet_records:
        return {}
    requests = [
        r["request"] for r in fleet_records
        if isinstance(r.get("request"), dict)
    ]
    events = [
        r["fleet_event"] for r in fleet_records
        if isinstance(r.get("fleet_event"), dict)
    ]
    summaries = [
        r for r in fleet_records if "fleet" in r or "fleet_slo" in r
    ]
    out: dict = {"requests": len(requests), "events": len(events)}
    times = [r["t_unix"] for r in requests if "t_unix" in r]
    span_s = (max(times) - min(times)) if len(times) > 1 else 0.0
    # per-replica obs homes live under fleet.fleet_dir when the run
    # configured one (cmd_fleet/ReplicaWorker honor it); default
    # <run_dir>/fleet
    fleet_dir = run_dir / "fleet"
    cfg_path = run_dir / "config.json"
    if cfg_path.exists():
        try:
            configured = (
                json.loads(cfg_path.read_text())
                .get("fleet", {}).get("fleet_dir")
            )
            if configured:
                fleet_dir = Path(configured)
        except (json.JSONDecodeError, OSError):
            pass
    # federated telemetry snapshots (obs/aggregate.py) are the
    # PREFERRED source for per-replica health: schema-validated,
    # torn-write-safe, and they carry the exactly-merged latency
    # histograms. Replica serve logs are the fallback.
    snap_replicas: dict[str, dict] = {}
    telemetry: dict = {}
    try:
        if list(Path(fleet_dir).glob("metrics-*.json")):
            from deepdfa_tpu.obs.aggregate import FleetAggregator
            aggregator = FleetAggregator(fleet_dir)
            telemetry = aggregator.stats_section()
            collected_replicas = aggregator.collect().get("replicas") or {}
            snap_replicas = {
                rid: rep["snapshot"]
                for rid, rep in collected_replicas.items()
            }
    except Exception as e:  # diag reports, it never crashes on bad input
        telemetry = {"problems": [f"snapshot aggregation failed: {e}"]}
    if telemetry:
        out["telemetry"] = telemetry
    # per-replica traffic + occupancy (occupancy from the replica's
    # published snapshot when the telemetry plane is on — else from its
    # own serve log: the router never sees batch fill, the batcher does)
    per_replica: dict[str, dict] = {}
    for req in requests:
        rid = req.get("replica")
        if not rid:
            continue
        agg = per_replica.setdefault(rid, {"requests": 0})
        agg["requests"] += 1
    for rid, agg in per_replica.items():
        if span_s > 0:
            agg["requests_per_sec"] = round(agg["requests"] / span_s, 3)
        snap = snap_replicas.get(rid)
        occ = (
            (snap.get("metrics") or {}).get("serve/batch_occupancy/mean")
            if snap else None
        )
        if occ is not None:
            agg["batch_occupancy_mean"] = round(occ, 4)
            agg["telemetry_source"] = "snapshots"
            continue
        if snap_replicas:
            # the fleet published snapshots but this replica's carries no
            # occupancy (or none at all) — say so out loud before we go
            # scrape its serve log
            agg["telemetry_source"] = (
                "serve_log (FALLBACK: no usable snapshot for this replica)"
            )
        for rec in reversed(
            _read_jsonl(fleet_dir / rid / "serve_log.jsonl")
        ):
            occ = (rec.get("serve") or {}).get("batch_occupancy/mean")
            if occ is not None:
                agg["batch_occupancy_mean"] = round(occ, 4)
                break
    if per_replica:
        out["replicas"] = dict(sorted(per_replica.items()))
    # shed analysis: rate overall, then by tenant and priority class
    shed = [r for r in requests if r.get("shed")]
    if requests:
        out["shed_rate"] = round(len(shed) / len(requests), 4)
    by_tenant: dict[str, dict] = {}
    by_priority: dict[str, dict] = {}
    for req in requests:
        tenant = str(req.get("tenant", "default"))
        prio = str(req.get("priority", "?"))
        for key, table in ((tenant, by_tenant), (prio, by_priority)):
            agg = table.setdefault(key, {"requests": 0, "shed": 0})
            agg["requests"] += 1
            agg["shed"] += 1 if req.get("shed") else 0
    for table in (by_tenant, by_priority):
        for agg in table.values():
            agg["shed_rate"] = round(agg["shed"] / agg["requests"], 4)
    if by_tenant:
        out["by_tenant"] = dict(sorted(by_tenant.items()))
    if by_priority:
        out["by_priority"] = dict(sorted(by_priority.items()))
    shed_reasons: dict[str, int] = {}
    for req in shed:
        reason = str(req.get("reason", "?"))
        shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
    if shed_reasons:
        out["shed_reasons"] = dict(sorted(shed_reasons.items()))
    # lifecycle log: the eject/readmit/drain evidence, in order
    out["event_log"] = [
        {
            k: ev[k]
            for k in ("name", "replica", "t_unix", "failures", "state")
            if k in ev
        }
        for ev in events
    ]
    if summaries:
        last = summaries[-1]
        fl = last.get("fleet") or {}
        out["counters"] = {
            k: fl[k]
            for k in ("requests", "forwarded", "retries", "ejects",
                      "readmits", "admitted", "shed",
                      "replicas_routable")
            if k in fl
        }
        slo = last.get("fleet_slo")
        if slo:
            out["slo"] = slo
    return out


def autoscale_section(fleet_records: list[dict]) -> dict:
    """The predictive-autoscaling section, rebuilt from the router's
    fleet_log.jsonl `{"autoscale": ...}` decision records
    (fleet/autoscale.py; docs/fleet.md): action counts, the decision
    timeline (forecast vs capacity ratio per bucket), and the first
    scale_up — the record that must land BEFORE the offered rate
    crosses capacity."""
    decisions = [
        r["autoscale"] for r in fleet_records
        if isinstance(r.get("autoscale"), dict)
    ]
    if not decisions:
        return {}
    actions: dict[str, int] = {}
    for d in decisions:
        a = str(d.get("action", "?"))
        actions[a] = actions.get(a, 0) + 1
    out: dict = {
        "decisions": len(decisions),
        "actions": dict(sorted(actions.items())),
        "timeline": [
            {
                k: d[k]
                for k in ("action", "reason", "forecast_rps",
                          "offered_rps", "capacity_rps", "ratio",
                          "replicas", "target_replicas", "stage")
                if k in d
            }
            for d in decisions
        ],
    }
    first_up = next(
        (d for d in decisions if d.get("action") == "scale_up"), None
    )
    if first_up is not None:
        out["first_scale_up"] = {
            k: first_up[k]
            for k in ("forecast_rps", "offered_rps", "capacity_rps",
                      "ratio", "replicas", "target_replicas")
            if k in first_up
        }
    return out


def alerts_section(fleet_records: list[dict]) -> dict:
    """The alert-engine section, rebuilt from the router's fleet_log
    `{"alert": ...}` transition records (obs/alerts.py; docs/alerts.md):
    per-rule transition counts, time-to-detect (first firing after the
    preceding resolved/inactive stretch), and whatever is STILL firing
    at the end of the log — the on-call summary."""
    transitions = [
        r["alert"] for r in fleet_records
        if isinstance(r.get("alert"), dict)
    ]
    if not transitions:
        return {}
    rules: dict[str, dict] = {}
    for tr in transitions:
        name = str(tr.get("rule", "?"))
        row = rules.setdefault(name, {
            "kind": tr.get("kind"), "transitions": 0,
            "fired": 0, "resolved": 0, "last_state": None,
        })
        row["transitions"] += 1
        state = tr.get("state")
        if state == "firing":
            row["fired"] += 1
        elif state == "resolved":
            row["resolved"] += 1
        row["last_state"] = state
        if "observed" in tr and tr["observed"] is not None:
            row["last_observed"] = tr["observed"]
        if "tenant" in tr:
            row["tenant"] = tr["tenant"]
    return {
        "transitions": len(transitions),
        "rules": dict(sorted(rules.items())),
        "still_firing": sorted(
            name for name, row in rules.items()
            if row["last_state"] in ("firing", "pending")
        ),
    }


def flywheel_section(fleet_records: list[dict]) -> dict:
    """The data-flywheel section, rebuilt from the fleet_log's
    `{"shadow"|"promotion"|"demotion": ...}` records
    (deepdfa_tpu/flywheel/; docs/flywheel.md): per-candidate ride
    summaries with the shadow-vs-incumbent comparison timeline
    (windowed agreement / calibration drift / AUC pair), and the
    promotion/demotion history — the audit trail of every time the
    fleet changed (or refused to change) its own model."""
    shadows = [
        r["shadow"] for r in fleet_records
        if isinstance(r.get("shadow"), dict)
    ]
    promotions = [
        r["promotion"] for r in fleet_records
        if isinstance(r.get("promotion"), dict)
    ]
    demotions = [
        r["demotion"] for r in fleet_records
        if isinstance(r.get("demotion"), dict)
    ]
    if not (shadows or promotions or demotions):
        return {}
    rides: dict[str, dict] = {}
    for s in shadows:
        cand = str(s.get("candidate", "?"))
        ride = rides.setdefault(cand, {
            "incumbent": s.get("incumbent"), "windows": 0,
            "timeline": [],
        })
        event = s.get("event")
        if event == "window":
            ride["windows"] += 1
            ride["timeline"].append({
                k: s[k]
                for k in ("samples", "labeled", "agreement",
                          "prob_drift", "lag_s", "auc_candidate",
                          "auc_incumbent", "verdict", "verdict_reason")
                if k in s
            })
        elif event == "ride_end":
            ride["ended"] = True
    history = sorted(
        [{"kind": "promotion", **p} for p in promotions]
        + [{"kind": "demotion", **d} for d in demotions],
        key=lambda e: e.get("t_unix") or 0.0,
    )
    return {
        "rides": dict(sorted(rides.items())),
        "promotions": len(promotions),
        "demotions": len(demotions),
        "history": [
            {
                k: e[k]
                for k in ("kind", "candidate", "reason", "rollout_ok",
                          "swapped", "halt_reason", "auc_candidate",
                          "auc_incumbent", "t_unix")
                if k in e
            }
            for e in history
        ],
    }


def drill_section(
    run_dir: Path, root: str | Path | None = None
) -> dict:
    """The scheduled chaos-drill trajectory (DRILL_r*.json records,
    fleet/drill.py; docs/fleet.md): every round's measured
    failover/readmit/reseed/rollback times plus the regression-gate
    verdict for the newest round (obs/bench_gate.py:gate_drill — the
    3.2 s failover bound is an absolute ceiling). Looks in the run dir
    first (the smoke fixture drops its record there), then the
    committed repo-root trajectory."""
    from deepdfa_tpu.fleet.drill import validate_drill_record
    from deepdfa_tpu.obs import bench_gate as bg

    trajectory = bg.load_drill_trajectory(Path(run_dir))
    if not trajectory:
        root = (
            Path(root) if root
            else Path(__file__).resolve().parents[2]
        )
        trajectory = bg.load_drill_trajectory(root)
    if not trajectory:
        return {}
    rows = []
    newest = None
    newest_source = None
    for entry in trajectory:
        rec = entry.get("record")
        row: dict = {"source": entry["source"]}
        if entry.get("round") is not None:
            row["round"] = entry["round"]
        if isinstance(rec, dict):
            row.update({
                k: rec[k]
                for k in ("mode", "rounds", "drill_failover_s",
                          "drill_readmit_s", "drill_reseed_s",
                          "drill_rollback_s", "drill_bound_s", "ok")
                if k in rec
            })
            row["valid"] = not validate_drill_record(rec)
            newest, newest_source = rec, entry["source"]
        if entry.get("note"):
            row["note"] = entry["note"]
        rows.append(row)
    out: dict = {"trajectory": rows}
    if newest is not None:
        # the newest round is part of the trajectory: exclude it from
        # its own reference selection, like the bench gate does
        out["gate"] = bg.gate_drill(
            newest, trajectory, exclude_source=newest_source
        )
    return out


def efficiency_section(run_dir: Path, records: list[dict]) -> dict:
    """The device efficiency view (obs/ledger.py, docs/efficiency.md),
    rebuilt from the run's own artifacts: the newest embedded ledger
    snapshot (epoch records, serve_log, scan_log — whichever is
    freshest), plus the per-epoch HBM watermark timeline."""
    snaps: list[dict] = []
    timeline: list[dict] = []
    for rec in records:
        led = rec.get("ledger")
        if isinstance(led, dict):
            snaps.append(led)
            mem = led.get("memory") or {}
            epoch_mem = mem.get("epoch") or next(
                iter(mem.values()), {}
            )
            if "epoch" in rec and epoch_mem:
                timeline.append({
                    "epoch": rec.get("epoch"),
                    **{
                        k: epoch_mem[k]
                        for k in ("bytes_in_use", "peak_bytes_in_use")
                        if k in epoch_mem
                    },
                })
    for log in ("serve_log.jsonl", "scan_log.jsonl"):
        for rec in _read_jsonl(run_dir / log):
            if isinstance(rec.get("ledger"), dict):
                snaps.append(rec["ledger"])
    if not snaps:
        return {}
    newest = snaps[-1]
    out: dict = {
        "sites": newest.get("sites") or {},
        "compile_seconds_total": newest.get("compile_seconds_total"),
    }
    for key in ("ceilings", "memory", "params", "errors"):
        if newest.get(key):
            out[key] = newest[key]
    if timeline:
        out["hbm_timeline"] = timeline
    return out


def tuning_section(run_dir: Path) -> dict:
    """The autotuner view (deepdfa_tpu/tune/, docs/tuning.md), rebuilt
    from the persisted tuned.json: per-signature candidate timings +
    numerics verdicts, the chosen layout, and the ladder fits' waste
    before (pow2) vs after (fitted). Looks in the run dir first, then
    the storage-wide default location."""
    from deepdfa_tpu.tune import cache as tune_cache

    # resolution order mirrors the server's (tune/cache.py:tuned_path):
    # the config-pinned tune.path WINS — the layout /healthz reports
    # must be the one this section renders; run_dir/tuned.json is the
    # smoke/ad-hoc location, the storage default last
    candidates = []
    try:
        saved = json.loads((run_dir / "config.json").read_text())
        override = (saved.get("tune") or {}).get("path")
        if override:
            candidates.append(Path(override))
    except (OSError, json.JSONDecodeError):
        pass
    candidates.append(run_dir / "tuned.json")
    try:
        from deepdfa_tpu.core import paths

        candidates.append(paths.storage_root() / "tuned.json")
    except Exception:
        pass
    doc = None
    path = candidates[0]
    for cand in candidates:
        doc = tune_cache.load_tuned(cand)
        if doc is not None:
            path = cand
            break
    if doc is None:
        return {}
    verdict = tune_cache.validate_tuned(doc)
    out: dict = {
        "path": str(path),
        "valid": verdict["ok"],
        "records": [],
    }
    if verdict["problems"]:
        out["problems"] = verdict["problems"]
    for rec in doc.get("records", []):
        if not isinstance(rec, dict):
            continue
        view: dict = {
            "hardware": rec.get("hardware"),
            "search_seconds": rec.get("search_seconds"),
        }
        kernel = {}
        for sig, sr in (rec.get("kernel") or {}).items():
            if not isinstance(sr, dict):
                continue
            kernel[sig] = {
                "winner": sr.get("winner"),
                "winner_step_us": sr.get("winner_step_us"),
                "lax_step_us": sr.get("lax_step_us"),
                "mfu_vs_measured_ceiling": sr.get(
                    "winner_mfu_vs_measured_ceiling"
                ),
                "candidates": [
                    {
                        "candidate": row.get("candidate"),
                        "step_us": row.get("step_us"),
                        "ok": (row.get("numerics") or {}).get("ok"),
                        # the variant axes (scatter since ISSUE 15,
                        # accum/unroll since ISSUE 16; absent on older
                        # records → None, renderer omits the column)
                        "scatter": row.get("scatter"),
                        "accum": row.get("accum"),
                        "unroll": row.get("unroll"),
                    }
                    for row in (sr.get("candidates") or [])
                    if isinstance(row, dict)
                ],
                "pruned": len(sr.get("pruned") or []),
            }
        if kernel:
            view["kernel"] = kernel
        ladders = {}
        for name, lr in (rec.get("ladders") or {}).items():
            if not isinstance(lr, dict):
                continue
            ladders[name] = {
                "rungs": lr.get("rungs") or lr.get("edges"),
                "padding_waste": lr.get("padding_waste"),
                "pow2_padding_waste": lr.get("pow2_padding_waste"),
                "samples": lr.get("samples"),
            }
        if ladders:
            view["ladders"] = ladders
        out["records"].append(view)
    return out


def load_postmortem(run_dir: Path) -> dict:
    """postmortem.json summary (crash flight recorder, obs/flight.py),
    validation verdict included — {} when the run never crashed."""
    return postmortem_summary(run_dir / "postmortem.json")


def postmortem_summary(path: str | Path) -> dict:
    path = Path(path)
    if not path.exists():
        return {}
    from deepdfa_tpu.obs import flight as obs_flight

    # parse once: the dump embeds full metrics/ledger snapshots, so the
    # validator runs on the already-parsed document
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        return {"path": str(path), "valid": False,
                "problems": [f"unreadable: {e}"]}
    verdict = obs_flight.validate_postmortem(doc)
    pm = doc.get("postmortem") if isinstance(doc, dict) else None
    pm = pm if isinstance(pm, dict) else {}
    out = {
        "path": str(path),
        "valid": verdict.get("ok", False),
        "trigger": pm.get("trigger"),
        "t_unix": pm.get("t_unix"),
        "steps": len(pm.get("steps") or []),
        "events": len(pm.get("events") or []),
    }
    steps = pm.get("steps") or []
    if steps:
        out["last_step"] = steps[-1].get("step")
    events = pm.get("events") or []
    if events:
        out["last_events"] = [
            {"name": e.get("name"), "cat": e.get("cat")}
            for e in events[-5:]
        ]
    if pm.get("ledger"):
        out["ledger_sites"] = len(
            (pm["ledger"].get("sites") or {})
        )
    if verdict.get("problems"):
        out["problems"] = verdict["problems"]
    return out


def bench_section(root: str | Path | None = None) -> dict:
    """The bench-trajectory section: every committed BENCH_r*/
    BENCH_TPU_* record's headline numbers plus the regression-gate
    verdict for the newest round (obs/bench_gate.py)."""
    from deepdfa_tpu.obs import bench_gate as bg

    root = Path(root) if root else Path(__file__).resolve().parents[2]
    trajectory = bg.load_trajectory(root)
    rows = []
    newest = None
    newest_source = None
    for entry in trajectory:
        rec = entry.get("record")
        row = {"source": entry["source"]}
        if entry.get("round") is not None:
            row["round"] = entry["round"]
        if isinstance(rec, dict):
            row.update({
                k: rec[k]
                for k in ("metric", "value", "unit", "platform",
                          "train_graphs_per_sec",
                          "serve_requests_per_sec", "mfu",
                          "fallback_from")
                if k in rec
            })
            row["class"] = bg.classify(rec)
            if entry.get("round") is not None:
                newest, newest_source = rec, entry["source"]
        if entry.get("note"):
            row["note"] = entry["note"]
        rows.append(row)
    out: dict = {"trajectory": rows}
    if newest is not None:
        # the newest round is part of the trajectory: exclude it from
        # its own reference selection (a self-comparison passes
        # vacuously)
        out["gate"] = bg.gate(
            newest, trajectory, exclude_source=newest_source
        )
    return out


def diagnose(run_dir: str | Path, bench_root: str | Path | None = None) -> dict:
    """One machine-readable object with every section."""
    run_dir = Path(run_dir)
    records = load_records(run_dir)
    events = load_events(run_dir)
    epochs = [r for r in records if "epoch_seconds" in r]
    summary = {
        "run_dir": str(run_dir),
        "records": len(records),
        "epochs": len(epochs),
        "trace_events": len(events),
    }
    if epochs:
        summary["final_train_loss"] = epochs[-1].get("train_loss")
        val_keys = sorted(
            k for k in epochs[-1] if k.startswith("val_")
        )
        if val_keys:
            summary["final_val"] = {k: epochs[-1][k] for k in val_keys}
    serve_records = load_serve_records(run_dir)
    fleet_records = load_fleet_records(run_dir)
    return {
        "summary": summary,
        "timeline": throughput_timeline(records),
        "stage_attribution": {
            "from_records": stage_attribution_from_records(records),
            "from_trace": stage_attribution_from_events(events),
        },
        "resilience": resilience_log(run_dir, records, events),
        "serve": serve_attribution(serve_records),
        "slo": slo_section(serve_records),
        "cascade": cascade_section(serve_records),
        "scan": scan_section(load_scan_records(run_dir)),
        "fleet": fleet_section(run_dir, fleet_records),
        "autoscale": autoscale_section(fleet_records),
        "alerts": alerts_section(fleet_records),
        "flywheel": flywheel_section(fleet_records),
        "drill": drill_section(run_dir, bench_root),
        "efficiency": efficiency_section(run_dir, records),
        "tuning": tuning_section(run_dir),
        "postmortem": load_postmortem(run_dir),
        "bench": bench_section(bench_root),
    }


# ---------------------------------------------------------------------------
# text rendering


def _bar(frac: float, width: int = 30) -> str:
    n = max(0, min(width, int(round(frac * width))))
    return "#" * n + "." * (width - n)


def render_text(report: dict, out=sys.stdout) -> None:
    w = out.write
    s = report["summary"]
    w(f"run: {s['run_dir']}\n")
    w(
        f"  records={s['records']} epochs={s['epochs']} "
        f"trace_events={s['trace_events']}\n"
    )
    if "final_train_loss" in s:
        w(f"  final train_loss={s['final_train_loss']}\n")
    for k, v in (s.get("final_val") or {}).items():
        w(f"  final {k}={v}\n")

    timeline = report["timeline"]
    if timeline:
        w("\nthroughput timeline (per epoch):\n")
        max_secs = max(r["epoch_seconds"] for r in timeline) or 1.0
        for r in timeline:
            extras = "".join(
                f" {k.split('train_')[-1]}={r[k]}"
                for k in ("train_examples_per_sec", "train_tokens_per_sec")
                if k in r
            )
            wait = r.get("input_wait_fraction")
            wait_s = f" wait={wait:.1%}" if isinstance(wait, float) else ""
            w(
                f"  epoch {r['epoch']:>3}  "
                f"{_bar(r['epoch_seconds'] / max_secs, 24)} "
                f"{r['epoch_seconds']:8.2f}s loss={r['train_loss']}"
                f"{wait_s}{extras}\n"
            )

    attr = report["stage_attribution"]
    rec_attr, trc_attr = attr["from_records"], attr["from_trace"]
    if rec_attr or trc_attr:
        w("\nhost/device stage attribution (seconds):\n")
        w(f"  {'stage':<14}{'records':>12}{'trace':>12}\n")
        for stage in _INPUT_STAGES:
            a = rec_attr.get(stage, "-")
            b = trc_attr.get(stage, "-")
            w(f"  {stage:<14}{a!s:>12}{b!s:>12}\n")
        for k in (
            "pack_worker_seconds", "train_dispatch_seconds",
            "device_step_seconds",
        ):
            if trc_attr.get(k):
                w(f"  {k:<26}{trc_attr[k]:>12}\n")
        if trc_attr.get("processes"):
            w(
                f"  trace processes: {len(trc_attr['processes'])} "
                f"(pids {trc_attr['processes']})\n"
            )

    serve = report.get("serve") or {}
    if serve:
        w("\nserving (newest serve_log.jsonl record):\n")
        for k in (
            "serve_requests_per_sec", "serve_latency_p50_ms",
            "serve_latency_p99_ms", "serve_batch_occupancy_mean",
            "serve_steady_state_recompiles",
        ):
            if k in serve:
                w(f"  {k.removeprefix('serve_')}={serve[k]}\n")
        stages = [
            (s, serve[f"{s}_mean_ms"])
            for s in ("frontend", "queue", "device")
            if f"{s}_mean_ms" in serve
        ]
        if stages:
            total = sum(v for _, v in stages) or 1.0
            w("  per-request latency attribution (mean ms):\n")
            for s, v in stages:
                w(f"    {s:<10}{_bar(v / total, 20)} {v:8.3f}ms\n")
        counters = {
            k: serve[k]
            for k in ("requests", "rejected", "failed", "batches",
                      "cache_hits", "cache_misses", "hot_swaps")
            if k in serve
        }
        if counters:
            w("  " + " ".join(f"{k}={int(v)}" for k, v in counters.items())
              + "\n")
        ladder = serve.get("ladder") or {}
        if ladder:
            lw = serve.get("ladder_waste")
            lw_s = (
                f" (overall waste {lw:.1%})"
                if isinstance(lw, (int, float)) else ""
            )
            w(f"  ladder fill per rung (real vs padded rows){lw_s}:\n")
            for rung, agg in ladder.items():
                waste = agg.get("waste", 0.0)
                w(
                    f"    {rung:<12}{_bar(1.0 - waste, 20)} "
                    f"real={int(agg.get('real_rows', 0))} "
                    f"padded={int(agg.get('padded_rows', 0))} "
                    f"waste={waste:.1%}\n"
                )

    slo = report.get("slo") or {}
    if slo:
        w("\nserving SLO (from serve_log.jsonl):\n")
        window_labels = sorted(
            (k for k in slo if k.endswith("s") and k[:-1].isdigit()),
            key=lambda k: int(k[:-1]),
        )
        for label in ["all", *window_labels]:
            v = slo.get(label)
            if not v:
                continue
            lat = v.get("latency_ms", {})
            lat_s = " ".join(f"{k}={val}ms" for k, val in lat.items())
            err = v.get("error_rate")
            err_s = f" error_rate={err:.2%}" if err is not None else ""
            w(
                f"  [{label:>4}] requests={v['requests']} {lat_s}"
                f"{err_s}\n"
            )
            status = v.get("status")
            if status:
                w("         status: " + " ".join(
                    f"{k}={c}" for k, c in status.items()
                ) + "\n")
            stages = [
                (s, v[f"{s}_ms_mean"])
                for s in ("frontend", "queue", "device")
                if f"{s}_ms_mean" in v
            ]
            if stages:
                total = sum(x for _, x in stages) or 1.0
                for s, x in stages:
                    w(
                        f"         {s:<10}{_bar(x / total, 20)} "
                        f"{x:8.3f}ms\n"
                    )
        eng = slo.get("engine") or {}
        if eng:
            w(
                f"  engine snapshot: queue_depth="
                f"{eng.get('queue_depth')} hot_swaps="
                f"{eng.get('hot_swaps')} requests_total="
                f"{eng.get('requests_total')}\n"
            )

    casc = report.get("cascade") or {}
    if casc:
        w("\ntwo-stage cascade (serve_log.jsonl, docs/cascade.md):\n")
        counters = casc.get("counters") or {}
        rate = counters.get(
            "escalation_rate", casc.get("escalation_rate_observed")
        )
        if rate is not None:
            w(
                f"  escalation rate {_bar(float(rate), 20)} "
                f"{float(rate):7.2%}"
            )
            w(
                f"  (requests={int(counters.get('requests', casc.get('requests', 0)))} "
                f"escalated={int(counters.get('escalations', casc.get('escalated', 0)))} "
                f"sheds={int(counters.get('sheds', casc.get('sheds_observed', 0)))})\n"
            )
        stages = [
            (s.removesuffix("_ms_mean"), casc[s])
            for s in ("cascade_stage1_ms_mean", "cascade_stage2_ms_mean")
            if s in casc
        ]
        if stages:
            total = sum(v for _, v in stages) or 1.0
            w("  per-stage latency attribution (mean ms):\n")
            for s, v in stages:
                w(f"    {s:<16}{_bar(v / total, 20)} {v:8.3f}ms\n")
        if counters.get("stage2_steady_state_recompiles") is not None:
            w(
                f"  stage-2 steady-state recompiles: "
                f"{int(counters['stage2_steady_state_recompiles'])}\n"
            )
        quant = casc.get("quant_entries") or {}
        if quant:
            w("  quantized registry entries (param bytes vs fp32):\n")
            for tag, v in quant.items():
                frac = v.get("fraction")
                frac_s = (
                    f" {_bar(frac, 16)} {frac:7.2%}"
                    if isinstance(frac, float) else ""
                )
                w(
                    f"    {tag}: {v['bytes']:.0f}B"
                    + (
                        f" vs {v['fp32_bytes']:.0f}B{frac_s}\n"
                        if v.get("fp32_bytes") else "\n"
                    )
                )

    scan = report.get("scan") or {}
    if scan:
        w("\nrepo scan (newest scan_log.jsonl record):\n")
        w(
            f"  files={scan.get('scan_files')} "
            f"functions={scan.get('scan_functions')} "
            f"findings={scan.get('scan_findings')} "
            f"failed={scan.get('scan_functions_failed')} "
            f"({scan.get('scans')} scan(s) logged)\n"
        )
        if "scan_functions_per_sec" in scan:
            w(f"  functions/s={scan['scan_functions_per_sec']}\n")
        skip = scan.get("scan_incremental_skip_fraction")
        if isinstance(skip, (int, float)):
            w(
                f"  incremental skip {_bar(skip, 20)} {skip:7.1%}"
                f"  (reused {scan.get('scan_reused')}/"
                f"{scan.get('scan_functions')})\n"
            )
        hit = scan.get("scan_cache_hit_fraction")
        if isinstance(hit, (int, float)):
            w(f"  frontend cache  {_bar(hit, 20)} {hit:7.1%}\n")
        stages = scan.get("stage_seconds") or {}
        if stages:
            total = sum(stages.values()) or 1.0
            w("  stage latency attribution (seconds):\n")
            for stage, v in stages.items():
                w(f"    {stage:<10}{_bar(v / total, 20)} {v:8.3f}s\n")
        rc = scan.get("scan_steady_state_recompiles")
        if rc is not None:
            w(
                f"  steady-state recompiles: score={rc} lines="
                f"{scan.get('scan_lines_steady_state_recompiles')}\n"
            )

    fleet = report.get("fleet") or {}
    if fleet:
        w("\nserving fleet (fleet_log.jsonl, docs/fleet.md):\n")
        shed_rate = fleet.get("shed_rate")
        shed_s = (
            f" shed_rate={shed_rate:.1%}"
            if isinstance(shed_rate, (int, float)) else ""
        )
        w(
            f"  requests={fleet.get('requests')} "
            f"events={fleet.get('events')}{shed_s}\n"
        )
        replicas = fleet.get("replicas") or {}
        for rid, agg in replicas.items():
            rps = agg.get("requests_per_sec")
            rps_s = f" req/s={rps}" if rps is not None else ""
            occ = agg.get("batch_occupancy_mean")
            occ_s = (
                f" occupancy={occ:.1%}"
                if isinstance(occ, (int, float)) else ""
            )
            src = agg.get("telemetry_source")
            src_s = f" [{src}]" if src else ""
            w(
                f"  replica {rid:<6} requests={agg['requests']}"
                f"{rps_s}{occ_s}{src_s}\n"
            )
        telem = fleet.get("telemetry") or {}
        if telem:
            w("  federated telemetry (obs/aggregate.py snapshots):\n")
            for rid, row in (telem.get("replicas") or {}).items():
                stale_s = " STALE" if row.get("stale") else ""
                cached_s = " cached" if row.get("cached") else ""
                w(
                    f"    {rid:<8} seq={row.get('seq')} "
                    f"age={row.get('age_s')}s "
                    f"requests={row.get('requests_total')}"
                    f"{stale_s}{cached_s}\n"
                )
            merged = telem.get("merged_latency") or {}
            for wlabel, stages in merged.items():
                tot = (stages.get("total") or {})
                p99 = tot.get("p99_ms")
                if p99 is not None:
                    w(
                        f"    merged[{wlabel}] total "
                        f"p50={tot.get('p50_ms'):.3f}ms "
                        f"p99={p99:.3f}ms n={tot.get('count')}\n"
                    )
            for prob in telem.get("problems") or []:
                w(f"    problem: {prob}\n")
        for title, key in (
            ("tenant", "by_tenant"), ("priority", "by_priority"),
        ):
            table = fleet.get(key) or {}
            if table:
                w(f"  shed by {title}:\n")
                for name, agg in table.items():
                    w(
                        f"    {name:<12}{_bar(agg['shed_rate'], 20)} "
                        f"{agg['shed_rate']:7.1%}  "
                        f"({agg['shed']}/{agg['requests']})\n"
                    )
        reasons = fleet.get("shed_reasons") or {}
        if reasons:
            w("  shed reasons: " + " ".join(
                f"{k}={v}" for k, v in reasons.items()
            ) + "\n")
        event_log = fleet.get("event_log") or []
        if event_log:
            w("  lifecycle events:\n")
            for ev in event_log:
                extra = "".join(
                    f" {k}={ev[k]}"
                    for k in ("failures", "state") if k in ev
                )
                w(
                    f"    {ev.get('name', '?'):<16}"
                    f"replica={ev.get('replica', '-')}{extra}\n"
                )
        counters = fleet.get("counters") or {}
        if counters:
            w("  " + " ".join(
                f"{k}={int(v)}" for k, v in counters.items()
            ) + "\n")

    alerts = report.get("alerts") or {}
    if alerts:
        w("\nalerts (fleet_log.jsonl, docs/alerts.md):\n")
        for name, row in (alerts.get("rules") or {}).items():
            obs_s = (
                f" observed={row['last_observed']}"
                if "last_observed" in row else ""
            )
            tenant_s = (
                f" tenant={row['tenant']}" if "tenant" in row else ""
            )
            w(
                f"  {name:<28}{row.get('kind', '?'):<16}"
                f"fired={row['fired']} resolved={row['resolved']} "
                f"last={row['last_state']}{obs_s}{tenant_s}\n"
            )
        still = alerts.get("still_firing") or []
        if still:
            w("  STILL FIRING: " + " ".join(still) + "\n")

    autoscale = report.get("autoscale") or {}
    if autoscale:
        w("\npredictive autoscaling (fleet_log.jsonl, docs/fleet.md):\n")
        w(
            f"  decisions={autoscale.get('decisions')}  "
            + " ".join(
                f"{k}={v}"
                for k, v in (autoscale.get("actions") or {}).items()
            )
            + "\n"
        )
        fs = autoscale.get("first_scale_up")
        if fs:
            w(
                f"  first scale_up: offered={fs.get('offered_rps')} "
                f"forecast={fs.get('forecast_rps')} capacity="
                f"{fs.get('capacity_rps')} -> replicas="
                f"{fs.get('target_replicas')}\n"
            )
        for d in autoscale.get("timeline") or []:
            ratio = d.get("ratio")
            bar = (
                _bar(min(1.0, float(ratio)), 20)
                if isinstance(ratio, (int, float)) else " " * 20
            )
            w(
                f"    {d.get('action', '?'):<18}{bar} "
                f"ratio={ratio} replicas={d.get('replicas')} "
                f"({d.get('reason')})\n"
            )

    flywheel = report.get("flywheel") or {}
    if flywheel:
        w("\ndata flywheel (fleet_log.jsonl, docs/flywheel.md):\n")
        for cand, ride in (flywheel.get("rides") or {}).items():
            w(
                f"  shadow ride: {cand} vs {ride.get('incumbent')} "
                f"({ride['windows']} windows"
                f"{', ended' if ride.get('ended') else ''})\n"
            )
            for t in ride.get("timeline") or []:
                agree = t.get("agreement")
                bar = (
                    _bar(float(agree), 20)
                    if isinstance(agree, (int, float)) else " " * 20
                )
                auc = (
                    f" auc {t['auc_candidate']} vs {t['auc_incumbent']}"
                    if "auc_candidate" in t and "auc_incumbent" in t
                    else ""
                )
                w(
                    f"    {t.get('verdict', '?'):<8}{bar} "
                    f"agree={agree} drift={t.get('prob_drift')}"
                    f"{auc} n={t.get('samples')}\n"
                )
        for e in flywheel.get("history") or []:
            mark = "+" if e.get("rollout_ok") else (
                "x" if e["kind"] == "demotion" else "~"
            )
            reason = e.get("reason") or e.get("halt_reason") or ""
            w(
                f"  [{mark}] {e['kind']:<10}{e.get('candidate'):<14}"
                f"{reason}\n"
            )

    drill = report.get("drill") or {}
    if drill.get("trajectory"):
        w("\nchaos drills (DRILL_r*.json, fleet/drill.py):\n")
        for row in drill["trajectory"]:
            if "drill_failover_s" in row:
                mark = "+" if row.get("ok") else "x"
                w(
                    f"  [{mark}] {row['source']:<18} "
                    f"mode={row.get('mode')} "
                    f"rounds={row.get('rounds')} "
                    f"failover={row.get('drill_failover_s')}s "
                    f"readmit={row.get('drill_readmit_s')}s "
                    f"reseed={row.get('drill_reseed_s')}s "
                    f"(bound {row.get('drill_bound_s')}s)\n"
                )
            else:
                w(
                    f"  [x] {row['source']:<18} "
                    f"{row.get('note', 'no record')}\n"
                )
        gate = drill.get("gate")
        if gate:
            w(
                f"  gate verdict: {gate['verdict']}"
                + (
                    f" ({', '.join(gate['failure_classes'])})"
                    if gate["failure_classes"] else ""
                )
                + "\n"
            )

    eff = report.get("efficiency") or {}
    if eff:
        w("\ndevice efficiency ledger (docs/efficiency.md):\n")
        sites = eff.get("sites") or {}
        if sites:
            max_cs = max(
                (s.get("compile_seconds", 0.0) for s in sites.values()),
                default=0.0,
            ) or 1.0
            w(
                f"  {'site':<28}{'compile_s':>10}{'gflops':>9}"
                f"{'execs':>7}{'mfu':>10}\n"
            )
            for label in sorted(sites):
                s = sites[label]
                mfu = s.get("mfu_vs_measured_ceiling")
                fps = s.get("flops_per_sec")
                mfu_s = (
                    f"{mfu:.4f}" if isinstance(mfu, (int, float))
                    else (f"{fps / 1e9:.2f}G/s"
                          if isinstance(fps, (int, float)) else "-")
                )
                w(
                    f"  {label:<28}"
                    f"{s.get('compile_seconds', 0.0):>10.3f}"
                    f"{s.get('flops', 0.0) / 1e9:>9.3f}"
                    f"{s.get('executions', 0):>7}"
                    f"{mfu_s:>10}  "
                    f"{_bar(s.get('compile_seconds', 0.0) / max_cs, 16)}\n"
                )
        if eff.get("compile_seconds_total") is not None:
            w(
                f"  compile_seconds_total="
                f"{eff['compile_seconds_total']}\n"
            )
        params = eff.get("params") or {}
        for tag, b in sorted(params.items()):
            w(f"  params[{tag}] = {b / 1e6:.2f} MB\n")
        tl = eff.get("hbm_timeline") or []
        if tl:
            peak = max(
                (r.get("peak_bytes_in_use", 0.0) for r in tl),
                default=0.0,
            ) or 1.0
            w("  HBM watermark timeline (peak bytes in use):\n")
            for r in tl:
                v = r.get("peak_bytes_in_use", 0.0)
                w(
                    f"    epoch {r.get('epoch'):>3}  "
                    f"{_bar(v / peak, 20)} {v / 1e6:10.1f} MB\n"
                )

    tuning = report.get("tuning") or {}
    if tuning:
        w("\nautotuner (tuned.json, docs/tuning.md):\n")
        w(
            f"  {tuning.get('path')} valid={tuning.get('valid')}\n"
        )
        for rec in tuning.get("records") or []:
            hw = rec.get("hardware") or {}
            w(
                f"  [{hw.get('device_kind')} x{hw.get('n_devices')} "
                f"@ {hw.get('node_budget')}x{hw.get('edge_budget')} "
                f"jax {hw.get('jax_version')}] search="
                f"{rec.get('search_seconds')}s\n"
            )
            for sig, sr in (rec.get("kernel") or {}).items():
                mfu = sr.get("mfu_vs_measured_ceiling")
                mfu_s = (
                    f" mfu={mfu}" if isinstance(mfu, (int, float))
                    else ""
                )
                w(
                    f"    kernel {sig}: winner {sr.get('winner')} "
                    f"{sr.get('winner_step_us')}us (lax "
                    f"{sr.get('lax_step_us')}us, "
                    f"{sr.get('pruned')} pruned){mfu_s}\n"
                )
                cands = [
                    c for c in sr.get("candidates") or []
                    if isinstance(c.get("step_us"), (int, float))
                ]
                if cands:
                    slowest = max(c["step_us"] for c in cands) or 1.0
                    for c in sorted(
                        cands, key=lambda c: c["step_us"]
                    ):
                        mark = "✗" if c.get("ok") is False else " "
                        # explicit axis columns next to the encoded
                        # label (old records carry no axis fields —
                        # the tail is simply empty then)
                        axes = "".join(
                            f" {k}={c[k]}"
                            for k in ("scatter", "accum", "unroll")
                            if isinstance(c.get(k), str)
                        )
                        w(
                            f"      {c['candidate']:<32}"
                            f"{_bar(c['step_us'] / slowest, 20)} "
                            f"{c['step_us']:9.2f}us{mark}{axes}\n"
                        )
            for name, lr in (rec.get("ladders") or {}).items():
                # a damaged/hand-edited record may miss waste fields;
                # the report must still render (next to valid=False)
                before = lr.get("pow2_padding_waste")
                after = lr.get("padding_waste")
                fmt = lambda v: (  # noqa: E731
                    f"{v:.1%}" if isinstance(v, (int, float)) else "?"
                )
                w(
                    f"    ladder {name}: rungs={lr.get('rungs')} "
                    f"waste {fmt(before)} (pow2) -> {fmt(after)} "
                    f"(fitted) over {lr.get('samples')} samples\n"
                )

    pm = report.get("postmortem") or {}
    if pm:
        w("\npostmortem (crash flight recorder):\n")
        w(
            f"  trigger={pm.get('trigger')} valid={pm.get('valid')} "
            f"steps={pm.get('steps')} events={pm.get('events')}"
            + (
                f" last_step={pm['last_step']}"
                if "last_step" in pm else ""
            )
            + "\n"
        )
        for e in pm.get("last_events") or []:
            w(f"    [{e.get('cat')}] {e.get('name')}\n")
        for p in pm.get("problems") or []:
            w(f"    PROBLEM: {p}\n")

    bench = report.get("bench") or {}
    if bench.get("trajectory"):
        w("\nbench trajectory (committed BENCH_* artifacts):\n")
        for row in bench["trajectory"]:
            if "value" in row:
                cls = row.get("class", "?")
                mark = {"healthy": "+", "cpu_fallback": "!"}.get(cls, "?")
                w(
                    f"  [{mark}] {row['source']:<34} "
                    f"{row.get('value', '?'):>10} "
                    f"{row.get('unit', ''):<9} "
                    f"{row.get('platform', '?'):<4} {cls}\n"
                )
            else:
                w(
                    f"  [x] {row['source']:<34} "
                    f"{row.get('note', 'no record')}\n"
                )
        gate = bench.get("gate")
        if gate:
            w(
                f"  gate verdict: {gate['verdict']}"
                + (
                    f" ({', '.join(gate['failure_classes'])})"
                    if gate["failure_classes"] else ""
                )
                + "\n"
            )

    res = report["resilience"]
    if res["events"] or res["counters"] or res["watchdog"]:
        w("\nresilience events:\n")
        for c, v in (res["counters"] or {}).items():
            w(f"  {c}={v}\n")
        for e in res["events"]:
            args = {
                k: v for k, v in e.items() if k not in ("name", "ts_us")
            }
            w(f"  [{e.get('ts_us', 0):>14.1f}us] {e['name']} {args}\n")
        for d in res["watchdog"]:
            w(
                f"  watchdog: stalled_stage={d.get('stalled_stage')} "
                f"after {d.get('seconds_since_heartbeat')}s\n"
            )
        for m in res.get("resume_manifests", []):
            w(
                f"  resume manifest {m['path']}: step={m['step']} "
                f"epoch={m['epoch']} reason={m['reason']}\n"
            )


# ---------------------------------------------------------------------------
# smoke fixture: a synthetic run dir built through the REAL emitters


def build_smoke_run(run_dir: Path) -> Path:
    """Fabricate a run dir exercising every diag section: epoch records
    via RunLogger, main-process + producer-thread spans via the real
    tracer, a second (synthetic-pid) worker trace file, resilience
    instants, and a watchdog diagnostic."""
    import threading
    import time

    from deepdfa_tpu.train.logging import RunLogger

    from deepdfa_tpu.obs import ledger as obs_ledger

    run_dir = Path(run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    # an efficiency ledger through the REAL emitters (obs/ledger.py):
    # fixture cost fields + injected ceilings/memory stats (the smoke is
    # CPU-only by design; a real run records these from XLA/the
    # allocator), snapshotted into each epoch record like
    # Instruments.finish_epoch does
    led = obs_ledger.enable(
        ceilings={"matmul_flops_per_sec": 1e12,
                  "gather_bytes_per_sec": 1e10}
    )
    try:
        led.record_compile(
            "train_step", "G4xN2048xE8192", None, 1.25,
            flops=2.5e9, bytes_accessed=4.0e8, live_bytes=1.5e8,
        )
        led.record_compile(
            "serve_score", "G2", None, 0.4,
            flops=1.1e9, bytes_accessed=2.0e8,
        )
        import numpy as _np

        led.record_params(
            "deepdfa:smoke:best",
            {"w": _np.zeros((25_000,), _np.float32)},
        )
        with RunLogger(run_dir, tensorboard=False) as lg:
            for epoch in range(3):
                led.observe_execution(
                    "train_step", "G4xN2048xE8192", 0.5 + 0.1 * epoch,
                    n=10,
                )
                led.record_memory("epoch", stats={
                    "bytes_in_use": 1.0e8 + 2e7 * epoch,
                    "peak_bytes_in_use": 1.5e8 + 3e7 * epoch,
                })
                lg.log({
                    "epoch": epoch, "train_loss": 0.9 - 0.2 * epoch,
                    "epoch_seconds": 2.0 + 0.5 * epoch,
                    "host_load_seconds": 0.1, "host_pack_seconds": 0.6,
                    "host_place_seconds": 0.2, "input_wait_seconds": 0.3,
                    "input_wait_fraction": 0.15,
                    "val_loss": 0.8 - 0.1 * epoch,
                    "val_f1": 0.5 + 0.1 * epoch,
                    "resumed_from_step": 4 if epoch else 0,
                    "skipped_steps": epoch, "rollbacks": 0,
                    "ledger": led.snapshot(),
                })
        ledger_snapshot = led.snapshot()
    finally:
        obs_ledger.disable()
    tdir = run_dir / "trace"
    trace.enable(tdir, process_name="main")
    try:
        # spans need non-zero wall time or attribution rounds to 0.0
        for _ in range(4):
            with trace.span("pack", cat="input"):
                time.sleep(0.002)
            with trace.span("place", cat="input"):
                time.sleep(0.001)
            with trace.span("wait", cat="input"):
                time.sleep(0.001)
            with trace.span("train_step", cat="train", step=0):
                time.sleep(0.001)

        def producer():
            with trace.span("pack", cat="input"):
                time.sleep(0.002)

        t = threading.Thread(target=producer, name="batch-prefetch-0")
        t.start()
        t.join()
        trace.instant("resumed", cat="resilience", step=4)
        trace.instant("rollback", cat="resilience", step=9, lr_scale=0.5)
    finally:
        trace.disable()
    # a packer-worker file as a spawn worker would leave it (synthetic
    # pid: the smoke fixture is single-process by design)
    worker = trace.Tracer(tdir, process_name="smoke-worker")
    worker.pid = 999999
    worker.path = tdir / "trace-999999.jsonl"
    with trace._Span(worker, "pack_plan", "pack_worker", {}):
        time.sleep(0.002)
    worker.close()
    # a serve_log.jsonl through the REAL emitters (server.RequestLog +
    # the SLO engine) so the diag SLO section has both of its sources:
    # per-request entries and an engine snapshot in a summary record
    from deepdfa_tpu.obs.slo import SloEngine
    from deepdfa_tpu.serve.server import RequestLog, write_serve_log

    rlog = RequestLog(run_dir / "serve_log.jsonl")
    engine = SloEngine()
    t_now = time.time()
    for i in range(12):
        status = 200 if i % 6 else 429
        latency_ms = 5.0 + i
        rlog.append({"request": {
            "id": f"smoke-{i}", "status": status,
            "latency_ms": latency_ms, "frontend_ms": 1.0,
            "queue_ms": 2.0, "device_ms": 2.0,
            "batch_size": 2, "t_unix": round(t_now - i, 3),
        }})
        engine.observe_request(
            status, latency_ms / 1e3, frontend_s=1e-3, queue_s=2e-3,
            device_s=2e-3,
        )
    # ladder-fill counters through the REAL executor emitter
    # (serve/batcher.py:_observe_ladder_fill) — the pow2 blind spot the
    # diag serving section renders: every chunk of 5 pads to the G8 rung
    from deepdfa_tpu.obs import metrics as obs_metrics
    from deepdfa_tpu.serve.batcher import _observe_ladder_fill

    for _ in range(5):
        _observe_ladder_fill("G8", 5, 8)
    _observe_ladder_fill("G2", 2, 2)
    ladder_snap = {
        k[len("serve/"):]: v
        for k, v in obs_metrics.REGISTRY.snapshot().items()
        if k.startswith("serve/ladder")
    }
    rlog.append({"serve_slo": engine.snapshot()})
    # cascade-mode entries through the SAME emitters (serve/cascade.py,
    # docs/cascade.md): stage-tagged requests, a cascade summary
    # section, and a quantized registry entry next to its fp32 twin in
    # the embedded ledger params — what the diag cascade section reads
    from deepdfa_tpu.obs.slo import CASCADE_STAGES, STAGES

    casc_engine = SloEngine(stages=STAGES + CASCADE_STAGES)
    for i in range(8):
        escalated = i % 4 == 0
        entry = {
            "id": f"casc-{i}", "status": 200,
            "latency_ms": 3.0 + i, "frontend_ms": 1.0,
            "queue_ms": 0.5, "device_ms": 1.0,
            "t_unix": round(t_now - i, 3),
            "stage": 2 if escalated else 1,
            "stage1_prob": 0.5, "calibrated_prob": 0.5,
            "cascade_stage1_ms": 2.0,
        }
        if escalated:
            entry["cascade_stage2_ms"] = 6.0
        rlog.append({"request": entry})
        casc_engine.observe_request(
            200, entry["latency_ms"] / 1e3, frontend_s=1e-3,
            extra={
                "cascade_stage1": 2e-3,
                "cascade_stage2": 6e-3 if escalated else None,
            },
        )
    rlog.append({
        "serve": {"requests": 8.0, **ladder_snap},
        "serve_slo": casc_engine.snapshot(),
        "cascade": {
            "requests": 8.0, "escalations": 2.0, "sheds": 0.0,
            "escalation_rate": 0.25,
            "stage2_steady_state_recompiles": 0,
        },
        # the full ledger snapshot a real serve record embeds, with the
        # quantized entry's param bytes next to its fp32 twin
        "ledger": {**ledger_snapshot, "params": {
            "combined:smoke:best": 4.0e6,
            "combined:smoke:best@int8": 1.1e6,
        }},
    })
    rlog.close()
    # a scan_log.jsonl through the REAL writer (scan/scanner.py) so the
    # diag scan section renders from the same record shape a repo scan
    # leaves: a cold scan followed by an incremental re-scan
    from deepdfa_tpu.scan.scanner import write_scan_log

    base = {
        "scan_files": 4, "scan_files_reused": 0, "scan_functions": 12,
        "scan_reused": 0, "scan_extracted": 12, "scan_scored": 11,
        "scan_functions_failed": 1, "scan_findings": 3,
        "scan_seconds": 2.4, "scan_functions_per_sec": 5.0,
        "scan_incremental_skip_fraction": 0.0,
        "scan_cache_hit_fraction": 0.0,
        "scan_walk_seconds": 0.05, "scan_split_seconds": 0.1,
        "scan_frontend_seconds": 1.2, "scan_score_seconds": 0.7,
        "scan_attribute_seconds": 0.3, "scan_write_seconds": 0.05,
        "scan_steady_state_recompiles": 0,
        "scan_lines_steady_state_recompiles": 0,
        "repo": "/tmp/smoke-repo",
    }
    write_scan_log(run_dir, [
        base,
        {
            **base, "scan_files_reused": 3, "scan_reused": 11,
            "scan_extracted": 1, "scan_scored": 1,
            "scan_functions_failed": 0, "scan_seconds": 0.4,
            "scan_functions_per_sec": 30.0,
            "scan_incremental_skip_fraction": 0.9167,
            "scan_cache_hit_fraction": 0.5,
        },
    ])
    # a fleet_log.jsonl through the REAL router emitters (fleet/
    # router.py:FleetLog + Router.log_request shapes) so the diag fleet
    # section renders the same record shapes a live router leaves:
    # admitted traffic on two replicas, shed by tenant/priority, and an
    # eject/readmit lifecycle
    from deepdfa_tpu.fleet.router import FleetLog

    flog = FleetLog(run_dir / "fleet_log.jsonl")
    t_now = time.time()
    for rid in ("r0", "r1"):
        flog.append({"fleet_event": {
            "name": "join", "replica": rid,
            "t_unix": round(t_now - 20, 3),
        }})
    for i in range(12):
        shed = i % 6 == 5
        tenant = ["interactive", "batch"][i % 2]
        entry = {
            "id": f"fleet-smoke-{i}",
            "status": 503 if shed else 200,
            "latency_ms": 0.5 if shed else 4.0 + i,
            "t_unix": round(t_now - 12 + i, 3),
            "tenant": tenant, "priority": i % 2,
            "retries": 1 if i == 7 else 0,
            "shed": 1 if shed else 0,
        }
        if shed:
            entry["reason"] = "deadline"
            entry["deadline_ms"] = 1.0
        else:
            entry["replica"] = f"r{i % 2}"
        flog.append({"request": entry})
    flog.append({"fleet_event": {
        "name": "eject", "replica": "r1", "failures": 1,
        "t_unix": round(t_now - 4, 3),
    }})
    flog.append({"fleet_event": {
        "name": "readmit", "replica": "r1",
        "t_unix": round(t_now - 2, 3),
    }})
    # autoscale decisions through the REAL controller + emitter
    # (fleet/autoscale.py): a replayed ramp escalates the degradation
    # ladder (shed_stage2 -> tighten_admission) and scales up, each
    # decision appended as the same {"autoscale": ...} record shape the
    # live fleet smoke leaves — what the diag autoscale section reads
    from deepdfa_tpu.fleet import autoscale as fleet_autoscale

    ctrl = fleet_autoscale.AutoscaleController(
        capacity_rps=10.0, cooldown_s=0.0, min_replicas=1,
        max_replicas=4, horizon_s=5.0, bucket_s=1.0,
    )
    ramp = [
        (round(t_now - 12 + k, 3), 2.0 + 1.2 * k) for k in range(12)
    ]
    for decision in fleet_autoscale.replay(ramp, ctrl, replicas=1):
        flog.append(fleet_autoscale.AutoscaleController.log_record(
            decision
        ))
    # flywheel shadow ride through the REAL record emitters
    # (flywheel/shadow.py): a candidate rides, improves across two
    # comparison windows, gets promoted; an earlier candidate is
    # demoted for trailing — the diag flywheel section renders both
    from deepdfa_tpu.flywheel import shadow as flywheel_shadow

    flywheel_shadow.record_shadow(
        flog, "ride_start", "cand-a", incumbent="incumbent",
        t_unix=round(t_now - 11, 3),
    )
    flywheel_shadow.record_shadow(
        flog, "window", "cand-a", samples=64, labeled=20,
        agreement=0.86, prob_drift=0.04, lag_s=0.2,
        auc_candidate=0.64, auc_incumbent=0.71,
        verdict="demote", verdict_reason="trailing",
        t_unix=round(t_now - 10, 3),
    )
    flywheel_shadow.record_shadow(
        flog, "ride_end", "cand-a", t_unix=round(t_now - 9.5, 3),
    )
    flywheel_shadow.record_demotion(
        flog, "cand-a", "trailing", auc_candidate=0.64,
        auc_incumbent=0.71, t_unix=round(t_now - 9, 3),
    )
    flywheel_shadow.record_shadow(
        flog, "ride_start", "cand-b", incumbent="incumbent",
        t_unix=round(t_now - 8, 3),
    )
    for k, (agree, auc_c) in enumerate([(0.91, 0.74), (0.94, 0.79)]):
        flywheel_shadow.record_shadow(
            flog, "window", "cand-b", samples=64 * (k + 1),
            labeled=24 * (k + 1), agreement=agree, prob_drift=0.02,
            lag_s=0.15, auc_candidate=auc_c, auc_incumbent=0.71,
            verdict="hold" if k == 0 else "promote",
            verdict_reason="within_margin" if k == 0 else "auc_margin",
            t_unix=round(t_now - 7 + 2 * k, 3),
        )
    flywheel_shadow.record_shadow(
        flog, "ride_end", "cand-b", t_unix=round(t_now - 4.5, 3),
    )
    flywheel_shadow.record_promotion(
        flog, "cand-b", rollout_ok=True, swapped=2,
        reason="auc_margin", auc_candidate=0.79, auc_incumbent=0.71,
        t_unix=round(t_now - 4, 3),
    )
    flog.append({
        "fleet": {
            "requests": 12, "forwarded": 10, "retries": 1,
            "ejects": 1, "readmits": 1, "admitted": 10, "shed": 2,
            "replicas_routable": 2,
        },
        "fleet_slo": engine.snapshot(),
        "fleet_replicas": 2,
    })
    flog.close()
    # a chaos-drill record through the REAL scheduler + recorder
    # (fleet/drill.py): a stub runner with plausible measured timings,
    # folded by DrillScheduler and written by write_drill_record — the
    # diag drill section renders it and gates it like a committed round
    from deepdfa_tpu.fleet import drill as fleet_drill

    drill_rec = fleet_drill.DrillScheduler(
        runner=lambda i: {
            "ok": True, "failover_s": 0.4 + 0.1 * i,
            "readmit_s": 1.2, "reseed_s": 0.05,
        },
        rounds=2, interval_s=0.0, mode="smoke",
    ).run()
    fleet_drill.write_drill_record(drill_rec, run_dir)
    # one replica's own serve log (per-replica obs home) so the fleet
    # section picks up batch occupancy from the replica side
    (run_dir / "fleet" / "r0").mkdir(parents=True, exist_ok=True)
    write_serve_log(run_dir / "fleet" / "r0", [{
        "serve": {"batch_occupancy/mean": 0.75, "requests": 6.0},
    }])
    ck = run_dir / "checkpoints-step"
    ck.mkdir(exist_ok=True)
    (ck / "watchdog_diagnostic.json").write_text(json.dumps({
        "event": "train_stall", "stalled_stage": "input",
        "seconds_since_heartbeat": 42.0, "timeout_s": 30.0,
    }))
    (ck / "resume.json").write_text(json.dumps({
        "tag": "step-00000004", "step": 4, "epoch": 1,
        "batch_index": 1, "reason": "preempt",
    }))
    # a tuned.json through the REAL search emitters (deepdfa_tpu/tune/,
    # docs/tuning.md): a minimal but genuine candidate search — two
    # layouts compiled, timed, verdict-checked, one of them off the
    # per-step/fp32 defaults so the unroll/accum axis columns render —
    # plus the skewed-distribution ladder fits, persisted by the real
    # cache writer; what the diag tuning section renders
    from deepdfa_tpu.tune import driver as tune_driver
    from deepdfa_tpu.tune import kernel as tune_kernel

    tune_driver.run_tune_smoke(
        out_path=run_dir / "tuned.json",
        reps=1,
        kernel_candidates=(
            tune_kernel.Candidate(64, 128),
            tune_kernel.Candidate(256, 512, "fold", "fp32", "fused"),
        ),
    )
    # a postmortem through the REAL flight recorder (obs/flight.py):
    # step + instant rings filled via the real note paths, dumped by the
    # real writer — what `diag --postmortem` and the postmortem section
    # render
    from deepdfa_tpu.obs import flight as obs_flight

    obs_flight.install(run_dir / "postmortem.json", max_steps=8)
    try:
        for s in range(12):
            obs_flight.note_step(s)
        trace.instant("train_stall", cat="resilience", stage="input")
        obs_flight.crash_dump("watchdog_abort", extra={
            "stalled_stage": "input",
        })
    finally:
        obs_flight.uninstall()
    return run_dir


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="deepdfa-tpu diag", description=__doc__
    )
    ap.add_argument("run_dir", nargs="?", default=None,
                    help="run directory (or a run name under storage/runs)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    ap.add_argument("--smoke", action="store_true",
                    help="build + render a synthetic run dir (tier-1)")
    ap.add_argument("--postmortem", default=None, metavar="PATH",
                    help="render ONE postmortem.json (crash flight "
                    "recorder dump) instead of a run dir")
    ap.add_argument("--fleet", default=None, metavar="FLEET_DIR",
                    help="fleet-wide mode: stitch every replica's "
                    "shipped trace segments into ONE Perfetto timeline "
                    "(fleet_trace.json), summarize the federated "
                    "metrics snapshots, and replay the fleet log's "
                    "alert records (docs/alerts.md)")
    args = ap.parse_args(argv)

    if args.fleet:
        fleet_dir = Path(args.fleet)
        if not fleet_dir.is_dir():
            print(f"no such fleet dir: {args.fleet}", file=sys.stderr)
            return 2
        from deepdfa_tpu.obs.aggregate import (
            FleetAggregator, stitch_fleet_trace,
        )
        out_path = fleet_dir / "fleet_trace.json"
        stitched = stitch_fleet_trace(fleet_dir, out_path)
        telemetry = {}
        if list(fleet_dir.glob("metrics-*.json")):
            telemetry = FleetAggregator(fleet_dir).stats_section()
        fleet_records = _read_jsonl(fleet_dir / "fleet_log.jsonl")
        report = {
            "fleet_dir": str(fleet_dir),
            "trace": stitched,
            "telemetry": telemetry,
            "alerts": alerts_section(fleet_records),
        }
        if args.json:
            print(json.dumps(report))
            return 0
        print(f"fleet: {fleet_dir}")
        print(
            f"  stitched trace: {stitched.get('out')} "
            f"({stitched.get('events')} events from "
            f"{len(stitched.get('sources') or [])} source(s))"
        )
        print(
            f"  request flows: {len(stitched.get('flows') or {})} total, "
            f"{len(stitched.get('unbroken_flows') or [])} unbroken, "
            f"{len(stitched.get('broken_flows') or [])} broken"
        )
        for fid in stitched.get("broken_flows") or []:
            print(f"  BROKEN flow chain: {fid}")
        for src in stitched.get("unanchored") or []:
            print(f"  WARNING: no clock anchor from {src} — its events "
                  "keep their local monotonic timebase")
        if telemetry:
            for rid, row in (telemetry.get("replicas") or {}).items():
                stale_s = " STALE" if row.get("stale") else ""
                print(
                    f"  snapshot {rid:<8} seq={row.get('seq')} "
                    f"age={row.get('age_s')}s{stale_s}"
                )
            for prob in telemetry.get("problems") or []:
                print(f"  problem: {prob}")
        else:
            print("  no metrics snapshots published "
                  "(set fleet.telemetry=true)")
        al = alerts_section(fleet_records)
        for name, row in (al.get("rules") or {}).items():
            print(
                f"  alert {name:<28} fired={row['fired']} "
                f"resolved={row['resolved']} last={row['last_state']}"
            )
        if al.get("still_firing"):
            print("  STILL FIRING: " + " ".join(al["still_firing"]))
        return 0

    if args.postmortem:
        pm = postmortem_summary(args.postmortem)
        if not pm:
            print(
                f"no such postmortem: {args.postmortem}", file=sys.stderr
            )
            return 2
        if args.json:
            print(json.dumps({"postmortem": pm}))
        else:
            render_text({
                "summary": {"run_dir": str(Path(args.postmortem).parent),
                            "records": 0, "epochs": 0, "trace_events": 0},
                "timeline": [],
                "stage_attribution": {"from_records": {},
                                      "from_trace": {}},
                "resilience": {"events": [], "counters": {},
                               "watchdog": []},
                "postmortem": pm,
            })
        return 0 if pm.get("valid") else 1

    if args.smoke:
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            run_dir = build_smoke_run(Path(d) / "run")
            report = diagnose(run_dir)
            if args.json:
                print(json.dumps(report))
            else:
                render_text(report)
            # the smoke contract: every section materialized from the
            # synthetic artifacts through the real readers
            attr = report["stage_attribution"]
            slo = report.get("slo") or {}
            scan = report.get("scan") or {}
            fleet = report.get("fleet") or {}
            eff = report.get("efficiency") or {}
            pm = report.get("postmortem") or {}
            fleet_events = {
                ev.get("name") for ev in fleet.get("event_log", [])
            }
            ok = (
                report["summary"]["epochs"] == 3
                and report["summary"]["trace_events"] > 0
                and attr["from_records"].get("pack", 0) > 0
                and attr["from_trace"].get("pack", 0) > 0
                and len(attr["from_trace"].get("processes", [])) >= 2
                and report["resilience"]["events"]
                and report["resilience"]["watchdog"]
                # ISSUE 6 sections: per-request SLO view + engine
                # snapshot + the committed bench trajectory and verdict
                and slo.get("all", {}).get("requests", 0) > 0
                and "latency_ms" in slo.get("all", {})
                and slo.get("engine")
                and report.get("bench", {}).get("trajectory")
                # ISSUE 8 section: the scan view rebuilt from
                # scan_log.jsonl — coverage, incremental skip rate,
                # stage attribution
                and scan.get("scan_functions", 0) > 0
                and scan.get("scan_incremental_skip_fraction") is not None
                and scan.get("stage_seconds")
                and scan.get("scans") == 2
                # ISSUE 11 section: the fleet view rebuilt from
                # fleet_log.jsonl — per-replica traffic + occupancy,
                # shed-rate by tenant/priority, lifecycle event log
                and len(fleet.get("replicas") or {}) == 2
                and fleet["replicas"]["r0"].get("batch_occupancy_mean")
                == 0.75
                and fleet.get("shed_rate") is not None
                and set(fleet.get("by_tenant") or {})
                == {"interactive", "batch"}
                and (fleet.get("by_priority") or {})
                and {"join", "eject", "readmit"} <= fleet_events
                and fleet.get("counters", {}).get("ejects") == 1
                # ISSUE 18 sections: the predictive-autoscale decision
                # timeline (real controller over a replayed ramp — the
                # ladder escalates before the scale_up) and the
                # chaos-drill trajectory (real scheduler/recorder,
                # gated under the 3.2 s failover ceiling)
                and (report.get("autoscale") or {}).get(
                    "actions", {}
                ).get("scale_up", 0) >= 1
                and report["autoscale"]["actions"].get(
                    "shed_stage2", 0
                ) >= 1
                and report["autoscale"]["actions"].get(
                    "tighten_admission", 0
                ) >= 1
                and report["autoscale"].get("first_scale_up")
                and (report.get("drill") or {}).get(
                    "gate", {}
                ).get("verdict") == "pass"
                and report["drill"]["trajectory"][-1].get(
                    "drill_failover_s"
                ) == 0.5  # worst of the two stub rounds (0.4, 0.5)
                and report["drill"]["trajectory"][-1].get("valid")
                # ISSUE 20 section: the flywheel view — two shadow
                # rides rebuilt from the real record emitters
                # (flywheel/shadow.py), one demoted for trailing, one
                # promoted on AUC margin, plus the promotion history
                and set(
                    (report.get("flywheel") or {}).get("rides") or {}
                ) == {"cand-a", "cand-b"}
                and len(
                    report["flywheel"]["rides"]["cand-b"]["timeline"]
                ) == 2
                and report["flywheel"]["rides"]["cand-b"]["timeline"][
                    -1
                ].get("verdict") == "promote"
                and [
                    h.get("kind")
                    for h in report["flywheel"].get("history") or []
                ] == ["demotion", "promotion"]
                and report["flywheel"]["history"][-1].get("swapped") == 2
                # ISSUE 10 sections: the efficiency ledger (per-site
                # MFU + compile bars + HBM watermark timeline) and the
                # postmortem view, both from the real emitters
                # ISSUE 12 section: the cascade view — escalation
                # accounting, per-stage attribution, quantized-entry
                # density table next to its fp32 twin
                and (report.get("cascade") or {}).get("escalated") == 2
                and report["cascade"].get("cascade_stage2_ms_mean") == 6.0
                and report["cascade"]["counters"].get(
                    "escalation_rate"
                ) == 0.25
                and report["cascade"]["quant_entries"][
                    "combined:smoke:best@int8"
                ].get("fraction") == 0.275
                and "train_step/G4xN2048xE8192" in eff.get("sites", {})
                and eff["sites"]["train_step/G4xN2048xE8192"].get(
                    "mfu_vs_measured_ceiling"
                ) is not None
                and eff.get("hbm_timeline")
                and pm.get("valid") is True
                and pm.get("trigger") == "watchdog_abort"
                and pm.get("steps") == 8  # ring bounded at max_steps
                # ISSUE 15 sections: the serving ladder-fill view (the
                # pow2 blind spot: 5-row chunks padding the G8 rung)
                # and the autotuner view — real search, real winner,
                # fitted ladder strictly beating pow2
                and (report["serve"].get("ladder") or {}).get(
                    "G8", {}
                ).get("padded_rows") == 15.0
                and report["serve"].get("ladder_waste") is not None
                and (report.get("tuning") or {}).get("valid") is True
                and any(
                    sr.get("winner") and (
                        ld.get("padding_waste")
                        < ld.get("pow2_padding_waste")
                    )
                    for rec in report["tuning"]["records"]
                    for sr in (rec.get("kernel") or {}).values()
                    for ld in [
                        (rec.get("ladders") or {}).get("serve") or {}
                    ]
                )
            )
            print(f"diag smoke {'OK' if ok else 'FAILED'}")
            return 0 if ok else 1

    if args.run_dir is None:
        ap.error("run_dir is required (or pass --smoke)")
    run_dir = Path(args.run_dir)
    if not run_dir.is_dir():
        from deepdfa_tpu.core import paths

        candidate = paths.runs_dir(args.run_dir)
        if candidate.is_dir():
            run_dir = candidate
        else:
            print(f"no such run dir: {args.run_dir}", file=sys.stderr)
            return 2
    report = diagnose(run_dir)
    if args.json:
        print(json.dumps(report))
    else:
        render_text(report)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
