"""Repo walker + C/C++ function splitter (docs/scanning.md).

The serving frontend scores ONE function at a time (that is what the
training corpus taught the model); a repository is files of many. This
module bridges the two without a compiler toolchain:

- `walk_repo` discovers candidate sources under a root: configured
  suffixes only, hidden and excluded directories pruned anywhere in the
  tree, oversized files skipped (generated/amalgamated sources dominate
  scan time and drown findings), deterministic order, content hashed for
  the file-level incremental check.
- `split_functions` splits one translation unit into top-level function
  definitions by lexing, not parsing: comments, string/char literals and
  preprocessor lines are masked first (so braces inside them cannot
  corrupt nesting), then top-level `{...}` blocks whose header looks
  like `... name ( ... ) [const|noexcept|...]` are taken as functions.
  `namespace`/`extern "C"` blocks are transparent (functions inside are
  found); class/struct bodies are opaque (out-of-line methods are still
  found, in-class definitions are not — documented walker rule).

Each `FunctionSpan` carries the function's full source lines and its
1-based line range in the file, so per-node attributions (computed in
the function's own coordinates) map back to absolute file lines.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import re
from pathlib import Path
from typing import Iterable

#: header tokens that can never be a function name: control/operator
#: keywords, attribute machinery, and reserved type/storage words (a
#: declarator like `int (*f(void))(int)` puts `int (` before `f (`)
_NOT_A_NAME = frozenset({
    "if", "for", "while", "switch", "do", "else", "return", "sizeof",
    "catch", "defined", "alignof", "decltype", "typeof",
    "__attribute__", "__declspec", "_Alignas", "static_assert",
    "_Static_assert", "asm", "__asm__", "noexcept", "throw",
    "int", "void", "char", "long", "short", "unsigned", "signed",
    "float", "double", "bool", "_Bool", "auto", "register", "volatile",
    "const", "static", "inline", "struct", "union", "enum",
    "template", "typename", "typedef",
})

#: tokens allowed between the closing `)` and the body `{`
_TRAILERS = frozenset({
    "const", "noexcept", "override", "final", "volatile", "restrict",
    "try", "&", "&&",
})

_IDENT_PAREN = re.compile(r"([A-Za-z_~][A-Za-z0-9_]*)\s*\(")


@dataclasses.dataclass(frozen=True)
class FunctionSpan:
    """One discovered function definition."""

    name: str
    start_line: int  # 1-based, inclusive (first header line)
    end_line: int  # 1-based, inclusive (closing brace line)
    code: str  # the full source lines start_line..end_line

    @property
    def n_lines(self) -> int:
        return self.end_line - self.start_line + 1


@dataclasses.dataclass(frozen=True)
class SourceFile:
    """One discovered source file."""

    path: Path  # absolute
    rel: str  # repo-relative, posix separators (the SARIF uri)
    text: str
    sha256: str


def mask_code(text: str) -> str:
    """A same-length copy with comment bodies, string/char literal
    contents, and preprocessor lines blanked (newlines preserved) —
    brace/paren scanning over the result cannot be fooled by `{` in a
    string or an unbalanced `#define`."""
    out = list(text)
    n = len(text)
    i = 0
    state = "normal"  # | line_comment | block_comment | string | char
    line_start = True  # at start-of-line modulo whitespace
    in_directive = False

    def blank(j: int) -> None:
        if out[j] != "\n":
            out[j] = " "

    while i < n:
        c = text[i]
        nxt = text[i + 1] if i + 1 < n else ""
        if state == "normal":
            if in_directive:
                # a preprocessor line runs to an unescaped newline
                if c == "\n" and text[i - 1 : i] != "\\":
                    in_directive = False
                    line_start = True
                else:
                    blank(i)
                i += 1
                continue
            if c == "/" and nxt == "/":
                state = "line_comment"
                blank(i)
                i += 1
            elif c == "/" and nxt == "*":
                state = "block_comment"
                blank(i)
                i += 1
            elif c == '"':
                state = "string"
            elif c == "'":
                state = "char"
            elif c == "#" and line_start:
                in_directive = True
                blank(i)
            if c == "\n":
                line_start = True
            elif not c.isspace():
                line_start = False
        elif state == "line_comment":
            if c == "\n":
                state = "normal"
                line_start = True
            else:
                blank(i)
        elif state == "block_comment":
            if c == "*" and nxt == "/":
                state = "normal"
                blank(i)
                i += 1
                blank(i)
                i += 1
                continue
            blank(i)
        else:  # string | char: keep the quotes, blank the contents
            quote = '"' if state == "string" else "'"
            if c == "\\":
                blank(i)
                i += 1
                if i < n:
                    blank(i)
                i += 1
                continue
            if c == quote:
                state = "normal"
            else:
                blank(i)
        i += 1
    return "".join(out)


def _header_name(header: str) -> str | None:
    """Function name from a masked header, or None when the header is
    not a function definition. The first `ident (`-shaped token that is
    not a keyword/attribute wins — this resolves `static inline int
    foo(...)`, `int (*f(void))(int)` (f), and attribute-macro prefixes."""
    if "(" not in header or "=" in header:
        return None
    # everything after the LAST ')' must be benign trailer tokens
    tail = header[header.rfind(")") + 1 :]
    for tok in tail.replace("->", " ").split():
        if tok not in _TRAILERS and not re.fullmatch(
            r"[A-Za-z_][A-Za-z0-9_:<>,\s]*", tok
        ):
            return None
    for m in _IDENT_PAREN.finditer(header):
        name = m.group(1)
        if name in _NOT_A_NAME:
            continue
        # qualified methods arrive as `Cls::method(` — the regex grabs
        # the trailing identifier already; reject pure operator spellings
        return name
    return None


def _is_transparent(header: str) -> bool:
    """Blocks the splitter descends into rather than consuming: C++
    namespaces and extern "C" linkage blocks (masked strings leave
    `extern ""`)."""
    toks = header.split()
    if not toks:
        return False
    if "namespace" in toks:
        return True
    return toks[0] == "extern" and '"' in header and "(" not in header


def split_functions(text: str, min_lines: int = 1) -> list[FunctionSpan]:
    """Top-level function definitions in one source text, in file
    order. Line numbers are 1-based and inclusive."""
    masked = mask_code(text)
    lines = text.split("\n")
    # line number of every character index, computed lazily via count
    out: list[FunctionSpan] = []
    n = len(masked)
    i = 0
    boundary = 0  # start of the current potential header (masked idx)
    depth_stack: list[str] = []  # "opaque" | "transparent" markers

    def line_of(idx: int) -> int:
        return masked.count("\n", 0, idx) + 1

    def at_top() -> bool:
        # function headers can start at file scope OR directly inside
        # transparent (namespace / extern "C") blocks — statement
        # boundaries must reset in both, or a `int g_x = 0;` inside an
        # extern block would poison the next function's header
        return not depth_stack or depth_stack[-1] == "transparent"

    while i < n:
        c = masked[i]
        if c in ";":
            if at_top():
                boundary = i + 1
        elif c == "}":
            if depth_stack:
                depth_stack.pop()
            if at_top():
                boundary = i + 1
        elif c == "{":
            header = masked[boundary:i]
            if at_top():
                if _is_transparent(header):
                    depth_stack.append("transparent")
                    boundary = i + 1
                    i += 1
                    continue
                name = _header_name(header)
                if name is not None:
                    end = _match_brace(masked, i)
                    if end is None:
                        break  # unbalanced tail: stop cleanly
                    start_idx = boundary + (len(header) - len(header.lstrip()))
                    start_line = line_of(start_idx)
                    end_line = line_of(end)
                    if end_line - start_line + 1 >= min_lines:
                        out.append(FunctionSpan(
                            name=name,
                            start_line=start_line,
                            end_line=end_line,
                            code="\n".join(
                                lines[start_line - 1 : end_line]
                            ),
                        ))
                    boundary = end + 1
                    i = end + 1
                    continue
            depth_stack.append("opaque")
            boundary = i + 1
        i += 1
    return out


def _match_brace(masked: str, open_idx: int) -> int | None:
    """Index of the `}` matching the `{` at open_idx, or None."""
    depth = 0
    for j in range(open_idx, len(masked)):
        if masked[j] == "{":
            depth += 1
        elif masked[j] == "}":
            depth -= 1
            if depth == 0:
                return j
    return None


def walk_repo(
    root: str | Path,
    suffixes: Iterable[str],
    exclude_dirs: Iterable[str],
    max_file_bytes: int,
    stats: dict | None = None,
) -> list[SourceFile]:
    """Deterministically ordered candidate sources under `root`.

    `stats` (optional dict) receives "files_seen", "files_too_large",
    "files_unreadable"."""
    root = Path(root).resolve()
    if not root.is_dir():
        raise FileNotFoundError(f"scan root {root} is not a directory")
    suffixes = {s.lower() for s in suffixes}
    exclude = set(exclude_dirs)
    if stats is None:
        stats = {}
    stats.update(files_seen=0, files_too_large=0, files_unreadable=0)
    out: list[SourceFile] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in exclude and not d.startswith(".")
        )
        for fn in sorted(filenames):
            p = Path(dirpath) / fn
            if p.suffix.lower() not in suffixes:
                continue
            stats["files_seen"] += 1
            try:
                if p.stat().st_size > max_file_bytes:
                    stats["files_too_large"] += 1
                    continue
                text = p.read_text(errors="replace")
            except OSError:
                stats["files_unreadable"] += 1
                continue
            out.append(SourceFile(
                path=p,
                rel=p.relative_to(root).as_posix(),
                text=text,
                sha256=hashlib.sha256(
                    text.encode("utf-8", "replace")
                ).hexdigest(),
            ))
    return out
