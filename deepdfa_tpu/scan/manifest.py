"""Persistent scan manifest: the incremental-rescan ledger
(docs/scanning.md).

Two keyed layers, both pruned to what the latest scan actually saw:

- `files[rel]` — {sha256, functions: [{key, name, start_line,
  end_line}]}: an unchanged file (same content hash) reuses its split
  without re-reading function boundaries;
- `functions[key]` — {ok, prob, error?, lines?}: the per-function scan
  result, keyed by the frontend CONTENT KEY (sha256 of the function's
  source + the feat-spec/gtype/parser identity,
  `RequestPreprocessor.content_key`), so a function reuses its score
  wherever it moves — across lines, files, or renames.

The manifest is pinned to a model identity (config digest, vocab
digest, checkpoint step, attribution method): any identity drift drops
every entry — content-keyed reuse must never serve scores from a
different checkpoint or feature recipe. Writes are atomic
(core/ioutil.py), so a killed scan leaves the previous complete
manifest, never a truncated one.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path

from deepdfa_tpu.core.ioutil import atomic_write_text

logger = logging.getLogger(__name__)

MANIFEST_VERSION = 1


class ScanManifest:
    """Content-keyed per-function scan state for one (repo, model)."""

    def __init__(self, path: str | Path, identity: dict):
        self.path = Path(path)
        self.identity = dict(identity)
        self.files: dict[str, dict] = {}
        self.functions: dict[str, dict] = {}
        #: True when an on-disk manifest with a MATCHING identity was
        #: loaded (the incremental-reuse precondition)
        self.resumed = False

    @classmethod
    def load(cls, path: str | Path, identity: dict) -> "ScanManifest":
        m = cls(path, identity)
        path = Path(path)
        if not path.exists():
            return m
        try:
            raw = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            logger.warning("scan manifest %s unreadable (%s); cold scan",
                           path, e)
            return m
        if raw.get("version") != MANIFEST_VERSION:
            logger.warning(
                "scan manifest %s has version %s (want %s); cold scan",
                path, raw.get("version"), MANIFEST_VERSION,
            )
            return m
        if raw.get("identity") != m.identity:
            drift = sorted(
                k for k in set(raw.get("identity", {})) | set(m.identity)
                if raw.get("identity", {}).get(k) != m.identity.get(k)
            )
            logger.warning(
                "scan manifest %s was written under a different model "
                "identity (%s changed); cold scan", path, drift,
            )
            return m
        files = raw.get("files")
        functions = raw.get("functions")
        if isinstance(files, dict) and isinstance(functions, dict):
            m.files = files
            m.functions = functions
            m.resumed = True
        return m

    def file_functions(self, rel: str, sha256: str) -> list[dict] | None:
        """The recorded function spans for an UNCHANGED file — None when
        the file is new, changed, or any of its functions is missing a
        result (a crashed previous scan), in which case the caller
        re-splits."""
        entry = self.files.get(rel)
        if not entry or entry.get("sha256") != sha256:
            return None
        fns = entry.get("functions", [])
        if any(f.get("key") not in self.functions for f in fns):
            return None
        return fns

    def record_file(self, rel: str, sha256: str, fns: list[dict]) -> None:
        self.files[rel] = {"sha256": sha256, "functions": fns}

    def result(self, key: str) -> dict | None:
        return self.functions.get(key)

    def record_result(self, key: str, result: dict) -> None:
        self.functions[key] = result

    def prune(self, seen_files: set[str], seen_keys: set[str]) -> None:
        """Keep only what this scan saw — the manifest mirrors the repo
        state, it is not an unbounded score archive."""
        self.files = {
            r: v for r, v in self.files.items() if r in seen_files
        }
        self.functions = {
            k: v for k, v in self.functions.items() if k in seen_keys
        }

    def save(self) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        atomic_write_text(self.path, json.dumps({
            "version": MANIFEST_VERSION,
            "identity": self.identity,
            "files": self.files,
            "functions": self.functions,
        }))
