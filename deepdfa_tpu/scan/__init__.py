"""Whole-repo incremental scanning (docs/scanning.md).

Turns the online scoring stack into a CI-shaped product surface:
`deepdfa-tpu scan <repo>` walks a repository, splits every C/C++ source
into function definitions (scan/walker.py), scores each through the
serving frontend/batcher/AOT executables, optionally attributes per-line
vulnerability scores (serve/localize.py), and streams findings to JSONL
and SARIF 2.1.0 (scan/sarif.py). A persistent content-keyed manifest
(scan/manifest.py) makes a re-scan of an edited repo touch only the
changed functions.
"""

from deepdfa_tpu.scan.manifest import ScanManifest
from deepdfa_tpu.scan.sarif import sarif_report, validate_sarif
from deepdfa_tpu.scan.scanner import RepoScanner, run_scan_smoke
from deepdfa_tpu.scan.walker import (
    FunctionSpan,
    SourceFile,
    split_functions,
    walk_repo,
)

__all__ = [
    "FunctionSpan",
    "RepoScanner",
    "ScanManifest",
    "SourceFile",
    "run_scan_smoke",
    "sarif_report",
    "split_functions",
    "validate_sarif",
    "walk_repo",
]
