"""Repo scan orchestration (docs/scanning.md).

`RepoScanner` drives one scan over the ONLINE serving engine — the
shared content-keyed frontend cache, the dynamic batcher's AOT bucket
executables, and (with `scan.lines`) the line-attribution executables —
so a scan exercises exactly the code paths live traffic does, at repo
scale:

    walk -> split -> (manifest reuse | frontend -> score -> attribute)
         -> findings JSONL + SARIF -> manifest save -> scan_log.jsonl

Incrementality is two-layered (scan/manifest.py): unchanged files skip
re-splitting, unchanged functions (content key) skip frontend AND device
entirely. The zero-steady-state-recompiles invariant holds across both
the scoring and attribution paths — the smoke (`deepdfa-tpu scan
--smoke`) asserts it after a cold scan plus an incremental re-scan.
"""

from __future__ import annotations

import hashlib
import json
import time
from collections import OrderedDict
from pathlib import Path

from deepdfa_tpu.obs import metrics as obs_metrics, trace as obs_trace
from deepdfa_tpu.scan.manifest import ScanManifest
from deepdfa_tpu.scan.sarif import sarif_report, validate_sarif, write_sarif
from deepdfa_tpu.scan.walker import split_functions, walk_repo


def write_scan_log(run_dir, records) -> Path:
    """Append scan records to <run_dir>/scan_log.jsonl — the log
    `scripts/check_obs_schema.py --scan-log` validates and the diag
    scan section renders."""
    path = Path(run_dir) / "scan_log.jsonl"
    with path.open("a") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return path


class RepoScanner:
    """One scan engine bound to a ScoringService (registry + shared
    frontend + batcher); `scan()` is re-entrant per repo."""

    def __init__(self, service, cfg=None, localizer=None):
        cfg = cfg if cfg is not None else service.cfg
        self.service = service
        self.cfg = cfg
        self.scfg = cfg.scan
        # the line-attribution executor: an injected (already-warmed)
        # one wins, then the server's (serve.lines warmed it), else
        # build our own over the SAME warmup ladder (scan.lines opts in)
        self.localizer = (
            localizer if localizer is not None else service.localizer
        )
        if self.localizer is None and self.scfg.lines:
            from deepdfa_tpu.serve.localize import GgnnLocalizer

            scfg = cfg.serve
            self.localizer = GgnnLocalizer(
                service.registry.model, service.registry.params,
                node_budget=service.executor.node_budget,
                edge_budget=service.executor.edge_budget,
                sizes=service.executor.sizes,
                method=scfg.lines_method, n_steps=scfg.lines_steps,
                top_k=scfg.lines_top_k,
                feat_width=service.registry._feat_width(),
                etypes=cfg.model.n_etypes > 1,
                pipeline_depth=scfg.pipeline_depth,
            )
            self.localizer.warmup()
        self._next_id = 0

    # -- identity & state -----------------------------------------------------

    def identity(self) -> dict:
        """What a reused score is pinned to: the model/feature identity
        plus the attribution recipe (a method change must re-attribute)."""
        reg = self.service.registry
        ident = {
            "config_digest": reg.config_digest,
            "vocab_digest": reg.vocab_digest,
            "checkpoint": reg.checkpoint,
            "checkpoint_step": reg._loaded_step,
            "lines": self.localizer is not None,
        }
        if self.localizer is not None:
            ident.update(
                method=self.localizer.method,
                attr_steps=self.localizer.n_steps,
                top_k=self.localizer.top_k,
            )
        return ident

    def state_path(self, repo_root) -> Path:
        if self.scfg.state:
            return Path(self.scfg.state)
        digest = hashlib.sha256(
            str(Path(repo_root).resolve()).encode()
        ).hexdigest()[:16]
        return (
            self.service.registry.run_dir / "scan_state"
            / f"{digest}.json"
        )

    # -- the scan -------------------------------------------------------------

    def scan(
        self,
        repo_root,
        out_jsonl=None,
        sarif_out=None,
        timeout_s: float = 300.0,
    ) -> dict:
        """Scan one repository; returns the summary record (also
        appended to <run_dir>/scan_log.jsonl)."""
        repo_root = Path(repo_root).resolve()
        run_dir = self.service.registry.run_dir
        out_jsonl = Path(
            out_jsonl if out_jsonl else run_dir / "scan" / "findings.jsonl"
        )
        sarif_out = Path(
            sarif_out if sarif_out else run_dir / "scan" / "findings.sarif"
        )
        r = obs_metrics.REGISTRY
        cache_hits0 = r.counter("serve/cache_hits").value
        cache_misses0 = r.counter("serve/cache_misses").value
        score_low0 = self.service.executor.jit_lowerings()
        lines_low0 = (
            self.localizer.jit_lowerings()
            if self.localizer is not None else 0
        )
        t_start = time.perf_counter()

        # -- walk + split + manifest reuse
        walk_stats: dict = {}
        t0 = time.perf_counter()
        with obs_trace.span("scan_walk", cat="scan"):
            files = walk_repo(
                repo_root, self.scfg.suffixes, self.scfg.exclude_dirs,
                self.scfg.max_file_kb * 1024, stats=walk_stats,
            )
        walk_s = time.perf_counter() - t0
        manifest = (
            ScanManifest.load(self.state_path(repo_root), self.identity())
            if self.scfg.incremental
            else ScanManifest(self.state_path(repo_root), self.identity())
        )

        rows: list[dict] = []  # one per discovered function, file order
        pending: "OrderedDict[str, str]" = OrderedDict()  # key -> code
        files_reused = 0
        reused_fns = 0
        t0 = time.perf_counter()
        with obs_trace.span("scan_split", cat="scan"):
            for sf in files:
                fns = manifest.file_functions(sf.rel, sf.sha256)
                if fns is None:
                    spans = split_functions(sf.text)
                    fns = []
                    for sp in spans:
                        key = self.service.frontend.content_key(sp.code)
                        fns.append({
                            "key": key, "name": sp.name,
                            "start_line": sp.start_line,
                            "end_line": sp.end_line,
                        })
                        if manifest.result(key) is None:
                            pending.setdefault(key, sp.code)
                    manifest.record_file(sf.rel, sf.sha256, fns)
                else:
                    files_reused += 1
                for fn in fns:
                    if manifest.result(fn["key"]) is not None:
                        reused_fns += 1
                    rows.append({**fn, "file": sf.rel})
        split_s = time.perf_counter() - t0

        # -- frontend (shared content-keyed cache)
        feats_by_key: "OrderedDict[str, object]" = OrderedDict()
        failed = 0
        t0 = time.perf_counter()
        with obs_trace.span(
            "scan_frontend", cat="scan", functions=len(pending)
        ):
            for key, code in pending.items():
                self._next_id += 1
                try:
                    feats_by_key[key] = (
                        self.service.frontend.features_full(
                            code, self._next_id
                        )
                    )
                except Exception as e:  # noqa: BLE001 - per-function
                    # fault isolation: one weird function is a failed
                    # row, never a dead scan (failures are content-
                    # keyed too, so re-scans skip re-attempting them)
                    manifest.record_result(
                        key, {"ok": False, "error": str(e)}
                    )
                    failed += 1
        frontend_s = time.perf_counter() - t0

        # -- score through the online batcher (AOT bucket executables)
        t0 = time.perf_counter()
        scored = 0
        with obs_trace.span(
            "scan_score", cat="scan", functions=len(feats_by_key)
        ):
            keys = list(feats_by_key)
            reqs = self.service.batcher.score_all(
                [feats_by_key[k].spec for k in keys]
            )
            for key, req in zip(keys, reqs):
                try:
                    prob = req.wait(timeout_s)
                    manifest.record_result(
                        key, {"ok": True, "prob": float(prob)}
                    )
                    scored += 1
                except Exception as e:  # noqa: BLE001 - per-function
                    manifest.record_result(
                        key, {"ok": False, "error": str(e)}
                    )
                    feats_by_key.pop(key, None)
                    failed += 1
        score_s = time.perf_counter() - t0

        # -- line attributions (AOT, shared ladder)
        attr_s = 0.0
        if self.localizer is not None and feats_by_key:
            t0 = time.perf_counter()
            with obs_trace.span(
                "scan_attribute", cat="scan", functions=len(feats_by_key)
            ):
                keys = list(feats_by_key)
                attrs = self.localizer.attribute_all(
                    [feats_by_key[k] for k in keys]
                )
                for key, (_, lines) in zip(keys, attrs):
                    manifest.functions[key]["lines"] = lines
            attr_s = time.perf_counter() - t0

        # -- findings
        t0 = time.perf_counter()
        findings: list[dict] = []
        n_findings = 0
        for row in rows:
            res = manifest.result(row["key"]) or {
                "ok": False, "error": "internal: no result",
            }
            finding = {
                "file": row["file"],
                "function": row["name"],
                "start_line": row["start_line"],
                "end_line": row["end_line"],
                "ok": bool(res.get("ok")),
            }
            if res.get("ok"):
                finding["prob"] = res["prob"]
                if res["prob"] >= self.scfg.threshold:
                    n_findings += 1
                if res.get("lines") is not None:
                    # manifest lines are in the FUNCTION's coordinates
                    # (content-keyed entries move with the function);
                    # findings carry absolute file lines
                    finding["lines"] = [
                        {
                            "line": row["start_line"] + la["line"] - 1,
                            "score": la["score"],
                        }
                        for la in res["lines"]
                    ]
            else:
                finding["error"] = res.get("error")
            findings.append(finding)

        out_jsonl.parent.mkdir(parents=True, exist_ok=True)
        with obs_trace.span("scan_write", cat="scan"):
            with out_jsonl.open("w") as f:
                for finding in findings:
                    f.write(json.dumps(finding) + "\n")
            sarif_doc = sarif_report(
                findings, repo_root, threshold=self.scfg.threshold,
            )
            write_sarif(sarif_doc, sarif_out)
            manifest.prune(
                {sf.rel for sf in files}, {row["key"] for row in rows},
            )
            manifest.save()
        write_s = time.perf_counter() - t0
        total_s = time.perf_counter() - t_start

        # -- metrics + summary record
        r.counter("scan/runs").inc()
        r.counter("scan/files").inc(len(files))
        r.counter("scan/files_reused").inc(files_reused)
        r.counter("scan/files_skipped").inc(
            walk_stats.get("files_too_large", 0)
            + walk_stats.get("files_unreadable", 0)
        )
        r.counter("scan/functions").inc(len(rows))
        r.counter("scan/functions_reused").inc(reused_fns)
        r.counter("scan/functions_failed").inc(failed)
        r.counter("scan/scored").inc(scored)
        r.counter("scan/findings").inc(n_findings)
        for name, v in (
            ("walk", walk_s), ("split", split_s),
            ("frontend", frontend_s), ("score", score_s),
            ("attribute", attr_s), ("write", write_s),
        ):
            r.histogram(f"scan/{name}_seconds").observe(v)

        hits = r.counter("serve/cache_hits").value - cache_hits0
        misses = r.counter("serve/cache_misses").value - cache_misses0
        summary = {
            "scan_files": len(files),
            "scan_files_reused": files_reused,
            "scan_functions": len(rows),
            "scan_reused": reused_fns,
            "scan_extracted": len(pending),
            "scan_scored": scored,
            "scan_functions_failed": failed,
            "scan_findings": n_findings,
            "scan_seconds": round(total_s, 3),
            "scan_functions_per_sec": (
                round(len(rows) / total_s, 2) if total_s else None
            ),
            "scan_incremental_skip_fraction": (
                round(reused_fns / len(rows), 4) if rows else 0.0
            ),
            "scan_cache_hit_fraction": (
                round(hits / (hits + misses), 4)
                if (hits + misses) else None
            ),
            "scan_walk_seconds": round(walk_s, 3),
            "scan_split_seconds": round(split_s, 3),
            "scan_frontend_seconds": round(frontend_s, 3),
            "scan_score_seconds": round(score_s, 3),
            "scan_attribute_seconds": round(attr_s, 3),
            "scan_write_seconds": round(write_s, 3),
            "scan_steady_state_recompiles": (
                self.service.executor.jit_lowerings() - score_low0
            ),
            "scan_lines_steady_state_recompiles": (
                (self.localizer.jit_lowerings() - lines_low0)
                if self.localizer is not None else 0
            ),
            "repo": str(repo_root),
            "scores_path": str(out_jsonl),
            "sarif_path": str(sarif_out),
        }
        record = dict(summary)
        snap = r.snapshot()
        for section in ("scan", "localize"):
            sub = {
                k[len(section) + 1:]: v
                for k, v in snap.items()
                if k.startswith(section + "/")
            }
            if sub:
                record[section] = sub
        from deepdfa_tpu.obs import ledger as obs_ledger

        led = obs_ledger.snapshot_or_none()
        if led is not None:
            # device efficiency view (docs/efficiency.md): the scan's
            # executable costs + rolling MFU ride the scan log record
            record["ledger"] = led
        write_scan_log(run_dir, [record])
        return summary


# ---------------------------------------------------------------------------
# the self-contained smoke (the `deepdfa-tpu scan --smoke` drive)


def _build_smoke_repo(run_dir: Path, sources_dir: Path, cfg) -> Path:
    """A synthetic repository exercising every walker rule: multi-
    function files in nested directories, an excluded VCS dir with a
    decoy source, and an oversized generated file."""
    repo = run_dir / "smoke_repo"
    src_files = sorted(sources_dir.glob("*.c"))
    texts = [p.read_text() for p in src_files]
    group = 3
    for gi in range(0, len(texts), group):
        sub = repo / ("src" if gi % 2 == 0 else "src/util")
        sub.mkdir(parents=True, exist_ok=True)
        (sub / f"mod_{gi // group:02d}.c").write_text(
            "\n".join(texts[gi : gi + group]) + "\n"
        )
    decoy = repo / ".git" / "decoy.c"
    decoy.parent.mkdir(parents=True, exist_ok=True)
    decoy.write_text("int decoy(void) { return 1; }\n")
    big = repo / "gen" / "amalgamated.c"
    big.parent.mkdir(parents=True, exist_ok=True)
    big.write_text(
        "/* generated */\n" + "int filler;\n"
        * (cfg.scan.max_file_kb * 1024 // 12 + 1)
    )
    return repo


def _edit_one_function(repo: Path) -> tuple[str, str]:
    """Insert one statement into the SECOND function of the first
    scanned file (shifting every later function's lines without
    changing their content) — the incremental-rescan probe. Returns
    (rel file, function name)."""
    target = sorted((repo / "src").glob("*.c"))[0]
    text = target.read_text()
    spans = split_functions(text)
    span = spans[1] if len(spans) > 1 else spans[0]
    lines = text.split("\n")
    lines.insert(span.start_line, "  int __scan_smoke_edited = 1;")
    target.write_text("\n".join(lines))
    return target.relative_to(repo).as_posix(), span.name


def run_scan_smoke(extra_overrides=None, **smoke_kw) -> dict:
    """Train a tiny checkpoint, scan a synthetic repo cold, edit one
    function, re-scan incrementally — the end-to-end acceptance drive
    (valid SARIF + JSONL, only the edited function re-extracts, zero
    steady-state recompiles on the score AND line paths)."""
    from deepdfa_tpu import obs
    from deepdfa_tpu.serve import driver
    from deepdfa_tpu.serve.registry import ModelRegistry
    from deepdfa_tpu.serve.server import ScoringService

    smoke_kw.setdefault("max_epochs", 1)  # scan scores, never trains
    cfg, run_dir, sources_dir = driver.build_smoke_run(
        run_name="scan-smoke", dataset="scan-smoke",
        extra_overrides=[
            "scan.lines=true",
            "serve.lines_steps=2",
            # every scored function lands in the SARIF results — the
            # tiny smoke model's probabilities hover near chance and
            # the smoke asserts a non-empty results array
            "scan.threshold=0.0",
            "scan.max_file_kb=64",
            "obs.trace=true",
            # efficiency ledger + flight recorder (docs/efficiency.md):
            # the scan smoke also proves the postmortem dump path
            "obs.ledger=true",
            "obs.flight=true",
            # caller overrides last so `scan --smoke --override ...`
            # can flip any knob (e.g. model.ggnn_kernel) end to end
            *(extra_overrides or []),
        ],
        **smoke_kw,
    )
    repo = _build_smoke_repo(run_dir, sources_dir, cfg)
    with obs.session(cfg, run_dir):
        registry = ModelRegistry(
            run_dir, family="deepdfa", checkpoint=cfg.serve.checkpoint,
            cfg=cfg,
        )
        service = ScoringService(registry, cfg)
        try:
            scanner = RepoScanner(service, cfg)
            cold = scanner.scan(repo)
            findings = [
                json.loads(ln)
                for ln in Path(cold["scores_path"])
                .read_text().splitlines()
            ]
            sarif_doc = json.loads(Path(cold["sarif_path"]).read_text())
            sarif_problems = validate_sarif(sarif_doc)
            sarif_results = len(sarif_doc["runs"][0]["results"])
            edited_file, edited_fn = _edit_one_function(repo)
            incr = scanner.scan(repo)
            from deepdfa_tpu.obs import flight as obs_flight

            postmortem_path = obs_flight.crash_dump(
                "smoke_test", extra={"reason": "scan-smoke validation"}
            )
        finally:
            service.close()
    from deepdfa_tpu.obs import flight as obs_flight

    postmortem = (
        obs_flight.validate_postmortem_file(postmortem_path)
        if postmortem_path is not None
        else {"ok": False, "problems": ["no postmortem dumped"]}
    )
    with_lines = sum(1 for f in findings if f.get("lines"))
    return {
        "cold": cold,
        "incremental": incr,
        "findings": len(findings),
        "findings_ok": sum(1 for f in findings if f["ok"]),
        "findings_with_lines": with_lines,
        "sarif_problems": sarif_problems,
        "sarif_results": sarif_results,
        "edited_file": edited_file,
        "edited_function": edited_fn,
        "postmortem": postmortem,
        "run_dir": str(run_dir),
        "repo": str(repo),
        "scan_log": str(run_dir / "scan_log.jsonl"),
    }
