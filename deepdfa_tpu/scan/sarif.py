"""SARIF 2.1.0 output for repo scans (docs/scanning.md).

One run, one driver, one rule: every function whose vulnerability score
clears `scan.threshold` becomes a `result` whose primary location is the
function's line range (repo-relative uri against the SRCROOT base) and
whose `relatedLocations` carry the per-line attributions when the scan
ran with `scan.lines=true`. The mapping is the SARIF mirror of the
findings JSONL — same fields, viewer-ingestible shape (GitHub code
scanning, VS Code SARIF viewer).

`validate_sarif` is the lightweight structural checker the smoke and
tests gate on — the load-bearing subset of the 2.1.0 schema (version,
run/tool/driver shape, rule declaration, location/region sanity), not a
full JSON-Schema validation (no jsonschema dependency in the image).
"""

from __future__ import annotations

import json
from pathlib import Path

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
RULE_ID = "DEEPDFA0001"


def sarif_report(
    findings: list[dict],
    repo_root: str | Path,
    threshold: float = 0.5,
    tool_version: str = "0",
) -> dict:
    """Findings (the JSONL rows) -> one SARIF 2.1.0 document."""
    results = []
    for f in findings:
        if not f.get("ok") or f.get("prob") is None:
            continue
        if f["prob"] < threshold:
            continue
        result = {
            "ruleId": RULE_ID,
            "level": "error" if f["prob"] >= 0.9 else "warning",
            "message": {
                "text": (
                    f"function `{f['function']}` scored "
                    f"{f['prob']:.4f} for vulnerability "
                    f"(threshold {threshold})"
                ),
            },
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f["file"],
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": int(f["start_line"]),
                        "endLine": int(f["end_line"]),
                    },
                },
            }],
            "properties": {
                "prob": f["prob"],
                "function": f["function"],
            },
        }
        lines = f.get("lines")
        if lines:
            result["relatedLocations"] = [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f["file"],
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": int(la["line"])},
                    },
                    "message": {
                        "text": (
                            f"line attribution score "
                            f"{la['score']:.6f}"
                        ),
                    },
                }
                for la in lines
            ]
            result["properties"]["line_scores"] = lines
        results.append(result)
    root_uri = Path(repo_root).resolve().as_uri()
    if not root_uri.endswith("/"):
        root_uri += "/"
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "deepdfa-tpu",
                    "informationUri":
                        "https://github.com/ISU-PAAL/DeepDFA",
                    "version": str(tool_version),
                    "rules": [{
                        "id": RULE_ID,
                        "name": "VulnerableFunction",
                        "shortDescription": {
                            "text": (
                                "Function classified vulnerable by the "
                                "DeepDFA abstract-dataflow GGNN"
                            ),
                        },
                        "defaultConfiguration": {"level": "warning"},
                    }],
                },
            },
            "originalUriBaseIds": {"SRCROOT": {"uri": root_uri}},
            "results": results,
        }],
    }


def validate_sarif(doc: dict) -> list[str]:
    """Structural problems in a SARIF document ([] = valid)."""
    bad: list[str] = []

    def need(cond: bool, msg: str) -> bool:
        if not cond:
            bad.append(msg)
        return cond

    if not need(isinstance(doc, dict), "document is not an object"):
        return bad
    need(doc.get("version") == SARIF_VERSION,
         f"version must be {SARIF_VERSION!r}, got {doc.get('version')!r}")
    need(isinstance(doc.get("$schema"), str), "$schema missing")
    runs = doc.get("runs")
    if not need(isinstance(runs, list) and len(runs) >= 1,
                "runs must be a non-empty list"):
        return bad
    for ri, run in enumerate(runs):
        driver = (run.get("tool") or {}).get("driver") or {}
        need(isinstance(driver.get("name"), str) and driver["name"],
             f"runs[{ri}].tool.driver.name missing")
        rule_ids = {
            r.get("id") for r in driver.get("rules", [])
            if isinstance(r, dict)
        }
        results = run.get("results")
        if not need(isinstance(results, list),
                    f"runs[{ri}].results must be a list"):
            continue
        bases = run.get("originalUriBaseIds", {})
        for i, res in enumerate(results):
            where = f"runs[{ri}].results[{i}]"
            need(isinstance(((res.get("message") or {}).get("text")), str),
                 f"{where}.message.text missing")
            rid = res.get("ruleId")
            need(rid in rule_ids,
                 f"{where}.ruleId {rid!r} not declared in driver.rules")
            locs = res.get("locations")
            if not need(isinstance(locs, list) and locs,
                        f"{where}.locations must be non-empty"):
                continue
            for loc in locs + res.get("relatedLocations", []):
                phys = loc.get("physicalLocation") or {}
                art = phys.get("artifactLocation") or {}
                uri = art.get("uri")
                need(isinstance(uri, str) and uri and not uri.startswith("/"),
                     f"{where}: artifactLocation.uri must be relative")
                base = art.get("uriBaseId")
                if base is not None:
                    need(base in bases,
                         f"{where}: uriBaseId {base!r} not declared")
                region = phys.get("region") or {}
                start = region.get("startLine")
                need(isinstance(start, int) and start >= 1,
                     f"{where}: region.startLine must be an int >= 1")
                end = region.get("endLine", start)
                need(isinstance(end, int) and end >= start,
                     f"{where}: region.endLine must be >= startLine")
    return bad


def write_sarif(doc: dict, path: str | Path) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=1))
    return path
