from deepdfa_tpu.core import backend, config, paths, prng
from deepdfa_tpu.core.config import (
    BatchConfig,
    Config,
    DataConfig,
    FeatureSpec,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    ResilienceConfig,
    ServeConfig,
    TrainConfig,
)

__all__ = [
    "backend",
    "config",
    "paths",
    "prng",
    "Config",
    "DataConfig",
    "ModelConfig",
    "TrainConfig",
    "OptimConfig",
    "MeshConfig",
    "BatchConfig",
    "FeatureSpec",
    "ResilienceConfig",
    "ServeConfig",
]
