"""Durable small-file I/O + transient-failure retry, shared by the
checkpoint manifests (train/checkpoint.py, train/resilience.py) and the
packed-batch cache (data/packed_cache.py).

The failure modes these helpers close (docs/resilience.md):

- a crash mid-`write_text` leaves a truncated/empty json that poisons
  every future read -> `atomic_write_text` stages to a tmp file, fsyncs
  the data, and renames into place, so readers only ever see the old or
  the new complete content;
- a rename alone is not durable across power loss (the data pages and the
  directory entry can land in either order) -> the tmp file AND the
  containing directory are fsynced;
- transient host I/O errors (network filesystems, overloaded disks)
  fail a whole epoch for a blip -> `with_retries` re-runs the operation
  with exponential backoff, bounded.
"""

from __future__ import annotations

import logging
import os
import time
from pathlib import Path
from typing import Callable, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")


def fsync_dir(directory: str | Path) -> None:
    """fsync a directory so a rename inside it is durable (no-op on
    platforms whose directory fds reject fsync)."""
    try:
        fd = os.open(str(directory), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_text(path: str | Path, text: str) -> None:
    """Crash-safe replacement for ``Path.write_text``: tmp + fsync +
    rename. A reader concurrent with (or after) a crash sees either the
    previous complete content or the new complete content, never a
    truncation."""
    path = Path(path)
    tmp = path.with_name(f".{path.name}.{os.getpid()}.tmp")
    with tmp.open("w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(path.parent)


def with_retries(
    fn: Callable[[], T],
    retries: int = 2,
    backoff_s: float = 0.05,
    exceptions: tuple[type[BaseException], ...] = (OSError,),
    no_retry: tuple[type[BaseException], ...] = (FileNotFoundError,),
    what: str = "io operation",
) -> T:
    """Run ``fn`` with up to ``retries`` retries on ``exceptions``,
    sleeping ``backoff_s * 2**attempt`` between attempts. The final
    failure propagates unchanged. ``no_retry`` carves subclasses out of
    ``exceptions`` that propagate immediately — by default
    FileNotFoundError, which signals deterministic absence (e.g. a
    concurrently evicted cache entry), not a transient blip."""
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions as e:
            if isinstance(e, no_retry) or attempt >= retries:
                raise
            delay = backoff_s * (2**attempt)
            logger.warning(
                "%s failed (%s: %s); retry %d/%d in %.3fs",
                what, type(e).__name__, e, attempt + 1, retries, delay,
            )
            time.sleep(delay)
            attempt += 1
