"""PRNG discipline: one root key per run, split-by-name, never reused.

Replaces the reference's global seeding (DDFA/code_gnn/globals.py:14-33
seed_all + dgl.seed in main_cli.py) with explicit functional JAX keys.
Host-side (numpy) randomness for sampling/shuffling derives from the same
integer seed so runs are reproducible end to end.

jax is imported lazily so host-only flows (config parsing, preprocessing)
don't pay the accelerator-runtime import.
"""

from __future__ import annotations

import hashlib
from typing import TYPE_CHECKING

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    import jax


def root_key(seed: int) -> "jax.Array":
    import jax

    return jax.random.key(seed)


def fold_name(key: "jax.Array", name: str) -> "jax.Array":
    """Derive a named subkey deterministically from a string tag."""
    import jax

    h = int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "little")
    return jax.random.fold_in(key, h)


def host_rng(seed: int, name: str = "") -> np.random.Generator:
    h = int.from_bytes(hashlib.sha256(f"{seed}:{name}".encode()).digest()[:8], "little")
    return np.random.default_rng(h)


def hashstr(s: str) -> int:
    """Stable 8-byte string hash for vocab bucketing and artifact naming."""
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "little")
