"""Backend health probing + CPU fallback for driver entry points.

This environment reaches the TPU through a tunnel whose remote compile
service can wedge: backend init then raises ``RuntimeError: Unable to
initialize backend`` or the first compile hangs indefinitely. A hang in the
*current* process is unrecoverable (the backend client blocks in C++), so
health is probed in a subprocess bounded by a timeout; only when the probe
succeeds does the parent touch the default backend. On failure the parent
forces the CPU platform, which always works.

Reference contract: the reference framework assumes a healthy local CUDA
device and has no equivalent (its failure mode is a CUDA OOM/driver error
that kills the run); here the driver artifacts (BENCH/MULTICHIP json) must
be produced even when the accelerator is unreachable, so degraded-mode
fallback is a first-class path.
"""

from __future__ import annotations

import os
import subprocess
import sys

_PROBE_SRC = """
import jax, jax.numpy as jnp
try:
    from deepdfa_tpu.core.backend import enable_compile_cache
    enable_compile_cache()
except Exception:
    pass  # probe must work even outside the repo checkout
x = jnp.ones((128, 128), jnp.bfloat16)
jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
print("PLATFORM:" + jax.devices()[0].platform, flush=True)
"""

#: cached (ok, detail) of the last probe, so entry points sharing a process
#: pay the subprocess cost once.
_last_probe: tuple[bool, str] | None = None


def bounded_run(
    argv: list[str], timeout: float, what: str = "subprocess"
) -> tuple[subprocess.CompletedProcess | None, str]:
    """Run argv with a hard timeout; (result, error-tail-or-empty).

    The single place that turns a child failure into a short diagnostic:
    timeout -> "timed out" message, nonzero rc -> last stderr/stdout line
    truncated to 500 chars.
    """
    try:
        res = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout
        )
    except subprocess.TimeoutExpired:
        return None, (
            f"{what} timed out after {timeout:.0f}s (compile service wedged?)"
        )
    if res.returncode != 0:
        lines = (res.stderr or res.stdout).strip().splitlines()
        tail = lines[-1] if lines else ""
        return None, f"{what} rc={res.returncode}: {tail[:500]}"
    return res, ""


def probe_default_backend(
    timeout: float = 240.0, use_cache: bool = True
) -> tuple[bool, str]:
    """Initialize the default backend + run one tiny jit in a subprocess.

    Returns ``(ok, detail)`` where detail is the platform name on success
    ("cpu" if the default resolution already lands on CPU) or a short error
    string on failure. A wedged compile service shows up as a timeout; a
    dead tunnel as a nonzero exit with the backend-init error.
    """
    global _last_probe
    if use_cache and _last_probe is not None:
        return _last_probe
    res, err = bounded_run(
        [sys.executable, "-c", _PROBE_SRC], timeout, what="backend probe"
    )
    if res is None:
        _last_probe = (False, err)
        return _last_probe
    platform = "unknown"
    for line in res.stdout.splitlines():
        if line.startswith("PLATFORM:"):
            platform = line[len("PLATFORM:") :].strip()
    _last_probe = (True, platform)
    return _last_probe


def set_platform(platform: str, n_devices: int | None = None):
    """Point jax at `platform` (optionally with N virtual CPU devices).

    Must go through jax.config, not env vars: the tunnel's sitecustomize
    imports jax at interpreter start with platforms pre-forced, so
    JAX_PLATFORMS / XLA_FLAGS set later are never re-read.
    """
    import jax

    has_count_opt = hasattr(jax.config, "jax_num_cpu_devices")
    if n_devices is not None and not has_count_opt:
        # jax < 0.5 has no jax_num_cpu_devices option; the device count
        # can only come from XLA_FLAGS, and XLA parses those ONCE per
        # process (C++ flag cache) — rewrite them now, before the first
        # backend init below can trigger that parse
        import re

        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        ).strip()
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{int(n_devices)}"
        ).strip()
    if jax.config.jax_platforms == platform:
        # already there: don't clear_backends (that would invalidate live
        # arrays and jit caches from earlier work in this process)
        devs = jax.devices()
        if n_devices is None or len(devs) == int(n_devices):
            return devs
    from jax.extend.backend import clear_backends

    clear_backends()
    jax.config.update("jax_platforms", platform)
    if n_devices is not None and has_count_opt:
        jax.config.update("jax_num_cpu_devices", int(n_devices))
    devs = jax.devices()
    if n_devices is not None and len(devs) != int(n_devices):
        # a backend initialized earlier in this process pinned the XLA
        # flag cache; a fresh process is the only way to change it
        print(
            f"[backend] wanted {n_devices} {platform} device(s) but the "
            f"process is stuck with {len(devs)} (XLA flags are parsed "
            "once); continuing with the existing devices",
            file=sys.stderr,
        )
    return devs


def force_cpu(n_devices: int | None = None):
    """Point jax at the host CPU platform (optionally N virtual devices)."""
    return set_platform("cpu", n_devices)


def apply_platform_override() -> str | None:
    """Apply DEEPDFA_TPU_PLATFORM=platform[:N] (e.g. ``cpu:8``) if set.

    The one user-facing platform knob, shared by the CLI and the driver
    entry points: run the pipeline on a host whose accelerator tunnel is
    down, or exercise multi-chip code on N virtual CPU devices. Returns the
    forced platform, or None when the knob is unset.

    Plain ``cpu`` (no ``:N``) pins the device count to 1 rather than
    inheriting whatever ``--xla_force_host_platform_device_count`` happens
    to sit in XLA_FLAGS: an inherited 8-virtual-device platform on a small
    host makes ``MeshConfig.dp=-1`` build an 8-way mesh whose in-process
    CPU collectives can starve past XLA's 40s rendezvous termination and
    SIGABRT the process (round-3 red test). Multi-device CPU runs are an
    explicit opt-in via ``cpu:N``. The reference trains regardless of the
    visible-device count (LineVul/linevul/linevul_main.py:165-166); plain
    ``cpu`` now matches that determinism.
    """
    spec = os.environ.get("DEEPDFA_TPU_PLATFORM")
    if not spec:
        return None
    platform, _, n = spec.partition(":")
    if not n and platform == "cpu":
        n = "1"
    set_platform(platform, int(n) if n else None)
    return platform


def cpu_pinned() -> bool:
    """True when this process is already pinned to CPU — by env knob
    (DEEPDFA_TPU_FORCE_CPU / DEEPDFA_TPU_PLATFORM=cpu[:N]) or an
    in-process jax.config pin (e.g. the test harness)."""
    if os.environ.get("DEEPDFA_TPU_FORCE_CPU"):
        return True
    if os.environ.get("DEEPDFA_TPU_PLATFORM", "").partition(":")[0] == "cpu":
        return True
    import jax

    return jax.config.jax_platforms == "cpu"


def ensure_backend(
    timeout: float = 240.0, n_cpu_devices: int | None = None
) -> str:
    """Make sure this process can run jax computations; return the platform.

    Order: DEEPDFA_TPU_FORCE_CPU env override -> subprocess probe of the
    default backend -> CPU fallback (always available). Never hangs longer
    than ``timeout``.
    """
    if cpu_pinned():
        # nothing to probe — and a subprocess probe would wrongly test the
        # default (tunnel) resolution instead of the pin. Re-force only
        # when the pin isn't applied to jax.config yet (avoid a needless
        # clear_backends when e.g. the test harness already pinned it).
        import jax

        if apply_platform_override() is None and (
            n_cpu_devices is not None or jax.config.jax_platforms != "cpu"
        ):
            force_cpu(n_cpu_devices)
        return "cpu"
    ok, detail = probe_default_backend(timeout)
    if ok and detail != "cpu":
        return detail
    if not ok:
        print(
            f"[deepdfa_tpu] default backend unhealthy ({detail}); "
            "falling back to CPU",
            file=sys.stderr,
        )
    force_cpu(n_cpu_devices)
    return "cpu"


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a storage-local dir.

    Executables compiled once (any process) are reused by later runs,
    which makes the driver's bench/entry invocations robust to the remote
    compile service's slow phases: a cache-hit run never talks to the
    compiler at all. No-op (returns None) when the config knob is absent
    or the directory cannot be created.
    """
    import jax

    from deepdfa_tpu.core import paths

    try:
        parent = paths.storage_root() / "compile_cache"
        cache = path or str(parent / _host_fingerprint())
        os.makedirs(cache, exist_ok=True)
        marker = parent / ".migrated"
        if path is None and not marker.exists():
            # one-time sweep (marker-guarded: without it every startup
            # re-unlinks loose files, racing concurrent older-version
            # processes still reading/writing them): loose files under
            # the legacy flat dir predate host-fingerprinting and may
            # hold AOT executables for another host's ISA (see
            # _host_fingerprint) — retire them so no older code path
            # can load one
            for name in os.listdir(parent):
                f = parent / name
                if f.is_file() and name != ".migrated":
                    try:
                        f.unlink()
                    except OSError:
                        pass
            marker.touch()
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # unsupported jax version / read-only fs
        return None
    return cache


def _host_fingerprint() -> str:
    """Cache-dir discriminator for the host's CPU feature set.

    XLA:CPU AOT executables bake in the compile host's ISA extensions,
    and the cache key does NOT include them — an artifact cached on one
    fleet machine and loaded on another logs 'Machine type used for
    XLA:CPU compilation doesn't match ... could lead to execution errors
    such as SIGILL' and can mis-execute (observed as a one-off wrong
    beam-search score in the slow test lane). Scoping the cache per CPU
    signature removes the cross-host reuse; TPU executables are
    host-independent so the extra partitioning only costs re-compiles
    after a container lands on new silicon.
    """
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    return hashlib.sha256(flags.encode())\
                        .hexdigest()[:16]
    except OSError:
        pass
    import platform

    return hashlib.sha256(
        f"{platform.machine()}-{platform.processor()}".encode()
    ).hexdigest()[:16]
