"""One typed configuration system for the whole framework.

Replaces the reference's three config mechanisms (LightningCLI+jsonargparse
YAML stacks in DDFA/code_gnn/main_cli.py:69-99, argparse in
LineVul/linevul/linevul_main.py:422-524 and CodeT5/configs.py) with nested
dataclasses, dotted-path CLI overrides, and JSON round-tripping.

The reference's string-encoded feature selection
(`_ABS_DATAFLOW_<subkeys>_all_limitall_<N>_limitsubkeys_<M>`, parsed by
DDFA/sastvd/helpers/datasets.py:560-585) is kept as `FeatureSpec`, the
dataset-artifact naming convention, but exposed as typed fields.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

ALL_SUBKEYS = ("api", "datatype", "literal", "operator")

#: pad-token id per encoder family — the ONE convention shared by the
#: text collaters (padding fill, data/text.py) and the encoders'
#: attention-mask derivation (`input_ids != pad`, models/transformer.py
#: and models/t5.py). RoBERTa-family vocabs put <pad> at 1, the T5 frame
#: at 0. Both sides read this table so they cannot drift apart at two
#: call sites that agree only by convention.
PAD_ID_BY_FAMILY = {"roberta": 1, "t5": 0}


@dataclass(frozen=True)
class FeatureSpec:
    """Which abstract-dataflow subkeys feed the model and vocab limits.

    input_dim per subkey table = limit_all + 2: index 0 = "node is not a
    definition", 1 = UNKNOWN hash, 2.. = the limit_all most frequent train
    hashes (reference: DDFA/sastvd/scripts/dbize_absdf.py:35-42 and
    DDFA/sastvd/linevd/datamodule.py:87-96).
    """

    subkeys: tuple[str, ...] = ALL_SUBKEYS
    limit_all: int | None = 1000  # None = unlimited (reference parse_limits)
    limit_subkeys: int | None = 1000
    #: attach reaching-definitions bit labels of this width at extraction
    #: (required for the dataflow_solution_{in,out} label styles)
    max_defs: int | None = None
    #: append the family-invariant structural channels at extraction
    #: (frontend/structfeat.py; consumed when model.struct_feats is on)
    struct_feats: bool = False

    def __post_init__(self):
        # canonical order so equal artifact names imply equal specs
        object.__setattr__(self, "subkeys", tuple(sorted(set(self.subkeys))))

    @property
    def input_dim(self) -> int:
        if self.limit_all is None:
            raise ValueError(
                "input_dim is undefined for an unlimited vocab (limit_all=None); "
                "size the embedding from the built vocab instead"
            )
        return self.limit_all + 2

    @property
    def name(self) -> str:
        sk = "_".join(sorted(self.subkeys))
        base = (
            f"_ABS_DATAFLOW_{sk}_all_limitall_{self.limit_all}"
            f"_limitsubkeys_{self.limit_subkeys}"
        )
        # artifact names must distinguish bit-labeled stores from plain ones
        if self.max_defs is not None:
            base += f"_maxdefs_{self.max_defs}"
        if self.struct_feats:
            base += "_struct"
        return base

    @classmethod
    def parse(cls, feat: str) -> "FeatureSpec":
        """Parse a reference-style feature string."""
        subkeys = tuple(k for k in ALL_SUBKEYS if k in feat) or ALL_SUBKEYS

        def _limit(key: str, default: int) -> int | None:
            if key not in feat:
                return default
            start = feat.find(key) + len(key) + 1
            end = feat.find("_", start)
            tok = feat[start:] if end == -1 else feat[start:end]
            return None if tok == "None" else int(tok)

        return cls(
            subkeys=subkeys,
            limit_all=_limit("limitall", 1000),
            limit_subkeys=_limit("limitsubkeys", 1000),
            max_defs=_limit("maxdefs", None),
            struct_feats="_struct" in feat,
        )


@dataclass(frozen=True)
class ModelConfig:
    """GGNN architecture (reference defaults: DDFA/configs/config_ggnn.yaml)."""

    hidden_dim: int = 32
    n_steps: int = 5
    # edge-relation count for the GGNN (dgl.nn.GatedGraphConv n_etypes);
    # >1 needs typed-edge graphs (pipeline gtype="cfg+dep")
    n_etypes: int = 1
    # lax.scan the shared-weight GGNN steps instead of unrolling — a
    # smaller compiled program for compile-time-constrained environments
    # (numerics pinned to the unrolled form; see nn/gnn.py docstring)
    scan_steps: bool = False
    num_output_layers: int = 3
    concat_all_absdf: bool = True
    # family-invariant structural channels (frontend/structfeat.py):
    # needs a corpus extracted with data.feat.struct_feats=true; widens
    # the encoder by len(STRUCT_VOCAB) * hidden_dim
    struct_feats: bool = False
    # graph | node | dataflow_solution_in | dataflow_solution_out
    # (dataflow styles need data.feat.max_defs set at extraction)
    label_style: str = "graph"
    encoder_mode: bool = False
    # TPU-specific knobs (no reference equivalent):
    param_dtype: str = "float32"
    compute_dtype: str = "float32"  # bfloat16 for large models
    # Pallas-fused GGNN message-passing step (nn/ggnn_kernel.py,
    # docs/ggnn_kernel.md): gather + etype transform + dst-sorted
    # segment scatter + GRU in one HBM-resident pass. Default off — the
    # lax path stays byte-identical; the knob flows through
    # GatedGraphConv so train, serve scoring, and localization all
    # switch at the one call site.
    ggnn_kernel: bool = False
    # scatter mode: "auto" (mxu on TPU hardware, the bit-exact fold
    # under the CPU interpreter), "fold", or "mxu"
    ggnn_kernel_scatter: str = "auto"
    # message-side dtype policy: "fp32" (bit-identical to lax), "bf16"
    # (halved gather traffic, f32 accumulation, f32 GRU state), or
    # "int8" (per-channel symmetric quantization, int8 MXU matmuls with
    # int32 accumulation, drift-bounded); tolerances pinned in
    # tests/test_ggnn_kernel.py
    ggnn_kernel_accum: str = "fp32"
    # step-loop placement: "per_step" (one pallas_call per GGNN step)
    # or "fused" (the whole n_steps unroll in ONE kernel with the node
    # state VMEM-resident; falls back to per_step loudly when the
    # residency estimate overflows VMEM or under scan_steps). A
    # LAYOUT-ONLY knob like the tile sizes: same numerics contract,
    # same param tree — excluded from the serve registry's digest
    ggnn_kernel_unroll: str = "per_step"
    # kernel block/tile sizes (0 = the hand-picked defaults in
    # nn/ggnn_kernel.py:block_sizes). LAYOUT-ONLY knobs: they change how
    # the fused step tiles, never the param tree or numerics contract —
    # excluded from the serve registry's config digest so a tuned layout
    # (deepdfa_tpu/tune/, docs/tuning.md) never refuses a hot swap
    ggnn_kernel_block_nodes: int = 0
    ggnn_kernel_block_edges: int = 0


@dataclass(frozen=True)
class BatchConfig:
    """Static-shape batching budgets (replaces dgl.batch dynamic shapes)."""

    graphs_per_batch: int = 256
    max_nodes_per_graph: int = 512
    node_budget: int = 16384  # padded node count per shard
    edge_budget: int = 65536  # padded edge count per shard (incl. self loops)


@dataclass(frozen=True)
class DataConfig:
    dataset: str = "bigvul"
    feat: FeatureSpec = field(default_factory=FeatureSpec)
    # edge-relation set (reference gtype axis, config_bigvul.yaml): "cfg"
    # (flagship) or "cfg+dep" (typed cfg/data-dep/control-dep edges for an
    # n_etypes=3 GGNN; set model.n_etypes=3 to match)
    gtype: str = "cfg"
    split: str = "fixed"  # fixed | random | fixed+random seed schemes
    seed: int = 0
    sample_mode: bool = False
    undersample: bool = True  # epoch-wise 1:1 undersampling of negatives
    batch: BatchConfig = field(default_factory=BatchConfig)
    # host input pipeline (docs/input_pipeline.md):
    # pack_workers > 1 packs first-epoch batches on a spawn process pool
    # (data/mp_pack.py) — packing is GIL-bound, threads cannot scale it
    pack_workers: int = 0
    # persist fully-packed batch streams under cache/<dataset>/packed and
    # replay them zero-copy (mmap) when the content key matches — epochs
    # with identical selections and every re-run skip packing entirely
    # (data/packed_cache.py)
    packed_cache: bool = False
    # entry cap for that cache: undersample selections are epoch-keyed
    # (one entry per epoch), so finalizing a new entry evicts the
    # least-recently-USED beyond this many (replay refreshes an entry's
    # stamp — the eval split, replayed every epoch, never ages out)
    packed_cache_max_entries: int = 64
    # sequence-length bucketing for the combined/text path
    # (docs/input_pipeline.md): each row pads to the smallest configured
    # bucket edge >= its real token length instead of the tokenizer's
    # fixed max_length, so transformer FLOPs follow the (lognormal)
    # length distribution instead of the worst case. () disables —
    # every batch pads to max_length as before. Edges must be ascending;
    # the CLI requires the largest edge to EQUAL its --max-length
    # (smaller cannot hold a full-length row, larger exceeds the
    # positional capacity the recipe configures for the encoder).
    seq_buckets: tuple[int, ...] = ()
    # token budget per bucketed batch (rows x T <= budget, split over dp
    # shards): short buckets run proportionally more rows at roughly
    # constant activation memory. 8192 = the legacy 16-row x 512-token
    # recipe's footprint.
    token_budget: int = 8192


@dataclass(frozen=True)
class OptimConfig:
    """Reference: Adam lr 1e-3 wd 1e-2 (DDFA/configs/config_default.yaml:43-47)."""

    name: str = "adamw"
    learning_rate: float = 1e-3
    weight_decay: float = 1e-2
    warmup_frac: float = 0.0
    grad_clip_norm: float = 0.0  # 0 = off
    b1: float = 0.9
    b2: float = 0.999


@dataclass(frozen=True)
class ResilienceConfig:
    """Preemption-safe, self-healing training runtime knobs
    (train/resilience.py, docs/resilience.md).

    Everything hangs off the master `enabled` switch so the default
    training path is byte-for-byte the historical one; the CLI train
    commands build a ResilientRunner when it is on."""

    enabled: bool = False
    # step-granular checkpoint cadence (steps); 0 = checkpoint only on
    # preemption. Each checkpoint captures the FULL TrainState (params +
    # optimizer + schedule step) plus the data cursor (epoch, batch
    # index), so a killed run resumes mid-epoch.
    step_checkpoint_every: int = 50
    # step checkpoints retained (the resume manifest always points at the
    # newest complete one)
    keep_last_k: int = 3
    # resume automatically when a resume manifest exists in the run dir
    auto_resume: bool = True
    # on-device loss/grad-norm finiteness guard: a non-finite step is
    # skipped inside jit (params/optimizer untouched) with no extra host
    # sync on the happy path (the flag is fetched `guard_lag` steps late)
    divergence_guard: bool = True
    guard_lag: int = 1
    # after this many CONSECUTIVE bad steps, roll back to the last-good
    # step checkpoint and multiply the effective LR by lr_cooldown;
    # rollback_budget bounds how many times before giving up loudly
    max_consecutive_bad: int = 3
    rollback_budget: int = 2
    lr_cooldown: float = 0.5
    # step watchdog: abort with a stage-attributed diagnostic when no
    # train-loop heartbeat lands for this long (hung device step or
    # stalled input pipeline); 0 = off
    watchdog_timeout_s: float = 0.0
    # stall threshold until the FIRST completed step — that step
    # legitimately includes jit compilation (minutes on TPU), which the
    # steady-state timeout would misread as a hang; 0 = 10x the timeout
    watchdog_first_step_grace_s: float = 0.0
    # transient host-I/O retry policy (packed-cache reads, manifests)
    io_retries: int = 2
    io_backoff_s: float = 0.05


@dataclass(frozen=True)
class ObsConfig:
    """Unified run telemetry knobs (deepdfa_tpu/obs/,
    docs/observability.md). Everything defaults OFF — the default
    training path emits exactly the historical records and artifacts."""

    # cross-process Chrome-trace span capture (obs/trace.py): per-process
    # JSONL files under trace_dir (default <run_dir>/trace), merged into
    # trace.json at run end; spawn-pool packer workers and CLI
    # subprocesses join via an exported env var
    trace: bool = False
    trace_dir: str | None = None
    # include the metrics-registry snapshot (obs/metrics.py), lagged
    # step-time decomposition, and device memory stats in epoch records
    # (flattened to obs/* TensorBoard tags)
    metrics: bool = False
    # jax.profiler capture of a step window (obs/xprof.py): start at this
    # global step (-1 = off) for xprof_num_steps steps, under
    # <run_dir>/xprof/ (TensorBoard profile plugin)
    xprof_start_step: int = -1
    xprof_num_steps: int = 5
    # live-run capture triggers: SIGUSR2, or touching
    # <run_dir>/xprof/TRIGGER, arms a capture of the next
    # xprof_num_steps steps
    xprof_trigger: bool = False
    # device efficiency ledger (obs/ledger.py, docs/efficiency.md):
    # per-executable cost-analysis flops/bytes, compile wall time, and
    # executable live bytes at every AOT compile site, joined with the
    # sync-free StepTimer device time into rolling per-signature MFU —
    # into epoch records, /metrics `ledger/*` families, /stats, and the
    # serve/scan logs. Host-side accounting only (zero new program
    # signatures); with the ledger ON, GraphTrainer additionally AOT-
    # compiles its already-jitted step once per signature to read the
    # cost analysis (a warmup-time cost, never steady-state).
    ledger: bool = False
    # run the runtime measured-ceiling probes (small dense-matmul +
    # gather probes, docs/roofline.md) once at session start so per-site
    # MFU reads against the MEASURED ceiling instead of raw FLOP/s;
    # costs ~a second of device time at enable
    ledger_ceilings: bool = False
    # crash flight recorder (obs/flight.py): a bounded in-memory ring of
    # the last N step records + recent telemetry instants + the ledger
    # snapshot, dumped atomically to <run_dir>/postmortem.json on
    # watchdog abort (exit 113), SIGTERM preemption, NaN-guard rollback,
    # backend WEDGE, or an unhandled exception (OOM classified)
    flight: bool = False
    flight_steps: int = 64
    flight_events: int = 128


@dataclass(frozen=True)
class ServeConfig:
    """Online inference knobs (deepdfa_tpu/serve/, docs/serving.md).

    Only the `serve`/`score` CLI commands read this section — the
    training/eval paths never touch it, so the default path stays
    byte-identical. SLO intuition: `queue_limit` bounds worst-case
    memory and queueing delay (admission control — a full queue rejects
    instead of growing latency unboundedly), `max_batch_delay_ms` bounds
    the latency a lone request pays waiting for co-batching."""

    # -- dynamic batcher (serve/batcher.py)
    # bounded request queue; submissions beyond this are REJECTED
    # (HTTP 429) instead of queued — backpressure, not buffering
    queue_limit: int = 256
    # flush timer: a partial batch executes once its oldest request has
    # waited this long, so a lone request never waits for co-arrivals
    max_batch_delay_ms: float = 25.0
    # largest serve batch (graphs per executable); the batcher AOT-warms
    # a power-of-two ladder 1, 2, ..., max_batch_graphs so partial
    # flushes pad to the nearest bucket executable, never recompile
    max_batch_graphs: int = 16
    # packed-batch budgets for serving; 0 = inherit data.batch.*
    node_budget: int = 0
    edge_budget: int = 0
    # bounded in-flight window for pipelined execution (docs/serving.md
    # "Pipelined execution"): >0 overlaps host pack/dispatch with device
    # execution, a FIFO fetch thread syncs at most this many dispatched
    # batches behind; 0 (default) keeps the serial inline path
    pipeline_depth: int = 0
    # -- model registry (serve/registry.py)
    checkpoint: str = "best"
    # between batches, poll the checkpoint manifest and hot-swap params
    # when a newer checkpoint of the SAME config/vocab digest appears
    hot_swap: bool = False
    # -- request frontend (serve/frontend.py)
    # content-keyed feature cache entries (repeat functions skip the
    # frontend entirely); 0 disables
    feature_cache_entries: int = 1024
    # route extraction through a pooled Joern JVM (frontend/
    # joern_session.py, bounded auto-restart) instead of the built-in
    # parser; needs `joern` on PATH
    use_joern: bool = False
    joern_pool_size: int = 1
    joern_timeout_s: float = 300.0
    # -- operational observability (obs/slo.py, obs/health.py, docs/slo.md)
    # append one {"request": {...}} entry per HTTP request (request_id,
    # status, per-stage latency) to <run_dir>/serve_log.jsonl; off by
    # default — the summary-record-only log is the historical behaviour
    request_log: bool = False
    # rolling SLO window lengths (seconds) the /metrics + /stats
    # aggregator maintains (obs/slo.py; labels render as e.g. "60s")
    slo_windows: tuple[int, ...] = (60, 300)
    # newest samples retained per window/stage (exact percentiles over
    # the retained sample set; older samples age out by time)
    slo_window_samples: int = 2048
    # GET /healthz?deep=1 backend probe budget: a bounded subprocess
    # compile-and-execute against the DEFAULT backend (obs/health.py) —
    # the wedged-compile-service detector, never run on the request path
    health_probe_timeout_s: float = 60.0
    # -- line-level attributions (serve/localize.py, docs/scanning.md)
    # AOT-warm the per-node attribution executables next to the scoring
    # ladder and accept {"lines": true} on POST /score; off by default —
    # the extra warmup compiles are only paid when localization serves
    lines: bool = False
    # attribution method for the served line scores (eval/localize.py
    # GGNN family: attention | saliency | input_x_gradient | deeplift |
    # lig)
    lines_method: str = "saliency"
    # Riemann steps for the path methods (deeplift/lig); small by
    # default — the serving tax is n_steps gradient evaluations
    lines_steps: int = 8
    # top-scoring lines echoed per request (0 = every tokenized line)
    lines_top_k: int = 10
    # -- quantized serving executables (serve/quant.py, docs/cascade.md)
    # a checkpoint tag with the @int8 suffix (serve.checkpoint=best@int8,
    # or a fleet co-serving entry) restores fp32 and serves per-channel
    # symmetric int8 matmul weights + bf16 rest, dequantized inside the
    # compiled program (f32 accumulation). Admission contract: the max
    # calibration prob drift vs the fp32 params must stay within this
    # bound or the registry REFUSES the entry loudly (offending param
    # paths named) — mirrors the PR-8 bf16 message-policy bound
    quant_drift_bound: float = 5e-2
    # calibration batch rows per family (deterministic random inputs;
    # the drift is measured over one packed batch of this many rows)
    quant_calibration_samples: int = 8
    # -- two-stage cascaded inference (serve/cascade.py, docs/cascade.md)
    # /score runs the cheap GGNN on EVERY request and escalates only
    # requests whose calibrated stage-1 probability falls inside the
    # uncertainty band to the combined/t5 executor. Default OFF — the
    # single-stage path stays byte-identical
    cascade: bool = False
    # the uncertainty band over CALIBRATED stage-1 probabilities:
    # lo <= p < hi escalates (fit both edges with eval/calibrate.py
    # from a labeled dev set; the default brackets maximum uncertainty)
    cascade_band: tuple[float, float] = (0.25, 0.75)
    # temperature for stage-1 probability calibration (1.0 = identity;
    # fit with eval/calibrate.py:fit_temperature on a labeled dev set)
    cascade_temperature: float = 1.0
    # stage-2 model: run directory (None = the serving run's own dir —
    # the smoke/test layout where checkpoints-combined/ sits next to
    # checkpoints/), family, and checkpoint tag (@int8 composes)
    cascade_run_dir: str | None = None
    cascade_family: str = "combined"
    cascade_checkpoint: str = "best"
    # per-escalation wait on the stage-2 batcher
    cascade_timeout_s: float = 60.0
    # cascade-aware degradation (docs/cascade.md shed-order table):
    # once the stage-2 queue holds this fraction of serve.queue_limit,
    # new escalations are SHED (the request answers with its stage-1
    # score, counted in serve/cascade_sheds) — under overload stage-2
    # escalations degrade before any stage-1 screen is refused
    cascade_shed_depth_fraction: float = 0.75
    # -- unified sharding (parallel/sharding.py, docs/sharding.md)
    # serve through a device mesh: params commit under the family's
    # path-pattern sharding map (train.mesh.rules prepend) on a mesh of
    # serve.mesh axes, batches replicate, and XLA/GSPMD partitions the
    # AOT ladder programs — a sharded checkpoint serves without a
    # reshape step. Default OFF: single-device placement, the serving
    # path stays byte-identical
    sharded: bool = False
    mesh: MeshConfig = field(
        default_factory=lambda: MeshConfig(dp=1)
    )


@dataclass(frozen=True)
class ScanConfig:
    """Whole-repo incremental scanning knobs (deepdfa_tpu/scan/,
    docs/scanning.md).

    Only the `scan` CLI command reads this section. A scan walks a
    repository, splits every C/C++ source into function definitions,
    scores each through the serving stack (shared content-keyed
    frontend cache + dynamic batcher + AOT executables), and streams
    findings to JSONL and SARIF 2.1.0. The persistent manifest makes a
    re-scan of an edited repo touch only the changed functions."""

    # source suffixes the walker collects (serve/driver.py's set plus
    # the C++ header spellings)
    suffixes: tuple[str, ...] = (
        ".c", ".cc", ".cpp", ".cxx", ".h", ".hpp", ".hh", ".hxx",
    )
    # directory names pruned anywhere in the tree (VCS metadata, build
    # output, vendored code); hidden directories are pruned regardless
    exclude_dirs: tuple[str, ...] = (
        ".git", ".hg", ".svn", "build", "cmake-build-debug", "out",
        "node_modules", "third_party", "vendor", "external",
    )
    # files larger than this are skipped (generated/amalgamated sources
    # dominate scan time and drown the findings)
    max_file_kb: int = 1024
    # findings threshold: functions scoring >= this land in the SARIF
    # results (every function still lands in the JSONL stream)
    threshold: float = 0.5
    # per-finding line attributions (serve/localize.py AOT executables;
    # method/steps/top-k shared with the serve endpoint via serve.lines_*)
    lines: bool = False
    # re-use the persistent manifest: functions whose content key and
    # model identity match the previous scan are not re-extracted or
    # re-scored. false = always scan cold (the manifest is still written)
    incremental: bool = True
    # manifest path override; default
    # <run_dir>/scan_state/<sha16 of repo abspath>.json
    state: str | None = None


@dataclass(frozen=True)
class FleetConfig:
    """Multi-replica serving fleet knobs (deepdfa_tpu/fleet/,
    docs/fleet.md).

    Only the `fleet`/`fleet-replica` CLI commands read this section —
    the single-process `serve` path never touches it, so the default
    serving path stays byte-identical. Topology: N shared-nothing
    replica processes (each a full ScoringService with its own
    AOT-warmed ladders) announce themselves via heartbeat files under
    `<run_dir>/fleet/`; one router process front-doors them with
    health-gated least-outstanding routing and per-tenant admission."""

    # -- topology (fleet/replica.py, cli `fleet`)
    # replica processes the `fleet` command spawns; 0 = unset, derive
    # the count from the per-entry param-bytes ledger signal via
    # fleet/admission.py:plan_replicas (checkpoint bytes on disk vs
    # hbm_budget_bytes; falls back to 2 when unbudgeted) — the computed
    # plan is logged loudly
    replicas: int = 0
    # router bind address (replicas always bind 127.0.0.1:ephemeral and
    # publish their real port via heartbeat)
    host: str = "127.0.0.1"
    port: int = 8470
    # heartbeat/obs directory override; default <run_dir>/fleet
    fleet_dir: str | None = None
    # -- heartbeats (fleet/heartbeat.py)
    # how often a replica refreshes its heartbeat file
    heartbeat_interval_s: float = 1.0
    # a heartbeat older than this marks the replica GONE (removed from
    # routing until a fresh one appears)
    heartbeat_timeout_s: float = 10.0
    # -- routing (fleet/router.py)
    # router-side heartbeat re-scan + ejected-replica probe cadence
    poll_interval_s: float = 0.5
    # transport failures before a replica is ejected (1 = first failed
    # forward ejects; the request is retried on a survivor either way)
    eject_threshold: int = 1
    # forward attempts per request beyond the first (each on a different
    # replica) before the router answers 503
    retries: int = 2
    # per-forward timeout the router waits on a replica
    request_timeout_s: float = 60.0
    # -- admission (fleet/admission.py)
    # JSON object {tenant: {"rate": r/s, "burst": b, "priority": p}};
    # priority 0 = interactive (never overload-shed), 1 = batch,
    # 2 = best-effort. Unlisted tenants get the default_* policy.
    tenants: str = ""
    default_rate: float = 100.0
    default_burst: float = 200.0
    default_priority: int = 1
    # assumed per-replica concurrent capacity for the overload shed
    # (outstanding > shed_fraction * healthy * replica_capacity sheds
    # priority>0 requests 503 before any device time is spent)
    replica_capacity: int = 64
    shed_fraction: float = 1.0
    # initial EWMA service-time estimate the deadline shed uses before
    # real completions calibrate it
    service_time_init_ms: float = 50.0
    # cascade-aware shedding (docs/cascade.md): requests marked
    # {"cascade_stage": 2} (stage-2 escalations re-entering through the
    # router) shed at this fraction of the overload capacity — BEFORE
    # plain stage-1 traffic sheds at shed_fraction — so overload
    # degrades the cascade to stage-1-only first
    cascade_shed_fraction: float = 0.75
    # -- drain (fleet/replica.py)
    # lame-duck period: after announcing `draining` in the heartbeat, a
    # replica keeps serving this long before tearing down, so the router
    # (poll cadence poll_interval_s) deterministically observes the
    # drain and stops routing to it
    drain_announce_s: float = 0.5
    # -- multi-model co-serving (fleet/admission.py:plan_coserving)
    # extra registry entries one replica co-serves, "name=run_dir" or
    # "name=run_dir:checkpoint"; requests pick one with {"model": name}
    models: tuple[str, ...] = ()
    # HBM budget (bytes) the per-entry param-bytes ledger arbitrates
    # co-serving against; 0 = unbudgeted (every configured entry loads)
    hbm_budget_bytes: float = 0.0
    # -- router HA (fleet/ha.py, docs/fleet.md)
    # spawn a standby `fleet-router` subprocess next to the active: it
    # tails the heartbeat dir + fleet_log, health-checks the active via
    # the router.json rendezvous file, and takes over the front door
    # within the documented failover window when the active dies
    standby_router: bool = False
    # active-router rendezvous refresh cadence (the router's own
    # heartbeat; router.json under the fleet dir)
    rendezvous_interval_s: float = 0.5
    # a rendezvous older than this marks the active presumed-dead; the
    # standby double-checks with a bounded /healthz probe, then takes
    # over. Documented failover bound: router_failover_timeout_s +
    # probe_timeout_s + one standby poll (rendezvous_interval_s)
    router_failover_timeout_s: float = 3.0
    # periodic fleet_log summary-record cadence — each summary embeds
    # the admission snapshot (token-bucket levels + service EWMA), the
    # re-seed source a restarted/failed-over router restores from;
    # 0 = summaries only at close
    summary_interval_s: float = 5.0
    # -- zero-downtime rollout (fleet/rollout.py, cli `fleet-rollout`)
    # max calibration score drift (|P_new - P_old| over deterministic
    # calibration batches, the PR-12 machinery) a rollout checkpoint may
    # show vs the serving params before the per-replica swap is REFUSED
    # and the rollout halts + rolls back
    rollout_drift_bound: float = 0.05
    # SLO guard: halt + roll back the rollout when the router's
    # smallest-window p99 (ms) or SERVER-error rate (5xx minus the 503
    # shed statuses — designed 429/503 load shedding never halts a
    # healthy deploy) breaches after any replica swap; 0 disables
    # either arm
    rollout_p99_ms: float = 0.0
    rollout_error_rate: float = 0.25
    # settle time after each replica swap before the SLO guard judges
    rollout_settle_s: float = 1.0
    # -- chaos drills (fleet/chaos.py, scripts/fault_inject.py)
    # enable the replica's /admin/chaos fault endpoints (wedge the
    # health probe, inject scoring latency) — the fleet chaos harness
    # flips this; never on by default
    chaos: bool = False
    # -- coordination backend (fleet/coord.py)
    # which CoordinationBackend the fleet's shared-state protocol
    # (heartbeats, router.json rendezvous, fleet_log) rides: "local"
    # (default; today's byte-identical atomic files under the fleet
    # dir) or "faultable" (the same files behind the chaos fault-
    # injection wrapper — drills only, never production)
    coord_backend: str = "local"
    # -- scheduled chaos drills (fleet/drill.py, cli `fleet-drill`)
    # cadence between drill rounds; the smoke collapses it to ~0 so
    # one scheduled round still exercises the scheduler
    drill_interval_s: float = 3600.0
    # failure-matrix rounds one `fleet-drill` invocation executes
    drill_rounds: int = 1
    # -- predictive autoscaling (fleet/autoscale.py; default OFF so
    # the default fleet path stays byte-identical)
    autoscale: bool = False
    # how far ahead the arrival-process forecast looks
    autoscale_horizon_s: float = 5.0
    # arrival-rate bucket width for the fleet_log replay
    autoscale_bucket_s: float = 1.0
    # degradation ladder engages (and a replica is spawned) when the
    # forecast crosses this fraction of measured fleet capacity —
    # BEFORE the offered load itself crosses it
    autoscale_up_fraction: float = 0.8
    # scale back down only below this fraction (the hysteresis band
    # between the two thresholds is where the controller holds)
    autoscale_down_fraction: float = 0.3
    # minimum seconds between replica-count changes (no flapping)
    autoscale_cooldown_s: float = 10.0
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 4
    # -- fleet telemetry plane (obs/aggregate.py; default OFF so the
    # default fleet path stays byte-identical)
    # replicas publish schema-validated metrics snapshots (+ trace
    # segments when tracing is on) through the coord backend, and the
    # router aggregates them into fleet-level /metrics and /stats
    telemetry: bool = False
    # snapshot publication cadence per replica
    telemetry_interval_s: float = 2.0
    # -- alert engine (obs/alerts.py; default OFF)
    # the router evaluates the alert rule catalog on a cadence and
    # appends every pending/firing/resolved transition to the fleet_log
    alerts: bool = False
    alert_interval_s: float = 1.0
    # JSON list overlaying the default rule catalog (replace by name,
    # {"disable": true} to remove, new names append) — docs/alerts.md
    alert_rules: str = ""
    # -- data flywheel (deepdfa_tpu/flywheel/, docs/flywheel.md;
    # default OFF so the default fleet path stays byte-identical)
    # master switch: the router mirrors a bounded sample of admitted
    # requests through the coord backend for a shadow candidate to score
    flywheel: bool = False
    # fraction of admitted 200s mirrored to the shadow (deterministic
    # every-kth sampling, k = round(1/rate) — no per-request RNG on the
    # serving path)
    flywheel_sample_rate: float = 0.25
    # unscored mirrored samples the sampler tolerates before it DROPS
    # new ones (counted under shadow/dropped) — backpressure, never a
    # queue that grows while the shadow falls behind
    flywheel_max_inflight: int = 64
    # scored comparisons required before promote/demote may trigger
    flywheel_min_samples: int = 50
    # rolling comparison window the {"shadow": ...} records summarize
    flywheel_window: int = 64
    # the promotion bound: candidate AUC (over labeled samples) must
    # beat the incumbent's by at least this margin
    flywheel_promote_margin: float = 0.02
    # the demotion bound: a candidate trailing the incumbent by this
    # margin (or drifting past flywheel_drift_bound) is demoted with a
    # {"demotion": ...} record instead of ever touching traffic
    flywheel_demote_margin: float = 0.05
    # max mean |P_candidate - P_incumbent| over the shadow window before
    # the ride is judged calibration-drifted (pre-promotion gate; the
    # rollout's own rollout_drift_bound still applies at swap time)
    flywheel_drift_bound: float = 0.25


@dataclass(frozen=True)
class TuneConfig:
    """Ledger-driven autotuner knobs (deepdfa_tpu/tune/, docs/tuning.md).

    `enabled` only controls whether consumers CONSULT tuned.json at
    warmup — the search itself runs offline via `deepdfa-tpu tune`,
    never in the request path. Default OFF: the default path stays
    byte-identical and warms exactly the hand-picked layouts."""

    # consult tuned.json at warmup: kernel block sizes, serve warmup
    # ladder rungs, data.seq_buckets edges — each falls back to its
    # hand-picked default LOUDLY when the hardware key doesn't match
    enabled: bool = False
    # tuned.json path; empty = <storage>/tuned.json
    path: str | None = None
    # ladder budgets: the rung/edge count cap (each rung is one AOT
    # compile, so this IS the compile budget's structural half) ...
    max_rungs: int = 6
    max_seq_buckets: int = 6
    # ... and the compile-seconds half: candidate compiles stop (and
    # ladder lengths shrink) once the measured compile time spent
    # crosses this; 0 = uncapped
    compile_budget_s: float = 120.0
    # interleaved timing reps per kernel candidate (best window kept)
    reps: int = 3


@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh + the declarative sharding layer's knobs
    (parallel/sharding.py, docs/sharding.md). Axis sizes of 1 collapse;
    -1 = all remaining."""

    dp: int = -1  # data parallel (graph batches / example batches)
    tp: int = 1  # tensor parallel (transformer heads / mlp)
    sp: int = 1  # sequence parallel (ring attention)
    pp: int = 1  # pipeline parallel (encoder layer stages, GPipe schedule)
    ep: int = 1  # expert parallel (MoE experts, all_to_all dispatch)
    # fsdp: weight-sharding axis for the path-pattern sharding maps
    # (SNIPPETS-style `tp`/`fsdp` rules); consumed by the GSPMD serve
    # path and any `rules` below — the shard_map train steps keep their
    # documented per-axis layouts
    fsdp: int = 1
    # LOGICAL data shards: the fixed leading-axis layout of every packed
    # batch. 0 = the mesh's dp size (the historical one-shard-per-device
    # layout). Elastic runs pin this (e.g. 8) and pick dp from its
    # divisors — every topology then consumes identical batches and the
    # GGNN step-loss trajectory is bit-identical across dp
    # (parallel/sharding.py, tests/test_sharding.py)
    num_shards: int = 0
    # extra sharding-map rules prepended to the family defaults:
    # "pattern=axes" with `/`-joined param-path globs, e.g.
    # "*/embedding=-,fsdp" (parallel/sharding.py:parse_rules)
    rules: tuple[str, ...] = ()


@dataclass(frozen=True)
class TrainConfig:
    max_epochs: int = 25
    eval_every_epochs: int = 1
    checkpoint_every_epochs: int = 25
    # keep only the newest k epoch checkpoints (the `best` copy is always
    # kept); 0 = unbounded, the historical behaviour
    checkpoint_keep_last: int = 0
    monitor: str = "val_loss"  # checkpoint-selection metric
    monitor_mode: str = "min"
    seed: int = 1
    pos_weight: float | None = None  # None = derived from train labels
    log_every_steps: int = 50
    # feature-identity dropout (train-time augmentation, beyond the
    # reference): with this probability per node, known abstract-dataflow
    # buckets are mapped to UNKNOWN so decisions also learn to ride the
    # graph structure — improves transfer to bug shapes whose defs hash
    # outside the train vocabulary (train/loop.py:drop_known_feats)
    feat_unknown_dropout: float = 0.0
    # sanitizer mode (reference runs Lightning detect_anomaly: true,
    # DDFA/configs/config_default.yaml:40): fail fast on NaN/inf in any
    # jitted computation + enable jax's internal invariant checks
    debug_nans: bool = False
    enable_checks: bool = False
    # async input pipeline: batches assembled + device_put by background
    # threads this many steps ahead of the training step (the reference
    # overlaps input work via DataLoader workers, datamodule.py:110-141);
    # 0 disables and iterates inline
    prefetch_batches: int = 2
    # producer threads in the prefetch pipeline: source pulls stay
    # serialized (ordering guarantee) but sharded device_put runs
    # concurrently — raise when H2D placement is a visible slice of
    # host_place_seconds in the epoch records
    prefetch_producers: int = 1
    # bound on the combined trainer's compiled-step cache: one entry per
    # (T, rows, num_graphs) batch signature (sequence bucketing makes
    # several legal per run), evicted least-recently-used beyond this.
    # Must be >= len(data.seq_buckets) or warmup'd signatures would
    # evict each other (CombinedTrainer.warmup raises).
    step_cache_entries: int = 8
    optim: OptimConfig = field(default_factory=OptimConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)


@dataclass(frozen=True)
class Config:
    run_name: str = "default"
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    scan: ScanConfig = field(default_factory=ScanConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    tune: TuneConfig = field(default_factory=TuneConfig)


# ---------------------------------------------------------------------------
# serialization + CLI overrides


def _to_dict(cfg: Any) -> Any:
    if dataclasses.is_dataclass(cfg):
        return {f.name: _to_dict(getattr(cfg, f.name)) for f in dataclasses.fields(cfg)}
    if isinstance(cfg, tuple):
        return list(cfg)
    return cfg


def to_json(cfg: Config, path: str | Path | None = None) -> str:
    s = json.dumps(_to_dict(cfg), indent=2)
    if path is not None:
        Path(path).write_text(s)
    return s


def _nested_dataclass(cls: type, field_name: str) -> type | None:
    """Resolve a field's dataclass type from annotations (handles the
    string annotations produced by `from __future__ import annotations`)."""
    hints = typing.get_type_hints(cls)
    t = hints.get(field_name)
    return t if dataclasses.is_dataclass(t) else None


#: keys that existed in older saved configs and were since removed;
#: tolerated (dropped with a warning) so old run dirs stay loadable
_REMOVED_KEYS = {"model.use_pallas"}


def from_dict(d: dict[str, Any]) -> Config:
    def resolve(cls, dd, prefix=""):
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(dd) - known
        removed = {k for k in unknown if prefix + k in _REMOVED_KEYS}
        if removed:
            import logging

            logging.getLogger(__name__).warning(
                "ignoring removed config key(s): %s",
                sorted(prefix + k for k in removed),
            )
            unknown -= removed
            dd = {k: v for k, v in dd.items() if k not in removed}
        if unknown:
            raise KeyError(
                f"unknown config key(s): {sorted(prefix + k for k in unknown)}"
            )
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name not in dd:
                continue
            v = dd[f.name]
            nested = _nested_dataclass(cls, f.name)
            if nested is not None and isinstance(v, dict):
                v = resolve(nested, v, prefix=f"{prefix}{f.name}.")
            elif isinstance(v, list):
                v = tuple(v)
            kwargs[f.name] = v
        return cls(**kwargs)

    return resolve(Config, d)


def apply_overrides(cfg: Config, overrides: list[str]) -> Config:
    """Apply `a.b.c=value` dotted overrides (values parsed as JSON or str)."""
    d = _to_dict(cfg)
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"override must be key=value, got {ov!r}")
        key, _, raw = ov.partition("=")
        try:
            val = json.loads(raw)
            parsed_json = True
        except json.JSONDecodeError:
            val = raw
            parsed_json = False
        node = d
        parts = key.split(".")
        for p in parts[:-1]:
            if not isinstance(node, dict) or p not in node:
                raise KeyError(f"unknown config key: {key}")
            node = node[p]
        if not isinstance(node, dict) or parts[-1] not in node:
            raise KeyError(f"unknown config key: {key}")
        old = node[parts[-1]]
        if isinstance(old, dict):
            if not isinstance(val, dict):
                raise TypeError(
                    f"override {key}={raw!r}: {key} is a config section; "
                    f"override its fields individually or pass a JSON object"
                )
            # merge into the section instead of replacing it wholesale,
            # so unspecified sibling fields keep their configured values
            node[parts[-1]] = {**old, **val}
            continue
        if old is None and not parsed_json:
            raise TypeError(
                f"override {key}={raw!r} is not valid JSON; quote strings "
                f'explicitly (e.g. {key}=\'"text"\')'
            )
        if (
            old is not None
            and val is not None
            and isinstance(val, bool) != isinstance(old, bool)
        ):
            raise TypeError(
                f"override {key}={raw!r}: expected {type(old).__name__}, "
                f"got {type(val).__name__}"
            )
        if old is not None and val is not None and not isinstance(val, type(old)):
            # bool is an int subclass: require exact match there; allow
            # int -> float widening
            if isinstance(old, float) and isinstance(val, int) and not isinstance(val, bool):
                val = float(val)
            else:
                raise TypeError(
                    f"override {key}={raw!r}: expected {type(old).__name__}, "
                    f"got {type(val).__name__}"
                )
        node[parts[-1]] = val
    return from_dict(d)


def load(path: str | Path) -> Config:
    return from_dict(json.loads(Path(path).read_text()))


#: relation count each gtype produces (pipeline.extract_graph)
GTYPE_ETYPES = {"cfg": 1, "pdg": 1, "cfg+dep": 3}


def validate(cfg: Config) -> None:
    """Cross-field consistency checks (raise early, not mid-train).

    The one cross-cutting invariant today: the GGNN's relation count must
    match the edge-relation set the frontend extracted — a typed store fed
    to a single-relation model (or vice versa) would silently mis-route
    messages (the model also guards at batch level; this catches it at
    config load)."""
    want = GTYPE_ETYPES.get(cfg.data.gtype)
    if want is None:
        raise ValueError(f"unknown data.gtype {cfg.data.gtype!r}")
    if cfg.model.n_etypes != want:
        raise ValueError(
            f"model.n_etypes={cfg.model.n_etypes} does not match "
            f"data.gtype={cfg.data.gtype!r} (needs n_etypes={want})"
        )


def apply_sanitizers(cfg: Config) -> None:
    """Enable jax's NaN/invariant sanitizers per train config.

    The TPU-native analog of the reference's autograd anomaly mode
    (Lightning `detect_anomaly: true`, DDFA/configs/config_default.yaml:40):
    `train.debug_nans=true` makes any NaN/inf produced under jit raise
    immediately with the offending primitive; `train.enable_checks=true`
    turns on jax's internal invariant checking."""
    import jax

    if cfg.train.debug_nans:
        jax.config.update("jax_debug_nans", True)
    if cfg.train.enable_checks:
        jax.config.update("jax_enable_checks", True)
