"""Storage layout and path helpers.

Replaces the reference's path zoo (DDFA/sastvd/__init__.py:42-88:
storage_dir/external_dir/processed_dir/cache_dir + SINGSTORAGE env redirect)
with one rooted, env-overridable layout:

    <root>/
      raw/<dataset>/        immutable inputs (csv, source files)
      cpg/<dataset>/        extracted CPG-lite json shards
      processed/<dataset>/  feature tables, vocab files, graph shards
      cache/<dataset>/      recomputable caches
      runs/<run-name>/      checkpoints, logs, metrics
"""

from __future__ import annotations

import os
from pathlib import Path

_ENV_VAR = "DEEPDFA_TPU_STORAGE"


def storage_root() -> Path:
    """Root of all on-disk artifacts. Override with DEEPDFA_TPU_STORAGE."""
    root = os.environ.get(_ENV_VAR)
    if root:
        return Path(root)
    return Path(__file__).resolve().parents[2] / "storage"


def _sub(kind: str, dataset: str | None = None) -> Path:
    p = storage_root() / kind
    if dataset is not None:
        p = p / dataset
    p.mkdir(parents=True, exist_ok=True)
    return p


def raw_dir(dataset: str | None = None) -> Path:
    return _sub("raw", dataset)


def cpg_dir(dataset: str | None = None) -> Path:
    return _sub("cpg", dataset)


def processed_dir(dataset: str | None = None) -> Path:
    return _sub("processed", dataset)


def cache_dir(dataset: str | None = None) -> Path:
    return _sub("cache", dataset)


def runs_dir(run_name: str | None = None) -> Path:
    return _sub("runs", run_name)
