"""Deterministic fault injection + test doubles for the resilience
runtime (train/resilience.py). Not imported by production code paths
unless the DEEPDFA_FAULTS env hook is armed."""

from deepdfa_tpu.testing.faults import (
    ENV_VAR,
    FaultInjector,
    FaultPlan,
    StalledSource,
    corrupt_cache_file,
    injector_from_env,
    parse_plan,
    poison_batch,
    truncate_cache_file,
)

__all__ = [
    "ENV_VAR",
    "FaultInjector",
    "FaultPlan",
    "StalledSource",
    "corrupt_cache_file",
    "injector_from_env",
    "parse_plan",
    "poison_batch",
    "truncate_cache_file",
]
