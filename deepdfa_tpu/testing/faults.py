"""Deterministic fault injection for the resilience runtime.

Every failure mode the runtime claims to survive (docs/resilience.md) is
injectable on purpose, so tier-1 tests and scripts/fault_inject.py can
exercise preemption, NaN batches, stalled producers, and corrupt cache
shards without flaky timing games:

- **SIGTERM at step N** — `FaultPlan(sigterm_at_step=N)`: the wrapped
  batch stream sends SIGTERM to its own process right before handing out
  the Nth batch; the PreemptionHandler flag is set, the loop finishes the
  in-flight step, checkpoints, and raises Preempted.
- **NaN batch at step N** — the Nth batch's float labels are poisoned to
  NaN, driving the loss non-finite so the divergence guard's skip path
  fires (GraphBatch streams; the guard itself is loop-agnostic).
- **stalled producer** — the stream blocks before the Nth batch (for the
  watchdog's input-stage attribution), or use `StalledSource` directly.
- **truncated / corrupt cache shard** — `truncate_cache_file` /
  `corrupt_cache_file` damage a packed-cache entry the way a killed
  writer or bit rot would, for the digest-verify + quarantine path.

Subprocess runs arm injection through the `DEEPDFA_FAULTS` env var, e.g.
``DEEPDFA_FAULTS="sigterm@12"`` or ``"nan@3,nan@4"`` — the CLI train
commands call `injector_from_env()` and wrap their train streams.

Step numbering is 1-based over the whole run (batch k feeds global step
k, counted across epochs). The injector acts when a batch is PULLED from
the source; with `train.prefetch_batches > 0` producers run ahead, so
SIGTERM lands while the consumer is up to that many steps behind — the
checkpoint cursor is exact either way, delivery is just a little early.
Set `train.prefetch_batches=0` when a test needs exact step alignment.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import threading
import time
from pathlib import Path
from typing import Iterable, Iterator

import numpy as np

logger = logging.getLogger(__name__)

ENV_VAR = "DEEPDFA_FAULTS"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """What to inject, keyed on the 1-based global batch/step count."""

    sigterm_at_step: int | None = None
    nan_at_steps: frozenset = frozenset()
    stall_at_step: int | None = None
    stall_seconds: float = 3600.0

    def __bool__(self) -> bool:
        return (
            self.sigterm_at_step is not None
            or bool(self.nan_at_steps)
            or self.stall_at_step is not None
        )


def parse_plan(spec: str) -> FaultPlan:
    """Parse ``"sigterm@12,nan@3,nan@4,stall@5"`` into a FaultPlan."""
    sigterm = stall = None
    nans: set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, at = part.partition("@")
        if not at:
            raise ValueError(f"fault {part!r}: expected kind@step")
        step = int(at)
        if kind == "sigterm":
            sigterm = step
        elif kind == "nan":
            nans.add(step)
        elif kind == "stall":
            stall = step
        else:
            raise ValueError(
                f"unknown fault kind {kind!r} (known: sigterm, nan, stall)"
            )
    return FaultPlan(
        sigterm_at_step=sigterm,
        nan_at_steps=frozenset(nans),
        stall_at_step=stall,
    )


def injector_from_env(env=None) -> "FaultInjector | None":
    """The CLI hook: a FaultInjector when DEEPDFA_FAULTS is set."""
    spec = (env if env is not None else os.environ).get(ENV_VAR, "").strip()
    if not spec:
        return None
    plan = parse_plan(spec)
    logger.warning("fault injection armed: %s", plan)
    return FaultInjector(plan)


def poison_batch(batch):
    """A copy of `batch` whose float label array is all-NaN, so the loss
    goes non-finite and the divergence guard's skip path fires. Defined
    for GraphBatch streams (graph_label is the one float label surface);
    other batch types raise loudly rather than inject nothing."""
    from deepdfa_tpu.graphs.batch import GraphBatch

    if not isinstance(batch, GraphBatch):
        raise TypeError(
            f"nan injection supports GraphBatch streams, got "
            f"{type(batch).__name__}"
        )
    label = np.asarray(batch.graph_label)
    return dataclasses.replace(
        batch, graph_label=np.full_like(label, np.nan)
    )


class FaultInjector:
    """Counts batches pulled across every wrapped stream (epochs
    included) and fires the plan's faults at their 1-based positions."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.delivered = 0
        self._lock = threading.Lock()

    def wrap(self, stream: Iterable) -> "_InjectedStream":
        return _InjectedStream(self, stream)

    def _next_index(self) -> int:
        with self._lock:
            self.delivered += 1
            return self.delivered

    def _apply(self, n: int, batch):
        plan = self.plan
        if plan.stall_at_step == n:
            logger.warning("fault: stalling producer at step %d", n)
            time.sleep(plan.stall_seconds)
        if n in plan.nan_at_steps:
            logger.warning("fault: poisoning batch %d with NaN labels", n)
            batch = poison_batch(batch)
        if plan.sigterm_at_step == n:
            logger.warning("fault: delivering SIGTERM at step %d", n)
            os.kill(os.getpid(), signal.SIGTERM)
        return batch


class _InjectedStream:
    """Iterable wrapper that preserves the source's `source_stage` hint
    (cli _BatchStream) so pipeline stage attribution is unchanged."""

    def __init__(self, injector: FaultInjector, inner: Iterable):
        self._injector = injector
        self._inner = inner
        stage = getattr(inner, "source_stage", None)
        if stage is not None:
            self.source_stage = stage

    def __iter__(self) -> Iterator:
        for batch in self._inner:
            n = self._injector._next_index()
            yield self._injector._apply(n, batch)


class StalledSource:
    """An iterable that yields `n_good` items then blocks (until
    `release()` or forever) — the watchdog's input-stall scenario in
    isolation."""

    def __init__(self, items: Iterable, n_good: int, stall_seconds: float = 3600.0):
        self._items = list(items)
        self.n_good = int(n_good)
        self.stall_seconds = float(stall_seconds)
        self._release = threading.Event()

    def release(self) -> None:
        self._release.set()

    def __iter__(self) -> Iterator:
        for i, item in enumerate(self._items):
            if i == self.n_good:
                self._release.wait(self.stall_seconds)
            yield item


# ---------------------------------------------------------------------------
# packed-cache damage (the killed-writer / bit-rot scenarios)


def _pick_entry_file(cache_root: str | Path, key: str | None) -> Path:
    from deepdfa_tpu.data import packed_cache as pc

    cache = pc.PackedBatchCache(cache_root)
    keys = [key] if key is not None else cache.keys()
    if not keys:
        raise FileNotFoundError(f"no complete cache entries under {cache_root}")
    files = sorted(cache.entry_dir(keys[-1]).glob("*.npy"))
    if not files:
        raise FileNotFoundError(f"entry {keys[-1]} has no npy files")
    # drop the entry's verified latch so an in-process replay re-hashes
    # (subprocess scenarios get this for free — fresh process, empty set)
    pc._VERIFIED.discard(str(files[0].parent))
    return files[0]


def truncate_cache_file(
    cache_root: str | Path, key: str | None = None, frac: float = 0.5
) -> Path:
    """Truncate one .npy of a complete entry to `frac` of its size — the
    on-disk state a writer killed mid-np.save (or a post-rename power
    loss) leaves behind. Returns the damaged path."""
    path = _pick_entry_file(cache_root, key)
    size = path.stat().st_size
    with path.open("rb+") as f:
        f.truncate(max(1, int(size * frac)))
    return path


def corrupt_cache_file(cache_root: str | Path, key: str | None = None) -> Path:
    """Flip bytes in the middle of one .npy WITHOUT changing its size —
    corruption only the content digest can catch. Returns the path."""
    path = _pick_entry_file(cache_root, key)
    data = bytearray(path.read_bytes())
    mid = len(data) // 2
    for i in range(mid, min(mid + 16, len(data))):
        data[i] ^= 0xFF
    path.write_bytes(bytes(data))
    return path
