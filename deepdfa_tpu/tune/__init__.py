"""Ledger-driven autotuner (docs/tuning.md, ROADMAP item 3).

The PR-10 efficiency ledger measures per-signature FLOP/s and roofline
position but nothing consumed it: every tile size in `nn/ggnn_kernel.py`,
every pow2 rung in the serve warmup ladders, and every
`data.seq_buckets` edge was hand-picked. This package closes the loop
with MEASURED search:

- `tune.kernel`   — enumerate legal (block_n, block_e, scatter, accum)
  kernel candidates per GGNN signature (divisibility + VMEM bound
  pruned BEFORE compile), compile-and-time each through the existing
  AOT path, assert the PR-8 numerics contract on every candidate, and
  pick by measured step time.
- `tune.ladder`   — fit serve warmup-ladder rungs and seq-bucket edges
  to the OBSERVED size distribution (replayed from serve/fleet logs or
  a training manifest), minimizing expected padded compute under a
  rung-count / compile-seconds budget, instead of blind pow2.
- `tune.cache`    — persist winning layouts in a schema-validated
  `tuned.json` keyed by hardware generation; consumers fall back to
  defaults LOUDLY on any mismatch.
- `tune.driver`   — the `deepdfa-tpu tune` CLI orchestration + the
  tier-1 `--smoke` acceptance drive.

Everything is default OFF (`cfg.tune.enabled`): the default path is
byte-identical and tuning only ever runs offline, never in the request
path.
"""

from deepdfa_tpu.tune.cache import (  # noqa: F401
    hardware_key,
    load_tuned,
    record_for_config,
    save_tuned,
    validate_tuned,
)
from deepdfa_tpu.tune.kernel import (  # noqa: F401
    Candidate,
    enumerate_candidates,
    numerics_verdict,
    search_kernel,
)
from deepdfa_tpu.tune.ladder import (  # noqa: F401
    fit_rungs,
    fit_serve_ladder,
    fit_seq_buckets,
    padding_waste,
)
