"""Measured kernel-layout search for the Pallas GGNN step
(docs/tuning.md; the search half of ROADMAP item 3).

`nn/ggnn_kernel.py` hand-pins 256-node/512-edge tiles at the flagship
shape. This module replaces the hand-pin with measurement:

1. **Enumerate** legal (block_n, block_e, scatter, accum, unroll)
   candidates per GGNN batch signature. Legality is checked BEFORE any
   compile: divisibility (the kernel's reshape contract), the TPU
   sublane alignment (f32 tiles are 8 x 128, docs/ggnn_kernel.md), and
   a VMEM working-set estimate against the ~16 MB/core budget — for
   `unroll="fused"` the estimate carries the x n_steps state-chain
   residency term (ping-ponged to 2 resident tables + the full output
   buffer; `nn/ggnn_kernel.py:fused_residency_bytes`). An illegal
   layout costs a pruned-row entry naming its reason, never a Mosaic
   error.
2. **Compile-and-time** each survivor through the SAME AOT
   lower()->compile() path the serve executors use, with interleaved
   best-of-reps timing (candidates alternate within each rep round so a
   drifting box biases nobody; the best window is kept — the PR-4/PR-10
   overhead-measurement rule).
3. **Assert the PR-8 numerics contract on every candidate** — fold/fp32
   must be BIT-IDENTICAL to the jitted lax path (per-step AND fused
   unroll: same math, same order), mxu within 1e-5, bf16 within 5e-2,
   int8 within its admission drift bound — and record the verdict on
   the candidate row. A candidate outside its tolerance can never win,
   no matter how fast.
4. **Pick by measured step time**, with `mfu_vs_measured_ceiling`
   recorded against the docs/roofline.md measured matmul ceiling so the
   winner's roofline position rides in tuned.json next to its time.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Sequence

import numpy as np

#: VMEM per TPU core (~16 MB; /opt/skills guide + docs/ggnn_kernel.md);
#: the estimate below prunes layouts whose working set cannot fit
DEFAULT_VMEM_LIMIT_BYTES = 16 * 2**20

#: the PR-8 numerics contract (docs/ggnn_kernel.md): max relative error
#: vs the jitted lax path, keyed by (scatter, accum). fold/fp32 is
#: bit-identical BY CONSTRUCTION (the sequential left fold is exactly
#: XLA's sorted segment_sum update order), so its tolerance is zero.
#: accum="int8" rung: mirrors nn/ggnn_kernel.py:INT8_DRIFT_BOUND (the
#: single declaration next to the kernel; pinned equal in tests so this
#: numpy-light module never imports the jax-heavy nn layer)
INT8_TOLERANCE = 5e-2

DEFAULT_TOLERANCES: dict[tuple[str, str], float] = {
    ("fold", "fp32"): 0.0,
    ("mxu", "fp32"): 1e-5,
    ("fold", "bf16"): 5e-2,
    ("mxu", "bf16"): 5e-2,
    ("fold", "int8"): INT8_TOLERANCE,
    ("mxu", "int8"): INT8_TOLERANCE,
}

#: default block-size grids (multiples of the f32 sublane, bracketing
#: the PR-8 hand-picked 256/512 tiles from both sides)
DEFAULT_BLOCK_NODES = (64, 128, 256, 512)
DEFAULT_BLOCK_EDGES = (128, 256, 512, 1024)

#: the PR-16 candidate axes: message-side dtype policy and step-loop
#: placement (docs/ggnn_kernel.md), enumerated jointly with the tiles
DEFAULT_ACCUMS = ("fp32", "bf16", "int8")
DEFAULT_UNROLLS = ("per_step", "fused")


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One kernel layout under consideration (hashable, JSON-able)."""

    block_n: int
    block_e: int
    scatter: str = "fold"  # fold | mxu
    accum: str = "fp32"  # fp32 | bf16 | int8
    unroll: str = "per_step"  # per_step | fused

    @property
    def label(self) -> str:
        # the "-fused" suffix appears ONLY off the default so every
        # pre-PR-16 label (committed TUNED_r* rows, gate references,
        # diag renders) keeps meaning the layout it always named
        suffix = "" if self.unroll == "per_step" else f"-{self.unroll}"
        return (
            f"bn{self.block_n}-be{self.block_e}-"
            f"{self.scatter}-{self.accum}{suffix}"
        )

    def as_dict(self) -> dict:
        return {
            "candidate": self.label,
            "block_n": self.block_n,
            "block_e": self.block_e,
            "scatter": self.scatter,
            "accum": self.accum,
            "unroll": self.unroll,
        }


def estimate_vmem_bytes(
    n: int, e: int, d: int, cand: Candidate, n_etypes: int = 1,
    n_steps: int = 1,
) -> int:
    """Working-set estimate for one fused-step grid program, mirroring
    the BlockSpecs in `nn/ggnn_kernel.py:_fwd_call` (and `_fused_call`
    for ``unroll="fused"``): the full message table + edge index/weight
    arrays are staged whole, per-block state and temporaries ride on
    top. Deliberately a slight over-estimate (double-buffering headroom
    is the compiler's business, not ours)."""
    msg_bytes = {"bf16": 2, "int8": 1}.get(cand.accum, 4)
    total = n * d * msg_bytes  # hm message table (full)
    total += 3 * cand.block_n * d * 4  # h block + hout + aout blocks
    total += 2 * e * 4  # src2 + dst2 (full [n_eb, block_e])
    total += n_etypes * e * 4  # per-type masked weights
    total += n_etypes * d * d * msg_bytes + n_etypes * d * 4  # wm + bm
    total += 2 * d * 3 * d * 4 + 2 * 3 * d * 4  # GRU weights + biases
    total += 2 * cand.block_e * d * 4  # gather + message temporaries
    if cand.accum == "int8":
        # dequant scale vectors (per-row + per-channel)
        total += n * 4 + n_etypes * d * 4
    if cand.scatter == "mxu":
        total += cand.block_e * cand.block_n * 4  # the one-hot block
    if getattr(cand, "unroll", "per_step") == "fused":
        # the x n_steps residency term: the whole-unroll kernel keeps
        # the inter-step state chain in VMEM. The per-step message
        # table is NOT staged (messages read the resident chain); in
        # its place sit feat (staged once, f32), the ping-pong chain
        # (min(n_steps + 1, 2) resident tables — each step reads one
        # parity and writes the other), and the constant-index full
        # output buffer. int8 re-quantizes in-kernel into a shadow
        # table (its scales are already counted above).
        total -= n * d * msg_bytes
        total += n * d * 4  # feat, staged once
        resident_states = min(int(n_steps) + 1, 2)
        total += (resident_states + 1) * n * d * 4
        if cand.accum == "int8":
            total += n * d  # quantized shadow of the resident table
    return int(total)


def enumerate_candidates(
    n: int,
    e: int,
    d: int,
    n_etypes: int = 1,
    block_nodes: Sequence[int] = DEFAULT_BLOCK_NODES,
    block_edges: Sequence[int] = DEFAULT_BLOCK_EDGES,
    scatters: Sequence[str] = ("fold", "mxu"),
    accums: Sequence[str] = DEFAULT_ACCUMS,
    unrolls: Sequence[str] = DEFAULT_UNROLLS,
    n_steps: int = 1,
    vmem_limit_bytes: int = DEFAULT_VMEM_LIMIT_BYTES,
) -> tuple[list[Candidate], list[dict]]:
    """(survivors, pruned) for one signature. Every pruned layout keeps
    a row naming its reason, so the search record shows what was ruled
    out and why — the divisibility + VMEM bound applied BEFORE compile.
    `n_steps` feeds the fused unroll's state-chain residency term, so a
    fused candidate that cannot keep the chain resident is pruned here
    with the residency named, never compiled."""
    survivors: list[Candidate] = []
    pruned: list[dict] = []
    seen: set[Candidate] = set()
    for bn in block_nodes:
        for be in block_edges:
            for scatter in scatters:
                for accum in accums:
                    for unroll in unrolls:
                        cand = Candidate(
                            int(bn), int(be), scatter, accum, unroll
                        )
                        if cand in seen:
                            continue
                        seen.add(cand)
                        reason = None
                        if n % cand.block_n:
                            reason = (
                                f"block_n {cand.block_n} does not "
                                f"divide node budget {n}"
                            )
                        elif e % cand.block_e:
                            reason = (
                                f"block_e {cand.block_e} does not "
                                f"divide edge budget {e}"
                            )
                        elif cand.block_n % 8 or cand.block_e % 8:
                            # f32 sublane alignment (8 x 128 tiles)
                            reason = (
                                f"blocks ({cand.block_n}, "
                                f"{cand.block_e}) not sublane-aligned "
                                f"(x8)"
                            )
                        else:
                            vmem = estimate_vmem_bytes(
                                n, e, d, cand, n_etypes, n_steps
                            )
                            if vmem > vmem_limit_bytes:
                                reason = (
                                    f"VMEM estimate {vmem} > limit "
                                    f"{vmem_limit_bytes}"
                                )
                                if cand.unroll == "fused":
                                    reason = (
                                        "fused unroll residency: "
                                        + reason
                                        + f" (state chain resident "
                                        f"across {n_steps} steps)"
                                    )
                        if reason is None:
                            survivors.append(cand)
                        else:
                            pruned.append(
                                {**cand.as_dict(), "reason": reason}
                            )
    return survivors, pruned


def numerics_verdict(
    got: np.ndarray,
    ref: np.ndarray,
    cand: Candidate,
    tolerances: dict[tuple[str, str], float] | None = None,
) -> dict:
    """The per-candidate numerics-contract verdict persisted on every
    tuned.json candidate row: relative max error vs the jitted lax
    reference against the candidate's (scatter, accum) tolerance."""
    tol_table = tolerances if tolerances is not None else DEFAULT_TOLERANCES
    tol = tol_table.get((cand.scatter, cand.accum), 0.0)
    got = np.asarray(got, np.float32)
    ref = np.asarray(ref, np.float32)
    rel = float(
        np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    )
    return {
        "ok": bool(rel <= tol),
        "rel_err": round(rel, 10),
        "tolerance": tol,
        "mode": f"{cand.scatter}/{cand.accum}",
    }


def _workload(n: int, e: int, d: int, seed: int = 0):
    """A realistic padded single-graph batch at the given budgets
    (CFG-degree dst-sorted edges with a padding tail — the
    scripts/bench_scatter.py shape family)."""
    import jax.numpy as jnp

    from deepdfa_tpu.graphs.batch import GraphBatch

    rng = np.random.default_rng(seed)
    n_real = int(min(e * 0.9, n * 2.0))
    dst = np.sort(rng.integers(0, n - 1, n_real)).astype(np.int32)
    src = rng.integers(0, n - 1, n_real).astype(np.int32)
    edge_src = np.full((e,), n - 1, np.int32)
    edge_dst = np.full((e,), n - 1, np.int32)
    edge_src[:n_real] = src
    edge_dst[:n_real] = dst
    edge_mask = np.zeros((e,), bool)
    edge_mask[:n_real] = True
    feat = rng.standard_normal((n, d)).astype(np.float32)
    batch = GraphBatch(
        node_feats=jnp.zeros((n, 4), jnp.int32),
        node_vuln=jnp.zeros((n,), jnp.int32),
        node_graph=jnp.zeros((n,), jnp.int32),
        node_mask=jnp.ones((n,), bool),
        edge_src=jnp.asarray(edge_src),
        edge_dst=jnp.asarray(edge_dst),
        edge_mask=jnp.asarray(edge_mask),
        graph_label=jnp.ones((1,), jnp.float32),
        graph_mask=jnp.ones((1,), bool),
        graph_ids=jnp.zeros((1,), jnp.int32),
        num_graphs=1,
    )
    return batch, jnp.asarray(feat)


def search_kernel(
    signatures: Sequence[tuple[int, int, int]],
    n_steps: int = 5,
    n_etypes: int = 1,
    candidates: Sequence[Candidate] | None = None,
    reps: int = 3,
    interpret: str | bool = "auto",
    compile_budget_s: float = 0.0,
    ceiling_flops_per_sec: float = 0.0,
    tolerances: dict[tuple[str, str], float] | None = None,
    **enumerate_kw,
) -> dict:
    """Measured search over kernel layouts; {"NxExD": record} per
    signature. Each record carries the lax reference time, every
    candidate row (compile seconds, best-of-reps step time, numerics
    verdict, VMEM estimate), the pruned rows, and the winner."""
    import jax

    from deepdfa_tpu.nn import GatedGraphConv

    out: dict[str, dict] = {}
    budget_left = float(compile_budget_s) if compile_budget_s else None
    for n, e, d in signatures:
        sig = f"{n}x{e}x{d}"
        batch, feat = _workload(n, e, d)
        lax_conv = GatedGraphConv(
            out_features=d, n_steps=n_steps, n_etypes=n_etypes
        )
        params = lax_conv.init(jax.random.key(0), batch, feat)
        lax_jit = jax.jit(
            lambda p, b, f, _c=lax_conv: _c.apply(p, b, f)
        )
        t0 = time.perf_counter()
        lax_compiled = lax_jit.lower(params, batch, feat).compile()
        lax_compile_s = time.perf_counter() - t0
        ref = np.asarray(jax.device_get(lax_compiled(params, batch, feat)))
        from deepdfa_tpu.obs.ledger import read_cost_analysis

        try:
            flops = read_cost_analysis(lax_compiled)["flops"]
        except Exception:
            flops = 0.0

        if candidates is None:
            cands, pruned = enumerate_candidates(
                n, e, d, n_etypes=n_etypes, n_steps=n_steps,
                **enumerate_kw
            )
        else:
            cands, pruned = list(candidates), []

        rows: list[dict] = []
        runnable: list[tuple[Candidate, object, dict]] = []
        for cand in cands:
            if budget_left is not None and budget_left <= 0:
                rows.append({
                    **cand.as_dict(),
                    "skipped": "compile-seconds budget exhausted",
                })
                continue
            conv = GatedGraphConv(
                out_features=d, n_steps=n_steps, n_etypes=n_etypes,
                use_kernel=True,
                kernel_scatter=cand.scatter,
                kernel_accum=cand.accum,
                kernel_unroll=cand.unroll,
                kernel_block_nodes=cand.block_n,
                kernel_block_edges=cand.block_e,
                kernel_interpret=interpret,
            )
            fn = jax.jit(lambda p, b, f, _c=conv: _c.apply(p, b, f))
            row = {
                **cand.as_dict(),
                "vmem_bytes_est": estimate_vmem_bytes(
                    n, e, d, cand, n_etypes, n_steps
                ),
            }
            t0 = time.perf_counter()
            try:
                compiled = fn.lower(params, batch, feat).compile()
                got = np.asarray(
                    jax.device_get(compiled(params, batch, feat))
                )
            except Exception as exc:  # a lowering gap costs one row,
                # never the search (the bench_scatter isolation rule)
                # — but its wall time still charges the compile budget
                # (a slowly-FAILING candidate spends the same seconds)
                if budget_left is not None:
                    budget_left -= time.perf_counter() - t0
                row["error"] = f"{type(exc).__name__}: {exc}"[:200]
                rows.append(row)
                continue
            dt = time.perf_counter() - t0
            if budget_left is not None:
                budget_left -= dt
            row["compile_seconds"] = round(dt, 3)
            # module attribute on purpose: tests monkeypatch the verdict
            # to prove a broken candidate can never win
            row["numerics"] = numerics_verdict(
                got, ref, cand, tolerances=tolerances
            )
            rows.append(row)
            runnable.append((cand, compiled, row))

        # interleaved best-of-reps: round-robin across candidates (+ the
        # lax reference) per rep so box drift hits everyone equally; the
        # MIN window survives (deterministic cost does, stalls don't)
        best: dict[str, float] = {}
        lax_best = None
        for _ in range(max(1, int(reps))):
            t0 = time.perf_counter()
            np.asarray(jax.device_get(lax_compiled(params, batch, feat)))
            dt = time.perf_counter() - t0
            lax_best = dt if lax_best is None else min(lax_best, dt)
            for cand, compiled, _row in runnable:
                t0 = time.perf_counter()
                np.asarray(jax.device_get(compiled(params, batch, feat)))
                dt = time.perf_counter() - t0
                prev = best.get(cand.label)
                best[cand.label] = (
                    dt if prev is None else min(prev, dt)
                )

        for cand, _compiled, row in runnable:
            step_s = best[cand.label] / max(1, n_steps)
            row["step_us"] = round(step_s * 1e6, 2)
            if flops > 0 and ceiling_flops_per_sec > 0:
                row["mfu_vs_measured_ceiling"] = round(
                    (flops / max(1, n_steps)) / step_s
                    / ceiling_flops_per_sec,
                    6,
                )

        ok_rows = [
            r for r in rows
            if r.get("numerics", {}).get("ok") and "step_us" in r
        ]
        winner = (
            min(ok_rows, key=lambda r: r["step_us"]) if ok_rows else None
        )
        rec: dict = {
            "signature": sig,
            "n_steps": int(n_steps),
            "n_etypes": int(n_etypes),
            "lax_step_us": (
                round(lax_best / max(1, n_steps) * 1e6, 2)
                if lax_best is not None else None
            ),
            "lax_compile_seconds": round(lax_compile_s, 3),
            "flops_per_step": (
                round(flops / max(1, n_steps), 1) if flops else None
            ),
            "candidates": rows,
            "pruned": pruned,
            "winner": winner["candidate"] if winner else None,
        }
        if winner:
            rec["winner_step_us"] = winner["step_us"]
            rec["winner_block_n"] = winner["block_n"]
            rec["winner_block_e"] = winner["block_e"]
            rec["winner_scatter"] = winner["scatter"]
            rec["winner_accum"] = winner["accum"]
            rec["winner_unroll"] = winner.get("unroll", "per_step")
            if "mfu_vs_measured_ceiling" in winner:
                rec["winner_mfu_vs_measured_ceiling"] = winner[
                    "mfu_vs_measured_ceiling"
                ]
        else:
            rec["error"] = (
                "no candidate passed the numerics contract — defaults "
                "stay in force for this signature"
            )
        out[sig] = rec
    return out
