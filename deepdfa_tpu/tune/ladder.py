"""Measured ladder search: fit batch-ladder rungs to the observed size
distribution (docs/tuning.md).

The serve executors AOT-warm a pow2 rung ladder (1, 2, 4, ...,
max_batch_graphs) and every executed chunk pads to the smallest rung
>= its row count. Pow2 is a fine prior with no traffic evidence, but it
has a blind spot the `serve/ladder_waste` gauge makes visible: a
request stream whose chunk sizes all land just above a rung (size 5
against rungs {4, 8}) pads ~2x every batch, forever. The same shape
problem exists for `data.seq_buckets` (rows pad to the smallest bucket
edge >= their token length).

This module fits the rungs to the distribution actually observed —
replayed from serve_log.jsonl / fleet_log.jsonl request entries, or a
training-manifest length list — by exact dynamic programming:

  minimize   sum_i w_i * rung(s_i)        (expected padded compute)
  subject to |rungs| <= max_rungs          (each rung is one AOT compile,
                                            so the rung count IS the
                                            compile-seconds budget)
             max(sizes) <= max(rungs) = capacity

Only observed sizes (plus the forced capacity) can be optimal rung
positions, so the candidate set is the distinct-size list and the DP is
O(max_rungs * k^2) over k distinct sizes — exact, not a heuristic.
`padding_waste` is the objective read back out, directly comparable to
the pow2 baseline (`pow2_rungs`) and to the `padding_waste` field the
input pipeline already reports for text batches.
"""

from __future__ import annotations

import json
import logging
import math
from collections import Counter
from pathlib import Path
from typing import Sequence

from deepdfa_tpu.serve.batcher import _pow2_sizes

logger = logging.getLogger(__name__)


def pow2_rungs(capacity: int) -> tuple[int, ...]:
    """The hand-picked baseline: the exact ladder the serve executors
    warm today (1, 2, 4, ..., capacity; serve/batcher.py)."""
    return _pow2_sizes(int(capacity))


def rung_for(size: int, rungs: Sequence[int]) -> int:
    """The smallest rung >= size (the executor's `_size_for` rule)."""
    for r in rungs:
        if r >= size:
            return int(r)
    return int(rungs[-1])


def padding_waste(
    sizes: Sequence[int],
    rungs: Sequence[int],
    weights: Sequence[float] | None = None,
) -> float:
    """Fraction of padded compute under a rung assignment:
    1 - sum(w*s) / sum(w*rung(s)). 0 = every batch lands exactly on a
    rung; 0.5 = half the executed rows/tokens are padding."""
    rungs = sorted(int(r) for r in rungs)
    real = 0.0
    padded = 0.0
    for i, s in enumerate(sizes):
        w = float(weights[i]) if weights is not None else 1.0
        real += w * s
        padded += w * rung_for(int(s), rungs)
    if padded <= 0:
        return 0.0
    return 1.0 - real / padded


def fit_rungs(
    sizes: Sequence[int],
    max_rungs: int,
    capacity: int,
    weights: Sequence[float] | None = None,
) -> tuple[int, ...]:
    """Exact min-expected-padded-compute rung set (ascending, capacity
    always the top rung so any legal chunk still fits a warmed rung).

    `weights` weight each observation (default 1 each — a batch is a
    batch); sizes above capacity raise (they could never have executed).
    """
    capacity = int(capacity)
    max_rungs = max(1, int(max_rungs))
    agg: dict[int, float] = {}
    for i, s in enumerate(sizes):
        s = int(s)
        if s < 1:
            continue
        if s > capacity:
            raise ValueError(
                f"observed size {s} exceeds capacity {capacity} — the "
                f"replayed log belongs to a larger-capacity deployment"
            )
        agg[s] = agg.get(s, 0.0) + (
            float(weights[i]) if weights is not None else 1.0
        )
    if not agg:
        return (capacity,)
    cand = sorted(set(agg) | {capacity})
    if len(cand) <= max_rungs:
        return tuple(cand)

    m = len(cand)
    wsum = [0.0] * (m + 1)  # prefix of weights, aligned to cand order
    for i, c in enumerate(cand):
        wsum[i + 1] = wsum[i] + agg.get(c, 0.0)

    def seg_cost(j: int, i: int) -> float:
        # candidates (j, i] all pad to rung cand[i]
        return cand[i] * (wsum[i + 1] - wsum[j + 1])

    inf = math.inf
    # dp[k][i]: min cost covering cand[0..i] with k rungs, last at cand[i]
    dp = [[inf] * m for _ in range(max_rungs + 1)]
    back = [[-1] * m for _ in range(max_rungs + 1)]
    for i in range(m):
        dp[1][i] = seg_cost(-1, i)
    for k in range(2, max_rungs + 1):
        for i in range(k - 1, m):
            best, arg = inf, -1
            for j in range(k - 2, i):
                c = dp[k - 1][j] + seg_cost(j, i)
                if c < best:
                    best, arg = c, j
            dp[k][i] = best
            back[k][i] = arg
    # the top rung is forced to capacity = cand[m-1]
    k_best = min(
        range(1, max_rungs + 1), key=lambda k: dp[k][m - 1]
    )
    rungs = [cand[m - 1]]
    k, i = k_best, m - 1
    while k > 1:
        i = back[k][i]
        rungs.append(cand[i])
        k -= 1
    return tuple(sorted(rungs))


def max_rungs_for_budget(
    compile_budget_s: float,
    per_compile_s: float,
    hard_max: int,
) -> int:
    """The rung-count the compile-seconds budget affords: each rung is
    one AOT compile, so the budget divided by the measured (or assumed)
    per-rung compile time caps the ladder length underneath the
    configured hard max. Always >= 1 (a ladder needs its capacity rung)."""
    n = int(hard_max)
    if compile_budget_s > 0 and per_compile_s > 0:
        n = min(n, int(compile_budget_s // per_compile_s))
    return max(1, n)


# ---------------------------------------------------------------------------
# observed-distribution replay


def batch_sizes_from_log(path: str | Path) -> list[int]:
    """Executed-chunk sizes replayed from a serve_log.jsonl /
    fleet_log.jsonl request stream.

    Each `{"request": {...}}` entry carries the `batch_size` of the
    batch that scored it, so a batch of size b appears b times — the
    replay divides the request count per size by the size to recover
    the BATCH distribution (the thing the ladder pads)."""
    counts: Counter[int] = Counter()
    path = Path(path)
    with path.open() as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            req = rec.get("request") if isinstance(rec, dict) else None
            if not isinstance(req, dict):
                continue
            b = req.get("batch_size")
            if isinstance(b, int) and not isinstance(b, bool) and b > 0:
                counts[b] += 1
    sizes: list[int] = []
    for b in sorted(counts):
        sizes.extend([b] * max(1, round(counts[b] / b)))
    if not sizes:
        logger.warning(
            "no request entries with batch_size in %s — was the log "
            "written with serve.request_log=true?", path,
        )
    return sizes


def lengths_from_manifest(path: str | Path) -> list[int]:
    """Real token lengths replayed from a training manifest: a JSON
    array of ints, or a JSONL stream whose rows carry one of
    length/tokens/token_length."""
    path = Path(path)
    text = path.read_text()
    stripped = text.lstrip()
    if stripped.startswith("["):
        return [int(x) for x in json.loads(text)]
    out: list[int] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, (int, float)) and not isinstance(row, bool):
            out.append(int(row))
            continue
        if isinstance(row, dict):
            for key in ("length", "tokens", "token_length"):
                v = row.get(key)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out.append(int(v))
                    break
    if not out:
        logger.warning("no lengths found in manifest %s", path)
    return out


# ---------------------------------------------------------------------------
# fit records (what tuned.json persists)


def fit_serve_ladder(
    sizes: Sequence[int],
    capacity: int,
    max_rungs: int,
    compile_budget_s: float = 0.0,
    per_compile_s: float = 0.0,
) -> dict:
    """Fit the serve warmup-ladder rungs to observed chunk sizes; one
    JSON-able record with the pow2 baseline alongside so the win (or
    regression) is always on the record."""
    max_rungs = max_rungs_for_budget(
        compile_budget_s, per_compile_s, max_rungs
    )
    rungs = fit_rungs(sizes, max_rungs, capacity)
    baseline = pow2_rungs(capacity)
    fitted_waste = padding_waste(sizes, rungs)
    baseline_waste = padding_waste(sizes, baseline)
    out = {
        "rungs": [int(r) for r in rungs],
        "pow2_rungs": [int(r) for r in baseline],
        "padding_waste": round(fitted_waste, 6),
        "pow2_padding_waste": round(baseline_waste, 6),
        "samples": len(sizes),
        "capacity": int(capacity),
        "max_rungs": int(max_rungs),
    }
    if fitted_waste > baseline_waste:
        # a tight rung budget CAN lose to pow2 (fewer rungs than the
        # incumbent ladder). The incumbent is already running — a tuned
        # record must never make serving WORSE, so persist the pow2
        # rungs as the layout (the fit-beats-pow2 gate invariant holds
        # by construction) and say so on the record.
        logger.warning(
            "ladder fit (%d rungs, waste %.3f) loses to the pow2 "
            "baseline (waste %.3f) under the rung budget — persisting "
            "the pow2 rungs instead", max_rungs, fitted_waste,
            baseline_waste,
        )
        out["rungs"] = [int(r) for r in baseline]
        out["padding_waste"] = out["pow2_padding_waste"]
        out["fallback_pow2"] = True
    return out


def fit_seq_buckets(
    lengths: Sequence[int],
    max_length: int,
    max_edges: int,
    compile_budget_s: float = 0.0,
    per_compile_s: float = 0.0,
) -> dict:
    """Fit `data.seq_buckets` edges to observed token lengths. The
    largest edge is forced to max_length (the CLI contract: smaller
    cannot hold a full-length row) and edges below 2 are illegal for
    the planner, so observed 0/1-token rows clamp to 2."""
    max_edges = max_rungs_for_budget(
        compile_budget_s, per_compile_s, max_edges
    )
    clamped = [min(max(int(ln), 2), int(max_length)) for ln in lengths]
    edges = fit_rungs(clamped, max_edges, int(max_length))
    baseline = tuple(
        e for e in pow2_rungs(int(max_length)) if e >= 2
    )
    fitted_waste = padding_waste(clamped, edges)
    baseline_waste = padding_waste(clamped, baseline)
    out = {
        "edges": [int(e) for e in edges],
        "pow2_edges": [int(e) for e in baseline],
        "padding_waste": round(fitted_waste, 6),
        "pow2_padding_waste": round(baseline_waste, 6),
        "samples": len(clamped),
        "max_length": int(max_length),
        "max_edges": int(max_edges),
    }
    if fitted_waste > baseline_waste:
        # same never-worse-than-the-incumbent rule as fit_serve_ladder
        logger.warning(
            "seq-bucket fit (%d edges, waste %.3f) loses to the pow2 "
            "baseline (waste %.3f) under the edge budget — persisting "
            "the pow2 edges instead", max_edges, fitted_waste,
            baseline_waste,
        )
        out["edges"] = [int(e) for e in baseline]
        out["padding_waste"] = out["pow2_padding_waste"]
        out["fallback_pow2"] = True
    return out
