"""`deepdfa-tpu tune` orchestration: one offline search pass writes one
hardware-keyed tuned.json record (docs/tuning.md).

Never in the request path: tuning is an OFFLINE command — serving only
ever reads the persisted record at warmup (cfg.tune.enabled), so a
search can run on a scratch box against replayed logs while production
keeps serving the previous layout.

`run_tune_smoke` is the tier-1 acceptance drive (CPU, reduced candidate
set, synthetic skewed distributions): a REAL search end to end — kernel
candidates compiled and timed under the numerics contract, ladder +
seq-bucket fits that must beat the pow2 baseline, a schema-valid
tuned.json on disk.
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path

import numpy as np

from deepdfa_tpu.tune import cache as tune_cache
from deepdfa_tpu.tune import kernel as tune_kernel
from deepdfa_tpu.tune import ladder as tune_ladder

logger = logging.getLogger(__name__)

#: the smoke's reduced search space: tiny budgets (d=32 relaxes the
#: lane rule under the interpreter), a handful of candidates bracketing
#: the auto-picked blocks, fold + one mxu row so both scatter modes
#: carry verdicts, plus one fused-unroll and one int8 row per scatter
#: so every search axis lands a measured, verdict-bearing smoke row
SMOKE_BUDGETS = (256, 512, 32)
SMOKE_CANDIDATES = (
    tune_kernel.Candidate(64, 128),
    tune_kernel.Candidate(64, 512),
    tune_kernel.Candidate(256, 128),
    tune_kernel.Candidate(256, 512),
    tune_kernel.Candidate(256, 512, "mxu"),
    tune_kernel.Candidate(256, 512, "fold", "fp32", "fused"),
    tune_kernel.Candidate(256, 512, "fold", "int8"),
    tune_kernel.Candidate(256, 512, "mxu", "int8"),
)


def _measure_ceiling_flops(smoke: bool) -> float:
    """The measured matmul ceiling the winner's MFU is read against
    (docs/roofline.md); 0.0 when the probe fails — MFU fields are then
    simply absent, never wrong."""
    try:
        from deepdfa_tpu.eval.profiling import measure_matmul_ceiling

        m = measure_matmul_ceiling(
            n=256 if smoke else 1024, chain=2, reps=1
        )
        return float(m["matmul_tflops_measured"]) * 1e12
    except Exception as e:  # the probe must never cost the search
        logger.warning("matmul ceiling probe failed: %s", e)
        return 0.0


def skewed_smoke_sizes(seed: int = 0) -> list[int]:
    """The pow2 blind-spot distribution: almost every observed chunk
    lands just ABOVE a pow2 rung (5 over 4, 9 over 8, 3 over 2), so the
    baseline ladder pads ~1.6x while a fitted ladder lands exact."""
    sizes = [5] * 40 + [9] * 25 + [3] * 10 + [16] * 5
    rng = np.random.default_rng(seed)
    rng.shuffle(sizes)
    return sizes


def lognormal_smoke_lengths(
    n: int = 400, max_length: int = 64, seed: int = 0
) -> list[int]:
    """Big-Vul-shaped token lengths (lognormal, docs/input_pipeline.md)
    clipped to the smoke encoder capacity."""
    rng = np.random.default_rng(seed)
    draws = rng.lognormal(mean=2.8, sigma=0.6, size=n)
    return [int(min(max(x, 2), max_length)) for x in draws]


def run_tune_smoke(
    out_path: str | Path | None = None,
    reps: int = 2,
    n_steps: int = 2,
    kernel_candidates=SMOKE_CANDIDATES,
    seed: int = 0,
) -> dict:
    """The tier-1 search: reduced candidates, synthetic distributions,
    real compiles/timings/verdicts, schema-valid tuned.json out."""
    from deepdfa_tpu.core import paths

    t0 = time.perf_counter()
    n, e, d = SMOKE_BUDGETS
    ceiling = _measure_ceiling_flops(smoke=True)
    kernel = tune_kernel.search_kernel(
        [(n, e, d)],
        n_steps=n_steps,
        candidates=list(kernel_candidates),
        reps=reps,
        ceiling_flops_per_sec=ceiling,
    )
    serve_fit = tune_ladder.fit_serve_ladder(
        skewed_smoke_sizes(seed), capacity=16, max_rungs=4
    )
    seq_fit = tune_ladder.fit_seq_buckets(
        lognormal_smoke_lengths(seed=seed), max_length=64, max_edges=4
    )
    search_seconds = time.perf_counter() - t0
    record = tune_cache.make_record(
        tune_cache.hardware_key(n, e),
        kernel=kernel,
        ladders={"serve": serve_fit, "seq_buckets": seq_fit},
        search_seconds=search_seconds,
    )
    path = (
        Path(out_path) if out_path
        else paths.storage_root() / "tuned.json"
    )
    doc = tune_cache.load_tuned(path) or tune_cache.empty_doc()
    doc = tune_cache.upsert_record(doc, record)
    tune_cache.save_tuned(path, doc)
    # the smoke's verdict judges ITS OWN record (the run_tune rule: a
    # damaged unrelated legacy record in the same file is not this
    # search's failure)
    verdict = tune_cache.validate_tuned(
        {"version": tune_cache.TUNED_VERSION, "records": [record]}
    )
    sig = f"{n}x{e}x{d}"
    srec = kernel[sig]
    return {
        "tuned_path": str(path),
        "valid": verdict["ok"],
        "problems": verdict["problems"],
        "signature": sig,
        "winner": srec.get("winner"),
        "winner_blocks": [
            srec.get("winner_block_n"), srec.get("winner_block_e"),
        ],
        "candidates_timed": sum(
            1 for r in srec["candidates"] if "step_us" in r
        ),
        "candidates_rejected": sum(
            1 for r in srec["candidates"]
            if r.get("numerics", {}).get("ok") is False
        ),
        "tuned_ggnn_step_us": srec.get("winner_step_us"),
        "lax_step_us": srec.get("lax_step_us"),
        "serve_rungs": serve_fit["rungs"],
        "tuned_ladder_padding_waste": serve_fit["padding_waste"],
        "pow2_ladder_padding_waste": serve_fit["pow2_padding_waste"],
        "seq_bucket_edges": seq_fit["edges"],
        "seq_bucket_padding_waste": seq_fit["padding_waste"],
        "seq_bucket_pow2_padding_waste": seq_fit["pow2_padding_waste"],
        "tune_search_seconds": round(search_seconds, 3),
    }


def run_tune(
    cfg,
    serve_logs: list[str] | None = None,
    manifest: str | None = None,
    out_path: str | Path | None = None,
    skip_kernel: bool = False,
) -> dict:
    """The full offline search at the configured budgets: kernel
    candidates from the full legal grid, ladder fits replayed from the
    given serve/fleet logs, seq-bucket fit from a training-manifest
    length list. Sections without evidence are skipped with a note —
    a tuned.json never carries a guessed layout."""
    t0 = time.perf_counter()
    scfg = cfg.serve
    node_budget = scfg.node_budget or cfg.data.batch.node_budget
    edge_budget = scfg.edge_budget or cfg.data.batch.edge_budget
    d = tune_cache.ggnn_feature_width(cfg.model)
    notes: list[str] = []
    kernel = None
    per_compile_s = 0.0
    if skip_kernel:
        notes.append("kernel search skipped (--skip-kernel)")
    else:
        ceiling = _measure_ceiling_flops(smoke=False)
        kernel = tune_kernel.search_kernel(
            [(node_budget, edge_budget, d)],
            n_steps=cfg.model.n_steps,
            n_etypes=cfg.model.n_etypes,
            reps=cfg.tune.reps,
            compile_budget_s=cfg.tune.compile_budget_s,
            ceiling_flops_per_sec=ceiling,
        )
        sig = kernel.get(f"{node_budget}x{edge_budget}x{d}") or {}
        per_compile_s = float(sig.get("lax_compile_seconds") or 0.0)
    ladders: dict = {}
    sizes: list[int] = []
    for log in serve_logs or []:
        sizes.extend(tune_ladder.batch_sizes_from_log(log))
    if sizes:
        ladders["serve"] = tune_ladder.fit_serve_ladder(
            sizes,
            capacity=scfg.max_batch_graphs,
            max_rungs=cfg.tune.max_rungs,
            compile_budget_s=cfg.tune.compile_budget_s,
            per_compile_s=per_compile_s,
        )
    else:
        notes.append(
            "serve ladder fit skipped: no observed batch sizes "
            "(pass --serve-log with a serve.request_log=true log)"
        )
    if manifest:
        lengths = tune_ladder.lengths_from_manifest(manifest)
        if lengths and cfg.data.seq_buckets:
            # tune.max_seq_buckets is the structural compile cap
            # (each edge is one AOT warmup compile) — it bounds the
            # fit even below the configured edge count
            ladders["seq_buckets"] = tune_ladder.fit_seq_buckets(
                lengths,
                max_length=int(cfg.data.seq_buckets[-1]),
                max_edges=cfg.tune.max_seq_buckets,
                compile_budget_s=cfg.tune.compile_budget_s,
                per_compile_s=per_compile_s,
            )
        else:
            notes.append(
                "seq-bucket fit skipped: empty manifest or no "
                "data.seq_buckets to anchor the max edge"
            )
    else:
        notes.append("seq-bucket fit skipped: no --manifest")
    search_seconds = time.perf_counter() - t0
    record = tune_cache.make_record(
        tune_cache.hardware_key(node_budget, edge_budget),
        kernel=kernel,
        ladders=ladders or None,
        search_seconds=search_seconds,
    )
    path = Path(out_path) if out_path else tune_cache.tuned_path(cfg)
    # validate the NEW record ALONE before it touches disk: a failed
    # search (no evidence sections, no surviving winner) must never
    # replace a previously-committed good record for this hardware key
    # — and a damaged UNRELATED legacy record in the same file must
    # never block persisting a good new one
    verdict = tune_cache.validate_tuned(
        {"version": tune_cache.TUNED_VERSION, "records": [record]}
    )
    if verdict["ok"]:
        doc = tune_cache.upsert_record(
            tune_cache.load_tuned(path) or tune_cache.empty_doc(),
            record,
        )
        tune_cache.save_tuned(path, doc)
    else:
        notes.append(
            "search produced an invalid record — tuned.json left "
            "untouched (fix the inputs and re-run)"
        )
        logger.warning(
            "not persisting invalid tuned record: %s",
            verdict["problems"],
        )
    report = {
        "tuned_path": str(path),
        "valid": verdict["ok"],
        "problems": verdict["problems"],
        "hardware": record["hardware"],
        "notes": notes,
        "tune_search_seconds": round(search_seconds, 3),
    }
    if kernel:
        sig_label = f"{node_budget}x{edge_budget}x{d}"
        srec = kernel.get(sig_label) or {}
        report["kernel"] = {
            "signature": sig_label,
            "winner": srec.get("winner"),
            "winner_step_us": srec.get("winner_step_us"),
            "lax_step_us": srec.get("lax_step_us"),
            "candidates": len(srec.get("candidates") or []),
            "pruned": len(srec.get("pruned") or []),
        }
    if "serve" in ladders:
        report["serve_ladder"] = ladders["serve"]
    if "seq_buckets" in ladders:
        report["seq_buckets"] = ladders["seq_buckets"]
    print(json.dumps(report), flush=True)
    return report
