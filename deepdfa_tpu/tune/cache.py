"""tuned.json: persisted winning layouts, keyed by hardware generation
(docs/tuning.md).

One schema-validated document holds one record per hardware generation —
the key is {device_kind, platform, n_devices, jax_version, node_budget,
edge_budget}: a layout measured on a v5e at the flagship budgets says
nothing about a v4 or about the smoke budgets, so a consumer only ever
uses a record whose key matches its own hardware EXACTLY and falls back
to the hand-picked defaults LOUDLY otherwise (a warning naming every
mismatched field — never a silent wrong layout).

Consumers (all behind `cfg.tune.enabled`, default OFF):
  - `GatedGraphConv` block sizes via `model.ggnn_kernel_block_*`
    (`apply_to_config` — the CLI entry points call it once at startup);
  - the serve executors' warmup ladder (`serve_rungs_for` —
    ScoringService consults it at construction, before warmup);
  - `data.seq_buckets` for the text plan + CombinedExecutor
    (`seq_edges_for` / `apply_to_config`).

The committed TUNED_r*.json trajectory is the same document shape;
`validate_tuned` is the one validator (`check_obs_schema.py --tuned`)
and `obs/bench_gate.py:gate_tuned` gates a round against it.
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Any

logger = logging.getLogger(__name__)

#: bump when the document shape changes
TUNED_VERSION = 1

#: every field a record's hardware key must carry; exact equality on
#: ALL of them is the match criterion
REQUIRED_HW_FIELDS = (
    "device_kind", "platform", "n_devices", "jax_version",
    "node_budget", "edge_budget",
)


def hardware_key(node_budget: int, edge_budget: int) -> dict:
    """The hardware-generation key for THIS process: device kind +
    platform + topology (visible device count) + jax version + the
    feature budgets the layouts were measured at."""
    import jax

    dev = jax.devices()[0]
    return {
        "device_kind": str(getattr(dev, "device_kind", dev.platform)),
        "platform": str(dev.platform),
        "n_devices": int(jax.device_count()),
        "jax_version": str(jax.__version__),
        "node_budget": int(node_budget),
        "edge_budget": int(edge_budget),
    }


def empty_doc() -> dict:
    return {"version": TUNED_VERSION, "records": []}


def load_tuned(path: str | Path) -> dict | None:
    path = Path(path)
    if not path.exists():
        return None
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        logger.warning("tuned.json at %s unreadable (%s)", path, e)
        return None
    return doc if isinstance(doc, dict) else None


def save_tuned(path: str | Path, doc: dict) -> Path:
    from deepdfa_tpu.core.ioutil import atomic_write_text

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    atomic_write_text(path, json.dumps(doc, indent=1))
    return path


def hw_mismatch(record_hw: dict, hw: dict) -> list[str]:
    """Mismatched-field names between a record's hardware key and ours
    ([] = exact match); missing fields count as mismatches."""
    out = []
    for f in REQUIRED_HW_FIELDS:
        if record_hw.get(f) != hw.get(f):
            out.append(
                f"{f}: record={record_hw.get(f)!r} vs ours={hw.get(f)!r}"
            )
    return out


def find_record(doc: dict, hw: dict) -> dict | None:
    """The newest record whose hardware key matches exactly."""
    best = None
    for rec in doc.get("records", []):
        if not isinstance(rec, dict):
            continue
        if not hw_mismatch(rec.get("hardware") or {}, hw):
            best = rec
    return best


def upsert_record(doc: dict, record: dict) -> dict:
    """Replace the record with the same hardware key (or append)."""
    hw = record.get("hardware") or {}
    records = [
        r for r in doc.get("records", [])
        if hw_mismatch((r.get("hardware") or {}), hw)
    ]
    records.append(record)
    return {"version": TUNED_VERSION, "records": records}


def make_record(
    hardware: dict,
    kernel: dict | None = None,
    ladders: dict | None = None,
    search_seconds: float = 0.0,
) -> dict:
    rec: dict = {
        "hardware": dict(hardware),
        "created_unix": round(time.time(), 3),
        "search_seconds": round(float(search_seconds), 3),
    }
    if kernel:
        rec["kernel"] = kernel
    if ladders:
        rec["ladders"] = ladders
    return rec


# ---------------------------------------------------------------------------
# validation (check_obs_schema.py --tuned; the TUNED_r* gate's precheck)


def _ascending(xs) -> bool:
    xs = list(xs)
    return all(
        isinstance(x, int) and not isinstance(x, bool) for x in xs
    ) and xs == sorted(set(xs))


def validate_tuned(doc: Any) -> dict:
    """Structural validation of a tuned.json / TUNED_r*.json document:
    hardware key complete, every candidate row carries its
    numerics-contract verdict, a winner present per signature, ladder
    records well-formed with their pow2 baseline on the record."""
    problems: list[str] = []
    n_signatures = 0
    n_candidates = 0
    if isinstance(doc, dict) and "tuned" in doc and "records" not in doc:
        doc = doc["tuned"]  # tolerate a wrapped driver artifact
    if not isinstance(doc, dict):
        return {"ok": False, "problems": ["document is not an object"]}
    if doc.get("version") != TUNED_VERSION:
        problems.append(
            f"version {doc.get('version')!r} != {TUNED_VERSION}"
        )
    records = doc.get("records")
    if not isinstance(records, list) or not records:
        problems.append("no records")
        records = []
    for ri, rec in enumerate(records):
        where = f"records[{ri}]"
        if not isinstance(rec, dict):
            problems.append(f"{where}: not an object")
            continue
        hw = rec.get("hardware")
        if not isinstance(hw, dict):
            problems.append(f"{where}: missing hardware key")
        else:
            for f in REQUIRED_HW_FIELDS:
                if hw.get(f) in (None, ""):
                    problems.append(
                        f"{where}: hardware key incomplete — "
                        f"missing {f}"
                    )
        if not isinstance(
            rec.get("search_seconds"), (int, float)
        ) or isinstance(rec.get("search_seconds"), bool):
            problems.append(f"{where}: missing search_seconds")
        kernel = rec.get("kernel")
        if kernel is not None:
            if not isinstance(kernel, dict):
                problems.append(f"{where}: kernel is not an object")
                kernel = {}
            for sig, sr in kernel.items():
                n_signatures += 1
                sw = f"{where}.kernel[{sig}]"
                if not isinstance(sr, dict):
                    problems.append(f"{sw}: not an object")
                    continue
                cands = sr.get("candidates")
                if not isinstance(cands, list) or not cands:
                    problems.append(f"{sw}: no candidate rows")
                    cands = []
                labels = set()
                for ci, row in enumerate(cands):
                    if not isinstance(row, dict):
                        problems.append(
                            f"{sw}.candidates[{ci}]: not an object"
                        )
                        continue
                    n_candidates += 1
                    labels.add(row.get("candidate"))
                    # Axis values are optional (pre-PR-16 rows carry
                    # neither accum nor unroll) but when present they
                    # must name a mode this codebase can replay.
                    if "accum" in row and row["accum"] not in (
                        "fp32", "bf16", "int8",
                    ):
                        problems.append(
                            f"{sw}.candidates[{ci}]"
                            f"[{row.get('candidate')}]: unknown accum "
                            f"{row['accum']!r}"
                        )
                    if "unroll" in row and row["unroll"] not in (
                        "per_step", "fused",
                    ):
                        problems.append(
                            f"{sw}.candidates[{ci}]"
                            f"[{row.get('candidate')}]: unknown unroll "
                            f"{row['unroll']!r}"
                        )
                    if "skipped" in row or "error" in row:
                        continue  # never timed: no verdict to carry
                    verdict = row.get("numerics")
                    if not isinstance(verdict, dict) or not isinstance(
                        verdict.get("ok"), bool
                    ):
                        problems.append(
                            f"{sw}.candidates[{ci}]"
                            f"[{row.get('candidate')}]: missing "
                            f"numerics-contract verdict"
                        )
                winner = sr.get("winner")
                if winner is None:
                    problems.append(f"{sw}: no winner")
                elif winner not in labels:
                    problems.append(
                        f"{sw}: winner {winner!r} is not a recorded "
                        f"candidate"
                    )
        ladders = rec.get("ladders")
        if ladders is not None:
            if not isinstance(ladders, dict):
                problems.append(f"{where}: ladders is not an object")
                ladders = {}
            for name, lr in ladders.items():
                lw = f"{where}.ladders[{name}]"
                if not isinstance(lr, dict):
                    problems.append(f"{lw}: not an object")
                    continue
                rungs = lr.get("rungs") or lr.get("edges")
                if not rungs or not _ascending(rungs):
                    problems.append(
                        f"{lw}: rungs/edges missing or not ascending "
                        f"unique ints"
                    )
                for f in ("padding_waste", "pow2_padding_waste"):
                    v = lr.get(f)
                    if not isinstance(v, (int, float)) or isinstance(
                        v, bool
                    ):
                        problems.append(f"{lw}: missing {f}")
        if kernel is None and ladders is None:
            problems.append(f"{where}: neither kernel nor ladders")
    return {
        "ok": not problems,
        "problems": problems,
        "records": len(records),
        "signatures": n_signatures,
        "candidates": n_candidates,
    }


def validate_tuned_file(path: str | Path) -> dict:
    try:
        doc = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError) as e:
        return {"ok": False, "problems": [f"unreadable: {e}"]}
    out = validate_tuned(doc)
    out["path"] = str(path)
    return out


# ---------------------------------------------------------------------------
# config-facing consumers (everything behind cfg.tune.enabled)


def tuned_path(cfg) -> Path:
    """Where tuned.json lives: cfg.tune.path, else
    <storage>/tuned.json (next to runs/ and cache/)."""
    p = getattr(getattr(cfg, "tune", None), "path", None)
    if p:
        return Path(p)
    from deepdfa_tpu.core import paths

    return paths.storage_root() / "tuned.json"


#: memo for record_for_config, keyed by (path, file mtime, hardware
#: key): serve-side startup resolves the record twice (the CLI's
#: _apply_tuned for kernel blocks, then ScoringService for the ladder)
#: — one read, one loud warning, not two of each
_RECORD_MEMO: dict[tuple, dict | None] = {}


def record_for_config(cfg, node_budget: int, edge_budget: int) -> dict | None:
    """The matching tuned record for this process's hardware key, or
    None — with the LOUD fallback the contract requires: a missing
    file, unreadable document, or key mismatch each names itself."""
    path = tuned_path(cfg)
    try:
        mtime = path.stat().st_mtime_ns
    except OSError:
        mtime = None
    memo_key = (str(path), mtime, int(node_budget), int(edge_budget))
    if memo_key in _RECORD_MEMO:
        return _RECORD_MEMO[memo_key]
    rec = _record_for_config_uncached(path, node_budget, edge_budget)
    if len(_RECORD_MEMO) > 16:
        _RECORD_MEMO.clear()
    _RECORD_MEMO[memo_key] = rec
    return rec


def _record_for_config_uncached(
    path: Path, node_budget: int, edge_budget: int
) -> dict | None:
    doc = load_tuned(path)
    if doc is None:
        logger.warning(
            "tune.enabled but no usable tuned.json at %s — serving the "
            "hand-picked default layouts (run `deepdfa-tpu tune`)", path,
        )
        return None
    hw = hardware_key(node_budget, edge_budget)
    rec = find_record(doc, hw)
    if rec is None:
        nearest = (doc.get("records") or [{}])[-1]
        if not isinstance(nearest, dict):
            # a hand-edited/corrupt records list must still fall back
            # loudly, never crash the server at warmup
            nearest = {}
        logger.warning(
            "tune.enabled but no tuned record matches this hardware "
            "generation — falling back to default layouts. ours=%s; "
            "nearest record mismatches: %s",
            hw,
            hw_mismatch((nearest.get("hardware") or {}), hw)
            or ["<no records>"],
        )
    return rec


def serve_rungs_from(record: dict | None, capacity: int) -> tuple[int, ...] | None:
    """The tuned serve warmup-ladder rungs, normalized for the
    configured capacity. ONE implementation of the clamp-and-force-
    capacity invariant: `serve/batcher.py:_ladder_sizes` (the executor
    applies it again idempotently on construction).

    The fit is only meaningful AT the capacity it was measured for: a
    ladder fitted at max_batch_graphs=32 clamped down to capacity 4
    would lose the small rungs the pow2 default keeps (a lone request
    padding 3-4x forever — strictly WORSE than no tuning). A capacity
    drift therefore falls back to the default ladder, loudly."""
    if not record:
        return None
    lr = (record.get("ladders") or {}).get("serve")
    if not isinstance(lr, dict) or not lr.get("rungs"):
        return None
    fitted_cap = lr.get("capacity", max(int(r) for r in lr["rungs"]))
    if int(fitted_cap) != int(capacity):
        logger.warning(
            "tuned serve ladder was fitted at capacity %s but "
            "serve.max_batch_graphs=%s — falling back to the pow2 "
            "default ladder (re-run `deepdfa-tpu tune` at this "
            "capacity)", fitted_cap, capacity,
        )
        return None
    from deepdfa_tpu.serve.batcher import _ladder_sizes

    return _ladder_sizes(lr["rungs"], int(capacity))


def seq_edges_from(record: dict | None) -> tuple[int, ...] | None:
    """The tuned data.seq_buckets edges, if the record fit them."""
    if not record:
        return None
    lr = (record.get("ladders") or {}).get("seq_buckets")
    if not isinstance(lr, dict) or not lr.get("edges"):
        return None
    return tuple(int(e) for e in lr["edges"])


def ggnn_feature_width(model_cfg) -> int:
    """The GGNN feature width d the kernel signatures key on: the
    embedded node-feature width `GatedGraphConv` actually tiles.

    Derived from the ONE model-side width source (`DeepDFA.out_dim` =
    the [ggnn_out, feat_embed] concat = exactly twice the embedding
    width the conv sees) instead of re-implementing the multiplier
    arithmetic — a future embedding-width change cannot desync the
    signatures the tuner keys on from the shapes the model compiles."""
    from deepdfa_tpu.models import DeepDFA

    # input_dim only sizes the vocab tables, never the feature width
    return DeepDFA.from_config(model_cfg, input_dim=1).out_dim // 2


def kernel_layout_from(
    record: dict | None, n: int, e: int, d: int
) -> dict | None:
    """The WHOLE winning layout for one signature — blocks AND
    scatter/accum/unroll, or None (an absent signature is a defaults
    case). The search timed the five axes jointly (Morphling-style
    variant selection), so a consumer must apply all of them together:
    blocks from a fold winner under an auto-resolved mxu scatter would
    be a layout nobody ever measured. Pre-PR-16 records carry no
    `winner_unroll`; the key is simply absent then (per_step was the
    only mode those searches timed)."""
    if not record:
        return None
    sr = (record.get("kernel") or {}).get(f"{n}x{e}x{d}")
    if not isinstance(sr, dict) or not sr.get("winner"):
        return None
    bn, be = sr.get("winner_block_n"), sr.get("winner_block_e")
    if not isinstance(bn, int) or not isinstance(be, int):
        return None
    out = {"block_n": int(bn), "block_e": int(be)}
    if isinstance(sr.get("winner_scatter"), str):
        out["scatter"] = sr["winner_scatter"]
    if isinstance(sr.get("winner_accum"), str):
        out["accum"] = sr["winner_accum"]
    if isinstance(sr.get("winner_unroll"), str):
        out["unroll"] = sr["winner_unroll"]
    return out


def apply_to_config(
    cfg,
    sections=("kernel", "seq_buckets"),
    node_budget: int | None = None,
    edge_budget: int | None = None,
):
    """(cfg', report): fold the matching tuned record's layout into a
    config — the kernel block sizes (model.ggnn_kernel_block_*, a
    layout-only knob excluded from the registry config digest) and,
    when "seq_buckets" is in `sections`, the fitted data.seq_buckets
    edges. Serve-side callers pass sections=("kernel",): their bucket
    edges flow through ScoringService instead, so the registry's
    config digest (hot-swap admission) never sees a tuned data
    section. No-op (loudly, via `record_for_config`) when nothing
    matches; callers gate on cfg.tune.enabled."""
    from deepdfa_tpu.core import config as config_mod

    if node_budget is None:
        node_budget = cfg.data.batch.node_budget
    if edge_budget is None:
        edge_budget = cfg.data.batch.edge_budget
    rec = record_for_config(cfg, node_budget, edge_budget)
    report: dict = {"matched": rec is not None, "overrides": []}
    if rec is None:
        return cfg, report
    overrides: list[str] = []
    if "kernel" in sections:
        d = ggnn_feature_width(cfg.model)
        layout = kernel_layout_from(rec, node_budget, edge_budget, d)
        if layout is not None:
            overrides += [
                f"model.ggnn_kernel_block_nodes={layout['block_n']}",
                f"model.ggnn_kernel_block_edges={layout['block_e']}",
            ]
            # the winner was measured as a JOINT layout: its scatter
            # and accum ride along (both digest-excluded lowering
            # knobs; numerics stay within the per-mode tolerances the
            # search asserted)
            if "scatter" in layout:
                overrides.append(
                    "model.ggnn_kernel_scatter="
                    + json.dumps(layout["scatter"])
                )
            if "accum" in layout:
                overrides.append(
                    "model.ggnn_kernel_accum="
                    + json.dumps(layout["accum"])
                )
            if "unroll" in layout:
                overrides.append(
                    "model.ggnn_kernel_unroll="
                    + json.dumps(layout["unroll"])
                )
    if "seq_buckets" in sections:
        edges = seq_edges_from(rec)
        if edges is not None and cfg.data.seq_buckets:
            # the max_length drift guard (the serve_rungs_from
            # capacity rule's train-side twin): a fit anchored at a
            # different top edge would silently truncate training
            # sequences to the stale capacity
            fit_max = (
                (rec.get("ladders") or {}).get("seq_buckets") or {}
            ).get("max_length", edges[-1])
            want_max = int(cfg.data.seq_buckets[-1])
            if int(fit_max) != want_max:
                logger.warning(
                    "tuned seq buckets were fitted at max_length %s "
                    "but data.seq_buckets tops at %s — keeping the "
                    "configured edges (re-run `deepdfa-tpu tune` with "
                    "a manifest at this length)", fit_max, want_max,
                )
                edges = None
        elif edges is not None:
            # no configured buckets to anchor the top edge: applying a
            # fitted set would silently flip bucketing on at a guessed
            # capacity — defaults win, loudly
            logger.warning(
                "tuned seq buckets present but data.seq_buckets is "
                "unset — not applying (set data.seq_buckets to anchor "
                "the max edge)"
            )
            edges = None
        if edges is not None:
            overrides.append(
                "data.seq_buckets="
                + json.dumps([int(x) for x in edges])
            )
    if overrides:
        cfg = config_mod.apply_overrides(cfg, overrides)
        logger.info("tuned layout applied: %s", overrides)
    report["overrides"] = overrides
    return cfg, report


# ---------------------------------------------------------------------------
# the committed TUNED_r* trajectory


def load_tuned_trajectory(root: str | Path) -> list[dict]:
    """Every committed TUNED_r*.json, oldest round first — the same
    entry shape the BENCH_r*/MULTICHIP_r* loaders return:
    [{"source", "round", "record"|None, "note"|None}] where `record`
    is the tuned document itself."""
    import re

    root = Path(root)
    out: list[dict] = []
    for path in sorted(root.glob("TUNED_r*.json")):
        m = re.search(r"TUNED_r(\d+)", path.name)
        entry: dict = {
            "source": path.name,
            "round": int(m.group(1)) if m else None,
        }
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            entry["note"] = f"unreadable: {e}"
            entry["record"] = None
            out.append(entry)
            continue
        if isinstance(doc, dict) and "tuned" in doc and (
            "records" not in doc
        ):
            doc = doc["tuned"]
        if not isinstance(doc, dict) or not doc.get("records"):
            entry["note"] = "no tuned records"
            entry["record"] = None
        else:
            entry["record"] = doc
        out.append(entry)
    out.sort(key=lambda e: (e.get("round") or 0, e["source"]))
    return out
