"""Build the native library: g++ -O2 -shared -fPIC.

Usage: python -m deepdfa_tpu.native.build
The library lands next to this file as libdeepdfa_native.so; the ctypes
loader (deepdfa_tpu.native) builds it on demand when missing.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

_DIR = Path(__file__).resolve().parent
SRC = _DIR / "src" / "native.cpp"
LIB = _DIR / "libdeepdfa_native.so"


def build(force: bool = False) -> Path:
    if LIB.exists() and not force:
        if LIB.stat().st_mtime >= SRC.stat().st_mtime:
            return LIB
    # atomic: concurrent on-demand builds (multiprocessing workers) must
    # never dlopen a partially written library
    import os
    import tempfile

    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            "-o", tmp, str(SRC),
        ]
        subprocess.run(cmd, check=True)
        os.replace(tmp, LIB)
    finally:
        Path(tmp).unlink(missing_ok=True)
    return LIB


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    print(f"built {path}")
