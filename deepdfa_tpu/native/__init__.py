"""ctypes bindings for the native host-side kernels.

Loads (building on demand if a toolchain exists) libdeepdfa_native.so and
exposes:
  rd_solve_native(...)  — bitset worklist reaching definitions
  lex_c_native(code)    — C tokenizer returning frontend Token objects
  available()           — whether the native path can be used

Every binding has a pure-Python equivalent (frontend/reaching.py,
frontend/tokens.py) that remains the executable spec; parity is enforced
by tests/test_native.py. Production routing: ReachingDefinitions.solve()
and frontend.tokens.tokenize() dispatch here automatically (the lexer
only for pure-ASCII input — its fast path is byte-based and does not
replicate the Python lexer's unicode identifier handling).
"""

from __future__ import annotations

import ctypes
import functools
from pathlib import Path

import numpy as np

_LIB_PATH = Path(__file__).resolve().parent / "libdeepdfa_native.so"


@functools.lru_cache()
def _lib():
    if not _LIB_PATH.exists():
        from deepdfa_tpu.native.build import build

        build()
    lib = ctypes.CDLL(str(_LIB_PATH))
    lib.rd_solve.restype = ctypes.c_int64
    lib.rd_solve.argtypes = [
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.lex_c.restype = ctypes.c_int64
    lib.lex_c.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    return lib


@functools.lru_cache()
def available() -> bool:
    try:
        _lib()
        return True
    except Exception:
        return False


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def rd_solve_native(
    n_nodes: int,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    def_var: np.ndarray,
) -> dict[int, set[int]]:
    """IN sets per node as {node: set(def_node_ids)}.

    def_var: [n_nodes] int32, the variable id defined at each node (-1 if
    the node defines nothing)."""
    lib = _lib()
    edge_src = np.ascontiguousarray(edge_src, np.int32)
    edge_dst = np.ascontiguousarray(edge_dst, np.int32)
    def_var = np.ascontiguousarray(def_var, np.int32)
    site_nodes = np.flatnonzero(def_var >= 0)
    n_words = max(1, (len(site_nodes) + 63) // 64)
    out = np.zeros((n_nodes, n_words), np.uint64)
    n_sites = lib.rd_solve(
        n_nodes,
        len(edge_src),
        _ptr(edge_src, ctypes.c_int32),
        _ptr(edge_dst, ctypes.c_int32),
        _ptr(def_var, ctypes.c_int32),
        _ptr(out, ctypes.c_uint64),
    )
    if n_sites < 0:
        raise RuntimeError("rd_solve failed")
    assert n_sites == len(site_nodes)
    result: dict[int, set[int]] = {}
    for n in range(n_nodes):
        bits = out[n]
        sites: set[int] = set()
        for w in range(n_words):
            word = int(bits[w])
            while word:
                b = word & -word
                sites.add(int(site_nodes[w * 64 + b.bit_length() - 1]))
                word ^= b
        result[n] = sites
    return result


_KINDS = ["id", "kw", "num", "str", "char", "op"]


def lex_c_native(code: str):
    """Tokenize with the native lexer; returns frontend Token objects
    (without the trailing EOF token)."""
    from deepdfa_tpu.frontend.tokens import Token

    lib = _lib()
    raw = code.encode("utf-8", errors="replace")
    max_tokens = max(64, len(raw) + 1)
    kinds = np.zeros(max_tokens, np.int32)
    starts = np.zeros(max_tokens, np.int64)
    ends = np.zeros(max_tokens, np.int64)
    lines = np.zeros(max_tokens, np.int32)
    n = lib.lex_c(
        raw,
        len(raw),
        max_tokens,
        _ptr(kinds, ctypes.c_int32),
        _ptr(starts, ctypes.c_int64),
        _ptr(ends, ctypes.c_int64),
        _ptr(lines, ctypes.c_int32),
    )
    if n < 0:
        raise RuntimeError("lex_c: token budget exceeded")
    toks = []
    for i in range(n):
        text = raw[starts[i] : ends[i]].decode("utf-8", errors="replace")
        toks.append(Token(_KINDS[kinds[i]], text, int(lines[i]), 0))
    return toks
