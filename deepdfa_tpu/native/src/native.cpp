// Native host-side kernels for deepdfa_tpu.
//
// The reference offloads its host-side hot paths to native code (Joern's
// Scala dataflow engine for reaching definitions, DGL's C++ graph batching,
// tree-sitter's compiled grammars). This library is the TPU framework's
// equivalent: corpus-scale preprocessing primitives behind a plain C ABI
// consumed via ctypes (no pybind11 in the image).
//
//   rd_solve   — bitset worklist reaching-definitions over a CFG
//   lex_c      — C tokenizer (mirrors frontend/tokens.py semantics)
//
// Build: python -m deepdfa_tpu.native.build  (g++ -O2 -shared -fPIC)

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Reaching definitions.
//
// Inputs:
//   n_nodes, n_edges: CFG sizes (dense node ids 0..n_nodes-1)
//   src/dst[n_edges]: CFG edges
//   def_var[n_nodes]: variable id defined at the node, or -1
// Output:
//   out_in: n_nodes * n_words uint64 words; bit d of node n's row set iff
//           definition-site #d (dense index over nodes with def_var >= 0,
//           in node order) reaches the entry of n.
// Returns the number of definition sites (<= n_nodes), or -1 on overflow.
int64_t rd_solve(int32_t n_nodes, int64_t n_edges, const int32_t* src,
                 const int32_t* dst, const int32_t* def_var,
                 uint64_t* out_in) {
  if (n_nodes <= 0) return 0;

  // dense definition-site indexing
  std::vector<int32_t> def_site(n_nodes, -1);
  std::vector<int32_t> site_node;
  for (int32_t n = 0; n < n_nodes; ++n) {
    if (def_var[n] >= 0) {
      def_site[n] = static_cast<int32_t>(site_node.size());
      site_node.push_back(n);
    }
  }
  const int64_t n_sites = static_cast<int64_t>(site_node.size());
  const int64_t n_words = (n_sites + 63) / 64;
  if (n_words == 0) {
    return 0;  // no definitions: all IN sets empty, out untouched
  }

  // kill masks per variable: all sites defining that variable
  int32_t max_var = 0;
  for (int32_t n = 0; n < n_nodes; ++n)
    if (def_var[n] > max_var) max_var = def_var[n];
  std::vector<uint64_t> var_mask(static_cast<size_t>(max_var + 1) * n_words, 0);
  for (int64_t s = 0; s < n_sites; ++s) {
    const int32_t v = def_var[site_node[s]];
    var_mask[static_cast<size_t>(v) * n_words + s / 64] |= 1ull << (s % 64);
  }

  // CSR adjacency (successors + predecessors)
  std::vector<int64_t> succ_off(n_nodes + 1, 0), pred_off(n_nodes + 1, 0);
  for (int64_t e = 0; e < n_edges; ++e) {
    ++succ_off[src[e] + 1];
    ++pred_off[dst[e] + 1];
  }
  for (int32_t n = 0; n < n_nodes; ++n) {
    succ_off[n + 1] += succ_off[n];
    pred_off[n + 1] += pred_off[n];
  }
  std::vector<int32_t> succ(n_edges), pred(n_edges);
  std::vector<int64_t> scur(succ_off.begin(), succ_off.end() - 1),
      pcur(pred_off.begin(), pred_off.end() - 1);
  for (int64_t e = 0; e < n_edges; ++e) {
    succ[scur[src[e]]++] = dst[e];
    pred[pcur[dst[e]]++] = src[e];
  }

  std::vector<uint64_t> out(static_cast<size_t>(n_nodes) * n_words, 0);
  std::memset(out_in, 0, sizeof(uint64_t) * n_nodes * n_words);

  // worklist to fixpoint
  std::vector<int32_t> work;
  std::vector<uint8_t> in_work(n_nodes, 1);
  work.reserve(n_nodes);
  for (int32_t n = n_nodes - 1; n >= 0; --n) work.push_back(n);

  std::vector<uint64_t> tmp(n_words);
  while (!work.empty()) {
    const int32_t n = work.back();
    work.pop_back();
    in_work[n] = 0;

    // IN = union of OUT(preds)
    std::fill(tmp.begin(), tmp.end(), 0);
    for (int64_t e = pred_off[n]; e < pred_off[n + 1]; ++e) {
      const uint64_t* po = &out[static_cast<size_t>(pred[e]) * n_words];
      for (int64_t w = 0; w < n_words; ++w) tmp[w] |= po[w];
    }
    std::memcpy(&out_in[static_cast<size_t>(n) * n_words], tmp.data(),
                sizeof(uint64_t) * n_words);

    // OUT = gen U (IN - kill)
    if (def_var[n] >= 0) {
      const uint64_t* kill =
          &var_mask[static_cast<size_t>(def_var[n]) * n_words];
      for (int64_t w = 0; w < n_words; ++w) tmp[w] &= ~kill[w];
      const int32_t s = def_site[n];
      tmp[s / 64] |= 1ull << (s % 64);
    }
    uint64_t* on = &out[static_cast<size_t>(n) * n_words];
    bool changed = false;
    for (int64_t w = 0; w < n_words; ++w) {
      if (on[w] != tmp[w]) {
        changed = true;
        break;
      }
    }
    if (changed) {
      std::memcpy(on, tmp.data(), sizeof(uint64_t) * n_words);
      for (int64_t e = succ_off[n]; e < succ_off[n + 1]; ++e) {
        const int32_t s = succ[e];
        if (!in_work[s]) {
          in_work[s] = 1;
          work.push_back(s);
        }
      }
    }
  }
  return n_sites;
}

// ---------------------------------------------------------------------------
// C tokenizer. Token kinds mirror frontend/tokens.py.
enum TokKind : int32_t {
  TOK_ID = 0,
  TOK_KW = 1,
  TOK_NUM = 2,
  TOK_STR = 3,
  TOK_CHAR = 4,
  TOK_OP = 5,
};

static bool is_ident_start(char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') || c == '_';
}
static bool is_ident(char c) {
  return is_ident_start(c) || (c >= '0' && c <= '9');
}
static bool is_digit(char c) { return c >= '0' && c <= '9'; }
static bool is_hex(char c) {
  return is_digit(c) || (c >= 'a' && c <= 'f') || (c >= 'A' && c <= 'F');
}

static const char* kKeywords[] = {
    "auto", "break", "case", "char", "const", "continue", "default", "do",
    "double", "else", "enum", "extern", "float", "for", "goto", "if",
    "inline", "int", "long", "register", "restrict", "return", "short",
    "signed", "sizeof", "static", "struct", "switch", "typedef", "union",
    "unsigned", "void", "volatile", "while", "_Bool", "bool", nullptr};

static bool is_keyword(const char* s, int64_t len) {
  for (int k = 0; kKeywords[k]; ++k) {
    if (static_cast<int64_t>(std::strlen(kKeywords[k])) == len &&
        std::strncmp(kKeywords[k], s, len) == 0)
      return true;
  }
  return false;
}

// three-char then two-char then one-char operators (maximal munch)
static const char* kOps3[] = {"<<=", ">>=", "...", nullptr};
static const char* kOps2[] = {"::", "->", "++", "--", "<<", ">>", "<=", ">=",
                              "==", "!=", "&&", "||", "+=", "-=", "*=",
                              "/=", "%=", "&=", "^=", "|=", nullptr};
static const char kOps1[] = "+-*/%=<>!~&|^?:.,;()[]{}";

// Tokenize `code[0..len)`. Writes up to max_tokens entries of
// (kind, start, end, line) into the parallel output arrays.
// Returns the token count (excluding EOF), or -1 if max_tokens exceeded.
int64_t lex_c(const char* code, int64_t len, int64_t max_tokens,
              int32_t* kinds, int64_t* starts, int64_t* ends,
              int32_t* lines) {
  int64_t i = 0, count = 0;
  int32_t line = 1;

  auto emit = [&](int32_t kind, int64_t s, int64_t e, int32_t l) -> bool {
    if (count >= max_tokens) return false;
    kinds[count] = kind;
    starts[count] = s;
    ends[count] = e;
    lines[count] = l;
    ++count;
    return true;
  };

  while (i < len) {
    const char c = code[i];
    if (c == '\n') {
      ++line;
      ++i;
      continue;
    }
    if (c == ' ' || c == '\t' || c == '\r' || c == '\f' || c == '\v') {
      ++i;
      continue;
    }
    // comments
    if (c == '/' && i + 1 < len && code[i + 1] == '/') {
      while (i < len && code[i] != '\n') ++i;
      continue;
    }
    if (c == '/' && i + 1 < len && code[i + 1] == '*') {
      i += 2;
      while (i + 1 < len && !(code[i] == '*' && code[i + 1] == '/')) {
        if (code[i] == '\n') ++line;
        ++i;
      }
      i = (i + 1 < len) ? i + 2 : len;
      continue;
    }
    // preprocessor: skip continued line. The Python spec strips comments
    // BEFORE seeing the '#', so a /* ... */ opening on the directive line
    // swallows its newlines and the skip must too.
    if (c == '#') {
      while (i < len && code[i] != '\n') {
        if (code[i] == '\\' && i + 1 < len && code[i + 1] == '\n') {
          i += 2;
          ++line;
        } else if (code[i] == '/' && i + 1 < len && code[i + 1] == '*') {
          // comment inside the directive: if it spans a newline, the
          // directive ends there (python strips comments first, so the
          // first newline inside the comment terminates the # line)
          bool had_newline = false;
          i += 2;
          while (i + 1 < len && !(code[i] == '*' && code[i + 1] == '/')) {
            if (code[i] == '\n') {
              ++line;
              had_newline = true;
            }
            ++i;
          }
          i = (i + 1 < len) ? i + 2 : len;
          if (had_newline) break;
        } else if (code[i] == '/' && i + 1 < len && code[i + 1] == '/') {
          break;  // line comment ends the directive at the newline
        } else {
          ++i;
        }
      }
      continue;
    }
    const int64_t start = i;
    const int32_t tline = line;
    if (is_ident_start(c)) {
      while (i < len && is_ident(code[i])) ++i;
      if (!emit(is_keyword(code + start, i - start) ? TOK_KW : TOK_ID, start,
                i, tline))
        return -1;
      continue;
    }
    if (is_digit(c) || (c == '.' && i + 1 < len && is_digit(code[i + 1]))) {
      if (c == '0' && i + 1 < len && (code[i + 1] == 'x' || code[i + 1] == 'X')) {
        i += 2;
        while (i < len && is_hex(code[i])) ++i;
      } else {
        while (i < len && (is_digit(code[i]) || code[i] == '.')) ++i;
        if (i < len && (code[i] == 'e' || code[i] == 'E')) {
          int64_t j = i + 1;
          if (j < len && (code[j] == '+' || code[j] == '-')) ++j;
          if (j < len && is_digit(code[j])) {
            i = j;
            while (i < len && is_digit(code[i])) ++i;
          }
        }
      }
      while (i < len && (code[i] == 'u' || code[i] == 'U' || code[i] == 'l' ||
                         code[i] == 'L' || code[i] == 'f' || code[i] == 'F'))
        ++i;
      if (!emit(TOK_NUM, start, i, tline)) return -1;
      continue;
    }
    if (c == '"' || c == '\'') {
      ++i;
      while (i < len && code[i] != c) {
        if (code[i] == '\\') ++i;
        if (i < len && code[i] == '\n') ++line;
        if (i < len) ++i;
      }
      if (i < len) ++i;  // closing quote
      if (!emit(c == '"' ? TOK_STR : TOK_CHAR, start, i, tline)) return -1;
      continue;
    }
    // operators: maximal munch
    bool matched = false;
    if (i + 3 <= len) {
      for (int k = 0; kOps3[k]; ++k) {
        if (std::strncmp(code + i, kOps3[k], 3) == 0) {
          if (!emit(TOK_OP, i, i + 3, tline)) return -1;
          i += 3;
          matched = true;
          break;
        }
      }
    }
    if (matched) continue;
    if (i + 2 <= len) {
      for (int k = 0; kOps2[k]; ++k) {
        if (std::strncmp(code + i, kOps2[k], 2) == 0) {
          if (!emit(TOK_OP, i, i + 2, tline)) return -1;
          i += 2;
          matched = true;
          break;
        }
      }
    }
    if (matched) continue;
    if (std::strchr(kOps1, c) != nullptr) {
      if (!emit(TOK_OP, i, i + 1, tline)) return -1;
      ++i;
      continue;
    }
    ++i;  // unknown byte: skip (robustness, same as python lexer)
  }
  return count;
}

}  // extern "C"
