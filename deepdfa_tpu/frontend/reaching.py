"""Reaching-definitions analysis over CPG-lite CFGs.

Semantics mirror the reference's in-Python worklist solver
(DDFA/code_gnn/analysis/dataflow.py:103-177) and, transitively, the Joern
ReachingDefProblem export it mimics:

- a definition site is any CFG node that is a CALL whose name is an
  assignment or increment/decrement operator (mod_ops, dataflow.py:60-84,
  including the "<operators>." spelling variant Joern sometimes emits);
- the defined variable is the *code string* of the first ARGUMENT child
  (ordered), i.e. `x` for `x = e`, `*p` for `*p = e`;
- gen(n) = {n}; kill(n) = all other definitions of the same variable;
- IN(n) = union of OUT(preds); OUT(n) = gen(n) u (IN(n) - kill(n));
  iterated with a worklist to fixpoint.

The pure-Python solver here is the executable spec; the C++ bitset solver
(native/) is the fast path and is parity-tested against this one.
"""

from __future__ import annotations

import dataclasses

from deepdfa_tpu.frontend.cpg import ARGUMENT, CFG, Cpg

_ASSIGNMENT_OPS = [
    "assignment", "assignmentAnd", "assignmentArithmeticShiftRight",
    "assignmentDivision", "assignmentExponentiation",
    "assignmentLogicalShiftRight", "assignmentMinus", "assignmentModulo",
    "assignmentMultiplication", "assignmentOr", "assignmentPlus",
    "assignmentShiftLeft", "assignmentXor",
]
_INC_DEC_OPS = [
    "incBy", "postDecrement", "postIncrement", "preDecrement", "preIncrement",
]

MOD_OPS = frozenset(
    f"{prefix}.{op}"
    for prefix in ("<operator>", "<operators>")
    for op in _ASSIGNMENT_OPS + _INC_DEC_OPS
)


@dataclasses.dataclass(frozen=True)
class Definition:
    var: str
    node: int
    code: str

    def __lt__(self, other):
        return self.node < other.node


class ReachingDefinitions:
    def __init__(self, cpg: Cpg):
        self.cpg = cpg
        self.cfg_nodes = cpg.cfg_nodes()
        self.gen_set: dict[int, frozenset[Definition]] = {}
        self._var: dict[int, str | None] = {}
        for n in self.cfg_nodes:
            v = self.assigned_variable(n)
            self._var[n] = v
            if v is not None:
                self.gen_set[n] = frozenset(
                    {Definition(v, n, cpg.nodes[n].code)}
                )
            else:
                self.gen_set[n] = frozenset()

    def assigned_variable(self, nid: int) -> str | None:
        node = self.cpg.nodes[nid]
        if node.label != "CALL" or node.name not in MOD_OPS:
            return None
        args = self.cpg.arguments(nid)
        if not args:
            return None
        return self.cpg.nodes[args[0]].code

    @property
    def domain(self) -> set[Definition]:
        out: set[Definition] = set()
        for s in self.gen_set.values():
            out |= s
        return out

    def gen(self, n: int) -> frozenset[Definition]:
        return self.gen_set[n]

    def kill(self, n: int, definitions) -> set[Definition]:
        v = self._var[n]
        if v is None:
            return set()
        return {d for d in definitions if d.var == v and d.node != n}

    def solve(self, backend: str = "auto") -> dict[int, set[Definition]]:
        """Worklist to fixpoint; returns IN sets per CFG node.

        backend: "python" (the executable spec below), "native" (the C++
        bitset solver, deepdfa_tpu/native), or "auto" (native when built).
        """
        if backend != "python":
            from deepdfa_tpu import native

            if native.available():
                return self._solve_native()
            if backend == "native":
                raise RuntimeError(
                    "native backend requested but libdeepdfa_native is "
                    "unavailable (no toolchain?); build with "
                    "`python -m deepdfa_tpu.native.build`"
                )
        return self._solve_python()

    def dense_cfg(self) -> tuple[list[int], dict[int, int], list[int], list[int]]:
        """(nodes, node->dense index, edge src, edge dst) over the CFG —
        the shared dense view used by the native solver and by training
        label builders (nn/bitprop.rd_bit_problem)."""
        nodes = self.cfg_nodes
        dense = {n: i for i, n in enumerate(nodes)}
        src, dst = [], []
        for n in nodes:
            for s in self.cpg.successors(n, CFG):
                if s in dense:
                    src.append(dense[n])
                    dst.append(dense[s])
        return nodes, dense, src, dst

    def _solve_native(self) -> dict[int, set[Definition]]:
        import numpy as np

        from deepdfa_tpu.native import rd_solve_native

        nodes, dense, src, dst = self.dense_cfg()
        var_ids: dict[str, int] = {}
        def_var = np.full(len(nodes), -1, np.int32)
        for n in nodes:
            v = self._var[n]
            if v is not None:
                def_var[dense[n]] = var_ids.setdefault(v, len(var_ids))
        raw = rd_solve_native(
            len(nodes), np.array(src, np.int32), np.array(dst, np.int32), def_var
        )
        by_node = {
            d.node: d for s in self.gen_set.values() for d in s
        }
        return {
            nodes[i]: {by_node[nodes[j]] for j in sites}
            for i, sites in raw.items()
        }

    def _solve_python(self) -> dict[int, set[Definition]]:
        """Worklist to fixpoint; returns IN sets per CFG node."""
        out: dict[int, set[Definition]] = {n: set() for n in self.cfg_nodes}
        in_: dict[int, set[Definition]] = {n: set() for n in self.cfg_nodes}
        work = list(self.cfg_nodes)
        while work:
            n = work.pop()
            new_in: set[Definition] = set()
            for p in self.cpg.predecessors(n, CFG):
                new_in |= out[p]
            in_[n] = new_in
            new_out = set(self.gen(n)) | (new_in - self.kill(n, new_in))
            if new_out != out[n]:
                out[n] = new_out
                for s in self.cpg.successors(n, CFG):
                    work.append(s)
        return in_

    def solve_out(self) -> dict[int, set[Definition]]:
        in_ = self.solve()
        return {
            n: set(self.gen(n)) | (in_[n] - self.kill(n, in_[n]))
            for n in self.cfg_nodes
        }
