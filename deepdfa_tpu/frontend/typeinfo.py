"""Translation-unit type registry: typedef aliases + struct member types.

Role of the reference's Joern type script
(DDFA/storage/external/get_type.sc:4-52): `trueTypeDecl` follows typedef
aliases to the underlying type declaration, and `mapToMemberTypes`
recursively expands a struct/union into its "most grandchild" leaf types
— leaves being external (unknown-here) types or internal types without
members, with a seen-set guarding recursive structs. The reference drives
a Joern JVM per query (run_joern_gettype, joern.py:84-130); here a single
pass over the translation unit's token stream builds the registry and
queries are dictionary lookups.

Handled declaration shapes:
    typedef unsigned long size_t;
    typedef struct Foo Bar;            // alias to a tag
    typedef struct { int a; } Anon;    // anonymous struct alias
    struct Foo { int a; struct Baz b; char *name; };
    union/enum analogously (enums expand to no members)
"""

from __future__ import annotations

import dataclasses

from deepdfa_tpu.frontend.tokens import Token, tokenize

_QUALIFIERS = frozenset(
    ("const", "volatile", "static", "extern", "inline", "restrict",
     "unsigned", "signed", "short", "long")
)
_TAGS = frozenset(("struct", "union", "enum"))


@dataclasses.dataclass
class StructInfo:
    name: str
    member_types: list[str]


class TypeRegistry:
    """Typedef aliases + struct member tables for one translation unit."""

    def __init__(self):
        self.aliases: dict[str, str] = {}
        self.structs: dict[str, StructInfo] = {}
        self._anon = 0

    # -- construction --------------------------------------------------------

    @classmethod
    def from_source(cls, code: str) -> "TypeRegistry":
        reg = cls()
        try:
            toks = tokenize(code)
        except Exception:
            return reg
        reg._scan(toks)
        return reg

    def _scan(self, toks: list[Token]) -> None:
        i = 0
        n = len(toks)
        while i < n:
            t = toks[i]
            if t.kind == "kw" and t.text == "typedef":
                i = self._typedef(toks, i + 1)
            elif t.kind == "kw" and t.text in _TAGS:
                i = self._tag_decl(toks, i)
            else:
                i += 1

    def _skip_braces(self, toks, i) -> tuple[int, list[Token]]:
        """From an opening '{', return (index after matching '}', body)."""
        depth = 0
        body = []
        while i < len(toks):
            t = toks[i]
            if t.text == "{":
                depth += 1
                if depth > 1:
                    body.append(t)
            elif t.text == "}":
                depth -= 1
                if depth == 0:
                    return i + 1, body
                body.append(t)
            else:
                if depth >= 1:
                    body.append(t)
            i += 1
        return i, body

    def _members(self, body: list[Token]) -> list[str]:
        """Member type names from a struct body (one per declaration)."""
        out = []
        j = 0
        while j < len(body):
            # collect the declaration-specifier run up to the declarator
            spec: list[str] = []
            tagged = False
            while j < len(body) and not (
                body[j].kind == "id" and spec and not tagged
            ):
                t = body[j]
                if t.text == ";":
                    j += 1
                    spec = []
                    tagged = False
                    continue
                if t.kind == "kw" and t.text in _TAGS:
                    tagged = True
                    j += 1
                    continue
                if t.kind == "kw" and t.text in _QUALIFIERS:
                    spec.append(t.text)
                    j += 1
                    continue
                if t.kind == "kw" or t.kind == "id":
                    spec.append(t.text)
                    if tagged or t.kind == "id":
                        # `struct X member;` / `MyType member;`
                        tagged = False
                        j += 1
                        break
                    j += 1
                    continue
                j += 1
            if not spec:
                continue
            # skip declarator tokens (pointers, names, arrays) to ';'
            while j < len(body) and body[j].text != ";":
                j += 1
            j += 1
            out.append(" ".join(spec) if len(spec) > 1 else spec[0])
        return out

    def _typedef(self, toks, i) -> int:
        """Parse one `typedef ... Name;` starting after the keyword."""
        underlying: str | None = None
        if i < len(toks) and toks[i].kind == "kw" and toks[i].text in _TAGS:
            tag_kw = toks[i].text
            i += 1
            tag_name = None
            if i < len(toks) and toks[i].kind == "id":
                tag_name = toks[i].text
                i += 1
            if i < len(toks) and toks[i].text == "{":
                i, body = self._skip_braces(toks, i)
                if tag_name is None:
                    tag_name = f"anonymous_type_{self._anon}"
                    self._anon += 1
                if tag_kw != "enum":
                    self.structs[tag_name] = StructInfo(
                        tag_name, self._members(body)
                    )
                else:
                    self.structs[tag_name] = StructInfo(tag_name, [])
            underlying = tag_name
        else:
            spec = []
            while i < len(toks) and (
                toks[i].kind == "kw"
                and toks[i].text in _QUALIFIERS | {"int", "char", "float",
                                                   "double", "void", "_Bool"}
                or (toks[i].kind == "id" and not spec)
            ):
                spec.append(toks[i].text)
                i += 1
            underlying = " ".join(spec) if spec else None
        # alias name: last identifier before ';' (skips '*' pointers).
        # A '(' in the declarator means a function/function-pointer
        # typedef — the last identifier would be a PARAMETER name, so
        # recording it would poison lookups; skip those entirely.
        alias = None
        is_function = False
        while i < len(toks) and toks[i].text != ";":
            if toks[i].text == "(":
                is_function = True
            if toks[i].kind == "id" and not is_function:
                alias = toks[i].text
            i += 1
        if alias and underlying and alias != underlying and not is_function:
            self.aliases[alias] = underlying
        return i + 1

    def _tag_decl(self, toks, i) -> int:
        """`struct Name { ... };` at top level (not a typedef)."""
        tag_kw = toks[i].text
        i += 1
        name = None
        if i < len(toks) and toks[i].kind == "id":
            name = toks[i].text
            i += 1
        if i < len(toks) and toks[i].text == "{":
            i, body = self._skip_braces(toks, i)
            if name is not None and tag_kw != "enum":
                self.structs[name] = StructInfo(name, self._members(body))
            elif name is not None:
                self.structs[name] = StructInfo(name, [])
        return i

    # -- queries -------------------------------------------------------------

    def resolve_alias(self, name: str) -> str:
        """Follow the typedef chain to the underlying type (trueTypeDecl
        role); cycle-safe, returns the input when it aliases nothing."""
        seen = set()
        while name in self.aliases and name not in seen:
            seen.add(name)
            name = self.aliases[name]
        return name

    def member_leaf_types(self, root: str) -> list[str]:
        """Recursive leaf member types of `root` (mapToMemberTypes role):
        leaves are types unknown to this unit ("external") or known types
        without members; recursion guards against self-referential
        structs with a seen-set. Sorted + deduped like the script."""
        out: list[str] = []
        seen: set[str] = set()

        def walk(name: str) -> None:
            name = self.resolve_alias(name)
            if name in seen:
                return
            seen.add(name)
            info = self.structs.get(name)
            if info is None:
                out.append(name)  # external leaf
                return
            if not info.member_types:
                out.append(name)  # memberless internal leaf
                return
            for mt in info.member_types:
                # strip tag keywords + qualifiers from member spellings
                base = [
                    w for w in mt.split()
                    if w not in _TAGS and w not in _QUALIFIERS
                ]
                walk(base[-1] if base else mt)

        walk(root)
        return sorted(set(out))
