"""Joern export import: drop-in backend for reference-produced artifacts.

The reference drives the Joern JVM to emit `<file>.nodes.json` /
`<file>.edges.json` per function (DDFA/storage/external/get_func_graph.sc,
parsed by DDFA/sastvd/helpers/joern.py:182-319). Users who already ran that
preprocessing — or who want bit-exact Joern CPGs instead of the built-in
frontend — can load those files here into the same `Cpg` the rest of the
pipeline consumes.

Format: nodes.json is a list of records (id, _label, name, code,
lineNumber, order, typeFullName, ...); edges.json is a list of
[innode, outnode, etype, dataflow] rows where OUTNODE is the source and
INNODE the destination (reference get_cpg edge construction,
code_gnn/analysis/dataflow.py:243-245). Reference filters are applied:
COMMENT/FILE nodes and CONTAINS/SOURCE_FILE/DOMINATE/POST_DOMINATE edges
are dropped.
"""

from __future__ import annotations

import json
from pathlib import Path

from deepdfa_tpu.frontend.cpg import Cpg

_DROP_NODE_LABELS = {"COMMENT", "FILE"}
_DROP_EDGE_TYPES = {"CONTAINS", "SOURCE_FILE", "DOMINATE", "POST_DOMINATE"}


def load_joern_cpg(path_prefix: str | Path) -> Cpg:
    """Load `<prefix>.nodes.json` + `<prefix>.edges.json` into a Cpg."""
    prefix = str(path_prefix)
    nodes_raw = json.loads(Path(prefix + ".nodes.json").read_text())
    edges_raw = json.loads(Path(prefix + ".edges.json").read_text())

    cpg = Cpg()
    dense: dict[int, int] = {}
    for rec in nodes_raw:
        label = rec.get("_label", "")
        if label in _DROP_NODE_LABELS:
            continue
        code = rec.get("code", "") or ""
        if code == "<empty>":
            code = ""
        name = rec.get("name", "") or ""
        if not code:
            code = name  # reference: code falls back to name
        line = rec.get("lineNumber")
        try:
            line = int(line) if line not in (None, "") else None
        except (TypeError, ValueError):
            line = None
        order = rec.get("order")
        try:
            order = int(order) if order not in (None, "") else 0
        except (TypeError, ValueError):
            order = 0
        nid = cpg.add_node(
            label=label,
            name=name,
            code=code,
            line=line,
            order=order,
            type_full_name=rec.get("typeFullName", "") or "ANY",
        )
        dense[int(rec["id"])] = nid
        if label == "METHOD" and cpg.method_id is None:
            cpg.method_id = nid
            cpg.method_name = name
        if label == "METHOD_RETURN" and cpg.method_return_id is None:
            cpg.method_return_id = nid

    for row in edges_raw:
        innode, outnode, etype = row[0], row[1], row[2]
        if etype in _DROP_EDGE_TYPES:
            continue
        try:
            src = dense[int(outnode)]
            dst = dense[int(innode)]
        except (KeyError, TypeError, ValueError):
            continue  # endpoint filtered out or synthetic id
        cpg.add_edge(src, dst, etype)
    return cpg


def load_joern_dataflow(path: str | Path) -> dict[str, dict[str, dict[int, frozenset[int]]]]:
    """Parse a `<file>.dataflow.json` reaching-definitions export.

    Produced by JoernSession.export_dataflow_json (role of the reference's
    get_dataflow_output.sc cache files, consumed via
    datasets.get_dataflow_output). Shape:
    {method fullName: {"in"|"out": {node id: frozenset(definition idx)}}}.
    """
    import re

    def node_id(key: str) -> int:
        # bare integer ids normally; tolerate joern-version drift where a
        # node's toString leaks through ("Call[label=CALL; id=42]")
        try:
            return int(key)
        except ValueError:
            m = re.search(r"id=(\d+)", key)
            if m:
                return int(m.group(1))
            raise ValueError(f"unparseable dataflow node key {key!r}")

    raw = json.loads(Path(path).read_text())
    out: dict[str, dict[str, dict[int, frozenset[int]]]] = {}
    for method, sol in raw.items():
        out[method] = {
            kind: {
                node_id(nid): frozenset(int(d) for d in defs)
                for nid, defs in sol.get(kind, {}).items()
            }
            for kind in ("in", "out")
        }
    return out
