from deepdfa_tpu.frontend.absdf import (
    decl_features,
    graph_features,
    is_decl,
    node_hash,
)
from deepdfa_tpu.frontend.cpg import Cpg, Node
from deepdfa_tpu.frontend.parser import ParseError, parse_function
from deepdfa_tpu.frontend.reaching import Definition, ReachingDefinitions
from deepdfa_tpu.frontend.vocab import AbsDfVocab, build_vocab, build_vocabs, encode_nodes

__all__ = [
    "Cpg",
    "Node",
    "ParseError",
    "parse_function",
    "Definition",
    "ReachingDefinitions",
    "decl_features",
    "graph_features",
    "is_decl",
    "node_hash",
    "AbsDfVocab",
    "build_vocab",
    "build_vocabs",
    "encode_nodes",
]
