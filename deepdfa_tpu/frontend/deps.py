"""Data- and control-dependence over CPG-lite graphs.

Powers the reference's statement-labeling closure ("lines removed by the
fix plus lines data/control dependent on added lines",
DDFA/sastvd/helpers/evaluate.py:194-236) and the pdg-style graph
reductions (joern.py rdg):

- data dependence: use-def edges from the reaching-definitions solution —
  node N depends on definition D when D reaches N and N references D's
  variable.
- control dependence: classic Ferrante-Ottenstein-Warren construction on
  the CFG via postdominance frontiers (reverse-CFG dominators, computed
  with the Cooper-Harvey-Kennedy iteration).
"""

from __future__ import annotations

from collections import defaultdict

from deepdfa_tpu.frontend.cpg import CFG, Cpg
from deepdfa_tpu.frontend.reaching import ReachingDefinitions


def data_dependences(cpg: Cpg) -> set[tuple[int, int]]:
    """(def_node, use_node) pairs: use_node references a variable whose
    definition at def_node reaches it."""
    rd = ReachingDefinitions(cpg)
    in_sets = rd.solve()
    out: set[tuple[int, int]] = set()
    for n in rd.cfg_nodes:
        node = cpg.nodes[n]
        # identifiers referenced at n: its own code plus AST descendants
        names = {node.name} if node.label == "IDENTIFIER" else set()
        for d in cpg.ast_descendants(n, skip_labels=("METHOD",)):
            if cpg.nodes[d].label == "IDENTIFIER":
                names.add(cpg.nodes[d].name)
        for dfn in in_sets.get(n, ()):
            # variable code strings may be compound ("*p"); match on the
            # identifier tokens they contain
            if dfn.var in names or any(tok in names for tok in _id_tokens(dfn.var)):
                out.add((dfn.node, n))
    return out


def _id_tokens(code: str) -> list[str]:
    import re

    return re.findall(r"[A-Za-z_][A-Za-z0-9_]*", code)


def _postorder(cpg: Cpg, entry: int, succ) -> list[int]:
    seen: set[int] = set()
    order: list[int] = []
    stack: list[tuple[int, int]] = [(entry, 0)]
    while stack:
        n, i = stack.pop()
        if i == 0:
            if n in seen:
                continue
            seen.add(n)
        nxt = succ(n)
        if i < len(nxt):
            stack.append((n, i + 1))
            stack.append((nxt[i], 0))
        else:
            order.append(n)
    return order


def _idoms(nodes: list[int], entry: int, preds, succ) -> dict[int, int]:
    """Cooper-Harvey-Kennedy iterative dominators over `nodes`."""
    order = _postorder_nodes(nodes, entry, succ)
    rpo = list(reversed(order))
    index = {n: i for i, n in enumerate(rpo)}
    idom: dict[int, int | None] = {n: None for n in rpo}
    idom[entry] = entry

    def intersect(a, b):
        while a != b:
            while index[a] > index[b]:
                a = idom[a]
            while index[b] > index[a]:
                b = idom[b]
        return a

    changed = True
    while changed:
        changed = False
        for n in rpo:
            if n == entry:
                continue
            new = None
            for p in preds(n):
                if p in index and idom.get(p) is not None:
                    new = p if new is None else intersect(new, p)
            if new is not None and idom[n] != new:
                idom[n] = new
                changed = True
    return {n: d for n, d in idom.items() if d is not None}


def _postorder_nodes(nodes, entry, succ):
    seen = set()
    order = []

    def rec_iter(start):
        stack = [(start, iter(succ(start)))]
        seen.add(start)
        while stack:
            n, it = stack[-1]
            advanced = False
            for s in it:
                if s not in seen:
                    seen.add(s)
                    stack.append((s, iter(succ(s))))
                    advanced = True
                    break
            if not advanced:
                order.append(n)
                stack.pop()
    rec_iter(entry)
    return order


def control_dependences(cpg: Cpg) -> set[tuple[int, int]]:
    """(controller, dependent) pairs via reverse-dominance frontiers."""
    cfg_nodes = cpg.cfg_nodes()
    if not cfg_nodes or cpg.method_return_id is None:
        return set()
    nodes = set(cfg_nodes)
    exit_n = cpg.method_return_id

    def rsucc(n):
        return [p for p in cpg.predecessors(n, CFG) if p in nodes]

    def rpred(n):
        return [s for s in cpg.successors(n, CFG) if s in nodes]

    ipdom = _idoms(cfg_nodes, exit_n, rpred, rsucc)

    out: set[tuple[int, int]] = set()
    # postdominance frontier: for each node n with multiple CFG successors,
    # walk up from each successor until ipdom(n)
    for n in cfg_nodes:
        succs = [s for s in cpg.successors(n, CFG) if s in nodes]
        if len(succs) < 2:
            continue
        for s in succs:
            runner = s
            guard = 0
            while runner != ipdom.get(n) and runner in ipdom and guard < len(nodes) + 2:
                if runner != n:
                    out.add((n, runner))
                runner = ipdom[runner]
                guard += 1
    return out


def dependent_lines(cpg: Cpg, target_lines: set[int]) -> set[int]:
    """Lines with statements data/control dependent on any statement whose
    line is in target_lines (one-step closure, reference semantics)."""
    by_line: dict[int, list[int]] = defaultdict(list)
    for n in cpg.nodes:
        if n.line is not None:
            by_line[n.line].append(n.id)
    targets = {nid for ln in target_lines for nid in by_line.get(ln, [])}
    deps: set[int] = set()
    for src, dst in data_dependences(cpg) | control_dependences(cpg):
        if src in targets:
            deps.add(dst)
        if dst in targets:
            deps.add(src)
    return {
        cpg.nodes[n].line for n in deps if cpg.nodes[n].line is not None
    }
