"""IVDetect identifier tokenization (subtoken splitting).

Port of DDFA/sastvd/helpers/tokenise.py:4-35: split on special characters,
split camelCase boundaries, drop single-character subtokens. Used by the
IVDetect-style per-line feature extraction.
"""

from __future__ import annotations

import re

_SPEC_CHAR = re.compile(r"[^a-zA-Z0-9\s]")
_CAMEL = re.compile(r".+?(?:(?<=[a-z])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])|$)")


def tokenise(s: str) -> str:
    spec_split = re.split(_SPEC_CHAR, s)
    space_split = " ".join(spec_split).split()
    camel_split = [
        m.group(0) for tok in space_split for m in re.finditer(_CAMEL, tok)
    ]
    return " ".join(t for t in camel_split if len(t) > 1)


def tokenise_lines(s: str) -> list[str]:
    out = []
    for line in s.split("\n"):  # \n-only, like every line consumer here
        tok = tokenise(line)
        if tok:
            out.append(tok)
    return out
