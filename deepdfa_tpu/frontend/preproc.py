"""Preprocessor conditional evaluation + object-like macro expansion.

The reference's Joern frontend preprocesses each function text with an
empty predefined-macro table before parsing (Eclipse-CDT semantics under
get_func_graph.sc's importCode); a hermetic frontend that skips directive
LINES but keeps every branch BODY (the round-2 behavior, tokens.py) sees
`#ifdef`/`#else` functions with both branches live — a CPG shape a real
preprocessor can never produce. This pass applies standard C-preprocessor
semantics to the conditional directives only:

- `#if` / `#elif` constant expressions are evaluated with unknown
  identifiers as 0 (ISO C 6.10.1p4), `defined(X)` / `defined X` resolved
  against the file-local `#define` table;
- `#ifdef` / `#ifndef` test that table;
- inactive branch lines are blanked (newlines kept, so line numbers in
  the CPG still match the original source);
- object-like `#define NAME <literal-or-id>` bodies are expanded in
  active code (token-boundary, outside string/char literals), matching
  what the reference's parser sees after real preprocessing. Unknown
  function-like macros are left intact — they parse as plain calls, the
  same recovery CDT applies when a macro definition is unavailable.

Expressions this mini-evaluator cannot decide default to ACTIVE (keep the
code visible) rather than dropping code on a guess.
"""

from __future__ import annotations

import re

_DIRECTIVE_RE = re.compile(r"^\s*#\s*(\w+)(.*)$", re.DOTALL)
_DEFINE_RE = re.compile(r"^\s*(\w+)(\([^)]*\))?\s*(.*?)\s*$", re.DOTALL)
_DEFINED_RE = re.compile(r"\bdefined\s*(?:\(\s*(\w+)\s*\)|(\w+))")
_ID_RE = re.compile(r"\b[A-Za-z_]\w*\b")
_SIMPLE_BODY_RE = re.compile(
    r"^(?:\d[\w.]*|0[xX][0-9a-fA-F]+[uUlL]*|'(?:\\.|[^'])*'|\"(?:\\.|[^\"])*\"|[A-Za-z_]\w*|\([^()]*\))$"
)

# -- bounded #if expression evaluator ---------------------------------------
#
# Hostile dataset source reaches this code (ADVICE r3): Python eval() of a
# directive like `#if 9**9**9**9` or `#if 1<<(1<<40)` computes astronomical
# integers. This tiny recursive-descent evaluator implements exactly the C
# preprocessor operator set with hard caps on literal size, shift counts,
# and intermediate magnitude; anything outside it raises -> undecidable ->
# the branch stays active (the module's keep-code-visible default).

_NUM_TOK = re.compile(r"0[xX][0-9a-fA-F]+|\d+")
_OP_TOK = re.compile(r"<<|>>|<=|>=|==|!=|&&|\|\||[()?:~!+\-*/%<>&|^]")
_MAX_BITS = 128  # magnitude cap for literals and every intermediate


class _CondError(Exception):
    pass


def _cond_tokens(s: str) -> list[str]:
    toks: list[str] = []
    i, n = 0, len(s)
    while i < n:
        if s[i].isspace():
            i += 1
            continue
        m = _NUM_TOK.match(s, i) or _OP_TOK.match(s, i)
        if not m:
            raise _CondError(s[i])
        toks.append(m.group(0))
        i = m.end()
    return toks


class _CondParser:
    """Precedence-climbing parser for C preprocessor constant expressions:
    ternary > || > && > | > ^ > & > ==/!= > relational > shifts > +- >
    */% > unary.

    Syntax errors raise _CondError (the whole directive is undecidable).
    SEMANTIC failures (overflow past the magnitude cap, div-by-zero,
    out-of-range shift counts) evaluate to ``None`` and propagate, so
    they poison only the value that actually depends on them: real
    preprocessors accept `0 && 1/0` and `x ? y : 1/0` with the bad
    operand unselected, and short-circuit / arm selection must honor
    that (code-review r4)."""

    _BINOPS: list[list[str]] = [
        ["||"], ["&&"], ["|"], ["^"], ["&"], ["==", "!="],
        ["<", ">", "<=", ">="], ["<<", ">>"], ["+", "-"], ["*", "/", "%"],
    ]

    def __init__(self, s: str):
        self.toks = _cond_tokens(s)
        self.pos = 0

    def _peek(self) -> str | None:
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def _next(self) -> str:
        tok = self._peek()
        if tok is None:
            raise _CondError("eof")
        self.pos += 1
        return tok

    @staticmethod
    def _check(v: int | None) -> int | None:
        if v is not None and v.bit_length() > _MAX_BITS:
            return None
        return v

    def parse(self) -> int | None:
        v = self._ternary()
        if self._peek() is not None:
            raise _CondError(self._peek())
        return v

    def _ternary(self) -> int | None:
        cond = self._binary(0)
        if self._peek() != "?":
            return cond
        self._next()
        # parse both arms (structure must be consumed either way); only
        # the SELECTED arm's semantic failures matter
        then = self._ternary()
        if self._next() != ":":
            raise _CondError(":")
        other = self._ternary()
        if cond is None:
            return None
        return then if cond else other

    def _binary(self, level: int) -> int | None:
        if level == len(self._BINOPS):
            return self._unary()
        v = self._binary(level + 1)
        ops = self._BINOPS[level]
        while self._peek() in ops:
            op = self._next()
            r = self._binary(level + 1)
            if op == "||":
                # short-circuit: a decided-true left absorbs a poisoned
                # right (C never evaluates it); a poisoned LEFT poisons
                # the result (C evaluates left first)
                if v is None:
                    v = None
                else:
                    v = 1 if v else (None if r is None else int(bool(r)))
            elif op == "&&":
                if v is None:
                    v = None
                else:
                    v = 0 if not v else (None if r is None else int(bool(r)))
            elif v is None or r is None:
                v = None
            elif op == "|":
                v |= r
            elif op == "^":
                v ^= r
            elif op == "&":
                v &= r
            elif op == "==":
                v = int(v == r)
            elif op == "!=":
                v = int(v != r)
            elif op == "<":
                v = int(v < r)
            elif op == ">":
                v = int(v > r)
            elif op == "<=":
                v = int(v <= r)
            elif op == ">=":
                v = int(v >= r)
            elif op in ("<<", ">>"):
                if r < 0 or r > _MAX_BITS:
                    v = None
                else:
                    v = v << r if op == "<<" else v >> r
            elif op == "+":
                v += r
            elif op == "-":
                v -= r
            elif op == "*":
                v *= r
            elif r == 0:  # / %
                v = None
            else:
                # C truncates toward zero; Python floors
                q, rem = abs(v) // abs(r), abs(v) % abs(r)
                if op == "/":
                    v = q if (v < 0) == (r < 0) else -q
                else:
                    v = rem if v >= 0 else -rem
            v = self._check(v)
        return v

    def _unary(self) -> int | None:
        tok = self._next()
        if tok == "(":
            v = self._ternary()
            if self._next() != ")":
                raise _CondError(")")
            return v
        if tok == "!":
            v = self._unary()
            return None if v is None else int(not v)
        if tok == "~":
            v = self._unary()
            return self._check(None if v is None else ~v)
        if tok == "-":
            v = self._unary()
            return self._check(None if v is None else -v)
        if tok == "+":
            return self._unary()
        if _NUM_TOK.fullmatch(tok):
            if tok[:2].lower() == "0x":
                v = int(tok, 16)
            elif len(tok) > 1 and tok[0] == "0":
                v = int(tok, 8)  # C octal; digits 8/9 raise -> undecidable
            else:
                v = int(tok)
            return self._check(v)
        raise _CondError(tok)


def _eval_expr(expr: str, defines: dict[str, str]) -> bool | None:
    """Evaluate a #if/#elif constant expression; None = undecidable."""
    expr = _DEFINED_RE.sub(
        lambda m: "1" if (m.group(1) or m.group(2)) in defines else "0", expr
    )
    # substitute known object-like macros (one round is enough for the
    # config-flag style expressions these corpora contain), then ISO
    # semantics: remaining identifiers evaluate to 0
    expr = _ID_RE.sub(lambda m: defines.get(m.group(0), "0"), expr)
    expr = _ID_RE.sub("0", expr)
    # integer suffixes are legal C but not part of the literal value
    expr = re.sub(r"(\d)[uUlL]+", r"\1", expr)
    try:
        v = _CondParser(expr).parse()
    except (_CondError, ValueError):
        return None
    return None if v is None else bool(v)


def _visible_text(line: str, in_block: bool) -> tuple[str, bool]:
    """Replace comment interiors with spaces, as translation phase 3 does
    before directive processing (ISO C 5.1.1.2): a ``#if`` inside a
    ``/* */`` block is plain text, not a directive. Returns the visible
    text and the block-comment state after this line. String/char
    literals shield comment openers; ``//`` hides the rest of the line."""
    out: list[str] = []
    i, n = 0, len(line)
    while i < n:
        if in_block:
            j = line.find("*/", i)
            if j == -1:
                return "".join(out), True
            out.append(" ")
            i = j + 2
            in_block = False
            continue
        c = line[i]
        if c in "\"'":
            j = i + 1
            while j < n and line[j] != c:
                j += 2 if line[j] == "\\" else 1
            j = min(j + 1, n)
            out.append(line[i:j])
            i = j
            continue
        if line.startswith("//", i):
            break
        if line.startswith("/*", i):
            in_block = True
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out), in_block


def _expand_macros(line: str, defines: dict[str, str]) -> str:
    """Expand object-like macros outside string/char literals."""
    if not defines:
        return line
    out: list[str] = []
    i, n = 0, len(line)
    while i < n:
        c = line[i]
        if c in "\"'":
            j = i + 1
            while j < n and line[j] != c:
                j += 2 if line[j] == "\\" else 1
            j = min(j + 1, n)
            out.append(line[i:j])
            i = j
            continue
        m = _ID_RE.match(line, i)
        if m:
            out.append(defines.get(m.group(0), m.group(0)))
            i = m.end()
            continue
        out.append(c)
        i += 1
    return "".join(out)


def evaluate_conditionals(code: str) -> str:
    """Resolve #if/#ifdef/#else/#endif regions; blank inactive lines.

    Line count and the content of active lines' positions are preserved,
    so downstream line numbers match the original source.
    """
    # splice continued directive lines (backslash-newline) logically but
    # keep physical structure by tracking how many lines each consumed
    lines = code.split("\n")
    out: list[str] = []
    defines: dict[str, str] = {}
    #: names that are defined (visible to #ifdef / defined()) but must not
    #: be text-expanded: function-like macros and complex object-like
    #: bodies, both left intact as CDT-style recovery
    no_expand: set[str] = set()
    # stack of (this_branch_active, any_branch_taken, parent_active)
    stack: list[list[bool]] = []

    def active() -> bool:
        return all(fr[0] for fr in stack)

    i = 0
    in_block = False  # /* */ state carried across lines
    while i < len(lines):
        line = lines[i]
        visible, next_block = _visible_text(line, in_block)
        if visible.lstrip().startswith("#"):
            # gather continuation lines (phase-2 splicing precedes
            # comment removal, so the backslash check is on raw text)
            full = line
            span = 1
            while full.rstrip().endswith("\\") and i + span < len(lines):
                full = full.rstrip()[:-1] + lines[i + span]
                span += 1
            # directives are parsed on comment-stripped text: `/* */`
            # interiors become spaces, `//` tails drop (phase 3)
            full, next_block = _visible_text(full, in_block)
            m = _DIRECTIVE_RE.match(full.strip())
            name = m.group(1) if m else ""
            rest = (m.group(2) if m else "").strip()
            parent = active()
            if name == "ifdef":
                cond = rest.split()[0] in defines if rest.split() else False
                stack.append([parent and cond, cond, parent])
            elif name == "ifndef":
                cond = rest.split()[0] not in defines if rest.split() else True
                stack.append([parent and cond, cond, parent])
            elif name == "if":
                v = _eval_expr(rest, defines)
                cond = True if v is None else v
                stack.append([parent and cond, cond, parent])
            elif name == "elif" and stack:
                fr = stack[-1]
                if fr[1]:
                    fr[0] = False
                else:
                    v = _eval_expr(rest, defines)
                    cond = True if v is None else v
                    fr[0] = fr[2] and cond
                    fr[1] = cond
            elif name == "else" and stack:
                fr = stack[-1]
                fr[0] = fr[2] and not fr[1]
                fr[1] = True
            elif name == "endif" and stack:
                stack.pop()
            elif name == "define" and parent:
                dm = _DEFINE_RE.match(rest)
                if dm and not dm.group(2):  # object-like
                    body = dm.group(3)
                    if body and _SIMPLE_BODY_RE.match(body):
                        defines[dm.group(1)] = body
                        no_expand.discard(dm.group(1))
                    elif not body:
                        # valueless annotation macro (`#define UNUSED`):
                        # a real preprocessor removes the name from the
                        # token stream, so expand it to nothing
                        defines[dm.group(1)] = ""
                        no_expand.discard(dm.group(1))
                    else:
                        # complex body we cannot safely expand: defined
                        # (for #ifdef) but the name stays visible
                        defines.setdefault(dm.group(1), "")
                        no_expand.add(dm.group(1))
                elif dm:  # function-like: left intact, parses as a call
                    defines.setdefault(dm.group(1), "")
                    no_expand.add(dm.group(1))
            elif name == "undef" and parent:
                nm = rest.split()[0] if rest.split() else ""
                defines.pop(nm, None)
                no_expand.discard(nm)
            # directive lines themselves are blanked (the lexer would
            # skip them anyway; blanking keeps native/python identical)
            for k in range(span):
                out.append("")
            i += span
            in_block = next_block
            continue
        in_block = next_block
        if active():
            out.append(
                _expand_macros(
                    line,
                    {k: v for k, v in defines.items() if k not in no_expand},
                )
            )
        else:
            out.append("")
        i += 1
    return "\n".join(out)
