"""Preprocessor conditional evaluation + object-like macro expansion.

The reference's Joern frontend preprocesses each function text with an
empty predefined-macro table before parsing (Eclipse-CDT semantics under
get_func_graph.sc's importCode); a hermetic frontend that skips directive
LINES but keeps every branch BODY (the round-2 behavior, tokens.py) sees
`#ifdef`/`#else` functions with both branches live — a CPG shape a real
preprocessor can never produce. This pass applies standard C-preprocessor
semantics to the conditional directives only:

- `#if` / `#elif` constant expressions are evaluated with unknown
  identifiers as 0 (ISO C 6.10.1p4), `defined(X)` / `defined X` resolved
  against the file-local `#define` table;
- `#ifdef` / `#ifndef` test that table;
- inactive branch lines are blanked (newlines kept, so line numbers in
  the CPG still match the original source);
- object-like `#define NAME <literal-or-id>` bodies are expanded in
  active code (token-boundary, outside string/char literals), matching
  what the reference's parser sees after real preprocessing. Unknown
  function-like macros are left intact — they parse as plain calls, the
  same recovery CDT applies when a macro definition is unavailable.

Expressions this mini-evaluator cannot decide default to ACTIVE (keep the
code visible) rather than dropping code on a guess.
"""

from __future__ import annotations

import re

_DIRECTIVE_RE = re.compile(r"^\s*#\s*(\w+)(.*)$", re.DOTALL)
_DEFINE_RE = re.compile(r"^\s*(\w+)(\([^)]*\))?\s*(.*?)\s*$", re.DOTALL)
_DEFINED_RE = re.compile(r"\bdefined\s*(?:\(\s*(\w+)\s*\)|(\w+))")
_ID_RE = re.compile(r"\b[A-Za-z_]\w*\b")
_SIMPLE_BODY_RE = re.compile(
    r"^(?:\d[\w.]*|0[xX][0-9a-fA-F]+[uUlL]*|'(?:\\.|[^'])*'|\"(?:\\.|[^\"])*\"|[A-Za-z_]\w*|\([^()]*\))$"
)
_ALLOWED_EVAL = re.compile(r"^[\d\s()+\-*/%<>=!&|^~]*$")


def _eval_expr(expr: str, defines: dict[str, str]) -> bool | None:
    """Evaluate a #if/#elif constant expression; None = undecidable."""
    expr = _DEFINED_RE.sub(
        lambda m: "1" if (m.group(1) or m.group(2)) in defines else "0", expr
    )
    # substitute known object-like macros (one round is enough for the
    # config-flag style expressions these corpora contain), then ISO
    # semantics: remaining identifiers evaluate to 0
    expr = _ID_RE.sub(lambda m: defines.get(m.group(0), "0"), expr)
    expr = _ID_RE.sub("0", expr)
    # integer suffixes confuse eval; drop them
    expr = re.sub(r"(\d)[uUlL]+", r"\1", expr)
    expr = expr.replace("&&", " and ").replace("||", " or ")
    expr = re.sub(r"!(?!=)", " not ", expr)
    if not _ALLOWED_EVAL.match(expr.replace("and", "").replace("or", "").replace("not", "")):
        return None
    import warnings

    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # e.g. "0(1)" SyntaxWarning
            return bool(eval(expr, {"__builtins__": {}}, {}))  # noqa: S307
    except Exception:
        return None


def _expand_macros(line: str, defines: dict[str, str]) -> str:
    """Expand object-like macros outside string/char literals."""
    if not defines:
        return line
    out: list[str] = []
    i, n = 0, len(line)
    while i < n:
        c = line[i]
        if c in "\"'":
            j = i + 1
            while j < n and line[j] != c:
                j += 2 if line[j] == "\\" else 1
            j = min(j + 1, n)
            out.append(line[i:j])
            i = j
            continue
        m = _ID_RE.match(line, i)
        if m:
            out.append(defines.get(m.group(0), m.group(0)))
            i = m.end()
            continue
        out.append(c)
        i += 1
    return "".join(out)


def evaluate_conditionals(code: str) -> str:
    """Resolve #if/#ifdef/#else/#endif regions; blank inactive lines.

    Line count and the content of active lines' positions are preserved,
    so downstream line numbers match the original source.
    """
    # splice continued directive lines (backslash-newline) logically but
    # keep physical structure by tracking how many lines each consumed
    lines = code.split("\n")
    out: list[str] = []
    defines: dict[str, str] = {}
    # stack of (this_branch_active, any_branch_taken, parent_active)
    stack: list[list[bool]] = []

    def active() -> bool:
        return all(fr[0] for fr in stack)

    i = 0
    while i < len(lines):
        line = lines[i]
        stripped = line.lstrip()
        if stripped.startswith("#"):
            # gather continuation lines
            full = line
            span = 1
            while full.rstrip().endswith("\\") and i + span < len(lines):
                full = full.rstrip()[:-1] + lines[i + span]
                span += 1
            m = _DIRECTIVE_RE.match(full.strip())
            name = m.group(1) if m else ""
            rest = (m.group(2) if m else "").strip()
            parent = active()
            if name == "ifdef":
                cond = rest.split()[0] in defines if rest.split() else False
                stack.append([parent and cond, cond, parent])
            elif name == "ifndef":
                cond = rest.split()[0] not in defines if rest.split() else True
                stack.append([parent and cond, cond, parent])
            elif name == "if":
                v = _eval_expr(rest, defines)
                cond = True if v is None else v
                stack.append([parent and cond, cond, parent])
            elif name == "elif" and stack:
                fr = stack[-1]
                if fr[1]:
                    fr[0] = False
                else:
                    v = _eval_expr(rest, defines)
                    cond = True if v is None else v
                    fr[0] = fr[2] and cond
                    fr[1] = cond
            elif name == "else" and stack:
                fr = stack[-1]
                fr[0] = fr[2] and not fr[1]
                fr[1] = True
            elif name == "endif" and stack:
                stack.pop()
            elif name == "define" and parent:
                dm = _DEFINE_RE.match(rest)
                if dm and not dm.group(2):  # object-like only
                    body = dm.group(3)
                    if body and _SIMPLE_BODY_RE.match(body):
                        defines[dm.group(1)] = body
                    else:
                        defines.setdefault(dm.group(1), "")
                elif dm:
                    defines.setdefault(dm.group(1), "")
            elif name == "undef" and parent:
                defines.pop(rest.split()[0] if rest.split() else "", None)
            # directive lines themselves are blanked (the lexer would
            # skip them anyway; blanking keeps native/python identical)
            for k in range(span):
                out.append("")
            i += span
            continue
        if active():
            out.append(
                _expand_macros(line, {k: v for k, v in defines.items() if v})
            )
        else:
            out.append("")
        i += 1
    return "\n".join(out)
