"""C tokenizer for the built-in CPG frontend.

The reference delegates all C parsing to the external Joern JVM
(DDFA/sastvd/helpers/joern_session.py); this framework ships its own
lightweight frontend so the pipeline runs hermetically, with Joern kept as
an optional drop-in backend (frontend/joern_io.py). The lexer handles the
C-function subset that appears in vulnerability datasets: comments, string
and char literals (with escapes), numeric literals (hex/octal/float/suffix),
all multi-char operators, and preprocessor-line skipping.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

KEYWORDS = {
    "auto", "break", "case", "char", "const", "continue", "default", "do",
    "double", "else", "enum", "extern", "float", "for", "goto", "if",
    "inline", "int", "long", "register", "restrict", "return", "short",
    "signed", "sizeof", "static", "struct", "switch", "typedef", "union",
    "unsigned", "void", "volatile", "while", "_Bool", "bool",
}

# longest-first so maximal munch works
OPERATORS = [
    "<<=", ">>=", "...",
    "::",
    "->", "++", "--", "<<", ">>", "<=", ">=", "==", "!=", "&&", "||",
    "+=", "-=", "*=", "/=", "%=", "&=", "^=", "|=",
    "+", "-", "*", "/", "%", "=", "<", ">", "!", "~", "&", "|", "^",
    "?", ":", ".", ",", ";", "(", ")", "[", "]", "{", "}",
]

#: extra multi-char operators per non-C dialect (CodeBLEU structural
#: matching parses java/c_sharp/js/go/php/ruby snippets through the same
#: frontend — eval/codebleu.py). Non-C dialects always lex through the
#: python path, so the native C++ lexer's bit-identical-on-C contract
#: (tests/test_native.py) is untouched.
DIALECT_OPERATORS: dict[str, list[str]] = {
    "c": [],
    "java": [">>>=", ">>>"],
    "cs": ["??=", "?.", "??", "=>"],
    "js": [">>>=", "===", "!==", ">>>", "??=", "**=", "?.", "??", "**", "=>"],
    "go": [":=", "<-", "&^=", "&^"],
    "php": ["===", "!==", "<=>", "?->", "??=", "**=", ".=", "??", "**", "=>"],
    "ruby": ["<=>", "===", "**=", "**", "=~", "!~", "=>", "&.", ".."],
}

#: dialects whose grammar ends statements at line end (Go's automatic
#: semicolon insertion; Ruby's newline termination). A ';' is inserted
#: when a line's last token can end an expression — Go spec §Semicolons:
#: after an identifier, literal, one of break/continue/fallthrough/
#: return, ++/--, or a closing bracket. Trailing binary operators keep
#: the statement open, exactly the rule both languages rely on.
_ASI_DIALECTS = frozenset(("go", "ruby"))
#: keywords that open a construct and therefore keep the line open
#: (ruby `loop do` / `x = if cond`; C-keyword collisions like `do`)
_ASI_CONTINUE_KW = frozenset(
    ("do", "else", "if", "for", "while", "switch", "case", "default",
     "goto", "struct", "union", "enum", "sizeof")
)


def _ends_statement(tok: Token) -> bool:
    if tok.kind in ("num", "str", "char", "id"):
        return True
    if tok.kind == "kw":
        # break/continue/return/`int` (go: `var x int`) end a line;
        # construct-openers don't
        return tok.text not in _ASI_CONTINUE_KW
    return tok.text in (")", "]", "}", "++", "--")


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str  # id | kw | num | str | char | op | eof
    text: str
    line: int
    col: int

    def __repr__(self):
        return f"{self.kind}:{self.text!r}@{self.line}"


class LexError(ValueError):
    pass


def strip_comments(code: str) -> str:
    """Replace comments with spaces, preserving line structure (the
    reference strips comments during dataset cleaning, datasets.py)."""
    out = []
    i, n = 0, len(code)
    while i < n:
        c = code[i]
        if c == "/" and i + 1 < n and code[i + 1] == "/":
            while i < n and code[i] != "\n":
                i += 1
        elif c == "/" and i + 1 < n and code[i + 1] == "*":
            j = code.find("*/", i + 2)
            j = n if j == -1 else j + 2
            # keep newlines so line numbers survive
            out.extend(ch if ch == "\n" else " " for ch in code[i:j])
            i = j
        elif c in "\"'":
            j = i + 1
            while j < n and code[j] != c:
                j += 2 if code[j] == "\\" else 1
            j = min(j + 1, n)
            out.append(code[i:j])
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)


def tokenize(code: str, backend: str = "auto", dialect: str = "c") -> list[Token]:
    """Tokenize C source (or a related-dialect snippet for CodeBLEU).

    backend "auto" routes pure-ASCII input through the native C++ lexer
    when built (bit-identical on ASCII, enforced by tests/test_native.py;
    native Tokens carry col=0). Non-ASCII input always takes the Python
    path, whose unicode identifier handling the native lexer does not
    replicate. "python" forces this implementation.

    dialect selects extra multi-char operators (DIALECT_OPERATORS), php
    `$identifiers`, js template literals, and go/ruby newline semicolon
    insertion; any non-"c" dialect always lexes through the python path.
    """
    if dialect != "c":
        if dialect not in DIALECT_OPERATORS:
            raise ValueError(f"unknown dialect {dialect!r}")
        return _tokenize_python(code, dialect)
    if backend != "python":
        is_ascii = code.isascii()
        if backend == "native" and not is_ascii:
            raise ValueError(
                "native lexer only supports ASCII input; use backend='auto'"
            )
        if is_ascii:
            from deepdfa_tpu import native

            if native.available():
                toks = native.lex_c_native(code)
                toks.append(Token("eof", "", toks[-1].line if toks else 1, 0))
                return toks
            if backend == "native":
                raise RuntimeError(
                    "native backend requested but libdeepdfa_native is "
                    "unavailable; build with `python -m deepdfa_tpu.native.build`"
                )
    return _tokenize_python(code)


def _tokenize_python(code: str, dialect: str = "c") -> list[Token]:
    code = strip_comments(code)
    operators = (
        sorted(DIALECT_OPERATORS[dialect] + OPERATORS, key=len, reverse=True)
        if dialect != "c"
        else OPERATORS
    )
    asi = dialect in _ASI_DIALECTS
    toks: list[Token] = []
    line, col = 1, 1
    i, n = 0, len(code)

    def emit(kind, text, l, c):
        toks.append(Token(kind, text, l, c))

    while i < n:
        c = code[i]
        if c == "\n":
            if asi and toks and _ends_statement(toks[-1]):
                emit("op", ";", line, col)
            line += 1
            col = 1
            i += 1
            continue
        if c in " \t\r\f\v":
            i += 1
            col += 1
            continue
        if c == "#":  # preprocessor directive: skip to end of (continued) line
            while i < n and code[i] != "\n":
                if code[i] == "\\" and i + 1 < n and code[i + 1] == "\n":
                    i += 2
                    line += 1
                else:
                    i += 1
            continue
        start_l, start_c = line, col
        if (
            (
                c == "$"
                and dialect in ("php", "ruby")
                and i + 1 < n
                and (code[i + 1].isalpha() or code[i + 1] == "_")
            )
            or (
                c == "@"
                and dialect == "ruby"
                and i + 1 < n
                and (
                    code[i + 1].isalpha()
                    or code[i + 1] == "_"
                    or code[i + 1] == "@"
                )
            )
        ):
            # php/ruby variables: the sigil ($ / @ / @@) is part of the
            # identifier
            j = i + 1
            if c == "@" and code[j] == "@":
                j += 1
            while j < n and (code[j].isalnum() or code[j] == "_"):
                j += 1
            emit("id", code[i:j], start_l, start_c)
            col += j - i
            i = j
            continue
        if c == "`" and dialect in ("js", "go"):
            # js template literal / go raw string: one opaque string token
            j = i + 1
            while j < n and code[j] != "`":
                if dialect == "js" and code[j] == "\\":
                    j += 1
                if j < n and code[j] == "\n":
                    line += 1
                j += 1
            j = min(j + 1, n)
            emit("str", code[i:j], start_l, start_c)
            col += j - i
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (code[j].isalnum() or code[j] == "_"):
                j += 1
            text = code[i:j]
            emit("kw" if text in KEYWORDS else "id", text, start_l, start_c)
            col += j - i
            i = j
            continue
        if c.isdigit() or (c == "." and i + 1 < n and code[i + 1].isdigit()):
            j = i
            if c == "0" and i + 1 < n and code[i + 1] in "xX":
                j = i + 2
                while j < n and (code[j].isdigit() or code[j] in "abcdefABCDEF"):
                    j += 1
            else:
                while j < n and (
                    code[j].isdigit()
                    or (
                        code[j] == "."
                        # ruby ranges: `1..9` is num op num, never `1..`
                        and not (
                            dialect == "ruby" and code[j : j + 2] == ".."
                        )
                    )
                ):
                    j += 1
                if j < n and code[j] in "eE":  # exponent
                    k = j + 1
                    if k < n and code[k] in "+-":
                        k += 1
                    if k < n and code[k].isdigit():
                        j = k
                        while j < n and code[j].isdigit():
                            j += 1
            while j < n and code[j] in "uUlLfF":
                j += 1
            emit("num", code[i:j], start_l, start_c)
            col += j - i
            i = j
            continue
        if c in "\"'":
            j = i + 1
            while j < n and code[j] != c:
                if code[j] == "\\":
                    j += 1
                if j < n and code[j] == "\n":
                    line += 1
                j += 1
            j = min(j + 1, n)
            emit("str" if c == '"' else "char", code[i:j], start_l, start_c)
            col += j - i
            i = j
            continue
        for op in operators:
            if code.startswith(op, i):
                if (
                    dialect == "ruby"
                    and op in ("?", "!")
                    and toks
                    and toks[-1].kind == "id"
                    and toks[-1].line == start_l
                    and toks[-1].col + len(toks[-1].text) == start_c
                ):
                    # ruby method-name suffixes: `empty?` / `save!` are
                    # one identifier (a spaced `x ? y : z` stays ternary)
                    toks[-1] = Token(
                        "id", toks[-1].text + op, start_l, toks[-1].col
                    )
                else:
                    emit("op", op, start_l, start_c)
                i += len(op)
                col += len(op)
                break
        else:
            # unknown byte (e.g. stray unicode): skip, stay robust
            i += 1
            col += 1
    toks.append(Token("eof", "", line, col))
    return toks


def iter_tokens(code: str) -> Iterator[Token]:
    yield from tokenize(code)
