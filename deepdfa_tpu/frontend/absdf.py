"""Abstract-dataflow feature extraction.

Faithful re-implementation of the reference's two-stage extractor
(DDFA/sastvd/scripts/abstract_dataflow_full.py):

stage 1 — per definition node (CALL with assignment-family name,
is_decl :44-51), collect (subkey, value) fields:
  datatype: recurse the first argument down accessor/cast operators to the
            underlying IDENTIFIER's declared type (:67-121), then clean it
            (strip const, collapse [N] -> [], squeeze spaces, :240-250)
  literal:  code of every LITERAL AST-descendant (:153-154)
  operator: "<operator>.X" descendant call names minus "indirection" (:155-159)
  api:      names of non-operator descendant CALLs (:160-162)
AST descendants skip METHOD subtrees (:136-145).

stage 2 — per node, hash = json dump of {subkey: sorted values} over the
selected subkeys (to_hash :285-295).
"""

from __future__ import annotations

import json
import logging
import re
from typing import Iterable

from deepdfa_tpu.frontend.cpg import Cpg

ALL_SUBKEYS = ("api", "datatype", "literal", "operator")

_ASSIGNMENT_TYPES = frozenset(
    f"<operator>.{op}"
    for op in (
        "assignmentDivision", "assignmentExponentiation", "assignmentPlus",
        "assignmentMinus", "assignmentModulo", "assignmentMultiplication",
        "preIncrement", "preDecrement", "postIncrement", "postDecrement",
        "assignment", "assignmentOr", "assignmentAnd", "assignmentXor",
        "assignmentArithmeticShiftRight", "assignmentLogicalShiftRight",
        "assignmentShiftLeft",
    )
)

# operator name -> which argument (1-based order) holds the variable whose
# datatype we want (reference name_idx, abstract_dataflow_full.py:72-84)
_DATATYPE_ARG_IDX = {
    "<operator>.indirectIndexAccess": 1,
    "<operator>.indirectFieldAccess": 1,
    "<operator>.indirection": 1,
    "<operator>.fieldAccess": 1,
    "<operator>.postIncrement": 1,
    "<operator>.postDecrement": 1,
    "<operator>.preIncrement": 1,
    "<operator>.preDecrement": 1,
    "<operator>.addressOf": 1,
    "<operator>.cast": 2,
    "<operator>.addition": 1,
}


def is_decl(cpg: Cpg, nid: int) -> bool:
    n = cpg.nodes[nid]
    return n.label == "CALL" and n.name in _ASSIGNMENT_TYPES


def clean_datatype(dt: str) -> str:
    """Reference cleanup_datatype (abstract_dataflow_full.py:240-250)."""
    dt = re.sub(r"\s*\[.*\]", "[]", dt)
    dt = re.sub(r"^const ", "", dt)
    dt = re.sub(r"\s+", " ", dt)
    return dt.strip()


def _recurse_datatype(cpg: Cpg, v: int) -> tuple[int, str]:
    """Unhandled shapes RAISE (NotImplementedError / KeyError), exactly like
    the reference (abstract_dataflow_full.py:67-107) — the exception aborts
    decl_features, so the node keeps only fields collected before it."""
    attr = cpg.nodes[v]
    if attr.label == "IDENTIFIER":
        return v, attr.type_full_name
    if attr.label == "CALL" and attr.name in _DATATYPE_ARG_IDX:
        args = {cpg.nodes[a].order: a for a in cpg.successors(v, "ARGUMENT")}
        arg = args[_DATATYPE_ARG_IDX[attr.name]]  # KeyError when absent
        arg_attr = cpg.nodes[arg]
        if arg_attr.label == "IDENTIFIER":
            return arg, arg_attr.type_full_name
        if arg_attr.label == "CALL":
            return _recurse_datatype(cpg, arg)
        raise NotImplementedError(
            f"recurse_datatype index could not handle {arg} {arg_attr}"
        )
    raise NotImplementedError(f"recurse_datatype var could not handle {v} {attr}")


def _raw_datatype(cpg: Cpg, decl: int) -> tuple[int, str]:
    attr = cpg.nodes[decl]
    if attr.label == "LOCAL":
        return decl, attr.type_full_name
    if attr.label == "CALL" and attr.name in _ASSIGNMENT_TYPES | {"<operator>.cast"}:
        args = {cpg.nodes[a].order: a for a in cpg.successors(decl, "ARGUMENT")}
        return _recurse_datatype(cpg, args[1])  # KeyError when no 1st arg
    raise NotImplementedError(f"get_raw_datatype did not handle {decl} {attr}")


def decl_features(cpg: Cpg, nid: int) -> list[tuple[str, str]]:
    """(subkey, value) fields for one definition node.

    Mirrors the reference's grab_declfeats error contract
    (abstract_dataflow_full.py:127-166): any failure — most commonly an
    unhandled LHS shape inside the datatype recursion — aborts collection
    and returns only the fields gathered so far (usually none, since
    datatype comes first). Nodes whose recursion fails therefore get NO
    hash, keeping the feature vocabulary aligned with the reference's.
    """
    fields: list[tuple[str, str]] = []
    try:
        ret = _raw_datatype(cpg, nid)
        if ret is not None:
            _, dt = ret
            if dt is not None:
                fields.append(("datatype", clean_datatype(dt)))
        for d in cpg.ast_descendants(nid, skip_labels=("METHOD",)):
            n = cpg.nodes[d]
            if n.label == "LITERAL":
                fields.append(("literal", n.code))
            elif n.label == "CALL":
                # reference matches '<operator>\.' only: legacy
                # '<operators>.x' names classify as api, not operator
                m = re.match(r"<operator>\.(.*)", n.name)
                if m:
                    if m.group(1) not in ("indirection",):
                        fields.append(("operator", m.group(1)))
                else:
                    fields.append(("api", n.name))
    except Exception:
        # the reference logs and keeps the partial fields ("node error" +
        # traceback, :163-166); debug level so corpus runs aren't flooded —
        # expected failures are NotImplementedError/KeyError from the
        # datatype recursion above
        logging.getLogger(__name__).debug(
            "decl_features aborted for node %s", nid, exc_info=True
        )
    return fields


def node_hash(fields: Iterable[tuple[str, str]], subkeys: Iterable[str] = ALL_SUBKEYS) -> str:
    """stage-2 hash: json of {subkey: sorted values} (reference to_hash).

    Values are NOT de-duplicated (the reference sorts the full list), so
    `x = y + y` and `x = y` hash differently.
    """
    d = {sk: sorted(v for k, v in fields if k == sk) for sk in subkeys}
    return json.dumps(d)


def graph_features(cpg: Cpg) -> dict[int, str]:
    """All definition nodes of a CPG -> stage-2 hash strings."""
    out: dict[int, str] = {}
    for n in cpg.nodes:
        if is_decl(cpg, n.id):
            fields = decl_features(cpg, n.id)
            if fields:
                out[n.id] = node_hash(fields)
    return out
