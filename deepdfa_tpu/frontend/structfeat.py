"""Family-invariant structural node features (VERDICT r4 #3).

The abstract-dataflow subkey features (frontend/absdf.py — the
reference's `_ABS_DATAFLOW_*` definition) are VOCABULARY features: on a
held-out bug family whose API/literal/datatype buckets never appeared in
training, nodes collapse to the UNKNOWN index and the GGNN is left with
nothing but bare graph structure — the round-4 diagnosis for held-out
family F1 0.11 ("the order signal is >5 featureless hops away",
docs/convergence_run_featdrop.json).

These channels are the structural complement: small FIXED vocabularies
derived from the CPG itself, so they are identical in distribution
across bug families and survive UNKNOWN-collapse by construction:

  ch0 op_class   (16) — operator CLASS of the statement's root call
                        (assign / arith / compare / logical / call /
                        access / cast / jump ...), from the Joern
                        operator name, not its identity
  ch1 degree     (16) — (min(cfg_in,3), min(cfg_out,3)) packed — branch
                        and join shape
  ch2 ast_depth   (8) — statement nesting depth, capped
  ch3 du_dist     (8) — CFG hops (backward) to the nearest definition
                        of any variable used at this node, capped 6;
                        7 = none found
  ch4 reach_count (4) — number of DISTINCT reaching definitions of this
                        node's used variables (from the same solver the
                        dataflow labels use), capped 3. This is the
                        order-family signal in local form: a use AFTER
                        a clamp/guard redefinition sees 2 reaching defs
                        where the buggy order sees 1.

The channels append as extra node_feats columns (data/pipeline.py
`extract(struct_feats=True)`); `nn/embedding.py` embeds them with their
own small tables when `ModelConfig.struct_feats` is on. Everything is
computed from the hermetic CPG — no reference counterpart exists (the
reference never attacks cross-family generalization; its paper Table 7
analog is cross-project, where the vocab largely transfers).
"""

from __future__ import annotations

import numpy as np

from deepdfa_tpu.frontend.cpg import AST, CFG, Cpg

#: vocab size per struct channel, in column order
STRUCT_VOCAB: tuple[int, ...] = (16, 16, 8, 8, 4)
NUM_STRUCT_FEATS = len(STRUCT_VOCAB)

_ASSIGN = 1
_ARITH = 2
_COMPARE = 3
_LOGICAL = 4
_CALL = 5
_ACCESS = 6
_CAST = 7
_JUMP = 8
_INCDEC = 9
_COND = 10

_OP_CLASS = {
    "<operator>.assignment": _ASSIGN,
    "<operator>.assignmentPlus": _ASSIGN,
    "<operator>.assignmentMinus": _ASSIGN,
    "<operator>.assignmentMultiplication": _ASSIGN,
    "<operator>.assignmentDivision": _ASSIGN,
    "<operator>.assignmentModulo": _ASSIGN,
    "<operator>.assignmentAnd": _ASSIGN,
    "<operator>.assignmentOr": _ASSIGN,
    "<operator>.assignmentXor": _ASSIGN,
    "<operator>.assignmentShiftLeft": _ASSIGN,
    "<operator>.assignmentArithmeticShiftRight": _ASSIGN,
    "<operator>.addition": _ARITH,
    "<operator>.subtraction": _ARITH,
    "<operator>.multiplication": _ARITH,
    "<operator>.division": _ARITH,
    "<operator>.modulo": _ARITH,
    "<operator>.shiftLeft": _ARITH,
    "<operator>.arithmeticShiftRight": _ARITH,
    "<operator>.and": _ARITH,
    "<operator>.or": _ARITH,
    "<operator>.xor": _ARITH,
    "<operator>.equals": _COMPARE,
    "<operator>.notEquals": _COMPARE,
    "<operator>.lessThan": _COMPARE,
    "<operator>.greaterThan": _COMPARE,
    "<operator>.lessEqualsThan": _COMPARE,
    "<operator>.greaterEqualsThan": _COMPARE,
    "<operator>.logicalAnd": _LOGICAL,
    "<operator>.logicalOr": _LOGICAL,
    "<operator>.logicalNot": _LOGICAL,
    "<operator>.fieldAccess": _ACCESS,
    "<operator>.indirectFieldAccess": _ACCESS,
    "<operator>.indirectIndexAccess": _ACCESS,
    "<operator>.indirection": _ACCESS,
    "<operator>.addressOf": _ACCESS,
    "<operator>.cast": _CAST,
    "<operator>.conditional": _COND,
    "<operator>.preIncrement": _INCDEC,
    "<operator>.postIncrement": _INCDEC,
    "<operator>.preDecrement": _INCDEC,
    "<operator>.postDecrement": _INCDEC,
}

_DU_CAP = 6  # ch3: distances 0..6; 7 = no def found / no vars used
_BFS_VISIT_CAP = 256  # bound the backward walk on pathological graphs


def _op_class(cpg: Cpg, nid: int) -> int:
    n = cpg.nodes[nid]
    if n.label == "RETURN" or n.label == "JUMP_TARGET":
        return _JUMP
    if n.label == "CALL":
        if n.name.startswith("<operator>"):
            return _OP_CLASS.get(n.name, 0)
        return _CALL
    return 0


def _used_vars(cpg: Cpg, nid: int) -> set[str]:
    names = set()
    if cpg.nodes[nid].label == "IDENTIFIER":
        names.add(cpg.nodes[nid].name)
    for d in cpg.ast_descendants(nid, skip_labels=("METHOD",)):
        if cpg.nodes[d].label == "IDENTIFIER":
            names.add(cpg.nodes[d].name)
    return names


def struct_features(cpg: Cpg, keep: list[int]) -> np.ndarray:
    """[len(keep), NUM_STRUCT_FEATS] int32 — channels documented above,
    rows aligned with `keep` (the extraction's dense node order)."""
    from deepdfa_tpu.frontend.reaching import ReachingDefinitions

    keep_set = set(keep)
    n = len(keep)
    out = np.zeros((n, NUM_STRUCT_FEATS), np.int32)

    # ast depth via BFS from the method root over AST edges
    depth: dict[int, int] = {}
    if cpg.method_id is not None:
        frontier = [(cpg.method_id, 0)]
        while frontier:
            nid, d = frontier.pop()
            if nid in depth and depth[nid] <= d:
                continue
            depth[nid] = d
            for c in cpg.successors(nid, AST):
                frontier.append((c, d + 1))

    rd = ReachingDefinitions(cpg)
    try:
        in_sets = rd.solve()
    except Exception:  # solver failure must not cost extraction
        in_sets = {}
    defines: dict[int, str] = {}
    for nid in keep:
        var = rd.assigned_variable(nid)
        if var is not None:
            defines[nid] = var

    used = {nid: _used_vars(cpg, nid) for nid in keep}

    for row, nid in enumerate(keep):
        out[row, 0] = _op_class(cpg, nid)
        indeg = sum(1 for p in cpg.predecessors(nid, CFG) if p in keep_set)
        outdeg = sum(1 for s in cpg.successors(nid, CFG) if s in keep_set)
        out[row, 1] = min(indeg, 3) * 4 + min(outdeg, 3)
        out[row, 2] = min(depth.get(nid, 0), 7)

        vars_here = used[nid]
        if not vars_here:
            out[row, 3] = 7
            continue
        # ch3: backward BFS to the nearest def of a used var
        dist = 7
        frontier = [nid]
        seen = {nid}
        for d in range(_DU_CAP + 1):
            if any(defines.get(f) in vars_here for f in frontier):
                dist = d
                break
            nxt = []
            for f in frontier:
                for p in cpg.predecessors(f, CFG):
                    if p in keep_set and p not in seen:
                        seen.add(p)
                        nxt.append(p)
            if not nxt or len(seen) > _BFS_VISIT_CAP:
                break
            frontier = nxt
        out[row, 3] = dist
        # ch4: distinct reaching defs of the used vars
        reaching = in_sets.get(nid, set())
        out[row, 4] = min(
            sum(1 for d in reaching if d.var in vars_here), 3
        )
    return out
