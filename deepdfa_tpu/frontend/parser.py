"""Recursive-descent C-function parser -> CPG-lite.

Parses a single C/C++ function (the unit of all DeepDFA datasets) into the
Joern-compatible CPG of frontend/cpg.py: expression ASTs with operator CALL
nodes, ARGUMENT edges with operand order, IDENTIFIER type annotation from a
scoped symbol table, and an expression-level CFG (post-order evaluation
chains per statement, branch/loop/switch/goto wiring, METHOD entry and
METHOD_RETURN exit).

Error recovery is Joern-like: statements that fail to parse become opaque
UNKNOWN nodes that still occupy their place in the CFG, so one weird line
never loses a whole function.
"""

from __future__ import annotations

from deepdfa_tpu.frontend import cpg as C
from deepdfa_tpu.frontend.tokens import Token, tokenize

TYPE_KEYWORDS = {
    "void", "char", "short", "int", "long", "float", "double", "signed",
    "unsigned", "_Bool", "bool", "struct", "union", "enum", "const",
    "volatile", "static", "register", "auto", "extern", "inline", "restrict",
    "typedef",
}

ASSIGN_OPS = {"=", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "<<=", ">>="}

# binary precedence (higher binds tighter); assignment/conditional handled
# separately (right-assoc)
BIN_PREC = {
    "||": 1, "&&": 2, "|": 3, "^": 4, "&": 5,
    "==": 6, "!=": 6,
    "<": 7, ">": 7, "<=": 7, ">=": 7,
    "<<": 8, ">>": 8,
    "+": 9, "-": 9,
    "*": 10, "/": 10, "%": 10,
}

# --- non-C dialect surface (CodeBLEU structural matching parses java/
# c_sharp/js/go/php/ruby generation snippets through this same frontend;
# everything here is gated on Parser.dialect so C/C++ behavior — the
# fidelity-tested dataset path — is bit-identical to before) -----------

#: extra punctuation binary operators per dialect (token must be lexed by
#: tokens.DIALECT_OPERATORS)
DIALECT_BIN_PREC: dict[str, dict[str, int]] = {
    "java": {">>>": 8},
    "cs": {"??": 1},
    "js": {"===": 6, "!==": 6, ">>>": 8, "**": 11, "??": 1},
    "go": {"&^": 5, "<-": 1},
    "php": {"===": 6, "!==": 6, "<=>": 6, ".": 9, "**": 11, "??": 1},
    "ruby": {"===": 6, "<=>": 6, "**": 11, "=~": 6, "!~": 6, "..": 6,
             "...": 6},
}

#: identifier-spelled binary operators (`o instanceof Foo`, `o is Foo`)
DIALECT_WORD_BINOPS: dict[str, dict[str, int]] = {
    "java": {"instanceof": 7},
    "cs": {"is": 7, "as": 10},
    "php": {"instanceof": 7, "and": 1, "or": 1, "xor": 1},
    "ruby": {"and": 1, "or": 1},
}

#: extra assignment operators per dialect; go's := IS a definition (its
#: call name must stay <operator>.assignment so the reaching-defs solver
#: and the abstract-dataflow extractor see the def)
DIALECT_ASSIGN_OPS: dict[str, set[str]] = {
    "java": {">>>="},
    "cs": {"??="},
    "js": {"**=", ">>>=", "??="},
    "go": {":=", "&^="},
    "php": {".=", "**=", "??="},
    "ruby": {"**="},
}

#: joern-style call names for operators OP_NAMES doesn't cover
EXTRA_OP_NAMES = {
    "instanceof": "<operator>.instanceOf",
    "is": "<operator>.instanceOf",
    "as": "<operator>.cast",
    "??": "<operator>.nullCoalesce",
    "===": "<operator>.identityEquals",
    "!==": "<operator>.identityNotEquals",
    ">>>": "<operator>.logicalShiftRight",
    "**": "<operator>.exponentiation",
    "&^": "<operator>.andNot",
    "<-": "<operator>.channelSend",
    ".": "<operator>.concat",
    "<=>": "<operator>.spaceship",
    "=~": "<operator>.match",
    "!~": "<operator>.notMatch",
    "..": "<operator>.range",
    "...": "<operator>.rangeExclusive",
    "and": "<operator>.logicalAnd",
    "or": "<operator>.logicalOr",
    "xor": "<operator>.logicalXor",
    ":=": None,  # filled below: plain assignment (definition semantics)
    "**=": "<operator>.assignmentExponentiation",
    ">>>=": "<operator>.assignmentLogicalShiftRight",
    ".=": "<operator>.assignmentConcat",
    "??=": "<operator>.assignmentNullCoalesce",
    "&^=": "<operator>.assignmentAndNot",
}


class ParseError(ValueError):
    pass


class _Scope:
    def __init__(self, parent=None):
        self.parent = parent
        self.vars: dict[str, str] = {}

    def lookup(self, name: str) -> str | None:
        s = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return None


# ---------------------------------------------------------------------------
# statement tree (intermediate, only for CFG construction)


class _Stmt:
    pass


class _Expr(_Stmt):
    def __init__(self, top: int | None):
        self.top = top  # CPG node id of the expression root (None = empty)


class _Seq(_Stmt):
    def __init__(self, body: list[_Stmt]):
        self.body = body


class _If(_Stmt):
    def __init__(self, cond: _Expr, then: _Stmt, els: _Stmt | None):
        self.cond, self.then, self.els = cond, then, els


class _While(_Stmt):
    def __init__(self, cond: _Expr, body: _Stmt):
        self.cond, self.body = cond, body


class _DoWhile(_Stmt):
    def __init__(self, body: _Stmt, cond: _Expr):
        self.body, self.cond = body, cond


class _For(_Stmt):
    def __init__(self, init, cond, update, body):
        self.init, self.cond, self.update, self.body = init, cond, update, body


class _Switch(_Stmt):
    #: cases: (is_default, label_code e.g. "case 0"/"default", line, body),
    #: in source order
    def __init__(self, cond: _Expr, cases: list[tuple[bool, str, int | None, _Stmt]], has_default: bool):
        self.cond, self.cases, self.has_default = cond, cases, has_default


class _Return(_Stmt):
    def __init__(self, expr: _Expr | None, node: int):
        self.expr, self.node = expr, node


class _Break(_Stmt):
    def __init__(self, line: int | None = None):
        self.line = line


class _Continue(_Stmt):
    def __init__(self, line: int | None = None):
        self.line = line


class _Goto(_Stmt):
    def __init__(self, label: str, node: int):
        self.label, self.node = label, node


class _Label(_Stmt):
    def __init__(self, name: str, line: int | None = None):
        self.name = name
        self.line = line


class _Try(_Stmt):
    #: handlers: (catch_node_id, body) per catch clause
    def __init__(self, body: _Stmt, handlers: list[tuple[int, _Stmt]]):
        self.body, self.handlers = body, handlers


class _Throw(_Stmt):
    def __init__(self, node: int):
        self.node = node


class _RangeFor(_Stmt):
    #: C++ range-for: `for (decl : expr) body`; expr_top is the per-
    #: iteration assignment call at the for line
    def __init__(self, expr: _Expr, body: _Stmt):
        self.expr, self.body = expr, body


# ---------------------------------------------------------------------------


class Parser:
    def __init__(self, code: str, dialect: str = "c"):
        self.dialect = dialect
        if dialect == "c":
            from deepdfa_tpu.frontend.preproc import evaluate_conditionals

            # resolve #if/#ifdef regions + expand file-local object macros
            # BEFORE lexing (shared pre-pass, so the native and python
            # lexers stay bit-identical); line structure is preserved
            self.toks = tokenize(evaluate_conditionals(code))
        else:
            # non-C dialects have no C preprocessor; the lexer handles
            # their extra operators / sigils / newline semicolons
            self.toks = tokenize(code, backend="python", dialect=dialect)
        self.i = 0
        self.cpg: C.Cpg | None = None
        self.scope = _Scope()
        self._bin_prec = dict(BIN_PREC, **DIALECT_BIN_PREC.get(dialect, {}))
        self._word_binops = DIALECT_WORD_BINOPS.get(dialect, {})
        self._assign_ops = ASSIGN_OPS | DIALECT_ASSIGN_OPS.get(dialect, set())

    @staticmethod
    def _op_name(op: str) -> str:
        if op in C.OP_NAMES:
            return C.OP_NAMES[op]
        name = EXTRA_OP_NAMES[op]
        return name if name is not None else C.OP_NAMES["="]  # := defines

    # -- token helpers -------------------------------------------------------

    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def at(self, text: str, k: int = 0) -> bool:
        t = self.peek(k)
        return t.text == text and t.kind in ("op", "kw")

    def eat(self, text: str | None = None) -> Token:
        t = self.peek()
        if text is not None and t.text != text:
            raise ParseError(f"expected {text!r}, got {t!r}")
        self.i += 1
        return t

    def at_eof(self) -> bool:
        return self.peek().kind == "eof"

    # -- type parsing --------------------------------------------------------

    def _at_type_start(self) -> bool:
        t = self.peek()
        if t.kind == "id" and self._at_new_delete():
            return False  # `delete p;` / `new T` statements are expressions
        if t.kind == "kw" and t.text in TYPE_KEYWORDS:
            return True
        # `Foo * bar` / `Foo bar` / `a::b::Foo* bar` typedef heuristic:
        # (possibly qualified) id, optional template args, then stars/refs,
        # then an id followed by a declarator-ish token
        if t.kind == "id":
            k = 1
            while self.peek(k).text == "::" and self.peek(k + 1).kind == "id":
                k += 2
            if self.peek(k).text == "<":
                k2 = self._match_angle(k)
                if k2 is not None:
                    k = k2
            while self.peek(k).text in ("*", "&"):
                k += 1
            if self.dialect in ("java", "cs"):
                # array types: `String[] parts = ...`, `int[][] grid`
                bracketed = False
                while (
                    self.peek(k).text == "[" and self.peek(k + 1).text == "]"
                ):
                    k += 2
                    bracketed = True
                if bracketed and self.peek(k).kind == "id":
                    return True
            nxt = self.peek(k)
            if nxt.kind == "id" and k > 0:
                after = self.peek(k + 1)
                if after.text in (";", "=", ",", "[", ")"):
                    return True
        return False

    # tokens that cannot occur in a template argument list: their presence
    # means the '<' was a comparison (e.g. `a < b && c > d;`)
    _NOT_TEMPLATE = frozenset(
        ("&&", "||", "==", "!=", "<=", ">=", "!", "+", "-", "/", "%", "?",
         "=", "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "<<=", ">>=")
    )

    def _match_angle(self, k: int) -> int | None:
        """If peek(k) is '<' opening a plausible template argument list,
        return the offset just past the matching '>'; else None."""
        if self.peek(k).text != "<":
            return None
        depth = 0
        limit = k + 64
        while k < limit:
            t = self.peek(k)
            if (
                t.kind == "eof"
                or t.kind in ("str", "char")
                or t.text in (";", "{", "}")
                or t.text in self._NOT_TEMPLATE
            ):
                return None
            if t.text == "<":
                depth += 1
            elif t.text == ">":
                depth -= 1
                if depth == 0:
                    return k + 1
            elif t.text == ">>":
                depth -= 2
                if depth <= 0:
                    return k + 1
            k += 1
        return None

    @staticmethod
    def _join_type_tokens(toks: list[str]) -> str:
        """Join type tokens, spacing word-word boundaries (unsigned long)."""
        out = ""
        prev_word = False
        for t in toks:
            word = bool(t) and (t[0].isalpha() or t[0] == "_")
            if out and prev_word and word:
                out += " "
            out += t
            prev_word = word
        return out

    def _eat_angle_args(self) -> str:
        """Consume a balanced <...> run (pre-validated by _match_angle);
        returns its text incl. brackets."""
        end = self._match_angle(0)
        if end is None:
            return ""
        return self._join_type_tokens([self.eat().text for _ in range(end)])

    def _eat_qualified_name(self) -> str:
        """id(::id)* with optional trailing template args -> one name."""
        name = self.eat().text
        while self.at("::") and self.peek(1).kind == "id":
            self.eat()
            name += "::" + self.eat().text
        if self._match_angle(0) is not None:
            name += self._eat_angle_args()
        return name

    _QUALIFIERS = frozenset(
        ("const", "volatile", "static", "register", "auto", "extern",
         "inline", "restrict", "typedef")
    )

    def _parse_type(self, in_params: bool = False) -> str:
        """Consume type specifier tokens; return canonical type string.

        in_params: parameter lists have no initializers, so a bare id
        before ','/')' IS the type (`void f(Foo)`), whereas in statement
        position it is the declarator name (`static x = 1;`)."""
        parts: list[str] = []

        def saw_base() -> bool:
            return any(p not in self._QUALIFIERS for p in parts)

        while True:
            t = self.peek()
            if t.kind == "kw" and t.text in TYPE_KEYWORDS:
                if t.text in ("struct", "union", "enum"):
                    parts.append(self.eat().text)
                    if self.peek().kind == "id":
                        parts.append(self.eat().text)
                    # inline body {...}: skip it
                    if self.at("{"):
                        depth = 0
                        while True:
                            tt = self.eat()
                            if tt.text == "{":
                                depth += 1
                            elif tt.text == "}":
                                depth -= 1
                                if depth == 0:
                                    break
                            if tt.kind == "eof":
                                break
                    continue
                parts.append(self.eat().text)
                continue
            if t.kind == "id" and t.text == "decltype" and self.peek(1).text == "(":
                # C++ decltype(expr) as a type atom: keep the token text,
                # skip the parenthesized expression
                self.eat()
                depth = 0
                while not self.at_eof():
                    tt = self.eat()
                    if tt.text == "(":
                        depth += 1
                    elif tt.text == ")":
                        depth -= 1
                        if depth == 0:
                            break
                parts.append("decltype(...)")
                continue
            if t.kind == "id" and not saw_base():
                # don't eat the declarator NAME as a base type: plain id
                # directly followed by a declarator terminator is the
                # variable of an implicit-int decl (`static x = 1;`)
                if not in_params and self.peek(1).text in ("=", ";", ",", ")", "["):
                    if not (
                        self.dialect in ("java", "cs")
                        and self.peek(1).text == "["
                        and self.peek(2).text == "]"
                    ):
                        # java/c# `String[] x`: the brackets belong to
                        # the TYPE, so the id IS the base — fall through
                        break
                parts.append(self._eat_qualified_name())
                continue
            break
        base = " ".join(p for p in parts if p not in self._QUALIFIERS)
        return base or "ANY"

    def _parse_declarator(self, base: str) -> tuple[str | None, str]:
        """Parse `*|& name [dims]` -> (name, full type string)."""
        stars = 0
        while (
            self.at("*")
            or self.at("&")
            or (
                self.peek().kind == "kw"
                and self.peek().text in ("const", "restrict", "volatile")
            )
        ):
            if self.at("*"):
                stars += 1
            self.eat()  # '&' references keep the base type, like joern
        name = None
        if self.peek().kind == "id":
            name = self.eat().text
        arrays = 0
        while self.at("["):
            depth = 0
            while True:
                t = self.eat()
                if t.text == "[":
                    depth += 1
                elif t.text == "]":
                    depth -= 1
                    if depth == 0:
                        break
                if t.kind == "eof":
                    break
            arrays += 1
        if (
            name is None
            and arrays
            and self.dialect in ("java", "cs")
            and self.peek().kind == "id"
        ):
            # java/c# spell the brackets on the TYPE: `int[] x`
            name = self.eat().text
        full = base + "*" * stars + "[]" * arrays
        return name, full

    # -- expressions ---------------------------------------------------------

    def _node(self, label, name="", code="", line=None, type_full_name="ANY"):
        return self.cpg.add_node(
            label, name=name, code=code, line=line, type_full_name=type_full_name
        )

    def _call(self, name: str, code: str, line: int, args: list[int]) -> int:
        nid = self._node("CALL", name=name, code=code, line=line)
        for order, a in enumerate(args, start=1):
            self.cpg.nodes[a].order = order
            self.cpg.add_edge(nid, a, C.AST)
            self.cpg.add_edge(nid, a, C.ARGUMENT)
        return nid

    def _code(self, nid: int) -> str:
        return self.cpg.nodes[nid].code

    def _looks_like_cast(self) -> bool:
        """At '(' — is this `(type) expr`?"""
        if not self.at("("):
            return False
        k = 1
        t = self.peek(k)
        if t.kind == "kw" and t.text in TYPE_KEYWORDS:
            pass
        elif t.kind == "id":
            # (Foo*)x or (Foo)x — require '*' or ')' right after the id,
            # and the token after ')' must start an expression
            k2 = k + 1
            stars = 0
            while self.peek(k2).text == "*":
                stars += 1
                k2 += 1
            if self.peek(k2).text != ")":
                return False
            nxt = self.peek(k2 + 1)
            if stars == 0 and self.dialect in ("java", "cs"):
                # `(Foo)o` object casts are everywhere in java/c#; in C
                # a star-less id cast stays ambiguous with `(expr)`, so
                # this path is dialect-gated and requires an unambiguous
                # expression starter after ')' (no + - * & which would
                # misread `(a) + b`)
                return nxt.kind in ("id", "num", "str", "char") or nxt.text in (
                    "(", "!", "~",
                )
            return stars > 0 and (
                nxt.kind in ("id", "num", "str", "char")
                or nxt.text in ("(", "*", "&", "!", "~", "-", "+", "++", "--")
            )
        else:
            return False
        return True

    def parse_expression(self) -> int:
        return self._parse_comma()

    def _parse_comma(self) -> int:
        first = self._parse_assign()
        if not self.at(","):
            return first
        items = [first]
        line = self.cpg.nodes[first].line
        while self.at(","):
            self.eat()
            items.append(self._parse_assign())
        code = ", ".join(self._code(x) for x in items)
        return self._call(C.COMMA, code, line, items)

    def _parse_assign(self) -> int:
        lhs = self._parse_conditional()
        t = self.peek()
        if self.at("=>") and self.dialect in ("cs", "js", "php", "ruby"):
            # c#/js lambda `x => body` / `(a, b) => { ... }`; php/ruby
            # use the same token for key=>value pairs
            self.eat()
            line = self.cpg.nodes[lhs].line
            if self.at("{"):
                depth = 0
                texts: list[str] = []
                while not self.at_eof():
                    tok = self.eat()
                    texts.append(tok.text)
                    if tok.text == "{":
                        depth += 1
                    elif tok.text == "}":
                        depth -= 1
                        if depth == 0:
                            break
                body = self._node(
                    "UNKNOWN", code=" ".join(texts), line=line
                )
            else:
                body = self._parse_assign()
            name = (
                "<operator>.lambda"
                if self.dialect in ("cs", "js")
                else "<operator>.keyValue"
            )
            code = f"{self._code(lhs)} => {self._code(body)}"
            return self._call(name, code, line, [lhs, body])
        if t.kind == "op" and t.text in self._assign_ops:
            op = self.eat().text
            rhs = self._parse_assign()
            code = f"{self._code(lhs)} {op} {self._code(rhs)}"
            return self._call(
                self._op_name(op), code, self.cpg.nodes[lhs].line, [lhs, rhs]
            )
        return lhs

    def _parse_conditional(self) -> int:
        cond = self._parse_binary(1)
        if not self.at("?"):
            return cond
        self.eat("?")
        then = self._parse_assign()
        self.eat(":")
        els = self._parse_conditional()
        code = f"{self._code(cond)} ? {self._code(then)} : {self._code(els)}"
        return self._call(
            C.CONDITIONAL, code, self.cpg.nodes[cond].line, [cond, then, els]
        )

    def _parse_binary(self, min_prec: int) -> int:
        lhs = self._parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op":
                prec = self._bin_prec.get(t.text)
            elif t.kind == "id":
                # identifier-spelled operators (instanceof / is / as ...)
                prec = self._word_binops.get(t.text)
            else:
                prec = None
            if prec is None or prec < min_prec:
                return lhs
            op = self.eat().text
            if op in ("instanceof", "is", "as") and self.peek().kind in (
                "id", "kw"
            ):
                # RHS is a TYPE, not an expression: `o instanceof Foo`,
                # `x as List<T>`, `o is System.IDisposable` — consume a
                # dot- or ::-qualified, possibly generic type name
                if self.peek().kind == "id":
                    ty = self._eat_qualified_name()
                    while self.at(".") and self.peek(1).kind == "id":
                        self.eat()
                        ty += "." + self._eat_qualified_name()
                else:
                    ty = self.eat().text
                rhs = self._node(
                    "TYPE_REF", code=ty, line=t.line, type_full_name=ty
                )
            else:
                rhs = self._parse_binary(prec + 1)
            code = f"{self._code(lhs)} {op} {self._code(rhs)}"
            lhs = self._call(
                self._op_name(op), code, self.cpg.nodes[lhs].line, [lhs, rhs]
            )

    def _parse_unary(self) -> int:
        t = self.peek()
        if self.dialect == "go" and t.kind == "op" and t.text == "<-":
            self.eat()
            operand = self._parse_unary()
            return self._call(
                "<operator>.channelReceive", f"<-{self._code(operand)}",
                t.line, [operand],
            )
        if t.kind == "op" and t.text in ("++", "--"):
            self.eat()
            operand = self._parse_unary()
            code = f"{t.text}{self._code(operand)}"
            return self._call(C.PRE_INC_DEC[t.text], code, t.line, [operand])
        if t.kind == "op" and t.text in ("!", "~", "-", "+", "*", "&"):
            self.eat()
            operand = self._parse_unary()
            code = f"{t.text}{self._code(operand)}"
            return self._call(C.UNARY_OP_NAMES[t.text], code, t.line, [operand])
        if t.kind == "kw" and t.text == "sizeof":
            self.eat()
            if self.at("("):
                # sizeof(type) or sizeof(expr): consume balanced parens
                depth = 0
                texts = []
                while True:
                    tt = self.eat()
                    texts.append(tt.text)
                    if tt.text == "(":
                        depth += 1
                    elif tt.text == ")":
                        depth -= 1
                        if depth == 0:
                            break
                    if tt.kind == "eof":
                        break
                inner = " ".join(texts[1:-1])
                arg = self._node("UNKNOWN", code=inner, line=t.line)
                return self._call(C.SIZEOF, f"sizeof({inner})", t.line, [arg])
            operand = self._parse_unary()
            return self._call(
                C.SIZEOF, f"sizeof {self._code(operand)}", t.line, [operand]
            )
        if t.kind == "id" and self._at_new_delete():
            return self._parse_new_delete()
        if self._looks_like_cast():
            lp = self.eat("(")
            # in_params mode: the type is followed by ')' (a declarator
            # terminator), which statement mode refuses to consume
            base = self._parse_type(in_params=True)
            stars = 0
            while self.at("*"):
                self.eat()
                stars += 1
            self.eat(")")
            ty = base + "*" * stars
            operand = self._parse_unary()
            # joern cast: arg 1 = TYPE_REF, arg 2 = expression
            tref = self._node("TYPE_REF", code=ty, line=lp.line, type_full_name=ty)
            code = f"({ty}) {self._code(operand)}"
            return self._call(C.CAST, code, lp.line, [tref, operand])
        return self._parse_postfix()

    def _at_new_delete(self) -> bool:
        """Is this C++ operator new/delete (vs. 'new' as a plain C
        identifier, legal and common in old C code)?"""
        t = self.peek()
        if t.kind != "id" or t.text not in ("new", "delete"):
            return False
        nxt = self.peek(1)
        if t.text == "delete":
            # delete[] p / delete p — but not `delete(x)` C calls or
            # `delete->field` / `delete = x` identifier uses
            return (nxt.text == "[" and self.peek(2).text == "]") or (
                nxt.kind == "id"
            )
        # new <type-ish>: a type keyword, or an id that heads a type
        if nxt.kind == "kw" and nxt.text in TYPE_KEYWORDS:
            return True
        if nxt.kind == "id":
            after = self.peek(2)
            return after.text in ("(", "[", ";", ")", ",", "*", "::", "<")
        return False

    def _parse_new_delete(self) -> int:
        """C++ new/delete as joern-style operator calls."""
        t = self.eat()  # 'new' | 'delete'
        if t.text == "delete":
            arr = ""
            if self.at("[") and self.peek(1).text == "]":
                self.eat()
                self.eat()
                arr = "[]"
            operand = self._parse_unary()
            code = f"delete{arr} {self._code(operand)}"
            return self._call("<operator>.delete", code, t.line, [operand])
        # new Type, new Type(args), new Type[n] — class-name types are
        # consumed as qualified names (the statement-position terminator
        # guard in _parse_type would refuse `Obj` before ';'/'[')
        if self.peek().kind == "id":
            base = self._eat_qualified_name()
        else:
            base = self._parse_type(in_params=True)
        stars = 0
        while self.at("*"):
            self.eat()
            stars += 1
        ty = base + "*" * stars
        tref = self._node("TYPE_REF", code=ty, line=t.line, type_full_name=ty)
        args = [tref]
        code = f"new {ty}"
        if self.at("("):
            self.eat("(")
            while not self.at(")") and not self.at_eof():
                args.append(self._parse_assign())
                if self.at(","):
                    self.eat()
            if self.at(")"):
                self.eat(")")
            code += "(...)"
        elif self.at("["):
            self.eat("[")
            size = self.parse_expression()
            if self.at("]"):
                self.eat("]")
            args.append(size)
            code = f"new {ty}[{self._code(size)}]"
        return self._call("<operator>.new", code, t.line, args)

    def _parse_call_arg(self) -> int:
        """One call argument; c# tolerates `out x` / `ref x` modifiers and
        `out T x` inline declarations. An `out` argument is a WRITE: it
        becomes a synthetic `name = *(out)` assignment call (like the
        foreach desugaring) so reaching-defs sees the def; `ref` stays a
        plain read (it is read-write, and the read is what dataflow
        triples key on)."""
        t = self.peek()
        if (
            self.dialect == "cs"
            and t.kind == "id"
            and t.text in ("out", "ref", "params")
            and self.peek(1).kind in ("id", "kw")
        ):
            mod = self.eat().text
            nxt = self.peek()
            name = None
            if nxt.kind == "kw" or (
                nxt.kind == "id" and self.peek(1).kind == "id"
            ):
                # inline declaration: `out int n` / `out var n`
                base = self._parse_type(in_params=True)
                name, full = self._parse_declarator(base)
                if name is None:
                    return self._node("UNKNOWN", code=base, line=nxt.line)
                self.scope.vars[name] = full
                self._node(
                    "LOCAL", name=name, code=f"{full} {name}",
                    line=nxt.line, type_full_name=full,
                )
                ident = self._node(
                    "IDENTIFIER", name=name, code=name, line=nxt.line,
                    type_full_name=full,
                )
            else:
                ident = self._parse_assign()
                node = self.cpg.nodes[ident]
                if node.label == "IDENTIFIER":
                    name = node.name
            if mod == "out" and name is not None:
                src = self._node("UNKNOWN", code="out", line=nxt.line)
                return self._call(
                    C.OP_NAMES["="], f"{name} = *(out)", nxt.line,
                    [ident, src],
                )
            return ident
        return self._parse_assign()

    def _parse_postfix(self) -> int:
        node = self._parse_primary()
        while True:
            t = self.peek()
            if self.at("("):
                # function call: node must be an identifier or expression
                self.eat("(")
                args = []
                if not self.at(")"):
                    args.append(self._parse_call_arg())
                    while self.at(","):
                        self.eat()
                        args.append(self._parse_call_arg())
                self.eat(")")
                callee = self.cpg.nodes[node]
                fname = callee.name if callee.label == "IDENTIFIER" else self._code(node)
                code = f"{fname}({', '.join(self._code(a) for a in args)})"
                # joern: the callee identifier is not an argument; drop the
                # identifier node for direct calls and name the CALL after it
                call = self._call(fname, code, callee.line or t.line, args)
                node = call
            elif self.at("["):
                self.eat("[")
                idx = self.parse_expression()
                self.eat("]")
                code = f"{self._code(node)}[{self._code(idx)}]"
                node = self._call(
                    C.INDEX_ACCESS, code, self.cpg.nodes[node].line, [node, idx]
                )
            elif (
                (self.at(".") and self.dialect != "php")  # php '.' = concat
                or self.at("->")
                or self.at("?.")   # c#/js null-conditional access
                or self.at("?->")  # php nullsafe access
                or self.at("&.")   # ruby safe navigation
            ):
                op = self.eat().text
                fld = self.eat()
                fid = self._node("FIELD_IDENTIFIER", name=fld.text, code=fld.text, line=fld.line)
                code = f"{self._code(node)}{op}{fld.text}"
                name = (
                    C.FIELD_ACCESS
                    if op in (".", "?.", "&.")
                    else C.INDIRECT_FIELD_ACCESS
                )
                node = self._call(name, code, self.cpg.nodes[node].line, [node, fid])
            elif t.kind == "op" and t.text in ("++", "--"):
                self.eat()
                code = f"{self._code(node)}{t.text}"
                node = self._call(
                    C.POST_INC_DEC[t.text], code, self.cpg.nodes[node].line, [node]
                )
            else:
                return node

    _CXX_CASTS = ("static_cast", "dynamic_cast", "reinterpret_cast", "const_cast")

    def _parse_array_literal(self, line: int | None) -> int:
        """js/php/ruby `[e1, e2, ...]` -> arrayInitializer call."""
        self.eat("[")
        args: list[int] = []
        while not self.at("]") and not self.at_eof():
            if self.at("..."):
                self.eat()
            args.append(self._parse_assign())
            if self.at(","):
                self.eat()
        if self.at("]"):
            self.eat()
        return self._call("<operator>.arrayInitializer", "[...]", line, args)

    def _parse_object_literal(self, line: int | None) -> int:
        """js `{k: v, m, ...}` / ruby `{k => v}` -> keyValue calls under
        an objectInitializer call."""
        self.eat("{")
        pairs: list[int] = []
        while not self.at("}") and not self.at_eof():
            if self.at("..."):
                self.eat()
                pairs.append(self._parse_assign())
            else:
                key = self._parse_assign()
                if self.at(":"):
                    self.eat()
                    val = self._parse_assign()
                    pairs.append(
                        self._call(
                            "<operator>.keyValue",
                            f"{self._code(key)}: {self._code(val)}",
                            line, [key, val],
                        )
                    )
                else:
                    pairs.append(key)  # shorthand property / hash-rocket
            if self.at(","):
                self.eat()
        if self.at("}"):
            self.eat()
        return self._call("<operator>.objectInitializer", "{...}", line, pairs)

    #: identifier-spelled unary operators per dialect
    _WORD_UNARY = {
        "js": {"typeof": "<operator>.typeOf", "await": "<operator>.await"},
        "cs": {"await": "<operator>.await"},
        "ruby": {"not": "<operator>.logicalNot"},
        "php": {"print": "print", "clone": "<operator>.clone"},
        "go": {"defer": "defer", "go": "go"},
    }

    def _parse_primary(self) -> int:
        t = self.peek()
        if self.dialect in ("js", "php", "ruby") and self.at("["):
            return self._parse_array_literal(t.line)
        if self.dialect in ("js", "ruby") and self.at("{"):
            return self._parse_object_literal(t.line)
        word_unary = self._WORD_UNARY.get(self.dialect, {})
        if (
            t.kind == "id"
            and t.text in word_unary
            and (
                self.peek(1).kind in ("id", "num", "str", "char")
                or self.peek(1).text in ("(", "[", "!", "-", "+", "~")
            )
        ):
            self.eat()
            operand = self._parse_unary()
            return self._call(
                word_unary[t.text], f"{t.text} {self._code(operand)}",
                t.line, [operand],
            )
        if (
            t.kind == "id"
            and (
                (self.dialect in ("js", "php") and t.text == "function")
                or (self.dialect == "go" and t.text == "func")
            )
            and self.peek(1).text in ("(", "*")
        ):
            # anonymous function expression: consume balanced params and
            # body into one opaque node (nested functions are out of the
            # per-function CPG's scope, like joern's nested-method stubs)
            self.eat()
            texts: list[str] = []
            depth = 0
            saw_body = False
            while not self.at_eof():
                tok = self.eat()
                texts.append(tok.text)
                if tok.text in ("(", "{"):
                    depth += 1
                    saw_body = saw_body or tok.text == "{"
                elif tok.text in (")", "}"):
                    depth -= 1
                    if depth == 0 and saw_body:
                        break
            return self._node(
                "UNKNOWN", code="function " + " ".join(texts), line=t.line
            )
        if t.kind == "id":
            if t.text in self._CXX_CASTS and self._match_angle(1) is not None:
                # static_cast<T>(expr) -> joern-style cast call
                self.eat()
                angle = self._eat_angle_args()
                ty = angle[1:-1]  # strip the outer <>
                self.eat("(")
                operand = self.parse_expression()
                self.eat(")")
                tref = self._node("TYPE_REF", code=ty, line=t.line, type_full_name=ty)
                code = f"{t.text}<{ty}>({self._code(operand)})"
                return self._call(C.CAST, code, t.line, [tref, operand])
            name = t.text
            self.eat()
            while self.at("::") and self.peek(1).kind == "id":
                self.eat()
                name += "::" + self.eat().text
            ty = self.scope.lookup(name) or "ANY"
            return self._node(
                "IDENTIFIER", name=name, code=name, line=t.line, type_full_name=ty
            )
        if t.kind == "num":
            self.eat()
            return self._node("LITERAL", code=t.text, line=t.line)
        if t.kind in ("str", "char"):
            self.eat()
            return self._node("LITERAL", code=t.text, line=t.line)
        if self.at("("):
            self.eat("(")
            inner = self.parse_expression()
            self.eat(")")
            return inner
        if t.kind == "kw" and t.text in ("true", "false"):
            self.eat()
            return self._node("LITERAL", code=t.text, line=t.line)
        if (
            self.dialect == "ruby"
            and self.at(":")
            and self.peek(1).kind in ("id", "str")
        ):
            # ruby symbol literal `:name` / `:"quoted"`
            self.eat()
            sym = self.eat()
            return self._node("LITERAL", code=f":{sym.text}", line=t.line)
        if (
            t.kind == "kw"
            and self.dialect in ("java", "cs", "js")
            and self.peek(1).text == "."
        ):
            # type keywords as receivers: `int.TryParse`, `long.MaxValue`
            self.eat()
            return self._node(
                "IDENTIFIER", name=t.text, code=t.text, line=t.line,
                type_full_name="ANY",
            )
        raise ParseError(f"unexpected token {t!r}")

    # -- statements ----------------------------------------------------------

    def _skip_to_semicolon(self) -> None:
        depth = 0
        while not self.at_eof():
            t = self.peek()
            if t.text in ("(", "[", "{"):
                depth += 1
            elif t.text in (")", "]", "}"):
                if depth == 0:
                    return
                depth -= 1
            elif t.text == ";" and depth == 0:
                self.eat()
                return
            self.eat()

    def parse_statement(self) -> _Stmt:
        t = self.peek()
        start = self.i
        try:
            stmt = self._parse_statement_inner()
        except ParseError:
            # error recovery: opaque UNKNOWN node occupying CFG position
            self._skip_to_semicolon()
            node = self._node("UNKNOWN", code="<parse error>", line=t.line)
            stmt = _Expr(node)
        if self.i == start and not self.at_eof():
            # no progress (e.g. `volatile(...)` gnu-ism): consume defensively
            self._skip_to_semicolon()
            if self.i == start:
                self.eat()
        return stmt

    def _parse_statement_inner(self) -> _Stmt:
        if self.dialect == "ruby":
            return self._parse_ruby_statement()
        t = self.peek()
        if self.at(";"):
            self.eat()
            return _Expr(None)
        if self.at("{"):
            return self._parse_block()
        if t.kind == "kw":
            if t.text == "if":
                return self._parse_if()
            if t.text == "while":
                return self._parse_while()
            if t.text == "do":
                return self._parse_do()
            if t.text == "for":
                return self._parse_for()
            if t.text == "switch":
                return self._parse_switch()
            if t.text == "return":
                self.eat()
                expr = None
                if not self.at(";"):
                    expr = _Expr(self.parse_expression())
                if self.at(";"):
                    self.eat()
                code = "return" + (f" {self._code(expr.top)}" if expr and expr.top is not None else "")
                node = self._node("RETURN", name="return", code=code, line=t.line)
                if expr and expr.top is not None:
                    self.cpg.add_edge(node, expr.top, C.AST)
                    self.cpg.add_edge(node, expr.top, C.ARGUMENT)
                    self.cpg.nodes[expr.top].order = 1
                return _Return(expr, node)
            if t.text == "break":
                self.eat()
                if self.at(";"):
                    self.eat()
                return _Break(t.line)
            if t.text == "continue":
                self.eat()
                if self.at(";"):
                    self.eat()
                return _Continue(t.line)
            if t.text == "goto":
                self.eat()
                label = self.eat().text
                if self.at(";"):
                    self.eat()
                node = self._node(
                    "CONTROL_STRUCTURE", name="goto",
                    code=f"goto {label};", line=t.line,
                )
                return _Goto(label, node)
        # C++ statement keywords are plain identifiers to the C lexer
        if t.kind == "id" and t.text == "try" and (
            self.peek(1).text == "{"
            or (self.dialect in ("java", "cs") and self.peek(1).text == "(")
        ):
            return self._parse_try()
        # c#/php iteration + resource statements (dialect-gated: in C these
        # spellings stay expression-statements, e.g. foreach() macros)
        if t.kind == "id" and self.peek(1).text == "(":
            if t.text == "foreach" and self.dialect in ("cs", "php"):
                return self._parse_foreach()
            if t.text in ("using", "lock", "fixed") and self.dialect == "cs":
                return self._parse_resource_stmt()
        # php keyword statements taking a bare expression list (reference
        # tree-sitter: echo_statement / global_declaration / ...)
        if (
            self.dialect == "php"
            and t.kind == "id"
            and t.text in ("echo", "global", "unset", "require",
                           "require_once", "include", "include_once")
            and not self.at(";", 1)
        ):
            self.eat()
            expr = self.parse_expression()
            node = self._call(
                t.text, f"{t.text} {self._code(expr)}", t.line, [expr]
            )
            if self.at(";"):
                self.eat()
            return _Expr(node)
        if t.kind == "id" and t.text == "throw":
            self.eat()
            if not self.at(";"):
                expr = self.parse_expression()
            else:
                expr = None
            if self.at(";"):
                self.eat()
            node = self._node(
                "CONTROL_STRUCTURE", name="throw",
                code="throw"
                + (f" {self._code(expr)};" if expr is not None else ";"),
                line=t.line,
            )
            if expr is not None:
                self.cpg.add_edge(node, expr, C.AST)
                self.cpg.add_edge(node, expr, C.ARGUMENT)
                self.cpg.nodes[expr].order = 1
            return _Throw(node)
        # label: `name:` followed by statement
        if t.kind == "id" and self.peek(1).text == ":" and self.peek(2).text != ":":
            self.eat()
            self.eat(":")
            return _Seq([_Label(t.text, t.line), self.parse_statement()])
        if self.dialect == "go":
            if t.kind == "id" and t.text == "var":
                return self._parse_go_var()
            ma = self._try_go_multi_assign()
            if ma is not None:
                if self.at(";"):
                    self.eat()
                return _Expr(ma)
        if self._at_type_start():
            return self._parse_declaration()
        # expression statement
        expr = self.parse_expression()
        if self.at(";"):
            self.eat()
        return _Expr(expr)

    def _try_go_multi_assign(self) -> int | None:
        """go `a, b := f(x)` / `x, y = y, x`: every LHS name is a
        definition. Returns the desugared call (or None when the
        lookahead is not a multi-name assignment — single-name `a := 1`
        already flows through _parse_assign)."""
        k = 0
        names: list[str] = []
        while True:
            if self.peek(k).kind != "id":
                return None
            names.append(self.peek(k).text)
            nxt = self.peek(k + 1).text
            if nxt == ",":
                k += 2
                continue
            if nxt in (":=", "=") and len(names) >= 2:
                op_k = k + 1
                break
            return None
        line = self.peek().line
        for _ in range(op_k):
            self.eat()
        op = self.eat().text
        rhs = self.parse_expression()
        calls: list[int] = []
        for i, nm in enumerate(names):
            if op == ":=":
                self.scope.vars[nm] = "ANY"
                self._node(
                    "LOCAL", name=nm, code=nm, line=line,
                    type_full_name="ANY",
                )
            ident = self._node(
                "IDENTIFIER", name=nm, code=nm, line=line,
                type_full_name=self.scope.lookup(nm) or "ANY",
            )
            # one AST parent per node: the first assignment owns the rhs
            src = (
                rhs
                if i == 0
                else self._node("UNKNOWN", code=self._code(rhs), line=line)
            )
            calls.append(
                self._call(
                    C.OP_NAMES["="], f"{nm} {op} {self._code(rhs)}",
                    line, [ident, src],
                )
            )
        if len(calls) == 1:
            return calls[0]
        return self._call(
            C.COMMA, ", ".join(self._code(x) for x in calls), line, calls
        )

    def _parse_go_var(self) -> _Stmt:
        """go `var x Type [= expr]` / `var x, y = a, b` — definitions with
        postfix types."""
        start = self.eat()  # 'var'
        names: list[str] = []
        while self.peek().kind == "id":
            names.append(self.eat().text)
            if self.at(","):
                self.eat()
            else:
                break
        # optional type tokens up to '=' / ';' at depth 0
        ty_toks: list[str] = []
        depth = 0
        while not self.at_eof():
            tt = self.peek()
            if tt.text in ("(", "["):
                depth += 1
            elif tt.text in (")", "]"):
                if depth == 0:
                    break
                depth -= 1
            elif depth == 0 and (tt.text in ("=", ";", "{") or tt.kind == "eof"):
                break
            ty_toks.append(self.eat().text)
        ty = self._join_type_tokens(ty_toks) or "ANY"
        stmts: list[_Stmt] = []
        rhs = None
        if self.at("="):
            self.eat()
            rhs = self.parse_expression()  # `var x, y = a, b` comma list
        for i, nm in enumerate(names):
            self.scope.vars[nm] = ty
            self._node(
                "LOCAL", name=nm, code=f"{ty} {nm}", line=start.line,
                type_full_name=ty,
            )
            if rhs is not None:
                ident = self._node(
                    "IDENTIFIER", name=nm, code=nm, line=start.line,
                    type_full_name=ty,
                )
                src = (
                    rhs
                    if i == 0
                    else self._node(
                        "UNKNOWN", code=self._code(rhs), line=start.line
                    )
                )
                stmts.append(
                    _Expr(
                        self._call(
                            C.OP_NAMES["="],
                            f"{nm} = {self._code(rhs)}",
                            start.line, [ident, src],
                        )
                    )
                )
        if self.at(";"):
            self.eat()
        return _Seq(stmts)

    def _parse_try(self) -> _Stmt:
        """`try { body } catch (param) { handler }...` — Joern keeps try/
        catch as CONTROL_STRUCTURE nodes; at line level the handlers are
        alternative paths entered via a `catch` node at the clause line.
        java/c# try-with-resources declarations become initializer
        statements ahead of the body; a `finally` block continues after."""
        self.eat()  # 'try'
        init: _Stmt | None = None
        if self.at("(") and self.dialect in ("java", "cs"):
            self.eat("(")
            inits: list[_Stmt] = []
            while not self.at(")") and not self.at_eof():
                if self._at_type_start():
                    inits.append(self._parse_declaration(expect_semicolon=False))
                else:
                    inits.append(_Expr(self.parse_expression()))
                if self.at(";"):
                    self.eat()
            if self.at(")"):
                self.eat(")")
            init = _Seq(inits)
        body = self._parse_block()
        if init is not None:
            body = _Seq([init, body])
        handlers: list[tuple[int, _Stmt]] = []
        while self.peek().kind == "id" and self.peek().text == "catch":
            kw = self.eat()
            param_code = ""
            if self.at("("):
                depth = 0
                toks = []
                while not self.at_eof():
                    tok = self.eat()
                    if tok.text == "(":
                        depth += 1
                        if depth == 1:
                            continue
                    if tok.text == ")":
                        depth -= 1
                        if depth == 0:
                            break
                    toks.append(tok.text)
                param_code = " ".join(toks)
            node = self._node(
                "CONTROL_STRUCTURE", name="catch",
                code=f"catch ({param_code})", line=kw.line,
            )
            handlers.append((node, self.parse_statement()))
        tr: _Stmt = _Try(body, handlers)
        if (
            self.peek().kind == "id"
            and self.peek().text == "finally"
            and self.peek(1).text == "{"
        ):
            self.eat()
            tr = _Seq([tr, self._parse_block()])
        return tr

    def _parse_block(self) -> _Stmt:
        self.eat("{")
        self.scope = _Scope(self.scope)
        body = []
        while not self.at("}") and not self.at_eof():
            body.append(self.parse_statement())
        if self.at("}"):
            self.eat()
        self.scope = self.scope.parent
        return _Seq(body)

    def _parse_paren_expr(self) -> _Expr:
        self.eat("(")
        e = self.parse_expression()
        self.eat(")")
        return _Expr(e)

    def _parse_if(self) -> _Stmt:
        self.eat("if")
        if self.dialect == "go" and not self.at("("):
            # `if [init;] cond { }` — paren-less, optional init statement
            init = self._try_go_multi_assign()
            first = None if init is not None else self.parse_expression()
            cond: _Expr
            if self.at(";"):
                self.eat()
                if init is None:
                    init = first
                cond = _Expr(self.parse_expression())
            else:
                cond = _Expr(first) if first is not None else _Expr(None)
            then = self.parse_statement()
            els = None
            if self.at("else"):
                self.eat()
                els = self.parse_statement()
            node: _Stmt = _If(cond, then, els)
            if init is not None:
                node = _Seq([_Expr(init), node])
            return node
        cond = self._parse_paren_expr()
        then = self.parse_statement()
        els = None
        if self.at("else"):
            self.eat()
            els = self.parse_statement()
        return _If(cond, then, els)

    def _parse_while(self) -> _Stmt:
        self.eat("while")
        cond = self._parse_paren_expr()
        body = self.parse_statement()
        return _While(cond, body)

    def _parse_do(self) -> _Stmt:
        self.eat("do")
        body = self.parse_statement()
        if self.at("while"):
            self.eat("while")
            cond = self._parse_paren_expr()
        else:
            cond = _Expr(None)
        if self.at(";"):
            self.eat()
        return _DoWhile(body, cond)

    def _at_range_for(self) -> bool:
        """After `for (` — does a ':' appear before the first ';' at
        depth 0 (C++ range-for)? `::` qualifiers don't count."""
        depth = 0
        quest = 0  # pending ternary '?'s — their ':' is not a range-for
        k = 0
        while True:
            t = self.peek(k)
            if t.kind == "eof" or t.text in (";", "{"):
                return False
            if t.text in ("(", "["):
                depth += 1
            elif t.text in (")", "]"):
                if depth == 0:
                    return False
                depth -= 1
            elif t.text == "?" and depth == 0:
                quest += 1
            elif t.text == ":" and depth == 0:
                if quest:
                    quest -= 1
                else:
                    return True
            k += 1

    def _bind_loop_var(
        self, name: str, full: str, rng: int, line: int | None
    ) -> int:
        """LOCAL + per-iteration `name = *(range)` assignment call
        (Joern's iterator desugaring) — the shared definition-site
        desugar for range-for / foreach / js for-in."""
        self.scope.vars[name] = full
        self._node(
            "LOCAL", name=name, code=f"{full} {name}", line=line,
            type_full_name=full,
        )
        ident = self._node(
            "IDENTIFIER", name=name, code=name, line=line,
            type_full_name=full,
        )
        return self._call(
            C.OP_NAMES["="], f"{name} = *({self._code(rng)})", line,
            [ident, rng],
        )

    def _parse_range_for(self) -> _Stmt:
        """`for (T x : expr) body` — per-iteration assignment at the for
        line (Joern's iterator desugaring yields an `<operator>.
        assignment` there), body loops back to it."""
        start = self.peek()
        base = self._parse_type()
        name, full = self._parse_declarator(base)
        if name is None:
            raise ParseError("range-for declarator")
        self.eat(":")
        rng = self.parse_expression()
        call = self._bind_loop_var(name, full, rng, start.line)
        self.eat(")")
        body = self.parse_statement()
        self.scope = self.scope.parent
        return _RangeFor(_Expr(call), body)

    def _parse_foreach(self) -> _Stmt:
        """c#: `foreach (T x in expr) body`; php: `foreach (expr as $v)` /
        `foreach (expr as $k => $v) body`. Same desugaring as the C++
        range-for: per-iteration assignment call(s) at the foreach line,
        body looping back."""
        start = self.eat()  # 'foreach'
        self.eat("(")
        self.scope = _Scope(self.scope)

        def bind(name: str, full: str, rng: int) -> int:
            return self._bind_loop_var(name, full, rng, start.line)

        if self.dialect == "php":
            rng = self.parse_expression()
            if not (self.peek().kind == "id" and self.peek().text == "as"):
                raise ParseError("foreach without 'as'")
            self.eat()
            first = self.eat().text  # $k or $v
            calls = []
            if self.at("=>"):
                self.eat()
                value = self.eat().text
                # the key var reads from its own node: one AST parent each
                key_src = self._node(
                    "UNKNOWN", code=self._code(rng), line=start.line
                )
                calls.append(bind(first, "ANY", key_src))
                calls.append(bind(value, "ANY", rng))
            else:
                calls.append(bind(first, "ANY", rng))
            top = (
                calls[0]
                if len(calls) == 1
                else self._call(
                    C.COMMA,
                    ", ".join(self._code(x) for x in calls),
                    start.line,
                    calls,
                )
            )
        else:
            base = self._parse_type()
            name, full = self._parse_declarator(base)
            if name is None or not (
                self.peek().kind == "id" and self.peek().text == "in"
            ):
                raise ParseError("foreach declarator")
            self.eat()  # 'in'
            rng = self.parse_expression()
            top = bind(name, full, rng)
        self.eat(")")
        body = self.parse_statement()
        self.scope = self.scope.parent
        return _RangeFor(_Expr(top), body)

    def _parse_resource_stmt(self) -> _Stmt:
        """c# `using (decl|expr) body` / `lock (expr) body` /
        `fixed (decl) body`: initializer then body (the resource
        acquisition is the dataflow-relevant part; the release is
        implicit and has no CFG seam at function granularity)."""
        self.eat()  # using/lock/fixed
        self.eat("(")
        self.scope = _Scope(self.scope)
        if self._at_type_start():
            init = self._parse_declaration(expect_semicolon=False)
        else:
            init = _Expr(self.parse_expression())
        if self.at(")"):
            self.eat(")")
        body = self.parse_statement()
        self.scope = self.scope.parent
        return _Seq([init, body])

    def _at_js_for_in(self) -> bool:
        """After `for (` — js `for (x of xs)` / `for (var k in obj)`:
        an `of`/`in` identifier at depth 0 before the first ';'."""
        depth = 0
        k = 0
        while True:
            t = self.peek(k)
            if t.kind == "eof" or t.text in (";", "{"):
                return False
            if t.text in ("(", "["):
                depth += 1
            elif t.text in (")", "]"):
                if depth == 0:
                    return False
                depth -= 1
            elif t.kind == "id" and t.text in ("of", "in") and depth == 0:
                return True
            k += 1

    def _parse_js_for_in(self) -> _Stmt:
        """`for ([var|let|const] x of|in expr) body` — same desugaring as
        the range-for: per-iteration assignment at the for line."""
        start = self.peek()
        if self.peek().kind in ("id", "kw") and self.peek().text in (
            "var", "let", "const",
        ):
            self.eat()
        if self.peek().kind != "id":
            raise ParseError("for-in declarator")
        name = self.eat().text
        self.eat()  # 'of' | 'in'
        rng = self.parse_expression()
        call = self._bind_loop_var(name, "ANY", rng, start.line)
        self.eat(")")
        body = self.parse_statement()
        self.scope = self.scope.parent
        return _RangeFor(_Expr(call), body)

    def _parse_go_for(self) -> _Stmt:
        """Paren-less go for: `for {}` / `for cond {}` /
        `for init; cond; post {}` / `for [i[, v]] := range xs {}`."""
        self.scope = _Scope(self.scope)
        start = self.peek()
        if self.at("{"):
            body = self.parse_statement()
            self.scope = self.scope.parent
            return _For(None, None, None, body)
        # range-scan: `range` id at depth 0 before '{'
        has_range = False
        has_semi = False
        depth = 0
        k = 0
        while True:
            t = self.peek(k)
            if t.kind == "eof" or (t.text == "{" and depth == 0):
                break
            if t.text in ("(", "["):
                depth += 1
            elif t.text in (")", "]"):
                depth -= 1
            elif depth == 0 and t.kind == "id" and t.text == "range":
                has_range = True
            elif depth == 0 and t.text == ";":
                has_semi = True
            k += 1
        if has_range:
            names: list[str] = []
            while self.peek().kind == "id" and self.peek().text != "range":
                names.append(self.eat().text)
                if self.at(","):
                    self.eat()
            if self.at(":=") or self.at("="):
                self.eat()
            if self.peek().text == "range":
                self.eat()
            rng = self.parse_expression()
            calls: list[int] = []
            for i, nm in enumerate(names):
                if nm == "_":
                    continue
                src = (
                    rng
                    if not calls
                    else self._node(
                        "UNKNOWN", code=self._code(rng), line=start.line
                    )
                )
                calls.append(
                    self._bind_loop_var(nm, "ANY", src, start.line)
                )
            if calls:
                top = (
                    calls[0]
                    if len(calls) == 1
                    else self._call(
                        C.COMMA,
                        ", ".join(self._code(x) for x in calls),
                        start.line, calls,
                    )
                )
                expr = _Expr(top)
            else:  # `for range xs` — the range expr still evaluates
                expr = _Expr(rng)
            body = self.parse_statement()
            self.scope = self.scope.parent
            return _RangeFor(expr, body)
        if has_semi:
            init: _Stmt | None = None
            if not self.at(";"):
                ma = self._try_go_multi_assign()
                init = _Expr(ma if ma is not None else self.parse_expression())
            if self.at(";"):
                self.eat()
            cond = None
            if not self.at(";"):
                cond = _Expr(self.parse_expression())
            if self.at(";"):
                self.eat()
            update = None
            if not self.at("{"):
                ma = self._try_go_multi_assign()
                update = _Expr(
                    ma if ma is not None else self.parse_expression()
                )
            body = self.parse_statement()
            self.scope = self.scope.parent
            return _For(init, cond, update, body)
        cond = _Expr(self.parse_expression())
        body = self.parse_statement()
        self.scope = self.scope.parent
        return _While(cond, body)

    def _parse_for(self) -> _Stmt:
        self.eat("for")
        if self.dialect == "go" and not self.at("("):
            return self._parse_go_for()
        self.eat("(")
        self.scope = _Scope(self.scope)
        if self.dialect == "js" and self._at_js_for_in():
            return self._parse_js_for_in()
        if self._at_range_for():
            return self._parse_range_for()
        init: _Stmt | None = None
        if not self.at(";"):
            if self._at_type_start():
                init = self._parse_declaration(expect_semicolon=True)
            else:
                init = _Expr(self.parse_expression())
                self.eat(";")
        else:
            self.eat(";")
        cond = None
        if not self.at(";"):
            cond = _Expr(self.parse_expression())
        self.eat(";")
        update = None
        if not self.at(")"):
            update = _Expr(self.parse_expression())
        self.eat(")")
        body = self.parse_statement()
        self.scope = self.scope.parent
        return _For(init, cond, update, body)

    def _parse_switch(self) -> _Stmt:
        self.eat("switch")
        if self.dialect == "go" and not self.at("("):
            # `switch [init;] [tag] { ... }` — any clause optional
            cond = _Expr(None)
            if not self.at("{"):
                ma = self._try_go_multi_assign()
                first = ma if ma is not None else self.parse_expression()
                if self.at(";"):
                    self.eat()
                    if not self.at("{"):
                        cond = _Expr(self.parse_expression())
                else:
                    cond = _Expr(first)
        else:
            cond = self._parse_paren_expr()
        self.eat("{")
        cases: list[tuple[bool, str, int | None, _Stmt]] = []
        has_default = False
        cur: list[_Stmt] | None = None
        cur_is_default = False
        cur_label, cur_line = "", None
        while not self.at("}") and not self.at_eof():
            if self.at("case"):
                if cur is not None:
                    cases.append((cur_is_default, cur_label, cur_line, _Seq(cur)))
                kw = self.eat("case")
                # consume the constant expression up to ':'
                const_toks = []
                while not self.at(":") and not self.at_eof():
                    const_toks.append(self.eat().text)
                self.eat(":")
                cur = []
                cur_is_default = False
                cur_label = "case " + " ".join(const_toks)
                cur_line = kw.line
                continue
            if self.at("default"):
                if cur is not None:
                    cases.append((cur_is_default, cur_label, cur_line, _Seq(cur)))
                kw = self.eat("default")
                self.eat(":")
                cur = []
                cur_is_default = True
                cur_label, cur_line = "default", kw.line
                has_default = True
                continue
            stmt = self.parse_statement()
            if cur is None:
                cur = []
            cur.append(stmt)
        if cur is not None:
            cases.append((cur_is_default, cur_label, cur_line, _Seq(cur)))
        if self.at("}"):
            self.eat()
        return _Switch(cond, cases, has_default)

    def _parse_declaration(self, expect_semicolon: bool = True) -> _Stmt:
        start = self.peek()
        base = self._parse_type()
        stmts: list[_Stmt] = []
        while True:
            name, full = self._parse_declarator(base)
            if name is None:
                break
            self.scope.vars[name] = full
            self._node(
                "LOCAL", name=name, code=f"{full} {name}", line=start.line,
                type_full_name=full,
            )
            if self.at("="):
                self.eat("=")
                ident = self._node(
                    "IDENTIFIER", name=name, code=name, line=start.line,
                    type_full_name=full,
                )
                # brace initializer: Joern models `T a[] = {..}` as an
                # assignment whose RHS is <operator>.arrayInitializer, so
                # the declaration still yields a definition node
                if self.at("{"):
                    rhs = (
                        self._parse_object_literal(start.line)
                        if self.dialect in ("js", "ruby")
                        else self._parse_brace_init(start.line)
                    )
                else:
                    rhs = self._parse_assign()
                code = f"{name} = {self._code(rhs)}"
                call = self._call(
                    C.OP_NAMES["="], code, start.line, [ident, rhs]
                )
                stmts.append(_Expr(call))
            if self.at(","):
                self.eat()
                continue
            break
        if expect_semicolon and self.at(";"):
            self.eat()
        return _Seq(stmts)

    def _parse_brace_init(self, line: int | None) -> int:
        """`{ e1, e2, {..}, ... }` -> <operator>.arrayInitializer CALL
        whose arguments are the element expressions (nested braces
        recurse). Designators (`[0] = x`, `.f = y`) parse via the normal
        assignment expression path."""
        self.eat("{")
        args: list[int] = []
        while not self.at("}") and not self.at_eof():
            if self.at("{"):
                args.append(self._parse_brace_init(line))
            else:
                args.append(self._parse_assign())
            if self.at(","):
                self.eat()
        if self.at("}"):
            self.eat()
        return self._call(
            "<operator>.arrayInitializer", "{...}", line, args
        )

    # -- function ------------------------------------------------------------

    #: Java method modifiers (id tokens, not C keywords) tolerated ahead
    #: of the return type so CONCODE-style generated methods parse
    #: (eval/codebleu.py lang="java"); `static`/`final` style C/C++
    #: qualifiers are handled by _parse_type itself
    _JAVA_MODIFIERS = frozenset(
        ("public", "private", "protected", "abstract", "synchronized",
         "native", "strictfp", "transient", "final")
    )
    #: c# adds its own id-spelled modifier set (dialect-gated: in C these
    #: could be attribute macros, which have their own recovery path)
    _CS_MODIFIERS = _JAVA_MODIFIERS | frozenset(
        ("virtual", "override", "sealed", "internal", "readonly",
         "unsafe", "async", "partial", "new")
    )

    def parse_function(self) -> C.Cpg:
        """Parse `ret_type name(params) { body }` — C, the common C++
        method shapes (template preamble, qualified Foo::bar names,
        reference parameters), and Java/C# method signatures (modifiers,
        `<T>` type-parameter lists, `throws`/`where` clauses)."""
        if self.dialect == "go" and self.peek().text == "func":
            return self._parse_go_function()
        if self.dialect == "ruby":
            if self.peek().text != "def":
                # bare statements: raise so the snippet wrapper (`def
                # __snippet__ ... end`) gets its turn in eval/codebleu
                raise ParseError(f"expected 'def', got {self.peek()!r}")
            return self._parse_ruby_function()
        if self.dialect in ("js", "php") and (
            self.peek().text in ("function", "async")
            or (self.peek().text in ("public", "private", "protected",
                                     "static", "final", "abstract")
                and self.dialect == "php")
        ):
            # php methods carry modifiers before `function`
            while (
                self.dialect == "php"
                and self.peek().kind in ("id", "kw")
                and self.peek().text != "function"
            ):
                self.eat()
            return self._parse_script_function()
        modifiers = (
            self._CS_MODIFIERS if self.dialect == "cs" else self._JAVA_MODIFIERS
        )
        while (
            self.peek().kind == "id"
            and self.peek().text in modifiers
            and self.peek(1).kind in ("id", "kw")
        ):
            self.eat()
        # optional template preamble: template <typename T, ...>
        if self.peek().kind == "id" and self.peek().text == "template":
            self.eat()
            end = self._match_angle(0)
            if end is not None:
                for _ in range(end):
                    self.eat()
        # Java generic method type parameters: `<T> T first(List<T> xs)`;
        # a `static` directly before `<` would otherwise be consumed by
        # _parse_type after the angle group it belongs in front of
        if (
            self.peek().kind == "kw"
            and self.peek().text in ("static", "inline")
            and self.peek(1).text == "<"
        ):
            self.eat()
        if self.at("<"):
            end = self._match_angle(0)
            if end is not None:
                for _ in range(end):
                    self.eat()
        # signature
        sig_start = self.peek()
        base = self._parse_type()
        stars = 0
        while self.at("*") or self.at("&"):
            if self.at("*"):
                stars += 1
            self.eat()
        if self.dialect in ("java", "cs"):
            # array return types: `public int[] toArray()`
            while self.at("[") and self.peek(1).text == "]":
                self.eat()
                self.eat()
                base += "[]"
        if self.at("(") and base not in ("", "ANY"):
            # constructor: `Foo::Foo(...)` — the "return type" IS the name
            fname = base
            base = "void"
        elif self.at("::") and self.peek(1).text == "~":
            # destructor: `Foo::~Foo(...)`
            self.eat()
            self.eat()
            fname = base + "::~" + (self.eat().text if self.peek().kind == "id" else "")
            base = "void"
        elif self.peek().kind != "id":
            raise ParseError(f"expected function name, got {self.peek()!r}")
        else:
            fname = self.eat().text
            # attribute-macro recovery: real-world signatures carry
            # unknown annotation macros (`IMATH_HOSTDEVICE inline T
            # name(`, `static __inline__ __u8 *name(`) that _parse_type
            # consumed as the base type, leaving the TYPE in fname's
            # slot. Gather the id/*/& soup up to '('; the LAST
            # identifier is the function name, the rest is type — the
            # same recovery CDT applies to unexpanded macros. (operator
            # overloads keep their op tokens for the handler below.)
            def _soup_tok() -> bool:
                t = self.peek()
                return (
                    t.kind == "id"
                    or t.text in ("*", "&")
                    # `__fortify_function __wur char *gets(`: keyword
                    # type specifiers can FOLLOW the attribute macros
                    # (qualifiers are a subset of TYPE_KEYWORDS)
                    or (t.kind == "kw" and t.text in TYPE_KEYWORDS)
                )

            if fname != "operator" and _soup_tok():
                soup = [fname]
                while _soup_tok():
                    tok = self.eat().text
                    soup.append(tok)
                    if tok == "operator":
                        # `MYMACRO Vec operator*(`: the overload's op
                        # token belongs to the handler below, not soup
                        break
                id_positions = [
                    k for k, t in enumerate(soup) if t not in ("*", "&")
                ]
                fname = soup[id_positions[-1]]
                extra = [
                    t for k, t in enumerate(soup) if k != id_positions[-1]
                ]
                if extra:
                    prefix = "" if base in ("", "ANY") else base + " "
                    base = prefix + " ".join(extra)
            while self.at("::") and self.peek(1).kind in ("id", "op"):
                self.eat()
                if self.at("~"):  # destructor
                    self.eat()
                    fname += "::~" + self.eat().text
                else:
                    fname += "::" + self.eat().text
            if fname.split("::")[-1] == "operator":
                # operator overloads: operator== / operator[] / operator()
                if self.at("(") and self.peek(1).text == ")":
                    self.eat()
                    self.eat()
                    fname += "()"
                elif self.at("[") and self.peek(1).text == "]":
                    self.eat()
                    self.eat()
                    fname += "[]"
                else:
                    while self.peek().kind == "op" and not self.at("("):
                        fname += self.eat().text
        if (
            self.dialect in ("java", "cs")
            and self.at("<")
            and self._match_angle(0) is not None
        ):
            self._eat_angle_args()  # generic method: `T Get<T>(...)`
        self.cpg = C.Cpg(fname)
        ret_type = base + "*" * stars
        method = self.cpg.add_node(
            "METHOD", name=fname, code=fname, line=sig_start.line,
            type_full_name=ret_type,
        )
        self.cpg.method_id = method
        self.eat("(")
        self.scope = _Scope()
        order = 1
        while not self.at(")") and not self.at_eof():
            if self.at("void") and self.peek(1).text == ")":
                self.eat()
                break
            if self.at("..."):
                self.eat()
                break
            param_start = self.i
            pbase = self._parse_type(in_params=True)
            pname, pfull = self._parse_declarator(pbase)
            if pname is None and self.i == param_start or not (
                self.at(",") or self.at(")")
            ):
                # unparsed declarator (function pointer, etc.): skip balanced
                # tokens to the next top-level ',' or ')'; salvage the last
                # identifier seen as the parameter name
                depth = 0
                last_id = None
                while not self.at_eof():
                    t = self.peek()
                    if t.text == "(" or t.text == "[":
                        depth += 1
                    elif t.text == ")" or t.text == "]":
                        if depth == 0:
                            break
                        depth -= 1
                    elif t.text == "," and depth == 0:
                        break
                    if t.kind == "id":
                        last_id = t.text
                    self.eat()
                if pname is None and last_id is not None:
                    pname, pfull = last_id, pbase + "*"
            if pname is not None:
                self.scope.vars[pname] = pfull
                pid = self.cpg.add_node(
                    "METHOD_PARAMETER_IN", name=pname, code=f"{pfull} {pname}",
                    line=self.peek().line, order=order, type_full_name=pfull,
                )
                self.cpg.add_edge(method, pid, C.AST)
                order += 1
            if self.at(","):
                self.eat()
        if self.at(")"):
            self.eat(")")
        # tolerate everything between ) and the body: C++ `const`,
        # `noexcept(...)`, `override`, Java `throws A, B` — none of it
        # shapes the CFG. A constructor member-initializer list needs its
        # own balanced skip first: `: x_(1), y_{v}` contains brace groups
        # that must not be mistaken for the function body.
        while (
            not self.at("{") and not self.at(";") and not self.at(":")
            and not self.at_eof()
        ):
            self.eat()
        if self.at(":"):
            self.eat()
            while not self.at_eof():
                # qualified, possibly templated member/base name:
                # `Base<T>::Nested`, `ns::m_` — angle groups may be
                # followed by further :: segments, so keep scanning
                while (
                    self.peek().kind == "id" or self.at("::") or self.at("<")
                ):
                    if self.at("<"):
                        end = self._match_angle(0)
                        if end is None:
                            break
                        for _ in range(end):
                            self.eat()
                    else:
                        self.eat()
                if self.at("(") or self.at("{"):
                    open_t = self.peek().text
                    close_t = ")" if open_t == "(" else "}"
                    depth = 0
                    while not self.at_eof():
                        t = self.eat()
                        if t.text == open_t:
                            depth += 1
                        elif t.text == close_t:
                            depth -= 1
                            if depth == 0:
                                break
                if self.at(","):
                    self.eat()
                    continue
                break
        while not self.at("{") and not self.at(";") and not self.at_eof():
            self.eat()
        body = self._parse_block() if self.at("{") else _Seq([])
        return self._finish_function(sig_start.line, ret_type, body)

    def _finish_function(
        self, sig_line: int | None, ret_type: str, body: _Stmt
    ) -> C.Cpg:
        """Shared tail: METHOD_RETURN node, CFG wiring, and adoption of
        parentless expression roots under the METHOD node."""
        method = self.cpg.method_id
        mret = self.cpg.add_node(
            "METHOD_RETURN", name="RET", code="RET", line=sig_line,
            type_full_name=ret_type,
        )
        self.cpg.method_return_id = mret
        self.cpg.add_edge(method, mret, C.AST)
        _CfgBuilder(self.cpg).build(body)
        # AST: method -> top-level expression roots that lack an AST parent
        have_parent = {d for _, d, t in self.cpg.edges if t == C.AST}
        for n in self.cpg.nodes:
            if n.id != method and n.id not in have_parent:
                self.cpg.add_edge(method, n.id, C.AST)
        return self.cpg

    def _parse_script_function(self) -> C.Cpg:
        """js `function name(a, b = 1, ...rest) { body }` (optionally
        `async`) and php `function name($a, &$b) { body }` — untyped
        parameter lists, then the same statement grammar."""
        if self.peek().text == "async":
            self.eat()
        if self.peek().text != "function":
            # e.g. `async (a) => a + 1`, or php modifiers without a
            # method: raise so _parse's wrapper fallback gets its turn
            raise ParseError(f"expected 'function', got {self.peek()!r}")
        self.eat()  # 'function'
        if self.at("&"):  # php return-by-reference
            self.eat()
        sig = self.peek()
        fname = self.eat().text if self.peek().kind == "id" else "__anon__"
        self.cpg = C.Cpg(fname)
        method = self.cpg.add_node(
            "METHOD", name=fname, code=fname, line=sig.line,
            type_full_name="ANY",
        )
        self.cpg.method_id = method
        self.scope = _Scope()
        order = 1
        if self.at("("):
            self.eat("(")
            while not self.at(")") and not self.at_eof():
                if self.at("..."):
                    self.eat()
                if self.at("&"):  # php by-reference parameter
                    self.eat()
                if self.peek().kind == "id":
                    p = self.eat()
                    self.scope.vars[p.text] = "ANY"
                    pid = self.cpg.add_node(
                        "METHOD_PARAMETER_IN", name=p.text, code=p.text,
                        line=p.line, order=order, type_full_name="ANY",
                    )
                    self.cpg.add_edge(method, pid, C.AST)
                    order += 1
                    if self.at("="):  # default value
                        self.eat()
                        self._parse_assign()
                elif not self.at(","):
                    self.eat()  # skip destructuring braces etc.
                if self.at(","):
                    self.eat()
            if self.at(")"):
                self.eat(")")
        # php closures: `use ($x, &$y)`; js: nothing between ) and {
        while not self.at("{") and not self.at(";") and not self.at_eof():
            self.eat()
        body = self._parse_block() if self.at("{") else _Seq([])
        return self._finish_function(sig.line, "ANY", body)

    # -- ruby ---------------------------------------------------------------
    #
    # ruby is end-delimited, newline-terminated (the lexer's ASI inserts
    # ';'), and expression-oriented; the statement forms below cover the
    # method shapes of generation corpora (reference grammar:
    # CodeT5/evaluator/CodeBLEU/parser/DFG.py DFG_ruby). Everything is
    # gated on dialect == "ruby".

    def _parse_ruby_function(self) -> C.Cpg:
        """`def [self.]name[(params)] ... end` (operator names and ?/!
        suffixes included — the lexer merges adjacent ?/! into the id)."""
        self.eat()  # 'def'
        sig = self.peek()
        if self.peek().kind == "id":
            fname = self.eat().text
            while self.at(".") and self.peek(1).kind == "id":
                self.eat()
                fname = self.eat().text  # `self.name`: the method name
            if self.at("=") and self.peek(1).text == "(":
                fname += self.eat().text  # setter: `def name=(value)`
        elif self.peek().kind == "op":
            fname = self.eat().text  # `def ==`, `def +`, `def []`...
            if fname == "[":
                if self.at("]"):
                    fname += self.eat().text
                if self.at("="):
                    fname += self.eat().text  # `def []=(k, v)`
        else:
            fname = "__anon__"
        self.cpg = C.Cpg(fname)
        method = self.cpg.add_node(
            "METHOD", name=fname, code=fname, line=sig.line,
            type_full_name="ANY",
        )
        self.cpg.method_id = method
        self.scope = _Scope()
        order = 1

        def add_param(tok: Token) -> None:
            nonlocal order
            self.scope.vars[tok.text] = "ANY"
            pid = self.cpg.add_node(
                "METHOD_PARAMETER_IN", name=tok.text, code=tok.text,
                line=tok.line, order=order, type_full_name="ANY",
            )
            self.cpg.add_edge(method, pid, C.AST)
            order += 1

        if self.at("("):
            self.eat("(")
            while not self.at(")") and not self.at_eof():
                if self.at("*") or self.at("&") or self.at("**"):
                    self.eat()
                if self.peek().kind == "id":
                    p = self.eat()
                    add_param(p)
                    if self.at(":"):  # keyword arg `name: default`
                        self.eat()
                        if not self.at(",") and not self.at(")"):
                            self._parse_assign()
                    elif self.at("="):
                        self.eat()
                        self._parse_assign()
                elif not self.at(","):
                    self.eat()
                if self.at(","):
                    self.eat()
            if self.at(")"):
                self.eat(")")
        elif self.peek().kind == "id" and not self.at(";", 0):
            # paren-less params: `def add a, b` (same line only)
            while self.peek().kind == "id":
                add_param(self.eat())
                if self.at(","):
                    self.eat()
                else:
                    break
        if self.at(";"):
            self.eat()
        body = self._parse_ruby_body(frozenset({"end"}))
        if self.peek().text == "end":
            self.eat()
        return self._finish_function(sig.line, "ANY", body)

    def _parse_ruby_body(self, stop: frozenset[str]) -> _Stmt:
        """Statements until a terminator word/token (end/else/when/...).
        Terminators are matched on token text — they are plain ids to
        this lexer."""
        out: list[_Stmt] = []
        while not self.at_eof() and self.peek().text not in stop:
            out.append(self.parse_statement())
        return _Seq(out)

    def _negate(self, cond_top: int, line: int | None) -> int:
        """unless/until are negated if/while (the shared desugar)."""
        return self._call(
            C.UNARY_OP_NAMES["!"], f"!({self._code(cond_top)})", line,
            [cond_top],
        )

    def _parse_ruby_if(self) -> _Stmt:
        """`if|unless|elsif cond [then] ... [elsif ...|else ...] end` —
        exactly one `end` closes the whole chain, eaten by the branch
        that reaches it."""
        kw = self.eat()
        cond_top = self.parse_expression()
        if kw.text == "unless":
            cond_top = self._negate(cond_top, kw.line)
        if self.peek().text == "then":
            self.eat()
        if self.at(";"):
            self.eat()
        then = self._parse_ruby_body(frozenset({"elsif", "else", "end"}))
        if self.peek().text == "elsif":
            els: _Stmt | None = self._parse_ruby_if()  # eats the shared end
            return _If(_Expr(cond_top), then, els)
        els = None
        if self.peek().text == "else":
            self.eat()
            els = self._parse_ruby_body(frozenset({"end"}))
        if self.peek().text == "end":
            self.eat()
        return _If(_Expr(cond_top), then, els)

    def _parse_ruby_case(self) -> _Stmt:
        kw = self.eat()  # 'case'
        cond = _Expr(None)
        if not self.at(";") and self.peek().text != "when":
            cond = _Expr(self.parse_expression())
        if self.at(";"):
            self.eat()
        cases: list[tuple[bool, str, int | None, _Stmt]] = []
        has_default = False
        while self.peek().text == "when":
            wkw = self.eat()
            label_toks: list[str] = []
            while (
                not self.at(";")
                and self.peek().text not in ("then",)
                and not self.at_eof()
            ):
                label_toks.append(self.eat().text)
            if self.peek().text == "then" or self.at(";"):
                self.eat()
            body = self._parse_ruby_body(frozenset({"when", "else", "end"}))
            # ruby when-clauses do not fall through: an implicit break
            # jumps each body to the exit, unlike C cases
            cases.append(
                (False, "case " + " ".join(label_toks), wkw.line,
                 _Seq([body, _Break(wkw.line)]))
            )
        if self.peek().text == "else":
            ekw = self.eat()
            body = self._parse_ruby_body(frozenset({"end"}))
            cases.append(
                (True, "default", ekw.line, _Seq([body, _Break(ekw.line)]))
            )
            has_default = True
        if self.peek().text == "end":
            self.eat()
        return _Switch(cond, cases, has_default)

    def _parse_ruby_begin(self) -> _Stmt:
        """`begin ... rescue [E [=> e]] ... ensure ... end`."""
        self.eat()  # 'begin'
        body = self._parse_ruby_body(frozenset({"rescue", "ensure", "end"}))
        handlers: list[tuple[int, _Stmt]] = []
        while self.peek().text == "rescue":
            kw = self.eat()
            param_toks: list[str] = []
            while not self.at(";") and self.peek().text not in (
                "then",
            ) and not self.at_eof():
                tok = self.eat()
                param_toks.append(tok.text)
                if tok.text == "=>" and self.peek().kind == "id":
                    evar = self.peek()
                    self.scope.vars[evar.text] = "ANY"
                    self._node(
                        "LOCAL", name=evar.text, code=evar.text,
                        line=evar.line, type_full_name="ANY",
                    )
            if self.peek().text == "then" or self.at(";"):
                self.eat()
            node = self._node(
                "CONTROL_STRUCTURE", name="catch",
                code=f"rescue {' '.join(param_toks)}".strip(), line=kw.line,
            )
            handlers.append(
                (node,
                 self._parse_ruby_body(
                     frozenset({"rescue", "ensure", "else", "end"})
                 ))
            )
        if self.peek().text == "else":
            self.eat()
            extra = self._parse_ruby_body(frozenset({"ensure", "end"}))
            body = _Seq([body, extra])
        tr: _Stmt = _Try(body, handlers)
        if self.peek().text == "ensure":
            self.eat()
            fin = self._parse_ruby_body(frozenset({"end"}))
            tr = _Seq([tr, fin])
        if self.peek().text == "end":
            self.eat()
        return tr

    def _parse_ruby_block_tail(self, recv: int) -> _Stmt:
        """`expr do |params| ... end` / `expr { |params| ... }` — the
        iterator-block reading: params are per-iteration definitions from
        the receiver, body loops (the dataflow shape DFG_ruby extracts
        from block parameters)."""
        opener = self.eat()  # 'do' or '{'
        closing = "end" if opener.text == "do" else "}"
        names: list[Token] = []
        if self.at("|"):
            self.eat()
            while self.peek().kind == "id":
                names.append(self.eat())
                if self.at(","):
                    self.eat()
                else:
                    break
            if self.at("|"):
                self.eat()
        calls: list[int] = []
        for i, nm in enumerate(names):
            src = (
                recv
                if i == 0
                else self._node(
                    "UNKNOWN", code=self._code(recv), line=opener.line
                )
            )
            calls.append(
                self._bind_loop_var(nm.text, "ANY", src, nm.line)
            )
        body = self._parse_ruby_body(frozenset({closing}))
        if self.peek().text == closing:
            self.eat()
        if not calls:
            return _RangeFor(_Expr(recv), body)
        top = (
            calls[0]
            if len(calls) == 1
            else self._call(
                C.COMMA, ", ".join(self._code(x) for x in calls),
                opener.line, calls,
            )
        )
        return _RangeFor(_Expr(top), body)

    #: tokens that can start a paren-less ruby command argument
    _RUBY_ARG_START = frozenset(("id", "num", "str", "char"))

    def _parse_ruby_statement(self) -> _Stmt:
        t = self.peek()
        if self.at(";"):
            self.eat()
            return _Expr(None)
        if t.kind == "kw":
            if t.text == "if":
                return self._ruby_with_modifiers(self._parse_ruby_if())
            if t.text == "while":
                self.eat()
                cond = _Expr(self.parse_expression())
                if self.peek().text == "do" or self.at(";"):
                    self.eat()
                body = self._parse_ruby_body(frozenset({"end"}))
                if self.peek().text == "end":
                    self.eat()
                return _While(cond, body)
            if t.text == "for":
                self.eat()
                if self.peek().kind != "id":
                    raise ParseError("ruby for declarator")
                name = self.eat().text
                if self.peek().text == "in":
                    self.eat()
                rng = self.parse_expression()
                call = self._bind_loop_var(name, "ANY", rng, t.line)
                if self.peek().text == "do" or self.at(";"):
                    self.eat()
                body = self._parse_ruby_body(frozenset({"end"}))
                if self.peek().text == "end":
                    self.eat()
                return _RangeFor(_Expr(call), body)
            if t.text == "case":
                return self._parse_ruby_case()
            if t.text == "return":
                self.eat()
                expr = None
                if not self.at(";") and not self.at_eof() and (
                    self.peek().text not in ("end", "if", "unless")
                ):
                    expr = _Expr(self.parse_expression())
                node = self._node(
                    "RETURN", name="return",
                    code="return"
                    + (
                        f" {self._code(expr.top)}"
                        if expr and expr.top is not None
                        else ""
                    ),
                    line=t.line,
                )
                if expr and expr.top is not None:
                    self.cpg.add_edge(node, expr.top, C.AST)
                    self.cpg.add_edge(node, expr.top, C.ARGUMENT)
                    self.cpg.nodes[expr.top].order = 1
                return self._ruby_with_modifiers(_Return(expr, node))
            if t.text == "break":
                self.eat()
                return self._ruby_with_modifiers(_Break(t.line))
        if t.kind == "id":
            if t.text in ("unless", "until"):
                if t.text == "unless":
                    return self._ruby_with_modifiers(self._parse_ruby_if())
                self.eat()  # until = while-not
                cond_top = self.parse_expression()
                cond_top = self._negate(cond_top, t.line)
                if self.peek().text == "do" or self.at(";"):
                    self.eat()
                body = self._parse_ruby_body(frozenset({"end"}))
                if self.peek().text == "end":
                    self.eat()
                return _While(_Expr(cond_top), body)
            if t.text == "next":
                self.eat()
                return self._ruby_with_modifiers(_Continue(t.line))
            if t.text == "begin":
                return self._parse_ruby_begin()
            if t.text == "raise":
                self.eat()
                expr = None
                if not self.at(";") and not self.at_eof():
                    expr = self.parse_expression()
                node = self._node(
                    "CONTROL_STRUCTURE", name="throw",
                    code="raise"
                    + (f" {self._code(expr)}" if expr is not None else ""),
                    line=t.line,
                )
                if expr is not None:
                    self.cpg.add_edge(node, expr, C.AST)
                    self.cpg.add_edge(node, expr, C.ARGUMENT)
                    self.cpg.nodes[expr].order = 1
                return self._ruby_with_modifiers(_Throw(node))
            if (
                self.peek(1).kind in self._RUBY_ARG_START
                or (
                    self.peek(1).text == ":"
                    and self.peek(2).kind in ("id", "str")
                )
            ) and self.peek(1).text not in (
                # statement operators/guards, not command arguments:
                # `cleanup unless failed`, `save and notify`
                "do", "unless", "until", "and", "or", "not", "if",
                "while", "then", "rescue", "in", "end",
            ):
                # paren-less command call: `puts x`, `attr_reader :name`
                name = self.eat().text
                args = self.parse_expression()
                call = self._call(
                    name, f"{name} {self._code(args)}", t.line, [args]
                )
                return self._ruby_with_modifiers(_Expr(call))
        expr = self.parse_expression()
        return self._ruby_with_modifiers(_Expr(expr))

    def _ruby_with_modifiers(self, stmt: _Stmt) -> _Stmt:
        """Trailing modifiers and iterator blocks: `x += 1 if cond`,
        `return nil unless ok`, `xs.each do |x| ... end`."""
        while True:
            t = self.peek()
            if (
                isinstance(stmt, _Expr)
                and stmt.top is not None
                and (
                    (t.kind in ("id", "kw") and t.text == "do")
                    or (t.kind == "op" and t.text == "{")
                )
            ):
                stmt = self._parse_ruby_block_tail(stmt.top)
                continue
            if t.kind == "kw" and t.text == "if" or (
                t.kind == "id" and t.text == "unless"
            ):
                self.eat()
                cond_top = self.parse_expression()
                if t.text == "unless":
                    cond_top = self._negate(cond_top, t.line)
                stmt = _If(_Expr(cond_top), stmt, None)
                continue
            if t.kind == "kw" and t.text == "while" or (
                t.kind == "id" and t.text == "until"
            ):
                self.eat()
                cond_top = self.parse_expression()
                if t.text == "until":
                    cond_top = self._negate(cond_top, t.line)
                stmt = _While(_Expr(cond_top), stmt)
                continue
            if self.at(";"):
                self.eat()
            return stmt

    def _parse_go_param_group(self, method: int, order: int) -> int:
        """One go parameter group `a, b Type` / `xs []int` /
        `f func(int) int` — names first, then a postfix type shared by
        the whole group. Returns the next parameter order."""
        names: list[Token] = []
        # `a, b int`: ids followed by ',' are names; a final id followed
        # by anything but ','/')' heads its group's type — except that a
        # LONE id before ')' is taken as an (untyped) name, the lenient
        # reading that favors dataflow over go's type-only params
        while self.peek().kind == "id" and self.peek(1).text == ",":
            names.append(self.eat())
            self.eat(",")
        if self.peek().kind == "id":
            names.append(self.eat())
        # whatever remains before ',' or ')' at depth 0 is the type
        ty_toks: list[str] = []
        depth = 0
        while not self.at_eof():
            t = self.peek()
            if t.text in ("(", "[", "{"):
                depth += 1
            elif t.text in (")", "]", "}"):
                if depth == 0:
                    break
                depth -= 1
            elif t.text == "," and depth == 0:
                break
            ty_toks.append(self.eat().text)
        ty = self._join_type_tokens(ty_toks) or "ANY"
        for p in names:
            self.scope.vars[p.text] = ty
            pid = self.cpg.add_node(
                "METHOD_PARAMETER_IN", name=p.text, code=f"{ty} {p.text}",
                line=p.line, order=order, type_full_name=ty,
            )
            self.cpg.add_edge(method, pid, C.AST)
            order += 1
        if self.at(","):
            self.eat()
        return order

    def _parse_go_function(self) -> C.Cpg:
        """go `func [(recv T)] name(params) [results] { body }` —
        postfix types; parameter groups share one type (`a, b int`)."""
        self.eat()  # 'func'
        sig = self.peek()
        recv: list[tuple[str, str]] = []
        if self.at("("):
            # method receiver: `(s *Server)`
            self.eat("(")
            if self.peek().kind == "id":
                rname = self.eat().text
                ty_toks = []
                while not self.at(")") and not self.at_eof():
                    ty_toks.append(self.eat().text)
                recv.append((rname, self._join_type_tokens(ty_toks) or "ANY"))
            else:
                while not self.at(")") and not self.at_eof():
                    self.eat()
            if self.at(")"):
                self.eat(")")
        fname = self.eat().text if self.peek().kind == "id" else "__anon__"
        self.cpg = C.Cpg(fname)
        method = self.cpg.add_node(
            "METHOD", name=fname, code=fname, line=sig.line,
            type_full_name="ANY",
        )
        self.cpg.method_id = method
        self.scope = _Scope()
        order = 1
        for rname, rty in recv:
            self.scope.vars[rname] = rty
            pid = self.cpg.add_node(
                "METHOD_PARAMETER_IN", name=rname, code=f"{rty} {rname}",
                line=sig.line, order=order, type_full_name=rty,
            )
            self.cpg.add_edge(method, pid, C.AST)
            order += 1
        if self.at("("):
            self.eat("(")
            while not self.at(")") and not self.at_eof():
                order = self._parse_go_param_group(method, order)
            if self.at(")"):
                self.eat(")")
        # result types: single, or parenthesized tuple — skip to '{'
        depth = 0
        while not self.at_eof():
            if self.at("{") and depth == 0:
                break
            if self.at(";") and depth == 0:
                break
            t = self.eat()
            if t.text in ("(", "["):
                depth += 1
            elif t.text in (")", "]"):
                depth -= 1
        body = self._parse_block() if self.at("{") else _Seq([])
        return self._finish_function(sig.line, "ANY", body)


# ---------------------------------------------------------------------------
# CFG construction


class _CfgBuilder:
    """Wires CFG edges: expression chains in post-order, branches, loops,
    switches, gotos; METHOD -> first node, exits -> METHOD_RETURN."""

    def __init__(self, cpg: C.Cpg):
        self.cpg = cpg
        self.frontier: list[int] = [cpg.method_id]
        self.break_stack: list[list[int]] = []
        self.continue_stack: list[tuple[str, list[int] | int]] = []
        self.labels: dict[str, int] = {}
        self.pending_gotos: list[tuple[str, int]] = []

    def build(self, body: _Stmt) -> None:
        self.stmt(body)
        for nid in self.frontier:
            self.cpg.add_edge(nid, self.cpg.method_return_id, C.CFG)
        for label, node in self.pending_gotos:
            if label in self.labels:
                self.cpg.add_edge(node, self.labels[label], C.CFG)

    # -- expression chains --

    def _postorder(self, top: int) -> list[int]:
        out: list[int] = []

        def rec(n: int):
            for ch in sorted(
                self.cpg.successors(n, C.AST), key=lambda c: self.cpg.nodes[c].order
            ):
                rec(ch)
            out.append(n)

        rec(top)
        return out

    def emit_expr(self, top: int | None) -> None:
        if top is None:
            return
        chain = self._postorder(top)
        for nid in self.frontier:
            self.cpg.add_edge(nid, chain[0], C.CFG)
        for a, b in zip(chain, chain[1:]):
            self.cpg.add_edge(a, b, C.CFG)
        self.frontier = [chain[-1]]

    def _first_of(self, top: int) -> int:
        return self._postorder(top)[0]

    def _loop_back_to_body(
        self, marker: int, entry_frontier: list[int], conts: list[int]
    ) -> None:
        """Close a condition-less loop: find the body's first CFG node
        (the dst of the first CFG edge out of the entry frontier added
        after `marker`) and wire the current frontier plus deferred
        continues back to it."""
        first_body = None
        for src, dst, t in self.cpg.edges[marker:]:
            if t == C.CFG and src in entry_frontier:
                first_body = dst
                break
        if first_body is None:
            return
        for nid in self.frontier:
            self.cpg.add_edge(nid, first_body, C.CFG)
        for nid in conts:
            self.cpg.add_edge(nid, first_body, C.CFG)
        self.frontier = []

    # -- statements --

    def stmt(self, s: _Stmt) -> None:
        if isinstance(s, _Seq):
            for sub in s.body:
                self.stmt(sub)
        elif isinstance(s, _Expr):
            self.emit_expr(s.top)
        elif isinstance(s, _If):
            self.emit_expr(s.cond.top)
            cond_f = list(self.frontier)
            self.stmt(s.then)
            then_f = self.frontier
            if s.els is not None:
                self.frontier = cond_f
                self.stmt(s.els)
                self.frontier = then_f + self.frontier
            else:
                self.frontier = then_f + cond_f
        elif isinstance(s, _While):
            if s.cond.top is None:
                # condition-less loop (parse recovery): loop forever;
                # body end and continues wire back to the body's first
                # node, only breaks exit
                self.break_stack.append([])
                marker = len(self.cpg.edges)
                entry_frontier = list(self.frontier)
                self.continue_stack.append(("defer", []))
                self.stmt(s.body)
                _, conts = self.continue_stack.pop()
                self._loop_back_to_body(marker, entry_frontier, conts)
                self.frontier = self.break_stack.pop()
                return
            cond_first = self._first_of(s.cond.top)
            self.emit_expr(s.cond.top)
            cond_top = self.frontier[0]
            self.break_stack.append([])
            self.continue_stack.append(("node", cond_first))
            self.stmt(s.body)
            for nid in self.frontier:
                self.cpg.add_edge(nid, cond_first, C.CFG)
            self.frontier = [cond_top] + self.break_stack.pop()
            self.continue_stack.pop()
        elif isinstance(s, _DoWhile):
            body_entry_marker = len(self.cpg.edges)
            entry_frontier = list(self.frontier)
            self.break_stack.append([])
            self.continue_stack.append(("defer", []))
            self.stmt(s.body)
            _, conts = self.continue_stack.pop()
            if s.cond.top is not None:
                cond_first = self._first_of(s.cond.top)
                for nid in conts:
                    self.cpg.add_edge(nid, cond_first, C.CFG)
                self.emit_expr(s.cond.top)
                cond_top = self.frontier[0]
                # loop back: cond -> first body node (first CFG edge dst
                # added after marker)
                first_body = None
                for src, dst, t in self.cpg.edges[body_entry_marker:]:
                    if t == C.CFG and src in entry_frontier:
                        first_body = dst
                        break
                if first_body is not None:
                    self.cpg.add_edge(cond_top, first_body, C.CFG)
                self.frontier = [cond_top] + self.break_stack.pop()
            else:
                self.frontier = self.frontier + self.break_stack.pop()
        elif isinstance(s, _For):
            if s.init is not None:
                self.stmt(s.init)
            cond_first = None
            if s.cond is not None and s.cond.top is not None:
                cond_first = self._first_of(s.cond.top)
                self.emit_expr(s.cond.top)
                cond_top = self.frontier[0]
            self.break_stack.append([])
            update_first = (
                self._first_of(s.update.top)
                if s.update is not None and s.update.top is not None
                else cond_first
            )
            self.continue_stack.append(
                ("node", update_first) if update_first is not None else ("defer", [])
            )
            marker = len(self.cpg.edges)
            entry_frontier = list(self.frontier)
            self.stmt(s.body)
            # body end -> update -> cond
            if s.update is not None and s.update.top is not None:
                self.emit_expr(s.update.top)
            if cond_first is not None:
                for nid in self.frontier:
                    self.cpg.add_edge(nid, cond_first, C.CFG)
                self.frontier = [cond_top] + self.break_stack.pop()
                self.continue_stack.pop()
            else:
                # for(;;): body end (after any update) loops back to the
                # body's first node; deferred continues join it; only
                # breaks exit
                _, conts = self.continue_stack.pop()
                if not isinstance(conts, list):
                    conts = []
                self._loop_back_to_body(marker, entry_frontier, conts)
                self.frontier = self.break_stack.pop()
        elif isinstance(s, _Switch):
            self.emit_expr(s.cond.top)
            cond_f = list(self.frontier)
            self.break_stack.append([])
            fallthrough: list[int] = []
            for is_default, label_code, line, body in s.cases:
                # Joern emits a JUMP_TARGET per case/default label, in
                # the CFG: dispatch edges go switch-cond -> jump target,
                # and fallthrough runs prev body -> next jump target
                jt = self.cpg.add_node(
                    "JUMP_TARGET", name=label_code,
                    code=f"{label_code}:", line=line,
                )
                for nid in cond_f + fallthrough:
                    self.cpg.add_edge(nid, jt, C.CFG)
                self.frontier = [jt]
                self.stmt(body)
                fallthrough = self.frontier
            exits = self.break_stack.pop() + fallthrough
            if not s.has_default:
                exits += cond_f
            self.frontier = exits
        elif isinstance(s, _Return):
            if s.expr is not None and s.expr.top is not None:
                self.emit_expr(s.expr.top)
            for nid in self.frontier:
                self.cpg.add_edge(nid, s.node, C.CFG)
            self.cpg.add_edge(s.node, self.cpg.method_return_id, C.CFG)
            self.frontier = []
        elif isinstance(s, _Break):
            # Joern keeps break in the CFG as a CONTROL_STRUCTURE node
            node = self.cpg.add_node(
                "CONTROL_STRUCTURE", name="break", code="break;",
                line=s.line,
            )
            for nid in self.frontier:
                self.cpg.add_edge(nid, node, C.CFG)
            if self.break_stack:
                self.break_stack[-1].append(node)
            self.frontier = []
        elif isinstance(s, _Continue):
            node = self.cpg.add_node(
                "CONTROL_STRUCTURE", name="continue", code="continue;",
                line=s.line,
            )
            for nid in self.frontier:
                self.cpg.add_edge(nid, node, C.CFG)
            if self.continue_stack:
                kind, target = self.continue_stack[-1]
                if kind == "node":
                    self.cpg.add_edge(node, target, C.CFG)
                else:
                    target.append(node)
            self.frontier = []
        elif isinstance(s, _Try):
            # handlers are alternative paths: entered from the try entry
            # (any body statement may throw; the line-level simplification
            # branches at entry and at body exit) via the catch node
            entry_f = list(self.frontier)
            self.stmt(s.body)
            body_exits = list(self.frontier)
            all_exits = list(body_exits)
            for catch_node, handler in s.handlers:
                # dedup: an empty try body makes entry_f == body_exits
                for nid in dict.fromkeys(entry_f + body_exits):
                    self.cpg.add_edge(nid, catch_node, C.CFG)
                self.frontier = [catch_node]
                self.stmt(handler)
                all_exits.extend(self.frontier)
            self.frontier = all_exits
        elif isinstance(s, _Throw):
            # throw leaves the function (line level): no fall-through
            for nid in self.frontier:
                self.cpg.add_edge(nid, s.node, C.CFG)
            self.cpg.add_edge(s.node, self.cpg.method_return_id, C.CFG)
            self.frontier = []
        elif isinstance(s, _RangeFor):
            expr_first = self._first_of(s.expr.top)
            self.emit_expr(s.expr.top)
            expr_top = self.frontier[0]
            self.break_stack.append([])
            self.continue_stack.append(("node", expr_first))
            self.stmt(s.body)
            for nid in self.frontier:
                self.cpg.add_edge(nid, expr_first, C.CFG)
            self.frontier = [expr_top] + self.break_stack.pop()
            self.continue_stack.pop()
        elif isinstance(s, _Goto):
            for nid in self.frontier:
                self.cpg.add_edge(nid, s.node, C.CFG)
            self.pending_gotos.append((s.label, s.node))
            self.frontier = []
        elif isinstance(s, _Label):
            # a label is a CFG join point; materialize as a no-op node
            node = self.cpg.add_node(
                "JUMP_TARGET", name=s.name, code=f"{s.name}:",
                line=s.line,
            )
            self.labels[s.name] = node
            for nid in self.frontier:
                self.cpg.add_edge(nid, node, C.CFG)
            self.frontier = [node]
        else:
            raise TypeError(f"unknown stmt {s!r}")


def parse_function(code: str, dialect: str = "c") -> C.Cpg:
    """Public entry: parse one function into a CPG-lite.

    dialect "c" (default) covers C/C++ — the dataset path, whose behavior
    is independent of every other dialect. "java"/"cs"/"js"/"go"/"php"
    adapt the same recursive-descent core for CodeBLEU structural
    matching of generation-task snippets (eval/codebleu.py; reference
    grammar list: CodeT5/evaluator/CodeBLEU/parser/DFG.py)."""
    return Parser(code, dialect=dialect).parse_function()
