"""CPG fidelity measurement: hermetic frontend vs Joern exports.

The framework replaces Joern (the reference's external JVM analyzer,
get_func_graph.sc:26-80) with the hermetic parser in frontend/parser.py;
CPG-shape divergence on real C code is the main effectiveness risk
(VERDICT r1). This module quantifies agreement between two CPGs of the
same function — typically parse_function(code) vs
load_joern_cpg(export) — on the signals that actually feed the model:

- statement coverage: CFG-participating source lines (the GGNN's nodes),
- cfg_edge_jaccard: CFG edges as (src_line, dst_line) pairs — the
  message-passing structure,
- def_line_jaccard: lines holding definition nodes (is_decl),
- hash_agreement: fraction of common def lines whose abstract-dataflow
  feature hash (to_hash over decl_features) is identical — the exact
  quantity that indexes the learned embedding table.

Line-keyed comparison deliberately ignores node-id numbering and interior
AST shape: two extractors that disagree there but agree on these metrics
produce identical model inputs.
"""

from __future__ import annotations

import json
from typing import Iterable

from deepdfa_tpu.frontend.absdf import graph_features
from deepdfa_tpu.frontend.cpg import CFG, Cpg


def _cfg_lines(cpg: Cpg) -> set[int]:
    out = set()
    for nid in cpg.cfg_nodes():
        n = cpg.node(nid)
        if n.line is not None and n.label not in ("METHOD", "METHOD_RETURN"):
            out.add(int(n.line))
    return out


def _cfg_line_edges(cpg: Cpg) -> set[tuple[int, int]]:
    out = set()
    for s, d, t in cpg.edges:
        if t != CFG:
            continue
        ls, ld = cpg.node(s).line, cpg.node(d).line
        if ls is not None and ld is not None and ls != ld:
            out.add((int(ls), int(ld)))
    return out


def _def_hashes_by_line(cpg: Cpg) -> dict[int, set[str]]:
    """line -> set of abstract-dataflow hashes of its definition nodes."""
    out: dict[int, set[str]] = {}
    for nid, h in graph_features(cpg).items():
        line = cpg.node(nid).line
        if line is not None:
            out.setdefault(int(line), set()).add(h)
    return out


def _jaccard(a: set, b: set) -> float:
    if not a and not b:
        return 1.0
    return len(a & b) / len(a | b)


def _rd_in_lines(cpg: Cpg) -> dict[int, set[int]]:
    """Line-keyed reaching-definitions IN sets: statement line -> the set
    of definition LINES reaching it (the hermetic solver runs on whatever
    CPG it is given, so comparing two CPGs through this isolates graph
    divergence from solver divergence)."""
    from deepdfa_tpu.frontend.reaching import ReachingDefinitions

    rd = ReachingDefinitions(cpg)
    by_line: dict[int, set[int]] = {}
    for nid, defs in rd.solve().items():
        line = cpg.nodes[nid].line
        if line is None:
            continue
        by_line.setdefault(int(line), set()).update(
            int(cpg.nodes[d.node].line)
            for d in defs
            if cpg.nodes[d.node].line is not None
        )
    return by_line


def compare_cpgs(ours: Cpg, theirs: Cpg) -> dict:
    """Agreement metrics between two CPGs of the same function."""
    lines_a, lines_b = _cfg_lines(ours), _cfg_lines(theirs)
    edges_a, edges_b = _cfg_line_edges(ours), _cfg_line_edges(theirs)
    defs_a = _def_hashes_by_line(ours)
    defs_b = _def_hashes_by_line(theirs)
    common_def_lines = set(defs_a) & set(defs_b)
    # a line agrees only when BOTH sides produce the identical hash set —
    # a missing/extra definition node is a real model-input divergence
    hash_match = sum(
        1 for ln in common_def_lines if defs_a[ln] == defs_b[ln]
    )
    rd_a, rd_b = _rd_in_lines(ours), _rd_in_lines(theirs)
    rd_lines = set(rd_a) | set(rd_b)
    rd_in_jaccard = (
        sum(_jaccard(rd_a.get(ln, set()), rd_b.get(ln, set())) for ln in rd_lines)
        / len(rd_lines)
        if rd_lines
        else 1.0
    )
    return {
        "stmt_line_jaccard": round(_jaccard(lines_a, lines_b), 4),
        "cfg_edge_jaccard": round(_jaccard(edges_a, edges_b), 4),
        "def_line_jaccard": round(
            _jaccard(set(defs_a), set(defs_b)), 4
        ),
        "hash_agreement": round(
            hash_match / len(common_def_lines), 4
        )
        if common_def_lines
        else 1.0,
        "rd_in_jaccard": round(rd_in_jaccard, 4),
        "n_stmt_lines": (len(lines_a), len(lines_b)),
        "n_cfg_edges": (len(edges_a), len(edges_b)),
        "n_def_lines": (len(defs_a), len(defs_b)),
    }


def agreement_report(pairs: Iterable[tuple[str, Cpg, Cpg]]) -> dict:
    """Aggregate compare_cpgs over (name, ours, theirs) pairs."""
    per_example = {}
    sums: dict[str, float] = {}
    n = 0
    for name, ours, theirs in pairs:
        m = compare_cpgs(ours, theirs)
        per_example[name] = m
        for k in ("stmt_line_jaccard", "cfg_edge_jaccard",
                  "def_line_jaccard", "hash_agreement", "rd_in_jaccard"):
            sums[k] = sums.get(k, 0.0) + m[k]
        n += 1
    report = {
        "n_examples": n,
        "mean": {k: round(v / n, 4) for k, v in sums.items()} if n else {},
        "per_example": per_example,
    }
    return report


def cpg_line_spec(cpg: Cpg) -> dict:
    """Line-level CPG spec: the exact signals the fidelity metrics read.

    {stmt_lines, cfg_edges (src_line,dst_line pairs), def_lines} — the
    compact ground-truth format used by the committed fidelity corpus
    (tests/fidelity_corpus/expected.json). Hash agreement needs full CPG
    structure and stays on the builder fixtures (tests/joern_fixtures.py).
    """
    return {
        "stmt_lines": sorted(_cfg_lines(cpg)),
        "cfg_edges": sorted(list(e) for e in _cfg_line_edges(cpg)),
        "def_lines": sorted(_def_hashes_by_line(cpg)),
    }


def compare_to_spec(cpg: Cpg, spec: dict) -> dict:
    """Agreement metrics between a CPG and a hand-specified line spec."""
    lines_a = _cfg_lines(cpg)
    edges_a = _cfg_line_edges(cpg)
    defs_a = set(_def_hashes_by_line(cpg))
    lines_b = set(spec["stmt_lines"])
    edges_b = {tuple(e) for e in spec["cfg_edges"]}
    defs_b = set(spec["def_lines"])
    return {
        "stmt_line_jaccard": round(_jaccard(lines_a, lines_b), 4),
        "cfg_edge_jaccard": round(_jaccard(edges_a, edges_b), 4),
        "def_line_jaccard": round(_jaccard(defs_a, defs_b), 4),
        "n_stmt_lines": (len(lines_a), len(lines_b)),
        "n_cfg_edges": (len(edges_a), len(edges_b)),
        "n_def_lines": (len(defs_a), len(defs_b)),
    }


def corpus_report(corpus_dir, expected_path=None) -> dict:
    """Fidelity report over a committed corpus directory.

    corpus_dir holds one function per .c/.cc file; expected_path (default
    <corpus_dir>/expected.json) maps file stem -> line spec. Aggregates
    the same jaccards as agreement_report.
    """
    from pathlib import Path

    from deepdfa_tpu.frontend.parser import parse_function

    corpus_dir = Path(corpus_dir)
    expected_path = Path(expected_path or corpus_dir / "expected.json")
    expected = json.loads(expected_path.read_text())
    per_example = {}
    sums: dict[str, float] = {}
    for path in sorted(corpus_dir.glob("*.c*")):
        name = path.stem
        if name not in expected:
            continue
        m = compare_to_spec(parse_function(path.read_text()), expected[name])
        per_example[name] = m
        for k in ("stmt_line_jaccard", "cfg_edge_jaccard", "def_line_jaccard"):
            sums[k] = sums.get(k, 0.0) + m[k]
    n = len(per_example)
    return {
        "n_examples": n,
        "mean": {k: round(v / n, 4) for k, v in sums.items()} if n else {},
        "per_example": per_example,
    }


def fidelity_against_joern(
    sources: dict[str, str],
    joern_prefixes: dict[str, str] | None = None,
    session=None,
) -> dict:
    """Compare the hermetic parser against Joern on named C functions.

    sources: name -> C code. Joern CPGs come from `joern_prefixes`
    (name -> path prefix of existing .nodes.json/.edges.json exports) or,
    when a live `session` (frontend/joern_session.JoernSession) is given,
    from driving the real binary per function.
    """
    import tempfile
    from pathlib import Path

    from deepdfa_tpu.frontend.joern_io import load_joern_cpg
    from deepdfa_tpu.frontend.parser import parse_function

    pairs = []
    for name, code in sources.items():
        ours = parse_function(code)
        if joern_prefixes and name in joern_prefixes:
            theirs = load_joern_cpg(joern_prefixes[name])
        elif session is not None:
            d = Path(tempfile.mkdtemp(prefix="fidelity-"))
            src = d / f"{name}.c"
            src.write_text(code)
            session.import_code(src)
            session.export_cpg_json(src)
            theirs = load_joern_cpg(src)
        else:
            raise ValueError(f"no joern source for {name!r}")
        pairs.append((name, ours, theirs))
    return agreement_report(pairs)


def main(argv=None) -> None:  # pragma: no cover - thin CLI shim
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("sources", nargs="*", help="C files to compare")
    ap.add_argument(
        "--corpus", default=None,
        help="corpus dir with *.c/*.cc + expected.json line specs "
        "(e.g. tests/fidelity_corpus)",
    )
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    from pathlib import Path

    if args.corpus:
        report = corpus_report(args.corpus)
        text = json.dumps(report, indent=2)
        print(text)
        if args.out:
            Path(args.out).write_text(text)
        return

    from deepdfa_tpu.frontend import joern_session

    sources = {Path(p).stem: Path(p).read_text() for p in args.sources}
    prefixes = {
        Path(p).stem: p
        for p in args.sources
        if Path(p + ".nodes.json").exists()
    }
    session = None
    if len(prefixes) < len(sources) and joern_session.available():
        session = joern_session.JoernSession()
    report = fidelity_against_joern(sources, prefixes, session)
    text = json.dumps(report, indent=2)
    print(text)
    if args.out:
        Path(args.out).write_text(text)


if __name__ == "__main__":  # pragma: no cover
    main()
