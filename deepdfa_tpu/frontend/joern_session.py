"""Persistent Joern session driver (optional external backend).

The reference drives a long-lived interactive Joern JVM over a pexpect pty
with per-worker workspaces (DDFA/sastvd/helpers/joern_session.py:35-121);
this driver provides the same capability on plain subprocess pipes:

    with JoernSession(worker_id=3) as s:
        s.import_code("/path/to/file.c")
        s.run_command('cpg.method.name.l')
        s.export_cpg_json("/path/to/file.c")   # -> .nodes.json/.edges.json

Export output is the format frontend/joern_io.py imports, so Joern-exact
CPGs flow into the same pipeline as the built-in frontend. The session is
only usable where a `joern` binary exists (it is an external JVM tool,
exactly as in the reference); `available()` reports that.
"""

from __future__ import annotations

import queue
import shutil
import subprocess
import tempfile
import threading
from pathlib import Path

from deepdfa_tpu.obs import trace as obs_trace

_MARKER = "===DEEPDFA_DONE==="

# scala snippet exporting nodes/edges json for the currently loaded cpg,
# mirroring the reference export surface (get_func_graph.sc): all nodes
# with their property map, all edges as [inNode, outNode, label] rows.
_EXPORT_TEMPLATE = r"""
{{
  import java.io.PrintWriter
  val nodes = cpg.all.map {{ n =>
    val m = scala.collection.mutable.Map[String, Any]("id" -> n.id, "_label" -> n.label)
    n.propertiesMap.forEach {{ (k, v) => m(k) = v }}
    m
  }}.l
  def esc(s: String) = s.replace("\\", "\\\\").replace("\"", "\\\"").replace("\n", "\\n").replace("\r", "")
  def jval(v: Any): String = v match {{
    case i: java.lang.Integer => i.toString
    case l: java.lang.Long => l.toString
    case s: String => "\"" + esc(s) + "\""
    case other => "\"" + esc(String.valueOf(other)) + "\""
  }}
  val nodesJson = nodes.map {{ m =>
    "{{" + m.map {{ case (k, v) =>
      val key = if (k == "LINE_NUMBER") "lineNumber" else if (k == "TYPE_FULL_NAME") "typeFullName"
        else if (k == "NAME") "name" else if (k == "CODE") "code" else if (k == "ORDER") "order" else k
      "\"" + key + "\": " + jval(v)
    }}.mkString(", ") + "}}"
  }}.mkString("[", ",\n", "]")
  new PrintWriter("{nodes_out}") {{ write(nodesJson); close }}
  val edgesJson = cpg.graph.edges().map {{ e =>
    "[" + e.inNode.id + ", " + e.outNode.id + ", \"" + e.label + "\", \"\"]"
  }}.l.mkString("[", ",\n", "]")
  new PrintWriter("{edges_out}") {{ write(edgesJson); close }}
}}
"""


# scala snippet exporting Joern's own reaching-definitions fixpoint per
# method (role of the reference's get_func_graph.sc / get_dataflow_output.sc
# solution export): {method fullName: {"in": {nodeId: [defIdx..]},
# "out": {...}}} where defIdx numbers the solver's definition domain.
_DATAFLOW_TEMPLATE = r"""
{{
  import java.io.PrintWriter
  import io.joern.dataflowengineoss.passes.reachingdef.{{DataFlowSolver, ReachingDefProblem}}
  def escDf(s: String) = s.replace("\\", "\\\\").replace("\"", "\\\"").replace("\n", "\\n").replace("\r", "")
  def nodeKey(node: Any): String = node match {{
    case n: io.shiftleft.codepropertygraph.generated.nodes.StoredNode => n.id.toString
    case other => escDf(String.valueOf(other))
  }}
  def setJson(m: scala.collection.Map[_, _]): String = m.map {{ case (node, defs) =>
    val ids = defs.asInstanceOf[scala.collection.Set[_]].map(String.valueOf(_)).toSeq.sorted
    "\"" + nodeKey(node) + "\": [" + ids.mkString(", ") + "]"
  }}.mkString("{{", ", ", "}}")
  val entries = cpg.method.l.map {{ m =>
    val problem = ReachingDefProblem.create(m)
    val solution = new DataFlowSolver().calculateMopSolutionForwards(problem)
    "\"" + escDf(m.fullName) + "\": {{\"in\": " + setJson(solution.in) +
      ", \"out\": " + setJson(solution.out) + "}}"
  }}
  new PrintWriter("{out}") {{ write(entries.mkString("{{", ", ", "}}")); close }}
}}
"""


def available() -> bool:
    return shutil.which("joern") is not None


class JoernTimeout(RuntimeError):
    """The JVM stopped responding within the per-command timeout."""


class JoernSession:
    def __init__(
        self,
        worker_id: int = 0,
        timeout: float = 300.0,
        binary: str = "joern",
        max_restarts: int = 1,
    ):
        """timeout: per-command bound — a hung JVM raises JoernTimeout
        instead of blocking the worker forever (the reference's pexpect
        driver has the same per-expect timeout, joern_session.py:87-102).
        binary: override for tests (a marker-echoing stub stands in for
        the real JVM to exercise the protocol).
        max_restarts: after a JoernTimeout the wedged JVM is killed and
        the session is DEAD; up to this many times per session a fresh
        JVM is spawned, the last importCode replayed, and the timed-out
        command retried ONCE — so one hung JVM does not fail a whole
        extraction batch. 0 restores the old fail-fast behaviour."""
        if binary == "joern" and not available():
            raise RuntimeError("joern binary not on PATH")
        self.timeout = timeout
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self._binary = binary
        self._last_import: str | None = None
        self.workspace = Path(tempfile.mkdtemp(prefix=f"joern-ws-{worker_id}-"))
        self._spawn()

    def _spawn(self) -> None:
        """Start (or restart) the JVM + reader thread and handshake."""
        argv = (
            [self._binary, "--nocolors"]
            if self._binary == "joern"
            else [self._binary]
        )
        self.proc = subprocess.Popen(
            argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=self.workspace,
            text=True,
            bufsize=1,
        )
        # reader thread: readline on a pipe cannot be interrupted, so all
        # reads flow through a queue that _exchange polls with a deadline.
        # Restart replaces the queue; an old reader drains into the old
        # queue and exits at EOF of its killed process.
        self._lines: queue.Queue[str | None] = queue.Queue()
        self._reader = threading.Thread(
            target=self._pump, args=(self.proc, self._lines), daemon=True
        )
        self._reader.start()
        self._drain_until_ready()

    # -- protocol ------------------------------------------------------------

    @staticmethod
    def _pump(proc, lines) -> None:
        assert proc.stdout is not None
        for line in proc.stdout:
            lines.put(line)
        lines.put(None)  # EOF sentinel

    def _drain_until_ready(self) -> None:
        self._exchange("1 + 1")

    def _exchange(self, cmd: str, timeout: float | None = None) -> str:
        """One command/marker round-trip on the CURRENT process; kills it
        and raises JoernTimeout on deadline. Each round-trip is a
        cat="joern" span in the unified trace (docs/observability.md) —
        JVM time is a first-class stage in the merged timeline."""
        with obs_trace.span("joern_exchange", cat="joern", cmd=cmd[:80]):
            return self._exchange_inner(cmd, timeout)

    def _exchange_inner(self, cmd: str, timeout: float | None = None) -> str:
        import time

        assert self.proc.stdin is not None
        deadline = time.monotonic() + (timeout or self.timeout)
        self.proc.stdin.write(cmd + "\n")
        self.proc.stdin.write(f'println("{_MARKER}")\n')
        self.proc.stdin.flush()
        lines: list[str] = []
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.proc.kill()
                self.proc.wait(timeout=10)
                raise JoernTimeout(
                    f"joern command exceeded {timeout or self.timeout:.0f}s: "
                    f"{cmd[:120]!r}"
                )
            try:
                line = self._lines.get(timeout=min(remaining, 1.0))
            except queue.Empty:
                continue
            if line is None:
                raise RuntimeError("joern session terminated unexpectedly")
            if _MARKER in line and "println" not in line:
                break
            lines.append(line)
        return "".join(lines)

    def run_command(self, cmd: str, timeout: float | None = None) -> str:
        """Send one command; collect output up to the marker echo.

        On JoernTimeout the wedged JVM is killed; within the
        `max_restarts` budget a fresh JVM is spawned, the last
        importCode is replayed (project state dies with the JVM), and the
        command is retried once — a second timeout propagates."""
        import logging

        try:
            return self._exchange(cmd, timeout)
        except JoernTimeout:
            if self.restarts >= self.max_restarts:
                raise
            self.restarts += 1
            logging.getLogger(__name__).warning(
                "joern JVM hung; restart %d/%d and retrying %r",
                self.restarts, self.max_restarts, cmd[:80],
            )
            self._spawn()
            # replay the loaded project UNLESS the timed-out command was
            # the importCode itself — replaying and then retrying it
            # would import twice (and double the slowest operation's
            # chance of hitting the same timeout again)
            if self._last_import is not None and not cmd.startswith(
                "importCode("
            ):
                # replay under the session's own budget, not the failed
                # command's (possibly much shorter) per-command timeout —
                # an import that took 60s must not be bounded by a 10s
                # query timeout
                self._exchange(f'importCode("{self._last_import}")')
            return self._exchange(cmd, timeout)

    # -- operations ----------------------------------------------------------

    def import_code(self, path: str | Path) -> str:
        # remembered so a post-timeout JVM restart can reload the project
        # before retrying the command that timed out
        self._last_import = str(path)
        return self.run_command(f'importCode("{path}")')

    def export_cpg_json(self, source_path: str | Path) -> tuple[Path, Path]:
        """Export the loaded CPG next to `source_path` in the reference's
        .nodes.json/.edges.json layout (loadable by joern_io)."""
        nodes_out = str(source_path) + ".nodes.json"
        edges_out = str(source_path) + ".edges.json"
        script = _EXPORT_TEMPLATE.format(nodes_out=nodes_out, edges_out=edges_out)
        self.run_command(script)
        return Path(nodes_out), Path(edges_out)

    def export_dataflow_json(self, source_path: str | Path) -> Path:
        """Export Joern's reaching-definitions solution for the loaded CPG
        to `<source>.dataflow.json` (role of the reference's
        get_dataflow_output.sc; loadable by joern_io.load_joern_dataflow)."""
        out = str(source_path) + ".dataflow.json"
        self.run_command(_DATAFLOW_TEMPLATE.format(out=out))
        return Path(out)

    def export_cpg_bin(self, source_path: str | Path) -> Path:
        """Copy the loaded project's binary CPG next to `source_path` as
        `<source>.cpg.bin` (the reference exports the same artifact for
        re-import without re-parsing, get_func_graph.sc cpg.bin role).

        Joern names workspace projects after the imported file, so the
        project matching `source_path` is preferred; when absent (layout
        differences across joern versions) the fallback search is
        restricted to project directories whose name contains the imported
        file's name — a most-recent-anywhere pick could silently copy a
        stale or wrong project's CPG when the session has imported
        several files."""
        name = Path(source_path).name
        exact = self.workspace / "workspace" / name / "cpg.bin"
        if exact.exists():
            src = exact
        else:
            candidates = sorted(
                (
                    p
                    for p in self.workspace.rglob("cpg.bin")
                    if name in p.parent.name
                ),
                key=lambda p: p.stat().st_mtime,
            )
            if not candidates:
                raise RuntimeError(
                    f"no cpg.bin for project {name!r} under workspace "
                    f"{self.workspace}; import the file first"
                )
            src = candidates[-1]
        dest = Path(str(source_path) + ".cpg.bin")
        shutil.copyfile(src, dest)
        return dest

    def close(self) -> None:
        try:
            if self.proc.stdin is not None:
                self.proc.stdin.write(":exit\n")
                self.proc.stdin.flush()
            self.proc.wait(timeout=10)
        except Exception:
            self.proc.kill()
        shutil.rmtree(self.workspace, ignore_errors=True)

    def __enter__(self) -> "JoernSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
