"""Persistent Joern session driver (optional external backend).

The reference drives a long-lived interactive Joern JVM over a pexpect pty
with per-worker workspaces (DDFA/sastvd/helpers/joern_session.py:35-121);
this driver provides the same capability on plain subprocess pipes:

    with JoernSession(worker_id=3) as s:
        s.import_code("/path/to/file.c")
        s.run_command('cpg.method.name.l')
        s.export_cpg_json("/path/to/file.c")   # -> .nodes.json/.edges.json

Export output is the format frontend/joern_io.py imports, so Joern-exact
CPGs flow into the same pipeline as the built-in frontend. The session is
only usable where a `joern` binary exists (it is an external JVM tool,
exactly as in the reference); `available()` reports that.
"""

from __future__ import annotations

import queue
import shutil
import subprocess
import tempfile
import threading
from pathlib import Path

_MARKER = "===DEEPDFA_DONE==="

# scala snippet exporting nodes/edges json for the currently loaded cpg,
# mirroring the reference export surface (get_func_graph.sc): all nodes
# with their property map, all edges as [inNode, outNode, label] rows.
_EXPORT_TEMPLATE = r"""
{{
  import java.io.PrintWriter
  val nodes = cpg.all.map {{ n =>
    val m = scala.collection.mutable.Map[String, Any]("id" -> n.id, "_label" -> n.label)
    n.propertiesMap.forEach {{ (k, v) => m(k) = v }}
    m
  }}.l
  def esc(s: String) = s.replace("\\", "\\\\").replace("\"", "\\\"").replace("\n", "\\n").replace("\r", "")
  def jval(v: Any): String = v match {{
    case i: java.lang.Integer => i.toString
    case l: java.lang.Long => l.toString
    case s: String => "\"" + esc(s) + "\""
    case other => "\"" + esc(String.valueOf(other)) + "\""
  }}
  val nodesJson = nodes.map {{ m =>
    "{{" + m.map {{ case (k, v) =>
      val key = if (k == "LINE_NUMBER") "lineNumber" else if (k == "TYPE_FULL_NAME") "typeFullName"
        else if (k == "NAME") "name" else if (k == "CODE") "code" else if (k == "ORDER") "order" else k
      "\"" + key + "\": " + jval(v)
    }}.mkString(", ") + "}}"
  }}.mkString("[", ",\n", "]")
  new PrintWriter("{nodes_out}") {{ write(nodesJson); close }}
  val edgesJson = cpg.graph.edges().map {{ e =>
    "[" + e.inNode.id + ", " + e.outNode.id + ", \"" + e.label + "\", \"\"]"
  }}.l.mkString("[", ",\n", "]")
  new PrintWriter("{edges_out}") {{ write(edgesJson); close }}
}}
"""


def available() -> bool:
    return shutil.which("joern") is not None


class JoernTimeout(RuntimeError):
    """The JVM stopped responding within the per-command timeout."""


class JoernSession:
    def __init__(
        self, worker_id: int = 0, timeout: float = 300.0, binary: str = "joern"
    ):
        """timeout: per-command bound — a hung JVM raises JoernTimeout
        instead of blocking the worker forever (the reference's pexpect
        driver has the same per-expect timeout, joern_session.py:87-102).
        binary: override for tests (a marker-echoing stub stands in for
        the real JVM to exercise the protocol)."""
        if binary == "joern" and not available():
            raise RuntimeError("joern binary not on PATH")
        self.timeout = timeout
        self.workspace = Path(tempfile.mkdtemp(prefix=f"joern-ws-{worker_id}-"))
        argv = [binary, "--nocolors"] if binary == "joern" else [binary]
        self.proc = subprocess.Popen(
            argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=self.workspace,
            text=True,
            bufsize=1,
        )
        # reader thread: readline on a pipe cannot be interrupted, so all
        # reads flow through a queue that run_command polls with a deadline
        self._lines: queue.Queue[str | None] = queue.Queue()
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()
        self._drain_until_ready()

    # -- protocol ------------------------------------------------------------

    def _pump(self) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            self._lines.put(line)
        self._lines.put(None)  # EOF sentinel

    def _drain_until_ready(self) -> None:
        self.run_command("1 + 1")

    def run_command(self, cmd: str, timeout: float | None = None) -> str:
        """Send one command; collect output up to the marker echo.

        Raises JoernTimeout when the whole exchange exceeds the bound (the
        session is killed — a wedged JVM is not reusable)."""
        import time

        assert self.proc.stdin is not None
        deadline = time.monotonic() + (timeout or self.timeout)
        self.proc.stdin.write(cmd + "\n")
        self.proc.stdin.write(f'println("{_MARKER}")\n')
        self.proc.stdin.flush()
        lines: list[str] = []
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.proc.kill()
                self.proc.wait(timeout=10)
                raise JoernTimeout(
                    f"joern command exceeded {timeout or self.timeout:.0f}s: "
                    f"{cmd[:120]!r}"
                )
            try:
                line = self._lines.get(timeout=min(remaining, 1.0))
            except queue.Empty:
                continue
            if line is None:
                raise RuntimeError("joern session terminated unexpectedly")
            if _MARKER in line and "println" not in line:
                break
            lines.append(line)
        return "".join(lines)

    # -- operations ----------------------------------------------------------

    def import_code(self, path: str | Path) -> str:
        return self.run_command(f'importCode("{path}")')

    def export_cpg_json(self, source_path: str | Path) -> tuple[Path, Path]:
        """Export the loaded CPG next to `source_path` in the reference's
        .nodes.json/.edges.json layout (loadable by joern_io)."""
        nodes_out = str(source_path) + ".nodes.json"
        edges_out = str(source_path) + ".edges.json"
        script = _EXPORT_TEMPLATE.format(nodes_out=nodes_out, edges_out=edges_out)
        self.run_command(script)
        return Path(nodes_out), Path(edges_out)

    def close(self) -> None:
        try:
            if self.proc.stdin is not None:
                self.proc.stdin.write(":exit\n")
                self.proc.stdin.flush()
            self.proc.wait(timeout=10)
        except Exception:
            self.proc.kill()
        shutil.rmtree(self.workspace, ignore_errors=True)

    def __enter__(self) -> "JoernSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
