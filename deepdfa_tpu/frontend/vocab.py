"""Abstract-dataflow vocabulary: train-split hash -> embedding index.

Reimplements the reference's vocab pipeline
(DDFA/sastvd/helpers/datasets.py:587-692 abs_dataflow +
DDFA/sastvd/scripts/dbize_absdf.py):

1. per subkey, the "known" values are the limit_subkeys most frequent
   values over TRAIN-split definition nodes (datatype is single-valued,
   others multi-valued — `single` table, datasets.py:551-556);
2. each definition node gets an "all"-hash: json of
   {subkey: sorted set of values, unknown values replaced by "UNKNOWN"};
3. the vocab is the limit_all most frequent train all-hashes;
4. node feature index: 0 = not a definition, 1 = UNKNOWN hash,
   2 + rank = known hash (dbize_absdf.py:35-42; input_dim = limit_all + 2).

The flagship model uses four independent single-subkey vocabs
(feat `_ABS_DATAFLOW_{subkey}_all_...` per embedding table).
"""

from __future__ import annotations

import dataclasses
import json
from collections import Counter
from typing import Iterable, Mapping

Fields = list[tuple[str, str]]  # (subkey, value) pairs for one def node

SINGLE_VALUED = {"api": False, "datatype": True, "literal": False, "operator": False}

NOT_A_DEF = 0
UNKNOWN_IDX = 1


def _subkey_values(fields: Fields, subkey: str) -> list[str]:
    """Raw values of one subkey for a node, in stage-2 hash order (sorted)."""
    return sorted(v for k, v in fields if k == subkey)


def _node_all_hash(
    fields: Fields, subkey: str, known: set[str] | None
) -> str | None:
    """The "all" hash for one node and one subkey; None if the node has no
    values for this subkey (reference: hash.{subkey} is NaN after explode)."""
    values = _subkey_values(fields, subkey)
    if not values:
        return None
    if SINGLE_VALUED[subkey]:
        vals = [values[0]]
    else:
        vals = sorted(set(values))
    if known is not None:
        vals = [v if v in known else "UNKNOWN" for v in vals]
    return json.dumps({subkey: sorted(set(vals))})


@dataclasses.dataclass
class AbsDfVocab:
    """One subkey's hash->index vocabulary."""

    subkey: str
    limit_all: int
    limit_subkeys: int
    known_values: tuple[str, ...]  # top train values (freq order)
    hash_index: dict[str, int]  # all-hash -> rank (0-based)

    def __post_init__(self):
        self._known_set = frozenset(self.known_values)

    def encode(self, fields: Fields | None) -> int:
        """Embedding index for one node (0 not-def / 1 unknown / 2+rank)."""
        if fields is None:
            return NOT_A_DEF
        h = _node_all_hash(fields, self.subkey, self._known_set)
        if h is None:
            return NOT_A_DEF
        rank = self.hash_index.get(h)
        return UNKNOWN_IDX if rank is None else rank + 2

    @property
    def input_dim(self) -> int:
        return self.limit_all + 2

    def to_json(self) -> dict:
        return {
            "subkey": self.subkey,
            "limit_all": self.limit_all,
            "limit_subkeys": self.limit_subkeys,
            "known_values": list(self.known_values),
            "hashes": [h for h, _ in sorted(self.hash_index.items(), key=lambda kv: kv[1])],
        }

    @classmethod
    def from_json(cls, d: dict) -> "AbsDfVocab":
        return cls(
            subkey=d["subkey"],
            limit_all=d["limit_all"],
            limit_subkeys=d["limit_subkeys"],
            known_values=tuple(d["known_values"]),
            hash_index={h: i for i, h in enumerate(d["hashes"])},
        )


def build_vocab(
    train_node_fields: Iterable[Fields],
    subkey: str,
    limit_all: int | None = 1000,
    limit_subkeys: int | None = 1000,
) -> AbsDfVocab:
    """Build one subkey vocab from TRAIN-split definition-node fields."""
    train_node_fields = list(train_node_fields)

    # step 1: known values = most frequent train values
    counts: Counter[str] = Counter()
    for fields in train_node_fields:
        values = _subkey_values(fields, subkey)
        if not values:
            continue
        if SINGLE_VALUED[subkey]:
            counts[values[0]] += 1
        else:
            # reference explodes sorted set -> one count per distinct value
            for v in sorted(set(values)):
                counts[v] += 1
    most = counts.most_common(limit_subkeys)
    known = tuple(v for v, _ in most)

    # step 2+3: all-hash frequency over train
    known_set = set(known)
    hash_counts: Counter[str] = Counter()
    for fields in train_node_fields:
        h = _node_all_hash(fields, subkey, known_set)
        if h is not None:
            hash_counts[h] += 1
    top = hash_counts.most_common(limit_all)
    hash_index = {h: i for i, (h, _) in enumerate(top)}
    return AbsDfVocab(
        subkey=subkey,
        limit_all=limit_all if limit_all is not None else len(hash_index),
        limit_subkeys=limit_subkeys if limit_subkeys is not None else len(known),
        known_values=known,
        hash_index=hash_index,
    )


def build_vocabs(
    train_node_fields: Iterable[Fields],
    subkeys: Iterable[str] = ("api", "datatype", "literal", "operator"),
    limit_all: int | None = 1000,
    limit_subkeys: int | None = 1000,
) -> dict[str, AbsDfVocab]:
    cached = list(train_node_fields)
    return {
        sk: build_vocab(cached, sk, limit_all, limit_subkeys) for sk in subkeys
    }


def encode_nodes(
    vocabs: Mapping[str, AbsDfVocab],
    node_fields: Mapping[int, Fields],
    node_ids: Iterable[int],
    subkey_order: Iterable[str] = ("api", "datatype", "literal", "operator"),
) -> "np.ndarray":
    """Feature matrix [n_nodes, n_subkeys] of embedding indices."""
    import numpy as np

    order = list(subkey_order)
    ids = list(node_ids)
    out = np.zeros((len(ids), len(order)), np.int32)
    for i, nid in enumerate(ids):
        fields = node_fields.get(nid)
        for j, sk in enumerate(order):
            out[i, j] = vocabs[sk].encode(fields)
    return out
