"""CPG-lite: the in-memory code property graph produced by the built-in
C frontend (and by the optional Joern import path).

Schema is deliberately Joern-compatible (node labels CALL / IDENTIFIER /
LITERAL / LOCAL / METHOD / METHOD_RETURN / METHOD_PARAMETER_IN /
FIELD_IDENTIFIER / RETURN / UNKNOWN; edge types AST / CFG / ARGUMENT;
operator call names like "<operator>.assignment") because the entire
downstream feature definition in the reference keys off those strings:
- mod-op detection (DDFA/code_gnn/analysis/dataflow.py:60-84)
- is_decl / datatype recursion / subkey extraction
  (DDFA/sastvd/scripts/abstract_dataflow_full.py:24-167)
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Iterable

AST = "AST"
CFG = "CFG"
ARGUMENT = "ARGUMENT"

# Joern operator-call names (joern.io default.semantics / operatorextension)
OP_NAMES = {
    "=": "<operator>.assignment",
    "+=": "<operator>.assignmentPlus",
    "-=": "<operator>.assignmentMinus",
    "*=": "<operator>.assignmentMultiplication",
    "/=": "<operator>.assignmentDivision",
    "%=": "<operator>.assignmentModulo",
    "&=": "<operator>.assignmentAnd",
    "|=": "<operator>.assignmentOr",
    "^=": "<operator>.assignmentXor",
    "<<=": "<operator>.assignmentShiftLeft",
    ">>=": "<operator>.assignmentArithmeticShiftRight",
    "+": "<operator>.addition",
    "-": "<operator>.subtraction",
    "*": "<operator>.multiplication",
    "/": "<operator>.division",
    "%": "<operator>.modulo",
    "==": "<operator>.equals",
    "!=": "<operator>.notEquals",
    "<": "<operator>.lessThan",
    ">": "<operator>.greaterThan",
    "<=": "<operator>.lessEqualsThan",
    ">=": "<operator>.greaterEqualsThan",
    "&&": "<operator>.logicalAnd",
    "||": "<operator>.logicalOr",
    "&": "<operator>.and",
    "|": "<operator>.or",
    "^": "<operator>.xor",
    "<<": "<operator>.shiftLeft",
    ">>": "<operator>.arithmeticShiftRight",
}

UNARY_OP_NAMES = {
    "!": "<operator>.logicalNot",
    "~": "<operator>.not",
    "-": "<operator>.minus",
    "+": "<operator>.plus",
    "*": "<operator>.indirection",
    "&": "<operator>.addressOf",
}

PRE_INC_DEC = {"++": "<operator>.preIncrement", "--": "<operator>.preDecrement"}
POST_INC_DEC = {"++": "<operator>.postIncrement", "--": "<operator>.postDecrement"}

FIELD_ACCESS = "<operator>.fieldAccess"
INDIRECT_FIELD_ACCESS = "<operator>.indirectFieldAccess"
INDEX_ACCESS = "<operator>.indirectIndexAccess"  # joern's name for C subscripts
CAST = "<operator>.cast"
CONDITIONAL = "<operator>.conditional"
SIZEOF = "<operator>.sizeOf"
COMMA = "<operator>.expressionList"


@dataclasses.dataclass
class Node:
    id: int
    label: str  # _label in joern terms
    name: str = ""
    code: str = ""
    line: int | None = None
    order: int = 0
    type_full_name: str = "ANY"


class Cpg:
    """Mutable CPG under construction; read interfaces used downstream."""

    def __init__(self, method_name: str = "<fn>"):
        self.method_name = method_name
        self.nodes: list[Node] = []
        self.edges: list[tuple[int, int, str]] = []  # (src, dst, etype)
        self._out: dict[str, dict[int, list[int]]] = defaultdict(
            lambda: defaultdict(list)
        )
        self._in: dict[str, dict[int, list[int]]] = defaultdict(
            lambda: defaultdict(list)
        )
        self.method_id: int | None = None
        self.method_return_id: int | None = None

    # -- construction -------------------------------------------------------

    def add_node(
        self,
        label: str,
        name: str = "",
        code: str = "",
        line: int | None = None,
        order: int = 0,
        type_full_name: str = "ANY",
    ) -> int:
        nid = len(self.nodes)
        self.nodes.append(Node(nid, label, name, code, line, order, type_full_name))
        return nid

    def add_edge(self, src: int, dst: int, etype: str) -> None:
        self.edges.append((src, dst, etype))
        self._out[etype][src].append(dst)
        self._in[etype][dst].append(src)

    # -- queries -------------------------------------------------------------

    def node(self, nid: int) -> Node:
        return self.nodes[nid]

    def successors(self, nid: int, etype: str) -> list[int]:
        return self._out[etype].get(nid, [])

    def predecessors(self, nid: int, etype: str) -> list[int]:
        return self._in[etype].get(nid, [])

    def cfg_nodes(self) -> list[int]:
        """Nodes participating in at least one CFG edge."""
        seen: set[int] = set()
        for s, d, t in self.edges:
            if t == CFG:
                seen.add(s)
                seen.add(d)
        return sorted(seen)

    def arguments(self, call_id: int) -> list[int]:
        """ARGUMENT successors sorted by their `order` attribute."""
        args = self.successors(call_id, ARGUMENT)
        return sorted(args, key=lambda a: self.nodes[a].order)

    def ast_descendants(self, root: int, skip_labels: Iterable[str] = ()) -> set[int]:
        """All AST descendants of `root` (root excluded), skipping subtrees
        rooted at nodes whose label is in skip_labels (reference behavior:
        METHOD subtrees are excluded, abstract_dataflow_full.py:137-145)."""
        skip = set(skip_labels)
        out: set[int] = set()
        stack = list(self.successors(root, AST))
        while stack:
            n = stack.pop()
            if self.nodes[n].label in skip or n in out:
                continue
            out.add(n)
            stack.extend(self.successors(n, AST))
        return out

    def __repr__(self):
        return (
            f"Cpg({self.method_name!r}, {len(self.nodes)} nodes, "
            f"{len(self.edges)} edges)"
        )
