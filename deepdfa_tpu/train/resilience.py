"""Preemption-safe, self-healing training runtime.

The reference leaned on PyTorch Lightning's checkpoint/resume machinery
(DDFA/code_gnn/main_cli.py, periodic_checkpoint.py) and restarted from
epoch boundaries; large-scale GNN trainers (Morphling, DGL — PAPERS.md)
treat restartability and stall detection as first-class runtime
requirements. This module is that runtime for all three train loops
(train/loop.py, train/combined_loop.py, train/gen_loop.py):

- **StepCheckpointer** — step-granular atomic checkpoints of the FULL
  TrainState (params + optimizer + LR-schedule step) plus a resume
  manifest carrying the data-pipeline cursor (epoch index, batch-plan
  position, global step, seed). Manifests are written tmp+fsync+rename
  (core/ioutil.py) and a sidecar cursor file per checkpoint lets a
  corrupt manifest be rebuilt from what is actually on disk.
- **PreemptionHandler** — SIGTERM/SIGINT set a flag; the loop finishes
  the in-flight step, checkpoints, and raises `Preempted`, which the CLI
  turns into a clean exit (EXIT_PREEMPTED) with the manifest printed.
- **divergence guard** (host half; the device half lives in each loop's
  `train_step_guarded`) — the jitted step computes loss/grad-norm
  finiteness ON DEVICE and skips poisoned updates via a select, so
  params and optimizer state never ingest a NaN; the host fetches the
  per-step ok flag `guard_lag` steps late (no sync on the happy path),
  counts skips, and after `max_consecutive_bad` consecutive bad steps
  rolls back to the last-good step checkpoint with an LR cool-down,
  bounded by `rollback_budget`.
- **Watchdog** — a daemon thread fed by loop heartbeats; when no beat
  lands for `watchdog_timeout_s`, it writes a stage-attributed
  diagnostic (input vs device, plus a PipelineStats snapshot) and aborts
  instead of hanging forever.

Resume semantics: batch streams are pure functions of (epoch, seed, data
digest) — the loops fast-forward the stream past the consumed batches,
restore the exact TrainState, and the step-loss trajectory continues
bit-identically with the uninterrupted run (tests/test_resilience.py).

Observability: every self-healing event (stall, skip, rollback, resume,
preemption) also lands in the unified telemetry stream — cat="resilience"
instants in the cross-process trace plus `obs/resilience/*` registry
counters (deepdfa_tpu/obs/, docs/observability.md) — so `deepdfa-tpu
diag <run_dir>` reconstructs the run's failure history without parsing
logs. No-ops when telemetry is off.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import signal
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable

from deepdfa_tpu.core.config import ResilienceConfig
from deepdfa_tpu.core.ioutil import atomic_write_text, with_retries
from deepdfa_tpu.obs import (
    flight as obs_flight,
    metrics as obs_metrics,
    trace as obs_trace,
)

logger = logging.getLogger(__name__)

#: process exit codes: 128+SIGTERM for a clean preemption exit (what a
#: scheduler that sent the signal expects), and a distinct code for a
#: watchdog abort so wrappers can tell "hung" from "killed"
EXIT_PREEMPTED = 143
EXIT_WATCHDOG = 113


class Preempted(RuntimeError):
    """A preemption signal arrived; the in-flight step was finished and
    (when a checkpointer is attached) the state + resume manifest were
    written before this was raised."""

    def __init__(self, message: str, manifest: Path | None = None):
        super().__init__(message)
        self.manifest = manifest


class DivergenceError(RuntimeError):
    """The divergence guard exhausted its rollback budget."""


@dataclasses.dataclass(frozen=True)
class ResumeCursor:
    """Data-pipeline position a checkpoint corresponds to: the batch
    stream for `epoch` has had `batch_index` batches consumed, and the
    optimizer has taken `step` global steps."""

    epoch: int
    batch_index: int
    step: int


# ---------------------------------------------------------------------------
# preemption


class PreemptionHandler:
    """Installs SIGTERM/SIGINT handlers that set a flag (the loop polls
    it after each step). A SECOND signal restores the previous handlers
    and re-raises, so an operator's double Ctrl-C still kills a run whose
    checkpoint write wedged. Signal handlers are process-global and only
    installable from the main thread; elsewhere this degrades to a
    flag that `trigger()` (the fault harness) can still set."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = signals
        self._previous: dict[int, Any] = {}
        self._triggered = threading.Event()
        self._installed = False

    @property
    def triggered(self) -> bool:
        return self._triggered.is_set()

    def trigger(self) -> None:
        self._triggered.set()

    def _handle(self, signum, frame) -> None:
        if self._triggered.is_set():
            # second signal: get out of the way and re-deliver
            self.uninstall()
            os.kill(os.getpid(), signum)
            return
        logger.warning(
            "received %s: finishing the in-flight step, then "
            "checkpointing and exiting cleanly",
            signal.Signals(signum).name,
        )
        self._triggered.set()

    def install(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "preemption handler not installed (not the main thread); "
                "only injected triggers will be observed"
            )
            return self
        for s in self._signals:
            self._previous[s] = signal.signal(s, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):  # not main thread / shutdown
                pass
        self._previous.clear()
        self._installed = False


# ---------------------------------------------------------------------------
# step-granular checkpoints


class StepCheckpointer:
    """Atomic step-granular TrainState checkpoints + resume manifest.

    Layout:

        <directory>/step-00000042/          orbax pytree (full TrainState)
        <directory>/step-00000042.cursor.json  sidecar written AFTER the
                                               orbax save completes
        <directory>/resume.json             newest complete checkpoint

    The sidecar is the completeness marker: it is written atomically
    after `wait_until_finished`, so a crash mid-save leaves a dir with no
    sidecar, which `latest()`/retention treat as garbage. A corrupt
    `resume.json` is rebuilt from the sidecars actually on disk.
    """

    def __init__(self, directory: str | Path, keep_last: int = 3):
        import orbax.checkpoint as ocp

        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.keep_last = max(1, int(keep_last))
        self._ckpt = ocp.StandardCheckpointer()

    # -- write ---------------------------------------------------------------

    @staticmethod
    def _tag(step: int) -> str:
        return f"step-{step:08d}"

    def save(self, host_state: Any, cursor: ResumeCursor, seed: int = 0,
             reason: str = "periodic", extra: dict | None = None) -> Path:
        """Persist a host-side TrainState pytree at `cursor`. Returns the
        resume-manifest path. Idempotent per step (force-overwrites).
        `extra` rides along in the manifest (the runner stores its guard
        state there so cool-downs/budgets survive a preemption)."""
        tag = self._tag(cursor.step)
        self._ckpt.save(self.directory / tag, host_state, force=True)
        self._ckpt.wait_until_finished()
        manifest = {
            "tag": tag,
            "step": int(cursor.step),
            "epoch": int(cursor.epoch),
            "batch_index": int(cursor.batch_index),
            "seed": int(seed),
            "reason": reason,
            "wall_time": time.time(),
            **(extra or {}),
        }
        payload = json.dumps(manifest, indent=2)
        atomic_write_text(self.directory / f"{tag}.cursor.json", payload)
        atomic_write_text(self.directory / "resume.json", payload)
        self._retain()
        return self.directory / "resume.json"

    def _retain(self) -> None:
        complete = sorted(
            p.name[: -len(".cursor.json")]
            for p in self.directory.glob("step-*.cursor.json")
        )
        for tag in complete[: -self.keep_last]:
            shutil.rmtree(self.directory / tag, ignore_errors=True)
            (self.directory / f"{tag}.cursor.json").unlink(missing_ok=True)
        # a dir without a sidecar is an interrupted save: collect it
        # unless it is the newest (a save may be in flight elsewhere)
        dirs = sorted(p.name for p in self.directory.glob("step-*")
                      if p.is_dir())
        for tag in dirs[:-1]:
            if not (self.directory / f"{tag}.cursor.json").exists():
                shutil.rmtree(self.directory / tag, ignore_errors=True)

    # -- read ----------------------------------------------------------------

    def latest(self) -> dict | None:
        """The newest complete checkpoint's manifest, or None. Tolerates
        a corrupt/missing resume.json by rebuilding from the sidecars."""
        path = self.directory / "resume.json"
        if path.exists():
            try:
                m = json.loads(path.read_text())
                if (self.directory / m["tag"]).is_dir():
                    return m
                logger.warning(
                    "resume.json points at missing checkpoint %s; "
                    "rebuilding from on-disk sidecars", m.get("tag"),
                )
            except (json.JSONDecodeError, KeyError, OSError) as e:
                logger.warning(
                    "corrupt resume.json (%s: %s); rebuilding from "
                    "on-disk sidecars", type(e).__name__, e,
                )
        best = None
        for sc in self.directory.glob("step-*.cursor.json"):
            try:
                m = json.loads(sc.read_text())
            except (json.JSONDecodeError, OSError):
                continue
            if not (self.directory / m.get("tag", "")).is_dir():
                continue
            if best is None or m["step"] > best["step"]:
                best = m
        if best is not None:
            atomic_write_text(
                self.directory / "resume.json", json.dumps(best, indent=2)
            )
        return best

    def restore(self, manifest: dict, target: Any) -> Any:
        """Restore the checkpoint named by `manifest` into the structure
        of `target` (a concrete host pytree, e.g. device_get of a
        freshly initialized state)."""
        return self._ckpt.restore(self.directory / manifest["tag"],
                                  target=target)


# ---------------------------------------------------------------------------
# watchdog


class Watchdog:
    """Detects a silent train loop: the loop beats before every stage
    transition (input pull, device step); when no beat lands within
    `timeout_s`, the watchdog writes a stage-attributed diagnostic and
    invokes `on_stall` (default: hard process abort — a hung device step
    cannot be unwound from a thread)."""

    def __init__(
        self,
        timeout_s: float,
        on_stall: Callable[[dict], None] | None = None,
        diagnostic_path: str | Path | None = None,
        poll_s: float | None = None,
        first_step_grace_s: float | None = None,
    ):
        """first_step_grace_s: stall threshold until the FIRST completed
        step (`step_done()`): the first step legitimately includes jit
        compilation — minutes on a TPU with a remote compile service —
        which a steady-state timeout would misread as a device hang.
        None/0 = 10x timeout_s."""
        self.timeout_s = float(timeout_s)
        self.first_step_grace_s = (
            float(first_step_grace_s)
            if first_step_grace_s
            else 10.0 * self.timeout_s
        )
        self.on_stall = on_stall if on_stall is not None else self._abort
        self.diagnostic_path = (
            Path(diagnostic_path) if diagnostic_path else None
        )
        self.poll_s = poll_s if poll_s is not None else min(
            1.0, max(0.05, self.timeout_s / 4)
        )
        self._lock = threading.Lock()
        self._last = time.monotonic()
        self._stage = "start"
        self._ctx: dict = {}
        self._stats = None  # optional PipelineStats for the diagnostic
        self._stepped = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.fired = False

    def beat(self, stage: str, **ctx) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._stage = stage
            if ctx:
                self._ctx = ctx

    def step_done(self) -> None:
        """A full train step completed: compiles are behind us, drop to
        the steady-state stall threshold."""
        self._stepped = True

    #: stages the steady-state timeout applies to — the in-loop batch
    #: pull and step dispatch. Anything else the loops announce (eval,
    #: checkpoint, epoch-end work) is legitimately long and bounded by
    #: the grace threshold instead, so a minutes-long BLEU decode or an
    #: orbax commit is not misread as a stall.
    STEADY_STAGES = frozenset({"input", "device"})

    def attach_stats(self, stats) -> None:
        self._stats = stats

    def start(self) -> "Watchdog":
        self.beat("start")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="train-watchdog"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                elapsed = time.monotonic() - self._last
                stage, ctx = self._stage, dict(self._ctx)
                threshold = (
                    self.timeout_s
                    if self._stepped and stage in self.STEADY_STAGES
                    else self.first_step_grace_s
                )
            if elapsed <= threshold:
                continue
            self.fired = True
            diag = self._diagnostic(stage, elapsed, ctx)
            # the stall joins the unified event stream (diag CLI renders
            # it); flush because the default on_stall is os._exit, which
            # skips the tracer's atexit hook
            obs_metrics.REGISTRY.counter(
                "obs/resilience/watchdog_stalls"
            ).inc()
            obs_trace.instant(
                "train_stall", cat="resilience", stage=stage,
                elapsed_s=round(elapsed, 1), **ctx,
            )
            obs_trace.flush()
            # flight recorder (docs/efficiency.md): the postmortem is
            # written BEFORE on_stall because the default on_stall is
            # os._exit — the last N steps + recent instants + ledger
            # must already be on disk when the process dies
            obs_flight.crash_dump("watchdog_abort", extra=diag)
            logger.critical("watchdog: %s", json.dumps(diag))
            if self.diagnostic_path is not None:
                try:
                    atomic_write_text(
                        self.diagnostic_path, json.dumps(diag, indent=2)
                    )
                except OSError:
                    pass
            self.on_stall(diag)
            return

    def _diagnostic(self, stage: str, elapsed: float, ctx: dict) -> dict:
        # stage attribution: "input" = the consumer was pulling the next
        # batch when it went silent (stalled producer / source), "device"
        # = it was inside a train-step dispatch or a result fetch (hung
        # device step or collective)
        diag = {
            "event": "train_stall",
            "stalled_stage": stage,
            "seconds_since_heartbeat": round(elapsed, 1),
            "timeout_s": self.timeout_s,
            **ctx,
        }
        stats = self._stats
        if stats is not None:
            try:
                diag["pipeline"] = stats.record()
            except Exception:  # diagnostics must never mask the stall
                pass
        return diag

    @staticmethod
    def _abort(diag: dict) -> None:
        # flush what we can, then leave: a hung XLA call cannot be
        # interrupted from a thread, so a hard exit is the only way to
        # return the machine to the scheduler
        print(f"FATAL train stall: {json.dumps(diag)}", flush=True)
        os._exit(EXIT_WATCHDOG)


# ---------------------------------------------------------------------------
# the runner the loops talk to


class ResilientRunner:
    """One object the fit loops thread their steps through.

    Lifecycle::

        res = ResilientRunner(cfg.train.resilience, run_dir / "checkpoints-step")
        with res:                                   # signals + watchdog
            state, cursor = res.maybe_resume(state, place)
            for epoch ...:
                res.attach_stats(stats)
                ...
                res.heartbeat("input"); batch = next(it)
                res.heartbeat("device")
                state, loss, ok = train_step_guarded(state, batch, res.lr_scale())
                state = res.after_step(state, ok, ResumeCursor(...))

    `after_step` is where everything meets: guard bookkeeping (lagged ok
    fetch, skip counting, rollback), the periodic step checkpoint, and
    the preemption check (raises `Preempted` after saving).

    The three fit loops implement this sequence by hand (their inner
    loops differ: prefetch+placer, prefetch+place+token-accounting,
    plain iterator) — when changing the protocol here, update all three
    in lockstep (train/loop.py, train/combined_loop.py,
    train/gen_loop.py).
    """

    def __init__(
        self,
        rcfg: ResilienceConfig,
        directory: str | Path | None = None,
        seed: int = 0,
        on_stall: Callable[[dict], None] | None = None,
        read_only: bool = False,
    ):
        """read_only: multi-host non-primary mode (docs/sharding.md) —
        the runner RESTORES from the shared checkpoint directory (every
        host must resume the same state and fast-forward the same
        cursor, or the collectives diverge) but never writes: process 0
        owns the saves and the resume manifest."""
        self.rcfg = rcfg
        self.seed = int(seed)
        self.read_only = bool(read_only)
        self.ckpt = (
            StepCheckpointer(directory, keep_last=rcfg.keep_last_k)
            if directory is not None
            else None
        )
        self.guard_active = bool(rcfg.enabled and rcfg.divergence_guard)
        self.handler = PreemptionHandler()
        self.watchdog = (
            Watchdog(
                rcfg.watchdog_timeout_s,
                on_stall=on_stall,
                diagnostic_path=(
                    Path(directory) / "watchdog_diagnostic.json"
                    if directory is not None
                    else None
                ),
                first_step_grace_s=getattr(
                    rcfg, "watchdog_first_step_grace_s", 0.0
                ),
            )
            if rcfg.watchdog_timeout_s > 0
            else None
        )
        self._place: Callable[[Any], Any] | None = None
        self._pending: deque[Any] = deque()  # lagged ok flags
        self._consec_bad = 0
        self._lr_scale = 1.0
        # counters surfaced into epoch records / bench history
        self.skipped_steps = 0
        self.rollbacks = 0
        self.resumed_from_step = 0
        # topology stamp (parallel/sharding.py:mesh_record) the loops
        # set before maybe_resume: rides every manifest so an ELASTIC
        # resume (same num_shards, different dp) is distinguishable from
        # a layout drift (different num_shards -> trajectory alignment
        # broken, warned loudly)
        self.topology: dict | None = None

    # -- context management ---------------------------------------------------

    def __enter__(self) -> "ResilientRunner":
        self.handler.install()
        if self.watchdog is not None:
            self.watchdog.start()
        return self

    def __exit__(self, *exc) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        self.handler.uninstall()

    # -- loop surface ---------------------------------------------------------

    def heartbeat(self, stage: str, **ctx) -> None:
        if self.watchdog is not None:
            self.watchdog.beat(stage, **ctx)

    def attach_stats(self, stats) -> None:
        if self.watchdog is not None:
            self.watchdog.attach_stats(stats)

    def set_topology(self, topology: dict) -> None:
        """Record the run's mesh/logical-shard layout for the resume
        manifests (the elastic-resume audit trail)."""
        self.topology = dict(topology)

    def lr_scale(self) -> float:
        """Effective LR multiplier (cooled down after rollbacks)."""
        return self._lr_scale

    def maybe_resume(
        self, state: Any, place: Callable[[Any], Any] | None = None
    ) -> tuple[Any, ResumeCursor | None]:
        """Restore the newest step checkpoint when auto_resume is on.

        `place` re-commits a restored host pytree to devices (the loop
        builds it from the live state's shardings); it is retained for
        divergence rollbacks either way."""
        import jax

        self._place = place
        if (
            self.ckpt is None
            or not self.rcfg.auto_resume
            or not self.rcfg.enabled
        ):
            return state, None
        manifest = self.ckpt.latest()
        if manifest is None:
            return state, None
        if manifest.get("seed", self.seed) != self.seed:
            logger.warning(
                "resume manifest seed %s != run seed %s — refusing to "
                "resume a different run's checkpoint",
                manifest.get("seed"), self.seed,
            )
            return state, None
        restored = self.ckpt.restore(manifest, jax.device_get(state))
        if place is not None:
            restored = place(restored)
        cursor = ResumeCursor(
            epoch=int(manifest["epoch"]),
            batch_index=int(manifest["batch_index"]),
            step=int(manifest["step"]),
        )
        self.resumed_from_step = cursor.step
        obs_metrics.REGISTRY.gauge("obs/resilience/resumed_from_step").set(
            cursor.step
        )
        obs_trace.instant(
            "resumed", cat="resilience", step=cursor.step,
            epoch=cursor.epoch, batch_index=cursor.batch_index,
        )
        # guard state survives the restart: a cooled-down LR stays
        # cooled, and rollback_budget bounds rollbacks ACROSS restarts —
        # otherwise a preempt/diverge cycle could repeat at full LR
        # forever instead of failing loudly
        guard = manifest.get("guard")
        if guard:
            self._lr_scale = float(guard.get("lr_scale", 1.0))
            self.rollbacks = int(guard.get("rollbacks", 0))
            self.skipped_steps = int(guard.get("skipped_steps", 0))
        # elastic resume (docs/sharding.md): a dp change with the SAME
        # num_shards restores bit-exactly (the logical-shard layout
        # fixes the batch stream and the reduction tree); a num_shards
        # drift breaks batch alignment — resume proceeds, but the
        # trajectory contract is void, so say it loudly
        saved_topo = manifest.get("mesh")
        if saved_topo and self.topology:
            saved_s = saved_topo.get("num_shards")
            cur_s = self.topology.get("num_shards")
            if saved_s is not None and cur_s is not None and saved_s != cur_s:
                logger.warning(
                    "elastic resume with num_shards %s -> %s: the batch "
                    "layout changed, so the resumed trajectory is NOT "
                    "the uninterrupted one (keep train.mesh.num_shards "
                    "fixed across topologies for bit-exact resume)",
                    saved_s, cur_s,
                )
            elif saved_topo.get("axes") != self.topology.get("axes"):
                logger.info(
                    "elastic resume across mesh shapes %s -> %s "
                    "(num_shards unchanged: trajectory preserved)",
                    saved_topo.get("axes"), self.topology.get("axes"),
                )
        logger.info(
            "resumed from %s at step %d (epoch %d, batch %d)",
            manifest["tag"], cursor.step, cursor.epoch, cursor.batch_index,
        )
        return restored, cursor

    def after_step(self, state: Any, ok: Any, cursor: ResumeCursor) -> Any:
        """Guard bookkeeping + periodic checkpoint + preemption check.
        Returns the (possibly rolled-back) state; raises `Preempted` after
        a preemption checkpoint, `DivergenceError` past the budget."""
        if self.watchdog is not None:
            # a completed step means compiles are done: the watchdog can
            # drop from the first-step grace to the steady-state timeout
            self.watchdog.step_done()
        if self.guard_active and ok is not None:
            self._pending.append(ok)
            if len(self._pending) > max(0, int(self.rcfg.guard_lag)):
                state = self._consume_ok(self._pending.popleft(), state)
        every = int(self.rcfg.step_checkpoint_every)
        if (
            self.ckpt is not None
            and self.rcfg.enabled
            and every > 0
            and cursor.step % every == 0
            and self._consec_bad == 0
        ):
            self._save(state, cursor, reason="periodic")
        if self.handler.triggered:
            manifest = None
            if self.ckpt is not None:
                # drain the lagged guard flags first so a poisoned
                # trailing step is never enshrined as the resume point
                while self._pending:
                    state = self._consume_ok(self._pending.popleft(), state)
                manifest = self._save(state, cursor, reason="preempt")
            obs_metrics.REGISTRY.counter("obs/resilience/preemptions").inc()
            obs_trace.instant(
                "preempted", cat="resilience", step=cursor.step,
                epoch=cursor.epoch,
            )
            obs_trace.flush()
            obs_flight.crash_dump("sigterm", extra={
                "step": cursor.step, "epoch": cursor.epoch,
                "batch_index": cursor.batch_index,
                "manifest": str(manifest) if manifest else None,
            })
            raise Preempted(
                f"preempted at step {cursor.step} "
                f"(epoch {cursor.epoch}, batch {cursor.batch_index})",
                manifest=manifest,
            )
        return state

    def finish(self, state: Any, cursor: ResumeCursor) -> Any:
        """End-of-run hook: drain lagged guard flags (the last `guard_lag`
        flags were still pending) and leave a final resume point."""
        while self._pending:
            state = self._consume_ok(self._pending.popleft(), state)
        if self.ckpt is not None and self.rcfg.enabled:
            self._save(state, cursor, reason="final")
        return state

    def record(self) -> dict:
        """Self-healing counters for epoch records / bench history."""
        return {
            "resumed_from_step": self.resumed_from_step,
            "skipped_steps": self.skipped_steps,
            "rollbacks": self.rollbacks,
        }

    # -- internals ------------------------------------------------------------

    def _save(
        self, state: Any, cursor: ResumeCursor, reason: str
    ) -> Path | None:
        import jax

        if self.read_only:
            return None  # non-primary host: process 0 owns the saves
        # the save itself (device_get sync + orbax commit) can be long
        # on big states/slow storage: announce it so the watchdog applies
        # the grace threshold instead of the per-step timeout
        self.heartbeat("checkpoint", step=cursor.step)
        # device_get syncs: the in-flight step is finished before the
        # bytes are captured (the preemption contract)
        extra: dict = {"guard": {
            "lr_scale": self._lr_scale,
            "rollbacks": self.rollbacks,
            "skipped_steps": self.skipped_steps,
        }}
        if self.topology is not None:
            extra["mesh"] = self.topology
        return self.ckpt.save(
            jax.device_get(state), cursor, seed=self.seed, reason=reason,
            extra=extra,
        )

    def _consume_ok(self, ok: Any, state: Any) -> Any:
        import jax

        if bool(jax.device_get(ok)):
            self._consec_bad = 0
            return state
        self.skipped_steps += 1
        self._consec_bad += 1
        obs_metrics.REGISTRY.counter("obs/resilience/skipped_steps").inc()
        obs_trace.instant(
            "step_skipped", cat="resilience", consecutive=self._consec_bad
        )
        logger.warning(
            "divergence guard: non-finite loss/grad — step skipped "
            "(%d consecutive)", self._consec_bad,
        )
        if self._consec_bad < int(self.rcfg.max_consecutive_bad):
            return state
        if self.rollbacks >= int(self.rcfg.rollback_budget):
            raise DivergenceError(
                f"divergence guard: {self._consec_bad} consecutive bad "
                f"steps after {self.rollbacks} rollbacks — rollback "
                f"budget exhausted"
            )
        self.rollbacks += 1
        self._lr_scale *= float(self.rcfg.lr_cooldown)
        obs_metrics.REGISTRY.counter("obs/resilience/rollbacks").inc()
        obs_trace.instant(
            "rollback", cat="resilience", rollbacks=self.rollbacks,
            lr_scale=self._lr_scale,
        )
        obs_flight.crash_dump("nan_rollback", extra={
            "rollbacks": self.rollbacks,
            "skipped_steps": self.skipped_steps,
            "lr_scale": self._lr_scale,
        })
        self._consec_bad = 0
        self._pending.clear()  # flags from the abandoned trajectory
        manifest = self.ckpt.latest() if self.ckpt is not None else None
        if manifest is None:
            logger.warning(
                "divergence guard: no step checkpoint to roll back to — "
                "cooling LR to x%.3g and continuing from current params",
                self._lr_scale,
            )
            return state
        import jax

        # restore can be long on big states: grace threshold, not the
        # per-step timeout, while it runs
        self.heartbeat("checkpoint", step=manifest["step"])
        restored = self.ckpt.restore(manifest, jax.device_get(state))
        if self._place is not None:
            restored = self._place(restored)
        logger.warning(
            "divergence guard: rolled back to %s (step %d), LR cooled "
            "to x%.3g (%d/%d rollbacks)",
            manifest["tag"], manifest["step"], self._lr_scale,
            self.rollbacks, int(self.rcfg.rollback_budget),
        )
        return restored


def make_runner(
    cfg, directory: str | Path | None, read_only: bool = False
) -> ResilientRunner | None:
    """CLI helper: a runner when `cfg.train.resilience.enabled`, else
    None (the loops then run the historical path untouched).
    `read_only` is the multi-host non-primary mode: restore from the
    shared directory, never write (parallel/sharding.py:is_primary)."""
    rcfg = cfg.train.resilience
    if not rcfg.enabled:
        return None
    return ResilientRunner(
        rcfg, directory, seed=cfg.train.seed, read_only=read_only
    )


def finite_mean(values) -> float:
    """Mean over the FINITE entries only — guarded runs keep the poisoned
    loss values of skipped steps in their per-step history (honest
    per-step logs), but the epoch aggregate must not report NaN for an
    epoch the runtime survived cleanly. NaN when nothing was finite."""
    import numpy as np

    a = np.asarray(values, np.float64)
    m = np.isfinite(a)
    return float(a[m].mean()) if m.any() else float("nan")


def skip_first(source, n: int, heartbeat: Callable[[], None] | None = None):
    """Drop the first `n` items of a batch source — the resume
    fast-forward. Applied to the RAW source, before the prefetch
    pipeline, so skipped batches are never device_put and never counted
    in PipelineStats/token accounting; preserves the source's
    `source_stage` hint. `heartbeat` is called once per skipped pull (a
    cold fast-forward can outlast the watchdog's grace otherwise)."""

    class _Skipped:
        def __init__(self):
            stage = getattr(source, "source_stage", None)
            if stage is not None:
                self.source_stage = stage

        def __iter__(self):
            it = iter(source)
            for _ in range(n):
                if heartbeat is not None:
                    heartbeat()
                if next(it, _SKIP_SENTINEL) is _SKIP_SENTINEL:
                    return
            yield from it

    return _Skipped()


_SKIP_SENTINEL = object()


def apply_guarded_update(tx, state, loss, grads, lr_scale):
    """Device-side core of every loop's `train_step_guarded` (traced
    inside the loop's jit): check loss/grad-norm finiteness ON DEVICE and
    skip a poisoned step via a select — params, optimizer state and the
    step counter stay exactly as they were, with no host sync added on
    the happy path (the runner fetches the returned `ok` flag lagged).
    Grads are zeroed BEFORE tx.update so adam's mu/nu never ingest a NaN
    even on the discarded branch; `lr_scale` is the runner's rollback
    cool-down multiplier (a traced scalar — changing it never
    recompiles). Returns (state, loss, ok)."""
    import jax
    import jax.numpy as jnp
    import optax

    from deepdfa_tpu.train.state import TrainState

    ok = jnp.isfinite(loss) & jnp.isfinite(optax.global_norm(grads))
    safe = jax.tree.map(lambda g: jnp.where(ok, g, jnp.zeros_like(g)), grads)
    updates, opt_state = tx.update(safe, state.opt_state, state.params)
    updates = jax.tree.map(lambda u: u * lr_scale, updates)
    params = optax.apply_updates(state.params, updates)
    new = TrainState(params=params, opt_state=opt_state, step=state.step + 1)
    return (
        jax.tree.map(lambda n, o: jnp.where(ok, n, o), new, state),
        loss,
        ok,
    )


def place_like(state):
    """A `place` callable that re-commits a host pytree with the same
    shardings as the live `state` (works for replicated and
    tensor/pipeline-sharded states alike)."""
    import jax

    shardings = jax.tree.map(lambda x: x.sharding, state)
    return lambda host: jax.device_put(host, shardings)
