from deepdfa_tpu.train.checkpoint import CheckpointManager
from deepdfa_tpu.train.loop import GraphTrainer
from deepdfa_tpu.train.losses import (
    bce_elements,
    bce_with_logits,
    classifier_loss,
    graph_labels,
)
from deepdfa_tpu.train.metrics import BinaryClassificationMetrics, classification_report
from deepdfa_tpu.train.sampler import oversample_epoch, positive_weight, undersample_epoch
from deepdfa_tpu.train.state import TrainState, make_optimizer

__all__ = [
    "CheckpointManager",
    "GraphTrainer",
    "bce_elements",
    "bce_with_logits",
    "classifier_loss",
    "graph_labels",
    "BinaryClassificationMetrics",
    "classification_report",
    "oversample_epoch",
    "positive_weight",
    "undersample_epoch",
    "TrainState",
    "make_optimizer",
]
