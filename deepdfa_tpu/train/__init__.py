from deepdfa_tpu.train.checkpoint import CheckpointManager
from deepdfa_tpu.train.logging import NullRunLogger, RunLogger
from deepdfa_tpu.train.loop import GraphTrainer
from deepdfa_tpu.train.resilience import (
    DivergenceError,
    Preempted,
    ResilientRunner,
    ResumeCursor,
    StepCheckpointer,
    Watchdog,
    make_runner,
)
from deepdfa_tpu.train.transfer import (
    freeze_mask,
    frozen_optimizer,
    graph_encoder_subset,
    load_graph_encoder,
)
from deepdfa_tpu.train.tuning import SearchSpace, Tuner, grid_search, random_search
from deepdfa_tpu.train.losses import (
    bce_elements,
    bce_with_logits,
    classifier_loss,
    graph_labels,
)
from deepdfa_tpu.train.metrics import BinaryClassificationMetrics, classification_report
from deepdfa_tpu.train.sampler import oversample_epoch, positive_weight, undersample_epoch
from deepdfa_tpu.train.state import TrainState, make_optimizer

__all__ = [
    "CheckpointManager",
    "NullRunLogger",
    "RunLogger",
    "GraphTrainer",
    "DivergenceError",
    "Preempted",
    "ResilientRunner",
    "ResumeCursor",
    "StepCheckpointer",
    "Watchdog",
    "make_runner",
    "freeze_mask",
    "frozen_optimizer",
    "graph_encoder_subset",
    "load_graph_encoder",
    "SearchSpace",
    "Tuner",
    "grid_search",
    "random_search",
    "bce_elements",
    "bce_with_logits",
    "classifier_loss",
    "graph_labels",
    "BinaryClassificationMetrics",
    "classification_report",
    "oversample_epoch",
    "positive_weight",
    "undersample_epoch",
    "TrainState",
    "make_optimizer",
]
