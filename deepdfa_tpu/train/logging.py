"""Run logging: jsonl always, TensorBoard when available.

The reference logs through a registered TensorBoardLogger
(DDFA/code_gnn/my_tb.py, config_default.yaml:4-13) plus persistent file
logs hard-linked into the run dir (main_cli.py:123-165). Here every run
writes `train_log.jsonl` unconditionally (machine-readable, append-only)
and mirrors scalar records into TensorBoard event files when a writer
implementation is importable (torch's is in the image).

Durability/robustness contract (ISSUE 4 satellites): one append handle
held for the logger's lifetime (not a reopen per record), flushed per
record so a killed run keeps every line it logged; non-finite scalars
are dropped-and-counted before the TensorBoard mirror instead of
crashing (or poisoning) the writer — the jsonl keeps them verbatim, the
honest record. Drop/collision counters are published to the obs metrics
registry (`obs/logging/*`, docs/observability.md).
"""

from __future__ import annotations

import json
import logging
import math
from pathlib import Path

logger = logging.getLogger(__name__)


def flatten_scalars(record: dict, prefix: str = "") -> dict[str, float]:
    """Flatten nested dict records into slash-keyed scalar pairs.

    The combined trainer emits per-signature compile/step counters as a
    nested mapping (``step_signatures -> T64xR32xG32 -> compiles``);
    jsonl keeps the structure, TensorBoard needs flat scalar tags — this
    is the ONE place that mapping is defined.

    Collision semantics: a literal ``"a/b"`` key and a nested
    ``{"a": {"b": ...}}`` flatten to the same tag. Resolution is
    deterministic last-write-wins in the record's insertion order, and
    every collision is counted (``obs/logging/flatten_collisions``) and
    warned once per distinct tag — silent shadowing is how a TensorBoard
    tag drifts away from the jsonl value it claims to mirror."""
    out: dict[str, float] = {}
    for k, v in record.items():
        if isinstance(v, dict):
            for fk, fv in flatten_scalars(v, f"{prefix}{k}/").items():
                _put(out, fk, fv)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            _put(out, f"{prefix}{k}", float(v))
    return out


_warned_collisions: set[str] = set()


def _put(out: dict[str, float], key: str, value: float) -> None:
    if key in out:
        _count_collision(key, out[key], value)
    out[key] = value


def _count_collision(key: str, old: float, new: float) -> None:
    from deepdfa_tpu.obs import metrics as obs_metrics

    obs_metrics.REGISTRY.counter("obs/logging/flatten_collisions").inc()
    if key not in _warned_collisions:
        _warned_collisions.add(key)
        logger.warning(
            "flatten_scalars: tag %r emitted twice (%.6g shadowed by "
            "%.6g) — a literal slash key collides with a nested dict; "
            "last write wins", key, old, new,
        )


class RunLogger:
    def __init__(self, run_dir: str | Path, tensorboard: bool = True):
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.jsonl_path = self.run_dir / "train_log.jsonl"
        # one handle for the logger's lifetime: a reopen per record costs
        # two syscalls + a page-cache round trip per step-log, which the
        # high-frequency step records (log_every_steps) pay thousands of
        # times per run; flush-per-record keeps the crash contract (a
        # killed run's log ends at its last completed record)
        self._file = self.jsonl_path.open("a")
        #: non-finite scalars dropped from the TensorBoard mirror (the
        #: jsonl keeps them; NaN losses are data, not crashes)
        self.nonfinite_dropped = 0
        self._tb = None
        if tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=str(self.run_dir / "tb"))
            except Exception:
                self._tb = None

    @property
    def has_tensorboard(self) -> bool:
        return self._tb is not None

    def log(self, record: dict) -> None:
        if self._file is None:  # log after close: reopen rather than die
            self._file = self.jsonl_path.open("a")
        self._file.write(json.dumps(record) + "\n")
        self._file.flush()
        if self._tb is not None:
            step = int(record.get("step", record.get("epoch", 0)))
            for k, v in flatten_scalars(record).items():
                if k in ("step", "epoch"):
                    continue
                if not math.isfinite(v):
                    # drop-and-count instead of handing NaN/inf to the
                    # event writer (some backends crash, all render junk)
                    self.nonfinite_dropped += 1
                    from deepdfa_tpu.obs import metrics as obs_metrics

                    obs_metrics.REGISTRY.counter(
                        "obs/logging/nonfinite_dropped"
                    ).inc()
                    continue
                self._tb.add_scalar(k, v, global_step=step)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        if self._tb is not None:
            if self.nonfinite_dropped:
                logger.warning(
                    "RunLogger: dropped %d non-finite scalar(s) from the "
                    "TensorBoard mirror (train_log.jsonl keeps them)",
                    self.nonfinite_dropped,
                )
            self._tb.flush()
            self._tb.close()

    def __enter__(self) -> "RunLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NullRunLogger:
    """Non-primary-process stand-in (multi-host runs,
    parallel/sharding.py:is_primary): the run log is a single-writer
    resource owned by process 0; every other host logs nowhere while
    running the identical training steps. Same context-manager surface
    as RunLogger, writes nothing, creates nothing."""

    has_tensorboard = False
    nonfinite_dropped = 0

    def log(self, record: dict) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullRunLogger":
        return self

    def __exit__(self, *exc) -> None:
        pass
