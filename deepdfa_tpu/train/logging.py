"""Run logging: jsonl always, TensorBoard when available.

The reference logs through a registered TensorBoardLogger
(DDFA/code_gnn/my_tb.py, config_default.yaml:4-13) plus persistent file
logs hard-linked into the run dir (main_cli.py:123-165). Here every run
writes `train_log.jsonl` unconditionally (machine-readable, append-only)
and mirrors scalar records into TensorBoard event files when a writer
implementation is importable (torch's is in the image).
"""

from __future__ import annotations

import json
from pathlib import Path


def flatten_scalars(record: dict, prefix: str = "") -> dict[str, float]:
    """Flatten nested dict records into slash-keyed scalar pairs.

    The combined trainer emits per-signature compile/step counters as a
    nested mapping (``step_signatures -> T64xR32xG32 -> compiles``);
    jsonl keeps the structure, TensorBoard needs flat scalar tags — this
    is the ONE place that mapping is defined."""
    out: dict[str, float] = {}
    for k, v in record.items():
        if isinstance(v, dict):
            out.update(flatten_scalars(v, f"{prefix}{k}/"))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[f"{prefix}{k}"] = float(v)
    return out


class RunLogger:
    def __init__(self, run_dir: str | Path, tensorboard: bool = True):
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.jsonl_path = self.run_dir / "train_log.jsonl"
        self._tb = None
        if tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=str(self.run_dir / "tb"))
            except Exception:
                self._tb = None

    @property
    def has_tensorboard(self) -> bool:
        return self._tb is not None

    def log(self, record: dict) -> None:
        with self.jsonl_path.open("a") as f:
            f.write(json.dumps(record) + "\n")
        if self._tb is not None:
            step = int(record.get("step", record.get("epoch", 0)))
            for k, v in flatten_scalars(record).items():
                if k not in ("step", "epoch"):
                    self._tb.add_scalar(k, v, global_step=step)

    def close(self) -> None:
        if self._tb is not None:
            self._tb.flush()
            self._tb.close()

    def __enter__(self) -> "RunLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
