"""Experiment-matrix runner (role of CodeT5/sh/run_exp.py:7-167).

The reference's sweep layer is a Python CLI that expands a (model x task x
sub_task) matrix into per-run shell commands with task-specific default
hyperparameters and dispatches them (bash or sbatch), logging each run
under a tag. This is the same layer over this framework's CLI:

- a matrix spec is a list of runs, each {"name": ..., "cmd": <subcommand>,
  "args": [...]} built either from a JSON file or from the built-in
  defaults table below (task -> subcommand + hyperparameters, the role of
  run_exp.py:get_args_by_task_model);
- runs execute sequentially as `python -m deepdfa_tpu.cli <cmd> <args>`
  subprocesses (use the SLURM assets in scripts/ for cluster fan-out);
- each run's final JSON/`best:` line is parsed into a summary table
  written to <runs>/experiments/<tag>/summary.jsonl (run_exp.py's
  saved_models/<tag> layout).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import subprocess
import sys
import time
from pathlib import Path
from typing import Sequence

#: task -> (cli subcommand, default args) — the get_args_by_task_model
#: defaults table, adapted to this framework's flags
TASK_DEFAULTS: dict[str, tuple[str, list[str]]] = {
    "deepdfa": ("train", []),
    "combined": ("train-combined", ["--encoder", "tiny"]),
    "combined-t5": ("train-combined", ["--arch", "t5", "--encoder", "tiny"]),
    "defect-gen": ("train-gen", ["--task", "defect", "--tiny"]),
    "summarize": ("train-gen", ["--task", "summarize", "--tiny"]),
    "translate": ("train-gen", ["--task", "translate", "--tiny"]),
    "refine": ("train-gen", ["--task", "refine", "--tiny"]),
    "concode": ("train-gen", ["--task", "concode", "--tiny"]),
    "clone": ("train-clone", ["--tiny"]),
}


@dataclasses.dataclass(frozen=True)
class Run:
    name: str
    cmd: str
    args: tuple[str, ...]

    def argv(self) -> list[str]:
        return [sys.executable, "-m", "deepdfa_tpu.cli", self.cmd, *self.args]


def expand_matrix(
    tasks: Sequence[str],
    seeds: Sequence[int] = (0,),
    extra_args: Sequence[str] = (),
    overrides: Sequence[str] = (),
) -> list[Run]:
    """tasks x seeds -> Runs with per-task defaults + shared extras.

    `overrides` are dotted config overrides appended last (they are
    positional in the CLI); run_name is forced per run so artifacts never
    collide (run_exp.py tags runs the same way)."""
    runs = []
    for task in tasks:
        if task not in TASK_DEFAULTS:
            raise ValueError(
                f"unknown task {task!r} (choose from {sorted(TASK_DEFAULTS)})"
            )
        cmd, defaults = TASK_DEFAULTS[task]
        for seed in seeds:
            name = f"{task}_seed{seed}"
            runs.append(
                Run(
                    name=name,
                    cmd=cmd,
                    args=tuple(defaults)
                    + tuple(extra_args)
                    + tuple(overrides)
                    + (f"train.seed={seed}", f"run_name={name}"),
                )
            )
    return runs


def load_matrix(path: str | Path) -> list[Run]:
    """JSON spec: [{"name": ..., "cmd": ..., "args": [...]}, ...]."""
    rows = json.loads(Path(path).read_text())
    return [Run(name=r["name"], cmd=r["cmd"], args=tuple(r["args"])) for r in rows]


_RESULT_RE = re.compile(r"^(?:best: )?(\{.*\})\s*$")


def parse_result(stdout: str) -> dict | None:
    """Last parseable JSON (or `best: {...}` repr) line of a run."""
    for line in reversed(stdout.strip().splitlines()):
        m = _RESULT_RE.match(line.strip())
        if not m:
            continue
        text = m.group(1)
        try:
            return json.loads(text)
        except json.JSONDecodeError:
            try:
                # `best: {'val_f1': ...}` python-repr dicts
                return json.loads(text.replace("'", '"'))
            except json.JSONDecodeError:
                continue
    return None


def run_matrix(
    runs: Sequence[Run],
    out_dir: str | Path,
    dry_run: bool = False,
    env: dict | None = None,
    timeout: float | None = None,
) -> list[dict]:
    """Execute runs sequentially; write summary.jsonl; return summaries."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    summaries = []
    for run in runs:
        if dry_run:
            print(" ".join(run.argv()))
            summaries.append({"name": run.name, "dry_run": True})
            continue
        t0 = time.time()
        try:
            proc = subprocess.run(
                run.argv(),
                capture_output=True,
                text=True,
                env={**os.environ, **(env or {})},
                timeout=timeout,
            )
        except subprocess.TimeoutExpired as exc:
            # a hung run must not abort the rest of the matrix — record it
            # as a failed row and move on
            out = (exc.stdout or b"")
            err = (exc.stderr or b"")
            (out_dir / f"{run.name}.log").write_text(
                (out if isinstance(out, str) else out.decode(errors="replace"))
                + (err if isinstance(err, str) else err.decode(errors="replace"))
            )
            summary = {
                "name": run.name,
                "cmd": run.cmd,
                "rc": None,
                "timeout": True,
                "seconds": round(time.time() - t0, 1),
                "result": None,
            }
            summaries.append(summary)
            with (out_dir / "summary.jsonl").open("a") as f:
                f.write(json.dumps(summary) + "\n")
            print(json.dumps(summary))
            continue
        (out_dir / f"{run.name}.log").write_text(proc.stdout + proc.stderr)
        summary = {
            "name": run.name,
            "cmd": run.cmd,
            "rc": proc.returncode,
            "seconds": round(time.time() - t0, 1),
            "result": parse_result(proc.stdout),
        }
        summaries.append(summary)
        with (out_dir / "summary.jsonl").open("a") as f:
            f.write(json.dumps(summary) + "\n")
        print(json.dumps(summary))
    return summaries
