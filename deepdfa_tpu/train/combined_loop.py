"""Training for the combined transformer(+graph) classifiers.

Replaces the reference's hand-rolled HF loops (LineVul linevul_main.py
train():141-251, CodeT5 run_defect.py): AdamW with linear warmup over 20%
of steps and grad clipping, cross-entropy over 2 classes, per-epoch eval,
best-F1 checkpoint selection. Data parallelism is the same shard_map
sum/count pattern as GraphTrainer; tp/sp axes thread into the encoder
(Megatron-sharded heads/FFN + ring attention) when the mesh has them.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from collections import OrderedDict
from functools import partial
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from deepdfa_tpu.parallel.compat import shard_map

from deepdfa_tpu.core.config import Config
from deepdfa_tpu.data.text import TextBatch
from deepdfa_tpu.models import combined as cmb
from deepdfa_tpu.parallel import sharding
from deepdfa_tpu.parallel.mesh import make_mesh
from deepdfa_tpu.train.metrics import BinaryClassificationMetrics
from deepdfa_tpu.train.state import TrainState, make_optimizer

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class _StepEntry:
    """One compiled-step cache slot for a (T, rows, num_graphs) batch
    signature. `train`/`eval` are what dispatch calls — the raw jit
    functions until `warmup` swaps in an ahead-of-time Compiled for the
    train step (jit's own `.lower().compile()` does NOT seed its call
    cache, so the AOT executable must be stored and invoked directly).
    `stats` is the signature's persistent counter dict
    (`CombinedTrainer.signature_stats`) — it survives LRU eviction, so a
    signature that cycles out and back records every recompile."""

    train: Callable
    eval: Callable
    train_jit: Any  # underlying jit fns: their _cache_size() is the
    eval_jit: Any  # ground-truth lowering count for jit_lowerings()
    stats: dict
    aot: bool = False
    # lazy path steady state: latched after a call that added no new
    # jit-cache entry (sharding-change recompiles keep it False)
    train_compiled: bool = False


def _graph_batch_struct(num_graphs: int):
    """A GraphBatch-shaped pytree (dummy leaves) for spec construction.

    num_graphs is static pytree metadata, so it must match the batches the
    spec is used against."""
    from deepdfa_tpu.graphs.batch import GraphBatch

    return GraphBatch(
        node_feats=0, node_vuln=0, node_graph=0, node_mask=0,
        edge_src=0, edge_dst=0, edge_mask=0,
        graph_label=0, graph_mask=0, graph_ids=0, num_graphs=num_graphs,
    )


def _squeeze_batch(batch: TextBatch) -> TextBatch:
    from deepdfa_tpu.graphs.batch import GraphBatch

    g = batch.graphs
    garr = {
        f.name: (v[0] if (v := getattr(g, f.name)) is not None else None)
        for f in dataclasses.fields(g)
        if f.name != "num_graphs"
    }
    return TextBatch(
        input_ids=batch.input_ids[0],
        labels=batch.labels[0],
        row_mask=batch.row_mask[0],
        has_graph=batch.has_graph[0],
        graphs=GraphBatch(**garr, num_graphs=g.num_graphs),
    )


class CombinedTrainer:
    """dp x tp x sp trainer for the combined models.

    Gradient bookkeeping (with the Megatron region ops inside the encoder,
    parallel/megatron.py):
    - tp: sharded weights get local-true grads, replicated weights get
      replicated-true grads — no tp reduction at all;
    - sp: encoder compute is token-partial -> psum over sp; the head and
      graph encoder run identically on every sp member (replicated-true);
    - dp: every grad sums over dp;
    - pp (RoBERTa arch, sp off): stage-sharded layer grads are local-true
      (each stage's layers exist only on its device — no pp reduction);
      the region_end output broadcast means exactly one stage's loss copy
      back-propagates through the pipeline, so embedding cotangents land
      on stage 0 and zeros elsewhere -> embeddings psum over pp; head and
      graph compute replicated-true per stage -> no pp reduction.
    Loss normalization uses the dp-global valid-row count only (tp/sp/pp
    members process the same rows, so their counts are not re-added).
    """

    def __init__(
        self,
        cfg: Config,
        model_cfg,
        mesh: Mesh | None = None,
        total_steps: int | None = None,
        freeze_graph: bool = False,
        pp_microbatches: int = 4,
    ):
        """model_cfg: cmb.CombinedConfig (RoBERTa-family, LineVul/UniXcoder
        style) or t5.DefectConfig (CodeT5 style, eos pooling)."""
        from deepdfa_tpu.models import t5 as t5m

        self.cfg = cfg
        self.model_cfg = model_cfg
        self.is_t5 = isinstance(model_cfg, t5m.DefectConfig)
        self.mesh = mesh if mesh is not None else make_mesh(cfg.train.mesh)
        self.tp = self.mesh.shape.get("tp", 1) > 1
        self.sp = self.mesh.shape.get("sp", 1) > 1
        self.pp_size = self.mesh.shape.get("pp", 1)
        self.pp = self.pp_size > 1
        self.pp_microbatches = pp_microbatches
        if self.pp and model_cfg.encoder.num_layers % self.pp_size:
            raise ValueError(
                f"{model_cfg.encoder.num_layers} encoder layers not "
                f"divisible by pp={self.pp_size} stages"
            )
        self.ep_size = self.mesh.shape.get("ep", 1)
        self.moe = bool(getattr(model_cfg, "moe_experts", 0))
        self.ep = self.ep_size > 1
        if self.ep and not self.moe:
            raise ValueError(
                "an ep>1 mesh needs an MoE block to shard "
                "(set model moe_experts)"
            )
        if self.moe and model_cfg.moe_experts % self.ep_size:
            raise ValueError(
                f"{model_cfg.moe_experts} experts not divisible by "
                f"ep={self.ep_size}"
            )
        self.step_cache_entries = max(
            1, int(getattr(cfg.train, "step_cache_entries", 8))
        )
        # divergence guard (train/resilience.py): when on, every step
        # entry is built in its guarded form — signature (state, batch,
        # key, lr_scale) -> (state, loss, ok) — so the AOT warmup and the
        # lazy compile accounting cover the exact step fit dispatches
        rcfg = getattr(cfg.train, "resilience", None)
        self.guard_active = bool(
            rcfg is not None and rcfg.enabled and rcfg.divergence_guard
        )
        self.tx = make_optimizer(cfg.train.optim, total_steps)
        if freeze_graph:
            # reference --freeze_graph: the pretrained GGNN stays fixed
            # while the transformer fine-tunes (main_cli.py:136-145)
            from deepdfa_tpu.train.transfer import frozen_optimizer

            self.tx = frozen_optimizer(self.tx, frozen_top_keys=("graph",))
        self._build_specs()
        self._build_steps()

    def make_checkpoints(self, directory):
        from deepdfa_tpu.train.checkpoint import CheckpointManager

        return CheckpointManager(
            directory,
            monitor=self.cfg.train.monitor,
            mode=self.cfg.train.monitor_mode,
            keep_last=getattr(self.cfg.train, "checkpoint_keep_last", 0),
        )

    # -- sharding layout -----------------------------------------------------

    def _init_params_fn(self):
        from deepdfa_tpu.models import t5 as t5m

        return t5m.init_defect_params if self.is_t5 else cmb.init_params

    def _build_specs(self) -> None:
        # structure only — eval_shape avoids materializing a throwaway init
        init_fn = self._init_params_fn()
        example = jax.eval_shape(
            lambda: init_fn(self.model_cfg, jax.random.key(0))
        )
        # the declarative per-param sharding layer (parallel/sharding.py,
        # docs/sharding.md): the family's path-pattern rules — Megatron
        # layer table over tp, T5 rel_bias heads, MoE experts over ep,
        # the stacked layer axis over pp — resolved against the example
        # tree; MeshConfig.rules prepend operator overrides. The SAME
        # map drives the serve executors (serve/registry.py), so a
        # sharded checkpoint serves without a reshape step.
        self.sharding_map = sharding.sharding_map_for(
            "t5" if self.is_t5 else "combined",
            model_cfg=self.model_cfg,
            mesh_shape=dict(self.mesh.shape),
            extra_rules=getattr(self.cfg.train.mesh, "rules", ()),
        )
        self.param_specs = self.sharding_map.param_specs(example)
        self.param_shardings = sharding.batch_shardings(
            self.mesh, self.param_specs
        )
        # grad reduction axes per top-level group (see class docstring);
        # under pp the encoder group is split inline in _steps_for
        # (stage-sharded layers local-true, embeddings psum over pp)
        self._grad_axes = {
            "encoder": ("dp", "sp"),
            "head": ("dp",),
            "graph": ("dp",),
            # moe: router replicated-true across ep, expert blocks
            # ep-sharded local-true -> dp reduction only (class docstring)
            "moe": ("dp",),
        }

    def _batch_specs(self, num_graphs: int) -> TextBatch:
        return TextBatch(
            input_ids=P(("dp",), None, "sp"),
            labels=P(("dp",)),
            row_mask=P(("dp",)),
            has_graph=P(("dp",)),
            graphs=jax.tree.map(
                lambda _: P(("dp",)), _graph_batch_struct(num_graphs)
            ),
        )

    def init_state(self, seed: int | None = None) -> TrainState:
        seed = self.cfg.train.seed if seed is None else seed
        params = self._init_params_fn()(self.model_cfg, jax.random.key(seed))
        params = jax.device_put(params, self.param_shardings)
        opt_state = self.tx.init(params)
        return TrainState(
            params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32)
        )

    def load_graph_encoder_params(
        self, state: TrainState, deepdfa_params
    ) -> TrainState:
        """Splice a pretrained standalone DeepDFA's encoder weights into
        the combined model's graph subtree (pairs with freeze_graph=True
        for the reference --freeze_graph recipe)."""
        from deepdfa_tpu.train.transfer import load_graph_encoder

        params = load_graph_encoder(
            dict(jax.device_get(state.params)), jax.device_get(deepdfa_params)
        )
        params = jax.device_put(params, self.param_shardings)
        return TrainState(
            params=params, opt_state=self.tx.init(params), step=state.step
        )

    def load_encoder(self, state: TrainState, encoder_params) -> TrainState:
        """Swap in pretrained encoder weights (e.g. from params_from_hf_torch)."""
        params = dict(jax.device_get(state.params))
        enc = dict(jax.device_get(encoder_params))
        enc.pop("pooler", None)  # combined head never uses it
        params["encoder"] = enc
        params = jax.device_put(params, self.param_shardings)
        return TrainState(
            params=params, opt_state=self.tx.init(params), step=state.step
        )

    # -- compiled steps ------------------------------------------------------

    def _forward(self, params, local: TextBatch, key):
        """(logits, moe_aux) — aux is 0.0 for architectures without MoE."""
        tp_axis = "tp" if self.tp else None
        if self.is_t5:
            from deepdfa_tpu.models import t5 as t5m

            logits = t5m.defect_forward(
                self.model_cfg,
                params,
                local.input_ids,
                graph_batch=local.graphs,
                has_graph=local.has_graph,
                dropout_key=key,
                tp_axis=tp_axis,
                sp_axis="sp" if self.sp else None,
                pp_axis="pp" if self.pp else None,
                pp_stages=self.pp_size,
                pp_microbatches=self.pp_microbatches,
            )
            return logits, jnp.zeros((), jnp.float32)
        sp_axis = "sp" if self.sp else None
        # the pipeline path derives the sp position offset internally
        offset = (
            jax.lax.axis_index("sp") * local.input_ids.shape[1]
            if self.sp and not self.pp
            else 0
        )
        return cmb.forward(
            self.model_cfg,
            params,
            local.input_ids,
            graph_batch=local.graphs,
            has_graph=local.has_graph,
            dropout_key=key,
            sp_axis=sp_axis,
            tp_axis=tp_axis,
            position_offset=offset,
            pp_axis="pp" if self.pp else None,
            pp_stages=self.pp_size,
            pp_microbatches=self.pp_microbatches,
            ep_axis="ep" if self.ep else None,
            ep_size=self.ep_size,
            with_aux=True,
        )

    def _loss_sum(self, params, local: TextBatch, key):
        logits, aux = self._forward(params, local, key)
        per = optax.softmax_cross_entropy_with_integer_labels(
            logits, local.labels
        )
        m = local.row_mask.astype(per.dtype)
        loss = (per * m).sum()
        if self.moe:
            # load-balancing term scales with the row count so the
            # per-example normalization downstream leaves its weight
            # constant across batch sizes
            loss = loss + self.model_cfg.moe_aux_weight * aux * m.sum()
        return loss, (m.sum(), logits)

    def _build_steps(self) -> None:
        # compiled steps keyed by (T, rows, num_graphs) batch signature —
        # sequence bucketing (data/text.py) makes several legal per run —
        # in a bounded LRU (cfg.train.step_cache_entries). Counters in
        # signature_stats persist across evictions; _evicted_lowerings
        # keeps jit_lowerings() monotonic when an entry is dropped.
        self._step_cache: OrderedDict[tuple, _StepEntry] = OrderedDict()
        self.signature_stats: dict[str, dict] = {}
        self._evicted_lowerings = 0

        def train_step(state, batch: TextBatch, key, lr_scale=1.0,
                       with_ok=False):
            # guarded entries (trainer built with the divergence guard
            # on) take the runner's LR cool-down multiplier and compute
            # the on-device ok flag — but the PUBLIC contract stays
            # (state, loss) so external callers (bench scripts, A/B
            # drivers) are unaffected; the fit loop opts into the flag
            # with with_ok=True
            args = (
                (state, batch, key, lr_scale)
                if self.guard_active
                else (state, batch, key)
            )
            entry = self._entry_for(self._signature(batch))
            if entry.aot or entry.train_compiled:
                out = entry.train(*args)
            else:
                # a lazy (un-warmed) entry lowers+compiles inside a
                # call: book that latency as the signature's compile
                # cost so the counters attribute it. Checked per call —
                # not once — because the jit re-lowers when the input
                # state's shardings change (the first call's output
                # state typically carries different shardings than the
                # init state, so call 2 compiles AGAIN); the flag only
                # latches after a call that added no cache entry.
                n0 = entry.train_jit._cache_size()
                t0 = time.perf_counter()
                out = entry.train(*args)
                if entry.train_jit._cache_size() > n0:
                    dt = time.perf_counter() - t0
                    entry.stats["compiles"] += 1
                    entry.stats["compile_seconds"] += dt
                    # a lazy compile has no reachable Compiled object:
                    # the ledger books the wall time under the signature
                    # (cost fields arrive if the signature is ever
                    # warmup'd)
                    from deepdfa_tpu.obs import ledger as obs_ledger

                    obs_ledger.record_compile(
                        "combined_train",
                        self._sig_label(self._signature(batch)),
                        None, dt,
                    )
                else:
                    entry.train_compiled = True
            entry.stats["train_steps"] += 1
            if self.guard_active and not with_ok:
                out = out[:2]  # drop the flag: legacy (state, loss)
            return out

        def eval_step(params, batch: TextBatch):
            entry = self._entry_for(self._signature(batch))
            entry.stats["eval_steps"] += 1
            return entry.eval(params, batch)

        self.train_step = train_step
        self.eval_step = eval_step

    @staticmethod
    def _signature(batch: TextBatch) -> tuple[int, int, int]:
        """(T, rows_per_shard, num_graphs): the static shapes that key one
        compiled step (input_ids is [num_shards, rows, T]; num_graphs is
        static GraphBatch metadata)."""
        ids = batch.input_ids
        return (
            int(ids.shape[-1]),
            int(ids.shape[-2]),
            int(batch.graphs.num_graphs),
        )

    @staticmethod
    def _sig_label(sig: tuple[int, int, int]) -> str:
        return f"T{sig[0]}xR{sig[1]}xG{sig[2]}"

    def _entry_for(self, sig: tuple[int, int, int]) -> _StepEntry:
        entry = self._step_cache.get(sig)
        if entry is not None:
            self._step_cache.move_to_end(sig)
            return entry
        stats = self.signature_stats.setdefault(
            self._sig_label(sig),
            {
                "compiles": 0,
                "compile_seconds": 0.0,
                "train_steps": 0,
                "eval_steps": 0,
            },
        )
        entry = self._make_entry(sig[2], stats)
        self._step_cache[sig] = entry
        while len(self._step_cache) > self.step_cache_entries:
            _, old = self._step_cache.popitem(last=False)
            self._evicted_lowerings += self._entry_lowerings(old)
        return entry

    @staticmethod
    def _entry_lowerings(entry: _StepEntry) -> int:
        # the AOT executable is lowered outside the jit call cache, so
        # it counts separately from any direct-call cache entries
        return (
            entry.train_jit._cache_size()
            + (1 if entry.aot else 0)
            + entry.eval_jit._cache_size()
        )

    def jit_lowerings(self) -> int:
        """Monotonic count of step lowerings this trainer triggered (AOT
        warmup compiles + jit call-cache entries, evicted entries
        included) — the guard for the zero-steady-state-recompiles
        invariant (tests/test_combined_bucketing.py)."""
        return self._evicted_lowerings + sum(
            self._entry_lowerings(e) for e in self._step_cache.values()
        )

    def place_batch(self, batch: TextBatch) -> TextBatch:
        """Sharded H2D copy with the exact specs the shard_map consumes
        (sp-sharded input_ids included) — the shared helper
        (parallel/sharding.py:place_batch, also behind the prefetch
        pipeline's device_placer)."""
        return sharding.place_batch(
            self.mesh, batch, self._batch_specs(batch.graphs.num_graphs)
        )

    def warmup(
        self,
        state: TrainState,
        buckets=None,
        token_budget: int | None = None,
        node_budget: int | None = None,
        edge_budget: int | None = None,
    ) -> dict[str, float]:
        """Ahead-of-time compile the train step for every configured
        bucket signature, before step 1 ever runs.

        Shapes follow the ONE batch-sizing formula the planner uses
        (`data/text.py:rows_for_bucket`), so the compiled signatures are
        exactly the batches `plan_bucketed_batches` emits. jit's
        ``.lower().compile()`` does NOT seed its call cache, so the
        Compiled executables are stored in the step cache and invoked
        directly — steady-state training then triggers zero new
        lowerings. Returns {signature label: compile seconds}.

        Defaults come from cfg.data (`seq_buckets`, `token_budget`,
        `batch.node_budget`/`edge_budget`); pass explicit values when
        batches are collated with different budgets, or the compiled
        graph-leaf shapes will not match the real stream.
        """
        from deepdfa_tpu.data.text import collate_shards, rows_for_bucket

        dcfg = self.cfg.data
        buckets = tuple(
            buckets if buckets is not None else getattr(dcfg, "seq_buckets", ())
        )
        if not buckets:
            return {}
        token_budget = int(
            token_budget if token_budget is not None else dcfg.token_budget
        )
        node_budget = int(
            node_budget if node_budget is not None else dcfg.batch.node_budget
        )
        edge_budget = int(
            edge_budget if edge_budget is not None else dcfg.batch.edge_budget
        )
        if len(buckets) > self.step_cache_entries:
            raise ValueError(
                f"{len(buckets)} seq_buckets > train.step_cache_entries="
                f"{self.step_cache_entries}: warmup'd signatures would "
                f"evict each other — raise the cache bound"
            )
        dp = self.mesh.shape.get("dp", 1)
        pad_id = int(getattr(self.model_cfg.encoder, "pad_token_id", 0))
        key = jax.random.key(0)
        report: dict[str, float] = {}
        for T in buckets:
            rows = rows_for_bucket(T, token_budget, dp)
            dummy = collate_shards(
                np.zeros((0, int(T)), np.int32), [], [], {},
                num_shards=dp, rows_per_shard=rows,
                node_budget=node_budget, edge_budget=edge_budget,
                pad_id=pad_id,
            )
            batch = self.place_batch(dummy)
            sig = self._signature(batch)
            entry = self._entry_for(sig)
            if entry.aot:
                continue  # idempotent: re-warmup never recompiles
            t0 = time.perf_counter()
            lower_args = (
                (state, batch, key, 1.0)
                if self.guard_active
                else (state, batch, key)
            )
            entry.train = entry.train_jit.lower(*lower_args).compile()
            dt = time.perf_counter() - t0
            entry.aot = True
            entry.stats["compiles"] += 1
            entry.stats["compile_seconds"] += dt
            # efficiency ledger (docs/efficiency.md): the warmup'd AOT
            # executable's XLA-exact cost analysis + compile wall time
            from deepdfa_tpu.obs import ledger as obs_ledger

            obs_ledger.record_compile(
                "combined_train", self._sig_label(sig), entry.train, dt
            )
            report[self._sig_label(sig)] = round(dt, 3)
        return report

    def _make_entry(self, num_graphs: int, sig_stats: dict) -> _StepEntry:
        mesh = self.mesh
        grad_axes = self._grad_axes
        pp = self.pp
        ep = self.ep
        batch_specs = self._batch_specs(num_graphs)

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(self.param_specs, batch_specs, P()),
            out_specs=(P(), self.param_specs),
            check_vma=False,
        )
        def _sharded_grads(params, batch, key):
            local = _squeeze_batch(batch)
            key = jax.random.fold_in(key, jax.lax.axis_index("dp"))

            # dp-global valid-row count (tp/sp see the same rows)
            count = local.row_mask.sum().astype(jnp.float32)
            count_g = jnp.maximum(jax.lax.psum(count, "dp"), 1.0)

            def fn(p):
                s, (c, _) = self._loss_sum(p, local, key)
                return s / count_g

            loss_local, grads = jax.value_and_grad(fn)(params)
            loss = jax.lax.psum(loss_local, "dp")

            def reduce(sub, axes):
                return jax.tree.map(lambda g: jax.lax.psum(g, axes), sub)

            out = {}
            for group, sub in grads.items():
                if group == "encoder" and pp:
                    # pp splits the encoder: stage-sharded layers are
                    # local-true over pp (still summed over dp/sp); the
                    # replicated non-layer params need a pp psum — word/
                    # position embeddings carry stage-0-only cotangents,
                    # the T5 rel_bias carries per-stage partials from each
                    # stage's layer block. T5's final_ln runs replicated
                    # on the broadcast output (identical cotangents per
                    # stage: replicated-true, no pp psum).
                    out[group] = {
                        k: reduce(
                            v,
                            ("dp", "sp")
                            if k in ("layers", "final_ln")
                            else ("dp", "sp", "pp"),
                        )
                        for k, v in sub.items()
                    }
                elif group == "moe" and ep:
                    # ep splits the moe block: expert slices are
                    # local-true; router grads are per-rank partial on the
                    # main path and rank-0-only on the aux path (the
                    # region_end in moe_stage_forward) -> ep psum is exact
                    out[group] = {
                        "router": reduce(sub["router"], ("dp", "ep")),
                        **{
                            k: reduce(v, ("dp",))
                            for k, v in sub.items()
                            if k != "router"
                        },
                    }
                else:
                    out[group] = reduce(sub, grad_axes[group])
            return loss, out

        @partial(jax.jit, donate_argnums=0)
        def train_step(state: TrainState, batch: TextBatch, key):
            loss, grads = _sharded_grads(state.params, batch, key)
            updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return (
                TrainState(params=params, opt_state=opt_state, step=state.step + 1),
                loss,
            )

        @partial(jax.jit, donate_argnums=0)
        def train_step_guarded(state: TrainState, batch: TextBatch, key, lr_scale):
            """Divergence-guarded step: the shared on-device skip/select
            core lives in train/resilience.py:apply_guarded_update."""
            from deepdfa_tpu.train.resilience import apply_guarded_update

            loss, grads = _sharded_grads(state.params, batch, key)
            return apply_guarded_update(self.tx, state, loss, grads, lr_scale)

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(self.param_specs, batch_specs),
            out_specs=(P(("dp",)),) * 4,
            check_vma=False,
        )
        def _sharded_eval(params, batch):
            local = _squeeze_batch(batch)
            logits, _ = self._forward(params, local, None)
            per = optax.softmax_cross_entropy_with_integer_labels(
                logits, local.labels
            )
            probs = jax.nn.softmax(logits)[:, 1]
            return (
                probs[None],
                local.labels[None],
                local.row_mask[None],
                per[None],
            )

        @jax.jit
        def eval_step(params, batch: TextBatch):
            return _sharded_eval(params, batch)

        step_fn = train_step_guarded if self.guard_active else train_step
        return _StepEntry(
            train=step_fn, eval=eval_step,
            train_jit=step_fn, eval_jit=eval_step,
            stats=sig_stats,
        )

    def evaluate(self, state_or_params, batches: Iterable[TextBatch]):
        params = getattr(state_or_params, "params", state_or_params)
        m = BinaryClassificationMetrics()
        loss_sum = 0.0
        count = 0.0
        for batch in batches:
            probs, labels, mask, per = jax.device_get(self.eval_step(params, batch))
            m.update(probs, labels, mask)
            valid = np.asarray(mask, bool)
            loss_sum += float(np.asarray(per, np.float64)[valid].sum())
            count += float(valid.sum())
        metrics = m.compute()
        metrics["loss"] = loss_sum / count if count else float("nan")
        return metrics, m

    def fit(
        self,
        state: TrainState,
        train_batches: Callable[[int], Iterable[TextBatch]],
        val_batches: Callable[[], Iterable[TextBatch]] | None = None,
        checkpoints=None,
        max_epochs: int | None = None,
        log_fn: Callable[[dict], None] | None = None,
        seed: int = 0,
        source_stage: str = "pack",
        resilience=None,
    ) -> TrainState:
        import contextlib

        from deepdfa_tpu import obs
        from deepdfa_tpu.data.prefetch import PipelineStats, prefetch

        from deepdfa_tpu.data.text import batch_token_counts
        from deepdfa_tpu.train.resilience import (
            ResumeCursor,
            finite_mean,
            place_like,
            skip_first,
        )

        # unified telemetry (docs/observability.md): no-op unless enabled
        inst = obs.instruments(self.cfg)
        tcfg = self.cfg.train
        max_epochs = max_epochs if max_epochs is not None else tcfg.max_epochs
        root = jax.random.key(seed)
        res = resilience
        guard = res is not None and res.guard_active and self.guard_active
        start_epoch = skip_batches = 0
        cursor = None
        if res is not None:
            # resume BEFORE warmup so the AOT executables are lowered
            # against the restored state's shardings (identical to a
            # fresh init's by construction of place_like)
            state, cursor = res.maybe_resume(state, place_like(state))
            if cursor is not None:
                start_epoch, skip_batches = cursor.epoch, cursor.batch_index
        # on resume the loop step comes from the DATA cursor, not
        # state.step: guard-skipped steps leave state.step behind the
        # host count the cursor (and RNG folding) was aligned to
        step = (
            cursor.step if cursor is not None
            else int(jax.device_get(state.step))
        )
        pad_id = int(getattr(self.model_cfg.encoder, "pad_token_id", 0))

        # bucketed runs compile every configured signature BEFORE step 1
        # (and outside any epoch's timing window); non-bucketed runs
        # keep the lazy compile-on-first-batch behaviour
        if getattr(self.cfg.data, "seq_buckets", ()):
            warm = self.warmup(state)
            if warm:
                logger.info("warmup compiled %d bucket signatures: %s",
                            len(warm), warm)
                if log_fn is not None:
                    log_fn({
                        "warmup_signatures": len(warm),
                        "warmup_compile_seconds": round(sum(warm.values()), 3),
                    })

        cm = res if res is not None else contextlib.nullcontext()
        with cm:
            for epoch in range(start_epoch, max_epochs):
                t0 = time.perf_counter()
                losses = []
                stats = PipelineStats()
                if res is not None:
                    res.attach_stats(stats)

                def place(batch: TextBatch) -> TextBatch:
                    # token accounting happens host-side, before the sharded
                    # H2D copy in the producer thread (place_batch uses the
                    # exact specs the shard_map consumes)
                    stats.add_tokens(
                        *batch_token_counts(batch.input_ids, batch.row_mask,
                                            pad_id)
                    )
                    return self.place_batch(batch)

                source = train_batches(epoch)
                batch_index = 0
                if epoch == start_epoch and skip_batches:
                    # deterministic fast-forward past the batches the
                    # resumed checkpoint already consumed — BEFORE the
                    # prefetch pipeline, so they are never device_put and
                    # never pollute the epoch's token/row accounting
                    source = skip_first(
                        source, skip_batches,
                        heartbeat=lambda: res.heartbeat(
                            "input", epoch=epoch, step=step
                        ),
                    )
                    batch_index = skip_batches
                stream = prefetch(
                    source, tcfg.prefetch_batches, place,
                    producers=tcfg.prefetch_producers,
                    stats=stats, source_stage=source_stage,
                )
                try:
                    it = iter(stream)
                    while True:
                        if res is not None:
                            res.heartbeat("input", epoch=epoch, step=step)
                        try:
                            batch = next(it)
                        except StopIteration:
                            break
                        if res is not None:
                            res.heartbeat("device", epoch=epoch, step=step)
                        key = jax.random.fold_in(root, step)
                        with inst.step_span(step):
                            if guard:
                                state, loss, ok = self.train_step(
                                    state, batch, key, res.lr_scale(),
                                    with_ok=True,
                                )
                            else:
                                state, loss = self.train_step(
                                    state, batch, key
                                )
                                ok = None
                        inst.dispatched(loss)
                        losses.append(loss)
                        step += 1
                        batch_index += 1
                        if res is not None:
                            state = res.after_step(
                                state, ok,
                                ResumeCursor(epoch, batch_index, step),
                            )
                finally:
                    stream.close()
                epoch_seconds = time.perf_counter() - t0
                record = {
                    "epoch": epoch,
                    # guarded runs exclude skipped steps' poisoned losses
                    # from the epoch aggregate (see GraphTrainer.fit)
                    "train_loss": (
                        (finite_mean(jax.device_get(losses)) if guard
                         else float(np.mean(jax.device_get(losses))))
                        if losses else float("nan")
                    ),
                    "epoch_seconds": epoch_seconds,
                    # same stage attribution as GraphTrainer.fit
                    "host_load_seconds": round(stats.load_seconds, 3),
                    "host_pack_seconds": round(stats.pack_seconds, 3),
                    "host_place_seconds": round(stats.place_seconds, 3),
                    "input_wait_seconds": round(stats.wait_seconds, 3),
                    "input_wait_fraction": round(
                        stats.wait_fraction(epoch_seconds), 4
                    ),
                }
                if res is not None:
                    # self-healing observables (docs/resilience.md)
                    record.update(res.record())
                if stats.padded_tokens:
                    # sequence-bucketing observables (docs/input_pipeline.md):
                    # REAL-token throughput is shape-invariant, so it compares
                    # across bucket layouts where examples/sec cannot
                    record.update(
                        train_examples_per_sec=round(
                            stats.rows / epoch_seconds, 2
                        ) if epoch_seconds else None,
                        train_tokens_per_sec=round(
                            stats.real_tokens / epoch_seconds, 1
                        ) if epoch_seconds else None,
                        real_tokens=stats.real_tokens,
                        padded_tokens=stats.padded_tokens,
                        padding_waste=round(stats.padding_waste(), 4),
                    )
                # cumulative per-signature compile/step attribution for the
                # bounded step cache; RunLogger flattens the nested dict into
                # `step_signatures/<sig>/<counter>` TensorBoard scalars
                record["step_signatures"] = {
                    k: dict(v) for k, v in self.signature_stats.items()
                }
                record["jit_lowerings"] = self.jit_lowerings()
                # absorb pipeline + per-signature counters into the
                # metrics registry; attach obs snapshot + device memory
                # (identical record when telemetry is off)
                inst.observe_pipeline(stats)
                inst.observe_signatures(self.signature_stats)
                inst.finish_epoch(record)
                if val_batches is not None:
                    if res is not None:
                        # epoch-end stages run under the watchdog's grace
                        # threshold, not the per-step timeout
                        res.heartbeat("eval", epoch=epoch)
                    val_metrics, _ = self.evaluate(state, val_batches())
                    record.update({f"val_{k}": v for k, v in val_metrics.items()})
                # mirror GraphTrainer.fit: without a val split, still persist on
                # the periodic cadence and on the final epoch, so a val-less run
                # never trains to completion and saves nothing
                if checkpoints is not None and (
                    any(k.startswith("val_") for k in record)
                    or (epoch + 1) % max(1, tcfg.checkpoint_every_epochs) == 0
                    or epoch == max_epochs - 1
                ):
                    if res is not None:
                        res.heartbeat("checkpoint", epoch=epoch)
                    checkpoints.save(
                        f"epoch-{epoch:04d}",
                        jax.device_get(state.params),
                        {
                            k: float(v)
                            for k, v in record.items()
                            if isinstance(v, (int, float)) and k != "epoch"
                        },
                        step=step,
                    )
                logger.info("epoch %d: %s", epoch, record)
                if log_fn is not None:
                    log_fn(record)
            if res is not None:
                state = res.finish(state, ResumeCursor(max_epochs, 0, step))
        return state
