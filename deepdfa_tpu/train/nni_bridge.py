"""NNI hyperparameter-tuning protocol bridge.

The reference integrates NNI directly into its CLI: `get_next_parameter()`
mutates the run config before training (DDFA/code_gnn/main_cli.py:110-120),
every validation epoch reports an intermediate result
(base_module.py:346), and the post-fit best metric is the final report
(main_cli.py:184). This bridge provides the same protocol surface against
the typed config, degrading to a no-op when the `nni` package or runtime
is absent — the in-process Tuner (train/tuning.py) is the search driver
for environments without an NNI experiment manager.

NNI parameters are dotted config keys (e.g. "train.optim.learning_rate",
"data.feat.limit_all"): the structured config replaces the reference's
string-encoded feat rewriting, so a tuned limit flows into
`data.feat.limit_all` (input_dim derives from it) instead of being
spliced into `_ABS_DATAFLOW_..._limitall_<N>_...`.
"""

from __future__ import annotations

import logging
import os
from typing import Callable

logger = logging.getLogger(__name__)


def _nni():
    """The nni module when running under an NNI experiment, else None."""
    if not os.environ.get("NNI_PLATFORM"):
        return None
    try:
        import nni  # noqa: PLC0415

        return nni
    except ImportError:
        logger.warning("NNI_PLATFORM set but the nni package is missing")
        return None


def active() -> bool:
    return _nni() is not None


def get_next_parameters() -> dict:
    """Next trial's parameters ({} outside an NNI experiment)."""
    nni = _nni()
    if nni is None:
        return {}
    params = nni.get_next_parameter() or {}
    logger.info("nni trial parameters: %s", params)
    return params


def nni_overrides() -> list[str]:
    """Trial parameters as dotted key=value config overrides.

    Values are always JSON-encoded: apply_overrides json-parses the value
    side, and only JSON spellings survive the typed-config checks
    (json.dumps(True) == "true"; Python's str(True) == "True" would not
    parse and the bool-mismatch check would kill the trial)."""
    import json

    return [f"{k}={json.dumps(v)}" for k, v in get_next_parameters().items()]


def report_intermediate(value: float) -> None:
    nni = _nni()
    if nni is not None:
        nni.report_intermediate_result(float(value))


def report_final(value: float) -> None:
    nni = _nni()
    if nni is not None:
        nni.report_final_result(float(value))


def intermediate_log_fn(
    monitor: str = "val_loss", inner: Callable[[dict], None] | None = None
) -> Callable[[dict], None]:
    """A train-loop log_fn that mirrors the reference's per-val-epoch
    report_intermediate_result (base_module.py:346), chaining to `inner`."""

    def log_fn(record: dict) -> None:
        if monitor in record:
            report_intermediate(record[monitor])
        if inner is not None:
            inner(record)

    return log_fn
