"""Multi-task seq2seq training — the run_multi_gen role.

Reference semantics (CodeT5/run_multi_gen.py):
- ONE model trains across several generation tasks; every step samples a
  task with probability proportional to |task|^0.7 (the size-tempered
  mixture, run_multi_gen.py:270-273) and takes one batch from that
  task's cycled stream (:226,:280-291).
- Per-task patience comes from a task-family table (summarize 2,
  translate 5, refine 5, concode 3, defect 2 — :253-266).
- At every eval interval each live task computes dev perplexity (and
  optionally BLEU/EM); a task early-stops when BOTH its ppl counter and
  its bleu counter exceed its patience (same dual-counter rule as
  run_gen.py:398-405, here per task). When sampling keeps landing on
  stopped tasks (>50 consecutive draws) the whole run ends (:279-287).

TPU-first differences from the reference:
- The compiled dp-sharded train/eval steps of one `GenTrainer` are
  shared by all tasks; tasks with different (batch, source, target)
  shapes simply hit distinct jit signatures, each compiled once. No
  per-task model copies, no host-side scatter.
- The reference cycles each task through `itertools.cycle(DataLoader)`,
  which freezes the first epoch's shuffle order for the rest of the
  run; here each pass re-invokes the task's batch factory with a fresh
  epoch index, so shuffling stays honest.
- Task sampling uses a seeded `np.random.Generator` on the host — the
  schedule is reproducible and independent of device PRNG.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Iterable, Iterator, Sequence

import jax
import numpy as np

from deepdfa_tpu.data.gen_data import GenBatch
from deepdfa_tpu.train.gen_loop import GenTrainer
from deepdfa_tpu.train.state import TrainState

logger = logging.getLogger(__name__)

#: per-task-family early-stop patience (run_multi_gen.py:253-266)
TASK_PATIENCE = {
    "summarize": 2,
    "translate": 5,
    "refine": 5,
    "concode": 3,
    "defect": 2,
}

#: consecutive draws of stopped tasks before the whole run ends (:285)
_STOP_DRAWS = 50


def task_target_length(name: str, default: int = 128) -> int:
    """Per-task-family decode length (run_multi_gen.py:52-67); task
    names follow the reference's "<family>_<subtask>" convention."""
    family = name.split("_")[0]
    sub = name.split("_")[-1]
    return {
        "summarize": 128,
        "translate": 256,
        "refine": 120 if sub == "small" else 240,
        "concode": 150,
        "defect": 3,
    }.get(family, default)


@dataclasses.dataclass
class GenTask:
    """One task in the mixture.

    train_batches(epoch) yields that pass's GenBatch stream (re-invoked
    with an incremented epoch each time the stream is exhausted);
    `size` is the example count driving the mixture weight.
    """

    name: str
    train_batches: Callable[[int], Iterable[GenBatch]]
    size: int
    val_batches: Callable[[], Iterable[GenBatch]] | None = None
    val_decode: tuple[np.ndarray, Sequence[Sequence[int]]] | None = None
    patience: int | None = None  # default: TASK_PATIENCE by name prefix

    def resolved_patience(self) -> int:
        if self.patience is not None:
            return self.patience
        return TASK_PATIENCE.get(self.name.split("_")[0], 2)


def mixture_probs(sizes: Sequence[int], alpha: float = 0.7) -> np.ndarray:
    """Size-tempered task mixture: normalize, raise to alpha, renormalize
    (run_multi_gen.py:270-273)."""
    p = np.asarray(sizes, np.float64)
    p = p / p.sum()
    p = p**alpha
    return p / p.sum()


def _cycled(task: GenTask) -> Iterator[GenBatch]:
    epoch = 0
    while True:
        it = iter(task.train_batches(epoch))
        got = False
        for batch in it:
            got = True
            yield batch
        if not got:
            raise ValueError(f"task {task.name!r} produced no batches")
        epoch += 1


@dataclasses.dataclass
class _TaskBook:
    """Per-task early-stop bookkeeping."""

    best_ppl: float = float("inf")
    best_bleu_em: float = -1.0
    not_ppl_dec: int = 0
    not_bleu_inc: float = 0  # stays inf when bleu eval is off
    stopped: bool = False
    stopped_at: int | None = None


def fit_multi(
    trainer: GenTrainer,
    state: TrainState,
    tasks: Sequence[GenTask],
    max_steps: int,
    eval_every: int | None = None,
    checkpoints: Callable[[str, str, str], object] | None = None,
    seed: int = 0,
    log_fn: Callable[[dict], None] | None = None,
) -> tuple[TrainState, dict[str, dict]]:
    """Train one model over the task mixture; returns (state, summary).

    checkpoints(task_name, monitor, mode) -> a CheckpointManager-like
    object; called lazily per task for best-ppl (and best-bleu when the
    task evaluates BLEU) checkpoints. eval_every defaults to one eval
    per ~mixture epoch (total batches across tasks).
    """
    assert tasks, "need at least one task"
    names = [t.name for t in tasks]
    assert len(set(names)) == len(names), f"duplicate task names: {names}"
    probs = mixture_probs([t.size for t in tasks])
    streams = {t.name: _cycled(t) for t in tasks}
    books = {t.name: _TaskBook() for t in tasks}
    for t in tasks:
        if t.val_decode is None:
            books[t.name].not_bleu_inc = float("inf")
    ppl_ckpt: dict[str, object] = {}
    bleu_ckpt: dict[str, object] = {}
    if eval_every is None:
        eval_every = max(1, sum(max(1, t.size) for t in tasks) // 8)

    rng = np.random.default_rng(seed)
    root = jax.random.key(seed)
    step = int(jax.device_get(state.step))
    t0 = time.perf_counter()
    losses: list = []
    skip_draws = 0
    while step < max_steps:
        task = tasks[int(rng.choice(len(tasks), p=probs))]
        book = books[task.name]
        if book.stopped:
            skip_draws += 1
            if skip_draws > _STOP_DRAWS:
                logger.info("all tasks early-stopped at step %d", step)
                break
            continue
        skip_draws = 0

        batch = next(streams[task.name])
        state, loss = trainer.train_step(
            state, batch, jax.random.fold_in(root, step)
        )
        losses.append(loss)
        step += 1

        if step % eval_every and step < max_steps:
            continue

        record: dict = {
            "step": step,
            "train_loss": float(np.mean(jax.device_get(losses))),
            "window_seconds": time.perf_counter() - t0,
        }
        losses, t0 = [], time.perf_counter()
        for t in tasks:
            b = books[t.name]
            if b.stopped or t.val_batches is None:
                continue
            ppl = trainer.eval_ppl(state, t.val_batches())
            record[f"{t.name}/val_ppl"] = ppl
            if ppl < b.best_ppl:
                b.best_ppl, b.not_ppl_dec = ppl, 0
                if checkpoints is not None:
                    mgr = ppl_ckpt.setdefault(
                        t.name, checkpoints(t.name, "val_ppl", "min")
                    )
                    mgr.save(
                        f"step-{step:07d}", jax.device_get(state.params),
                        {"val_ppl": ppl}, step=step,
                    )
            else:
                b.not_ppl_dec += 1
            if t.val_decode is not None:
                src, refs = t.val_decode
                scores = trainer.eval_bleu_em(state, src, refs)
                record[f"{t.name}/val_bleu_em"] = scores["bleu_em"]
                if scores["bleu_em"] > b.best_bleu_em:
                    b.best_bleu_em, b.not_bleu_inc = scores["bleu_em"], 0
                    if checkpoints is not None:
                        mgr = bleu_ckpt.setdefault(
                            t.name,
                            checkpoints(t.name + "-bleu", "val_bleu_em", "max"),
                        )
                        mgr.save(
                            f"step-{step:07d}", jax.device_get(state.params),
                            {"val_bleu_em": scores["bleu_em"]}, step=step,
                        )
                else:
                    b.not_bleu_inc += 1
            patience = t.resolved_patience()
            if (
                patience
                and b.not_ppl_dec > patience
                and b.not_bleu_inc > patience
            ):
                b.stopped, b.stopped_at = True, step
                logger.info(
                    "task %s early-stopped at step %d "
                    "(ppl counter %d, bleu counter %s)",
                    t.name, step, b.not_ppl_dec, b.not_bleu_inc,
                )
        logger.info("step %d: %s", step, record)
        if log_fn is not None:
            log_fn(record)
        if all(
            books[t.name].stopped for t in tasks if t.val_batches is not None
        ) and any(t.val_batches is not None for t in tasks):
            logger.info("every evaluated task early-stopped; ending run")
            break

    summary = {
        name: {
            "best_ppl": None if np.isinf(b.best_ppl) else b.best_ppl,
            "best_bleu_em": None if b.best_bleu_em < 0 else b.best_bleu_em,
            "stopped_at": b.stopped_at,
        }
        for name, b in books.items()
    }
    return state, summary
