"""Checkpointing: orbax pytrees + best-metric selection.

Replaces Lightning's ModelCheckpoint/PeriodicModelCheckpoint
(DDFA/configs/config_default.yaml:23-29, DDFA/code_gnn/periodic_checkpoint.py)
and the manual torch.save best-F1 scheme (LineVul/linevul/linevul_main.py:
225-251). Best selection is recorded in a json manifest instead of being
parsed back out of filenames (reference main_cli.py:175-183).

Durability (docs/resilience.md): the manifest is written atomically
(tmp+fsync+rename, core/ioutil.py) so a crash mid-write can never leave a
truncated json that poisons every future resume; a manifest corrupted by
other means (partial page writes after power loss, manual edits) is
tolerated by rebuilding the tag list from the checkpoint directories
actually on disk. `keep_last` bounds how many tagged checkpoints a long
run accumulates (the `best` copy is always kept).
"""

from __future__ import annotations

import json
import logging
import shutil
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

from deepdfa_tpu.core.ioutil import atomic_write_text

logger = logging.getLogger(__name__)


class CheckpointMismatch(RuntimeError):
    """A checkpoint's on-disk parameter tree does not match the model
    being restored into — named key paths instead of orbax's opaque
    pytree-structure error, so the operator can see WHICH config knob
    (model dims, feature-vocab limits) drifted between train and serve.

    `missing`: param paths the model expects but the checkpoint lacks;
    `unexpected`: paths the checkpoint holds but the model lacks;
    `shape_mismatches`: {path: (checkpoint_shape, model_shape)}."""

    def __init__(self, directory, missing, unexpected, shape_mismatches):
        self.directory = str(directory)
        self.missing = tuple(missing)
        self.unexpected = tuple(unexpected)
        self.shape_mismatches = dict(shape_mismatches)
        parts = [f"checkpoint {self.directory} does not match the model"]
        if self.missing:
            parts.append(
                "missing from checkpoint: " + ", ".join(self.missing[:8])
                + ("..." if len(self.missing) > 8 else "")
            )
        if self.unexpected:
            parts.append(
                "not in model: " + ", ".join(self.unexpected[:8])
                + ("..." if len(self.unexpected) > 8 else "")
            )
        if self.shape_mismatches:
            parts.append(
                "shape mismatches: " + ", ".join(
                    f"{k}: ckpt{tuple(a)} vs model{tuple(b)}"
                    for k, (a, b) in list(self.shape_mismatches.items())[:8]
                )
            )
        parts.append(
            "(likely a model/data config drift between the training run "
            "and this restore — e.g. model.hidden_dim, model.n_steps, "
            "data.feat.limit_all, model.struct_feats)"
        )
        super().__init__("; ".join(parts))


def jax_tree_zeros(meta_tree: Any) -> Any:
    """Zero-filled numpy arrays shaped like an orbax metadata subtree —
    placeholder restore targets for state we read but discard (the
    optimizer half of a full-TrainState checkpoint)."""
    import jax
    import numpy as np

    return jax.tree.map(
        lambda m: np.zeros(
            tuple(getattr(m, "shape", ()) or ()),
            getattr(m, "dtype", np.float32),
        ),
        meta_tree,
    )


def _flat_paths(tree: Any) -> dict[str, Any]:
    """Flatten a params pytree (or orbax metadata tree) to
    {'a/b/c': leaf} — the shared coordinate system CheckpointMismatch
    reports in."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in leaves:
        out["/".join(str(getattr(k, "key", k)) for k in path)] = leaf
    return out


class CheckpointManager:
    def __init__(
        self,
        directory: str | Path,
        monitor: str = "val_loss",
        mode: str = "min",
        keep_last: int | None = None,
    ):
        """keep_last: retain only the newest N tagged checkpoints (`best`
        is exempt); None/0 = unbounded (the historical behaviour)."""
        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.monitor = monitor
        self.mode = mode
        self.keep_last = int(keep_last) if keep_last else 0
        self._ckpt = ocp.StandardCheckpointer()
        self._manifest_path = self.directory / "manifest.json"
        self._manifest: dict[str, Any] = {"best": None, "last": None, "history": []}
        if self._manifest_path.exists():
            try:
                self._manifest = json.loads(self._manifest_path.read_text())
            except (json.JSONDecodeError, OSError) as e:
                logger.warning(
                    "corrupt checkpoint manifest %s (%s: %s); rebuilding "
                    "from on-disk checkpoint dirs",
                    self._manifest_path, type(e).__name__, e,
                )
                self._manifest = self._rebuild_manifest()
                atomic_write_text(
                    self._manifest_path, json.dumps(self._manifest, indent=2)
                )

    def _rebuild_manifest(self) -> dict[str, Any]:
        """Best-effort manifest from the checkpoint dirs on disk: tags in
        name order, metrics unknown (empty). `best` keeps working when its
        directory survived — with no recorded metric the next save wins
        the comparison, which is the safe direction."""
        tags = sorted(
            p.name
            for p in self.directory.iterdir()
            if p.is_dir() and p.name != "best"
        )
        history = [{"tag": t, "step": -1, "metrics": {}} for t in tags]
        best = (
            {"tag": "best", "step": -1, "metrics": {}}
            if (self.directory / "best").is_dir()
            else None
        )
        return {
            "best": best,
            "last": history[-1] if history else None,
            "history": history,
        }

    def _is_better(self, value: float) -> bool:
        best = self._manifest["best"]
        if best is None:
            return True
        prev = best["metrics"].get(self.monitor)
        if prev is None:  # rebuilt manifest: no recorded metric to beat
            return True
        return value < prev if self.mode == "min" else value > prev

    def save(self, tag: str, state: Any, metrics: dict[str, float], step: int) -> bool:
        """Save under `tag`; update best/last pointers. Returns is_best."""
        path = self.directory / tag
        self._ckpt.save(path, state, force=True)
        # synchronous semantics: orbax saves are async by default and the
        # pending commit futures crash at interpreter shutdown otherwise
        self._ckpt.wait_until_finished()
        entry = {"tag": tag, "step": step, "metrics": metrics}
        self._manifest["history"].append(entry)
        self._manifest["last"] = entry
        is_best = self.monitor in metrics and self._is_better(metrics[self.monitor])
        if is_best:
            best_path = self.directory / "best"
            self._ckpt.save(best_path, state, force=True)
            self._ckpt.wait_until_finished()
            self._manifest["best"] = entry
        self._retain()
        atomic_write_text(
            self._manifest_path, json.dumps(self._manifest, indent=2)
        )
        return is_best

    def _retain(self) -> None:
        """keep-last-k: drop the oldest tagged checkpoint DIRS beyond the
        bound (history entries are kept — they are the metric log; the
        `best` dir is a separate copy, so the best weights always
        survive). The `last` pointer's dir is never dropped."""
        if not self.keep_last:
            return
        tags: list[str] = []
        for e in self._manifest["history"]:
            if e["tag"] not in tags:
                tags.append(e["tag"])
        keep = set(tags[-self.keep_last:])
        last = self._manifest.get("last")
        if last:
            keep.add(last["tag"])
        for tag in tags:
            if tag in keep:
                continue
            path = self.directory / tag
            if path.is_dir():
                shutil.rmtree(path, ignore_errors=True)

    def restore(self, tag: str, target: Any) -> Any:
        """Restore into the structure of `target` (an abstract or concrete
        pytree of the same shape)."""
        return self._ckpt.restore(self.directory / tag, target=target)

    def available_tags(self) -> list[str]:
        """Checkpoint directories actually on disk (manifest-independent)."""
        if not self.directory.is_dir():
            return []
        return sorted(
            p.name for p in self.directory.iterdir() if p.is_dir()
        )

    def restore_for_inference(
        self, tag: str, params_target: Any, shardings: Any = None
    ) -> Any:
        """Params-only restore for serving (serve/registry.py).

        Accepts both checkpoint layouts this repo writes: the epoch
        checkpoints (bare params pytree, what `save` stores) and the
        resilience step checkpoints (full TrainState dict) — for the
        latter only the `params` subtree is returned, the optimizer
        state is discarded (zero-filled placeholders satisfy orbax's
        full-structure restore; it is never device_put).

        `shardings`: an optional NamedSharding pytree (a resolved
        sharding map, parallel/sharding.py) the restored params are
        committed under — elastic placement: a checkpoint written on
        ANY training topology restores sharded for the serving mesh
        with no reshape step (the host tree is topology-free).

        Structure problems raise `CheckpointMismatch` naming the
        missing/extra/mis-shaped parameter paths (and the config knobs
        that usually cause them) instead of orbax's opaque pytree error.
        """
        import numpy as np

        path = self.directory / tag
        if not path.is_dir():
            avail = self.available_tags()
            raise FileNotFoundError(
                f"no checkpoint tag {tag!r} under {self.directory}"
                + (f"; available: {avail}" if avail else " (empty dir)")
            )
        try:
            meta = self._ckpt.metadata(path)
        except Exception as e:  # unreadable/corrupt checkpoint dir
            raise CheckpointMismatch(
                path, missing=(), unexpected=(f"<unreadable: {e}>",),
                shape_mismatches={},
            ) from e
        # full-TrainState layout (resilience step checkpoints): restore
        # params for real, everything else into throwaway zero buffers
        # "opt_state" alongside "params" is unambiguous: no model's own
        # param dict carries that sibling (flax trees nest under a single
        # "params" key; combined trees use encoder/head/graph)
        wrap = (
            isinstance(meta, dict)
            and "params" in meta
            and "opt_state" in meta
        )
        saved_params_meta = meta["params"] if wrap else meta
        want = _flat_paths(params_target)
        have = _flat_paths(saved_params_meta)
        missing = sorted(set(want) - set(have))
        unexpected = sorted(set(have) - set(want))
        shape_mismatches = {}
        for k in set(want) & set(have):
            ws = tuple(getattr(want[k], "shape", ()) or ())
            hs = tuple(getattr(have[k], "shape", ()) or ())
            if ws != hs:
                shape_mismatches[k] = (hs, ws)
        if missing or unexpected or shape_mismatches:
            raise CheckpointMismatch(
                path, missing, unexpected, shape_mismatches
            )
        if not wrap:
            restored = self._ckpt.restore(path, target=params_target)
        else:
            full_target = {
                k: (
                    params_target if k == "params"
                    else jax_tree_zeros(v)
                )
                for k, v in meta.items()
            }
            restored = self._ckpt.restore(path, target=full_target)["params"]
        if shardings is not None:
            import jax

            restored = jax.device_put(restored, shardings)
        return restored

    def best_metrics(self) -> dict[str, float] | None:
        best = self._manifest["best"]
        return None if best is None else dict(best["metrics"])


def restore_candidate_params(
    ckpt_dir, params_target: Any, tag: str | None = None
) -> Any:
    """Warm-start restore for a flywheel candidate fine-tune
    (deepdfa_tpu/flywheel/retrain.py, docs/flywheel.md).

    Resolves the tag the way serving would pick it — manifest "best",
    falling back to "last", falling back to the newest dir on disk —
    and restores params-only through `restore_for_inference`, so both
    checkpoint layouts (bare params and full TrainState) warm-start a
    candidate identically to how they'd serve. Keeping the resolution
    here (not in flywheel/) means the retrainer can never diverge from
    the registry about which params "the incumbent" means.
    """
    mgr = CheckpointManager(ckpt_dir)
    if tag is None:
        for entry in (mgr._manifest.get("best"), mgr._manifest.get("last")):
            if entry and entry.get("tag"):
                tag = entry["tag"]
                break
    if tag is None:
        tags = mgr.available_tags()
        if not tags:
            raise FileNotFoundError(
                f"no checkpoints under {mgr.directory} to warm-start from"
            )
        tag = tags[-1]
    return mgr.restore_for_inference(tag, params_target)
