"""Checkpointing: orbax pytrees + best-metric selection.

Replaces Lightning's ModelCheckpoint/PeriodicModelCheckpoint
(DDFA/configs/config_default.yaml:23-29, DDFA/code_gnn/periodic_checkpoint.py)
and the manual torch.save best-F1 scheme (LineVul/linevul/linevul_main.py:
225-251). Best selection is recorded in a json manifest instead of being
parsed back out of filenames (reference main_cli.py:175-183).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str | Path, monitor: str = "val_loss", mode: str = "min"):
        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.monitor = monitor
        self.mode = mode
        self._ckpt = ocp.StandardCheckpointer()
        self._manifest_path = self.directory / "manifest.json"
        self._manifest: dict[str, Any] = {"best": None, "last": None, "history": []}
        if self._manifest_path.exists():
            self._manifest = json.loads(self._manifest_path.read_text())

    def _is_better(self, value: float) -> bool:
        best = self._manifest["best"]
        if best is None:
            return True
        prev = best["metrics"][self.monitor]
        return value < prev if self.mode == "min" else value > prev

    def save(self, tag: str, state: Any, metrics: dict[str, float], step: int) -> bool:
        """Save under `tag`; update best/last pointers. Returns is_best."""
        path = self.directory / tag
        self._ckpt.save(path, state, force=True)
        # synchronous semantics: orbax saves are async by default and the
        # pending commit futures crash at interpreter shutdown otherwise
        self._ckpt.wait_until_finished()
        entry = {"tag": tag, "step": step, "metrics": metrics}
        self._manifest["history"].append(entry)
        self._manifest["last"] = entry
        is_best = self.monitor in metrics and self._is_better(metrics[self.monitor])
        if is_best:
            best_path = self.directory / "best"
            self._ckpt.save(best_path, state, force=True)
            self._ckpt.wait_until_finished()
            self._manifest["best"] = entry
        self._manifest_path.write_text(json.dumps(self._manifest, indent=2))
        return is_best

    def restore(self, tag: str, target: Any) -> Any:
        """Restore into the structure of `target` (an abstract or concrete
        pytree of the same shape)."""
        return self._ckpt.restore(self.directory / tag, target=target)

    def best_metrics(self) -> dict[str, float] | None:
        best = self._manifest["best"]
        return None if best is None else dict(best["metrics"])
