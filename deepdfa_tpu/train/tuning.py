"""Hyperparameter search: the framework's NNI-role component.

The reference wires NNI through LightningCLI (nni.get_next_parameter
mutating the config, per-epoch report_intermediate_result, final report —
DDFA/code_gnn/main_cli.py:110-120,184, base_module.py:346). Here search is
a plain in-process driver over the typed config:

- `SearchSpace`: dotted-config-key -> choices / (low, high[, log]) ranges,
- `random_search` / `grid_search`: yield override lists,
- `Tuner`: runs a user train_fn per trial, records intermediate metrics
  (the train loop's log_fn hooks straight in), tracks the best trial, and
  persists every trial to a jsonl ledger for offline analysis.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import math
import time
from pathlib import Path
from typing import Any, Callable, Iterator, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class SearchSpace:
    """choices: key -> list of values; ranges: key -> (low, high, log?)."""

    choices: dict[str, Sequence[Any]] = dataclasses.field(default_factory=dict)
    ranges: dict[str, tuple] = dataclasses.field(default_factory=dict)

    def sample(self, rng: np.random.Generator) -> list[str]:
        out = []
        for key, vals in self.choices.items():
            out.append(f"{key}={json.dumps(vals[int(rng.integers(len(vals)))])}")
        for key, spec in self.ranges.items():
            low, high = spec[0], spec[1]
            log = len(spec) > 2 and spec[2]
            if log:
                v = math.exp(rng.uniform(math.log(low), math.log(high)))
            else:
                v = rng.uniform(low, high)
            out.append(f"{key}={v}")
        return out


def random_search(
    space: SearchSpace, n_trials: int, seed: int = 0
) -> Iterator[list[str]]:
    rng = np.random.default_rng(seed)
    for _ in range(n_trials):
        yield space.sample(rng)


def grid_search(space: SearchSpace) -> Iterator[list[str]]:
    if space.ranges:
        raise ValueError("grid search requires pure choice spaces")
    keys = list(space.choices)
    for combo in itertools.product(*(space.choices[k] for k in keys)):
        yield [f"{k}={json.dumps(v)}" for k, v in zip(keys, combo)]


class Tuner:
    """Trial runner + ledger (monitor metric maximized by default)."""

    def __init__(
        self,
        ledger_path: str | Path,
        monitor: str = "val_f1",
        mode: str = "max",
    ):
        self.ledger_path = Path(ledger_path)
        self.ledger_path.parent.mkdir(parents=True, exist_ok=True)
        self.monitor = monitor
        self.mode = mode
        self.best: dict | None = None

    def _better(self, value: float) -> bool:
        if not math.isfinite(value):
            return False  # diverged trials (NaN/inf) never become best
        if self.best is None:
            return True
        prev = self.best["metric"]
        return value > prev if self.mode == "max" else value < prev

    def run(
        self,
        trials: Iterator[list[str]],
        train_fn: Callable[[list[str], Callable[[dict], None]], dict],
    ) -> dict | None:
        """train_fn(overrides, report) -> final metrics dict; `report` may
        be called with intermediate records (the fit loop's log_fn)."""
        for i, overrides in enumerate(trials):
            t0 = time.perf_counter()
            intermediates: list[dict] = []
            final = train_fn(overrides, intermediates.append)
            record = {
                "trial": i,
                "overrides": overrides,
                "final": final,
                "intermediate": intermediates,
                "seconds": time.perf_counter() - t0,
            }
            value = final.get(self.monitor)
            if value is not None and self._better(float(value)):
                self.best = {"trial": i, "overrides": overrides, "metric": float(value)}
                record["is_best"] = True
            with self.ledger_path.open("a") as f:
                f.write(json.dumps(record) + "\n")
        return self.best
